"""Benchmark driver — resilient, incremental, timeout-proof.

Metric (BASELINE.json): **candidate quorums checked/sec/chip** — how many
candidate node-subsets per second the engine can push through the full
check (is-quorum greatest-fixpoint + disjointness probe, i.e. the unit of
work at the heart of the reference's `containsQuorum`-driven search,
`/root/reference/quorum_intersection.cpp:140-177, :348-400`).

Workload: a 256-node hierarchical FBAS (16 orgs × 16 validators, nested
inner sets — the BASELINE.json "synthetic FBAS, nested inner-sets" config),
random candidate subsets.  Baseline: the same checks on one CPU core via the
host oracle semantics (the native C++ oracle when built, else pure Python —
reported in the `baseline` field).

Resilience contract (the tunneled TPU is known to hang indefinitely —
`utils/platform.py`): the PARENT process pins itself to the CPU platform and
never performs device work; every device phase runs in a child subprocess
under a hard timeout and is SIGKILLed on overrun.  A full headline JSON line
is (re)printed after every completed phase, so the driver's log always ends
with a parseable result even if a later phase dies or the driver window
closes early.  `--budget-seconds` bounds total wall-clock; phases that no
longer fit are skipped and recorded in `phases`.

Tunnel flakes are survivable in BOTH directions: the initial probe retries
with backoff, a mid-run fallback re-probes before the remaining device
phases (recovering onto the chip re-runs the headline throughput there),
and every device phase stamps the hardware it actually ran on in
`phase_devices` — "chip-unavailable" is distinguishable from "regressed"
per phase, not per run.  Beyond throughput/sweep, the record carries the
north-star `verdict_256`/`verdict_1024` time-to-verdict comparisons
(BASELINE.json configs) and a `sweep_mfu_pct` roofline estimate.

Usage::

    python bench.py                     # full run (driver mode, real chip)
    python bench.py --quick             # small shapes for smoke-testing
    python bench.py --budget-seconds N  # hard wall-clock bound (default 1500)
"""

from __future__ import annotations

import argparse
import json
import math
import os
import pathlib
import subprocess
import sys
import time

HEADLINE_METRIC = "candidate_quorums_checked_per_sec_per_chip"
# Children shorter than this can't even finish jax import + handshake;
# module-level so tests can shrink it to exercise timeout paths quickly.
MIN_CHILD_TIMEOUT = 20.0

# Captured before the parent pins itself to CPU: device children must see
# the AMBIENT platform config (the image exports the axon TPU platform),
# not the parent's safety pin.
_AMBIENT_JAX_PLATFORMS = os.environ.get("JAX_PLATFORMS")

# Full-mode workload shapes: 32k-candidate blocks, 128 blocks per device
# program (one program ≈ 4M candidates — big enough that the fixed
# per-program dispatch overhead on a tunneled chip is noise, kernels.py
# module docs); all `steps` programs dispatch asynchronously so the tunnel
# RTT overlaps with device compute (sweep.py MAX_INFLIGHT rationale).
FULL = dict(n_orgs=16, per_org=16, batch=32768, steps=24, chunks=128,
            samples=40, sweep_nodes=31, wide_sweep_nodes=34)
QUICK = dict(n_orgs=4, per_org=4, batch=256, steps=2, chunks=2,
             samples=10, sweep_nodes=13)
# CPU-fallback shapes: the emulated CPU backend sustains ~0.5M cand/s, so a
# real-chip-sized run would blow the budget; these finish in well under a
# minute while still exercising the full pipeline.
CPU_FALLBACK = dict(n_orgs=4, per_org=4, batch=4096, steps=4, chunks=8,
                    samples=10, sweep_nodes=17)

# Per-phase hard timeouts, seconds (full / quick).  First device contact
# includes jax import (~15 s) + tunnel handshake + first compile (20-40 s).
TIMEOUTS = {
    "probe": (90, 120),  # per ATTEMPT in full mode — see PROBE_RETRY_WAITS
    "throughput": (600, 240),
    "sweep": (420, 240),
    "sweep_wide": (420, 0),
    "verdict": (700, 240),
    "snapshot": (360, 240),
    "pagerank": (240, 120),
    "frontier": (420, 180),
    "auto_race": (120, 120),
}

# Tunnel-flake posture (VERDICT r3 §weak-1: one bad handshake at t=0 must not
# downgrade the whole artifact).  The tunnel is known to flake AND recover
# within a bench window, so: (a) the initial probe retries with backoff —
# short attempts beat one long one because a down tunnel HANGS rather than
# errors; (b) after a fallback, cheap re-probes before the remaining device
# phases switch back to the chip the moment it returns, re-running the
# headline throughput phase on it.
PROBE_RETRY_WAITS = (40.0, 80.0)     # sleep before attempts 2, 3 (full mode)
PROBE_RESERVE_S = 600.0              # keep this much budget for CPU fallback
RECOVERY_PROBE_TIMEOUT = 60.0
RECOVERY_MIN_REMAINING = 300.0

# North-star verdict configs (BASELINE.json configs[3..4]): end-to-end
# time-to-verdict through `auto` vs the single-core native oracle on the
# same instance.  The k-of-n core is the quorum-bearing sink SCC; the
# native baseline's full cost at these core sizes is hours, so it is
# measured as (instance-measured call rate) × (call-count model) with the
# measured floor alongside — see phase_verdict.
VERDICT_CONFIGS = {
    "256": dict(n_total=256, core=34, nested=False),
    "1024": dict(n_total=1024, core=34, nested=True),
}
VERDICT_CONFIGS_QUICK = {
    "256": dict(n_total=64, core=14, nested=False),
    "1024": dict(n_total=96, core=16, nested=True),
}
NATIVE_CAP_S = {"full": 120.0, "quick": 20.0}
# B&B call-count model for a symmetric k-of-n core, measured n = 8..26
# (benchmarks/results/native_calls_model_r4.txt): odd n lands on exactly
# 4·C(n, n//2); even n on 4·C(n, n//2)·(1 − 1/(n+2)) (3-decimal match for
# n >= 14; small even n a few thousandths lower).
# Beyond n=26 this is an extrapolation of that law and labeled as such.
NATIVE_CALLS_MODEL = "4*C(n,n//2)*(1-1/(n+2) if even) (native_calls_model_r4.txt n=8..26)"


def native_calls_estimate(core: int) -> float:
    mult = 4.0 - (4.0 / (core + 2) if core % 2 == 0 else 0.0)
    return mult * math.comb(core, core // 2)

# int8 MXU peak MACs/s by device kind substring — the sweep kernel's
# operands are int8 on TPU (kernels.CircuitArrays), so the roofline basis
# is the int8 TOPS figure (1 MAC = 2 ops): v5e/v5 lite ≈ 394 TOPS int8.
# Kinds not listed (e.g. v5p) get no MFU line rather than a wrong one.
INT8_PEAK_MACS = {"v5 lite": 1.97e14, "v5e": 1.97e14}


# --------------------------------------------------------------------------
# Phase bodies (run in-process in a CHILD; the parent only orchestrates).
# --------------------------------------------------------------------------

def build_workload(n_orgs: int, per_org: int):
    from quorum_intersection_tpu.encode.circuit import encode_circuit
    from quorum_intersection_tpu.fbas.graph import build_graph
    from quorum_intersection_tpu.fbas.schema import parse_fbas
    from quorum_intersection_tpu.fbas.synth import hierarchical_fbas

    graph = build_graph(parse_fbas(hierarchical_fbas(n_orgs, per_org)))
    return graph, encode_circuit(graph)


def phase_probe() -> dict:
    """Touch the device: init the backend, run one tiny compiled program."""
    import jax
    import jax.numpy as jnp

    t0 = time.perf_counter()
    devices = jax.devices()
    x = jax.jit(lambda a: (a @ a).sum())(jnp.eye(8)).block_until_ready()
    return {
        "device": devices[0].device_kind,
        "platform": devices[0].platform,
        "n_devices": len(devices),
        "probe_seconds": round(time.perf_counter() - t0, 2),
        "probe_result": float(x),
    }


def phase_throughput(n_orgs: int, per_org: int, batch: int, steps: int,
                     chunks: int) -> dict:
    """Candidates/sec through the full check (fixpoint + disjoint probe).

    Each device program evaluates ``chunks`` independent sub-batches via
    ``fori_loop`` (amortizing the fixed per-program dispatch overhead — see
    kernels.py module docs) and reduces to one scalar hit count; ``steps``
    programs are dispatched asynchronously and pipelined.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    from quorum_intersection_tpu.backends.tpu.kernels import CircuitArrays, fixpoint

    graph, circuit = build_workload(n_orgs, per_org)
    arrays = CircuitArrays(circuit)
    n = circuit.n
    full = jnp.ones((n,), dtype=arrays.dtype)

    @jax.jit
    def step(key):
        def body(i, acc):
            masks = jax.random.bernoulli(
                jax.random.fold_in(key, i), 0.5, (batch, n)
            ).astype(arrays.dtype)
            q = fixpoint(arrays, masks)
            comp = jnp.clip(full - q, 0, 1).astype(arrays.dtype)
            d = fixpoint(arrays, comp)
            hits = jnp.logical_and(
                q.sum(-1, dtype=jnp.int32) > 0, d.sum(-1, dtype=jnp.int32) > 0
            )
            return acc + hits.sum(dtype=jnp.int32)

        return lax.fori_loop(0, chunks, body, jnp.int32(0))

    keys = jax.random.split(jax.random.PRNGKey(0), steps + 1)
    step(keys[0]).block_until_ready()  # compile + warm up
    t0 = time.perf_counter()
    for i in range(steps):
        hits = step(keys[i + 1])
    hits.block_until_ready()
    seconds = time.perf_counter() - t0
    return {
        "rate": batch * chunks * steps / seconds,
        "throughput_seconds": round(seconds, 3),
        "workload": f"{graph.n}-node hierarchical FBAS, {circuit.n_units} circuit units",
        "batch": batch,
        "chunks": chunks,
        "device": jax.devices()[0].device_kind,
    }


def phase_sweep(n_nodes: int) -> dict:
    """Time-to-verdict for a FULL exhaustive sweep of a safe n-node majority
    FBAS (2^(n-1) candidates) through the production sweep backend — the
    headline end-to-end number.  The Python re-model of the reference's B&B
    timed out (>110 s) at n=24 (BASELINE.md); this sweeps n=31's 1.07e9
    candidates exhaustively in seconds."""
    from quorum_intersection_tpu.backends.tpu.sweep import TpuSweepBackend
    from quorum_intersection_tpu.fbas.synth import majority_fbas
    from quorum_intersection_tpu.pipeline import solve

    t0 = time.perf_counter()
    res = solve(majority_fbas(n_nodes), backend=TpuSweepBackend())
    seconds = time.perf_counter() - t0
    assert res.intersects is True
    out = {
        "sweep_nodes": n_nodes,
        "sweep_candidates": res.stats["candidates_checked"],
        "sweep_seconds": round(seconds, 2),
        "sweep_device_cand_per_sec": round(res.stats["candidates_per_sec"], 1),
    }
    # Wall-clock decomposition (VERDICT r2 §next-2): compile vs setup vs
    # per-ramp-level throughput, so the end-to-end vs device-rate gap is on
    # the record instead of asserted.
    for key in ("compile_seconds", "setup_seconds", "steady_rate", "steady_level",
                "ramp_profile"):
        if key in res.stats:
            out[f"sweep_{key}"] = res.stats[key]
    # qi-cert coverage row (ISSUE 7): the ledger numbers tools/bench_trend.py
    # gates — pruning wins must show up as a falling enumeration ratio, not
    # just MACs/sec (ROADMAP "Prune the search space").
    ledger = res.stats.get("cert") or {}
    if ledger.get("window_space"):
        out["sweep_windows_enumerated"] = ledger["windows_enumerated"]
        out["sweep_windows_pruned"] = ledger["windows_pruned_guard"]
        out["sweep_enumeration_ratio"] = round(
            ledger["windows_enumerated"] / ledger["window_space"], 6
        )
    # Pruned-sweep row (ISSUE 10): the tracked pruning gates go live with
    # REAL baselines measured on the adversarial near-disjoint-cores preset
    # — a symmetric majority's maximal candidates almost always contain a
    # quorum, so its ratio sits at ~1.0 by construction and would keep the
    # gates inert.  The preset's ledger OVERRIDES the three tracked keys
    # above (lower enumerated/ratio = better, higher pruned = better).
    try:
        out.update(_pruned_sweep_row(n_nodes))
    except Exception as exc:  # noqa: BLE001 — diagnostics row, never fatal
        out["sweep_pruned_error"] = f"{type(exc).__name__}: {exc}"
    import jax

    out["sweep_device"] = jax.devices()[0].device_kind
    try:
        out.update(_sweep_roofline(n_nodes, out.get("sweep_steady_rate")))
    except Exception as exc:  # noqa: BLE001 — roofline is diagnostics, never fatal
        out["sweep_mfu_error"] = f"{type(exc).__name__}: {exc}"
    return out


def _pruned_sweep_row(n_nodes: int) -> dict:
    """Rank-ordered + block-guard-pruned exhaustive sweep on the
    ``near_disjoint_cores`` preset (fbas/synth.py): two dense cores joined
    by a thin bridge, where most window blocks' maximal candidates hold no
    quorum and the guard prunes them into the certificate's
    ``windows_pruned_guard`` term.  The emitted keys are the
    tools/bench_trend.py pruning gates."""
    from quorum_intersection_tpu.backends.tpu.sweep import TpuSweepBackend
    from quorum_intersection_tpu.fbas.synth import near_disjoint_cores
    from quorum_intersection_tpu.pipeline import solve

    core = max(6, min(10, (n_nodes - 1) // 2))
    data = near_disjoint_cores(core, 1)
    t0 = time.perf_counter()
    res = solve(data, backend=TpuSweepBackend(order="rank", prune=True))
    seconds = time.perf_counter() - t0
    assert res.intersects is True
    ledger = res.stats.get("cert") or {}
    if not ledger.get("window_space"):
        return {"sweep_pruned_error": "no sweep ledger on the pruned row"}
    return {
        "sweep_pruned_nodes": 2 * core + 1,
        "sweep_pruned_seconds": round(seconds, 2),
        "sweep_windows_enumerated": ledger["windows_enumerated"],
        "sweep_windows_pruned": ledger["windows_pruned_guard"],
        "sweep_enumeration_ratio": round(
            ledger["windows_enumerated"] / ledger["window_space"], 6
        ),
    }


def _sweep_roofline(n_nodes: int, steady_rate) -> dict:
    """Utilization calibration (VERDICT r3 §weak-5): relate the steady sweep
    rate to the MXU's int8 peak.

    MACs/candidate = (trips_Q + trips_D) × per-iteration matmul cost, where
    the trip counts are MEASURED (kernels.fixpoint_iters) on random subsets
    of the same circuit — representative of the enumeration, since the
    fixpoint's convergence depends on the subset's density, not its index —
    and the per-iteration cost is node_sat's n·U direct-vote matmul plus
    depth·U² child propagation when inner sets exist.  `sweep_mfu_pct`
    answers "is the kernel or the pipeline the next lever": single-digit %
    ⇒ kernel headroom remains; tens of % ⇒ only pipeline work is left.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from quorum_intersection_tpu.backends.tpu.kernels import (
        CircuitArrays, fixpoint_iters,
    )
    from quorum_intersection_tpu.encode.circuit import encode_circuit
    from quorum_intersection_tpu.fbas.graph import build_graph
    from quorum_intersection_tpu.fbas.schema import parse_fbas
    from quorum_intersection_tpu.fbas.synth import majority_fbas

    circuit = encode_circuit(build_graph(parse_fbas(majority_fbas(n_nodes))))
    arrays = CircuitArrays(circuit)
    n, U = arrays.n, arrays.n_units

    @jax.jit
    def sample(key):
        masks = jax.random.bernoulli(key, 0.5, (2048, n)).astype(arrays.dtype)
        q, tq = fixpoint_iters(arrays, masks)
        comp = jnp.clip(1 - q, 0, 1).astype(arrays.dtype)
        _, td = fixpoint_iters(arrays, comp)
        return tq, td

    trips = [sample(jax.random.PRNGKey(i)) for i in range(4)]
    tq = float(np.mean([int(t[0]) for t in trips]))
    td = float(np.mean([int(t[1]) for t in trips]))
    per_iter = n * U + (arrays.depth * U * U if arrays.has_inner else 0)
    macs = (tq + td) * per_iter
    out = {
        "sweep_fixpoint_trips": [round(tq, 2), round(td, 2)],
        "sweep_macs_per_candidate": round(macs, 1),
    }
    kind = jax.devices()[0].device_kind
    peak = next((v for k, v in INT8_PEAK_MACS.items() if k in kind.lower()), None)
    if peak and steady_rate and arrays.dtype == jnp.int8:
        out["sweep_mfu_pct"] = round(steady_rate * macs / peak * 100, 3)
        out["sweep_mfu_peak"] = f"{kind} int8 {peak / 1e12:.0f}T MACs/s"
        # Structural context for the single-digit number (VERDICT r4
        # §next-4): the MXU multiplies 128x128 tiles, so a matmul whose
        # contraction/output dims are this circuit's (n, U) can use at most
        # n·U/128² of the array per pass no matter how it is scheduled —
        # the candidate/batch axis streams through and cannot widen the
        # other two.  Measured MFU relative to THIS ceiling says how much
        # of the shape-permitted compute the kernel actually extracts.
        ceiling = min(1.0, (min(n, 128) * min(U, 128)) / (128 * 128))
        out["sweep_mfu_tile_ceiling_pct"] = round(ceiling * 100, 2)
        out["sweep_mfu_of_ceiling_pct"] = round(
            out["sweep_mfu_pct"] / (ceiling * 100) * 100, 1
        )
    return out


def phase_verdict(config: str, quick: bool) -> dict:
    """North-star end-to-end time-to-verdict (VERDICT r3 §missing-3):
    BASELINE.json configs[3..4] through whatever engine `auto` picks, vs the
    single-core native oracle on the SAME instance.

    The native baseline at full core sizes costs hours, so it is reported
    three ways, each honestly labeled: `native_seconds` (measured, a FLOOR
    when `native_completed` is false), `native_rate` (B&B calls/s measured
    on this instance), and `native_est_seconds` (rate × the
    NATIVE_CALLS_MODEL count — an extrapolation of the call-count law
    measured to n=26 in benchmarks/results/native_calls_model_r4.txt).
    `ratio_est` uses the estimate; `ratio_floor` uses only measured
    time."""
    from quorum_intersection_tpu.fbas.synth import benchmark_fbas
    from quorum_intersection_tpu.pipeline import solve

    shape = (VERDICT_CONFIGS_QUICK if quick else VERDICT_CONFIGS)[config]
    data = benchmark_fbas(
        shape["n_total"], shape["core"], nested_watchers=shape["nested"]
    )

    import jax

    out = {
        "nodes": shape["n_total"],
        "core": shape["core"],
        "nested": shape["nested"],
        "device": jax.devices()[0].device_kind,
    }

    t0 = time.perf_counter()
    res = solve(data, backend="auto")
    auto_s = time.perf_counter() - t0
    out.update({
        "auto_seconds": round(auto_s, 2),
        "auto_backend": res.stats.get("backend", "scc-guard"),
        "verdict_ok": res.intersects is True,
    })
    print(json.dumps(out), flush=True)  # salvage point: auto half done

    out.update(_native_verdict_baseline(
        data, shape["core"], NATIVE_CAP_S["quick" if quick else "full"]
    ))
    if out.get("native_seconds") is not None and auto_s > 0:
        out["ratio_floor"] = round(out["native_seconds"] / auto_s, 2)
        if out.get("native_completed"):
            out["ratio"] = out["ratio_floor"]
    if out.get("native_est_seconds") and auto_s > 0:
        out["ratio_est"] = round(out["native_est_seconds"] / auto_s, 1)
    return out


def _native_verdict_baseline(data, core: int, cap_s: float) -> dict:
    """Single-core native-oracle cost on the instance's quorum-bearing SCC:
    measure the call rate with a budgeted probe run, finish the search if
    the model says it fits in ``cap_s``, else report the measured floor plus
    the model estimate."""
    from quorum_intersection_tpu.backends.base import OracleBudgetExceeded
    from quorum_intersection_tpu.fbas.graph import build_graph, group_sccs, tarjan_scc
    from quorum_intersection_tpu.fbas.schema import parse_fbas
    from quorum_intersection_tpu.pipeline import scan_scc_quorums

    graph = build_graph(parse_fbas(data))
    count, comp = tarjan_scc(graph.n, graph.succ)
    sccs = group_sccs(graph.n, comp, count)
    scc = next(
        s for s, q in zip(sccs, scan_scc_quorums(graph, sccs)) if q
    )
    expected_calls = native_calls_estimate(core)

    try:  # native oracle, degrading to pure Python like every other consumer
        from quorum_intersection_tpu.backends.cpp import CppOracleBackend as Oracle

        Oracle(budget_calls=1).ensure_built()
        engine = "cpp"
    except Exception:  # noqa: BLE001 — no g++ etc.
        from quorum_intersection_tpu.backends.python_oracle import (
            PythonOracleBackend as Oracle,
        )

        engine = "python"

    def run(budget_calls: int):
        backend = Oracle(budget_calls=budget_calls)
        t0 = time.perf_counter()
        try:
            res = backend.check_scc(graph, None, scc)
            return time.perf_counter() - t0, res.stats["bnb_calls"], True
        except OracleBudgetExceeded:
            return time.perf_counter() - t0, budget_calls, False

    seconds, calls, completed = run(2_000_000)
    rate = calls / seconds if seconds > 0 else 0.0
    if not completed and rate > 0 and expected_calls / rate <= cap_s:
        seconds, calls, completed = run(int(rate * cap_s * 2))
        rate = calls / seconds if seconds > 0 else rate
    out = {
        "native_engine": engine,
        "native_seconds": round(seconds, 4),
        "native_calls": int(calls),
        "native_rate": round(rate, 1),
        "native_completed": completed,
    }
    if not completed and rate > 0:
        out["native_est_calls"] = int(expected_calls)
        out["native_est_seconds"] = round(expected_calls / rate, 1)
        out["native_est_model"] = NATIVE_CALLS_MODEL
    return out


def phase_snapshot(quick: bool) -> dict:
    """Time-to-verdict on a stellarbeat-snapshot-shaped ~150-validator
    network (BASELINE.json north-star config), auto backend."""
    from quorum_intersection_tpu.fbas.synth import stellar_like_fbas
    from quorum_intersection_tpu.pipeline import solve

    data = stellar_like_fbas(n_core_orgs=5, n_watchers=30) if quick else stellar_like_fbas()
    t0 = time.perf_counter()
    res = solve(data, backend="auto")
    seconds = time.perf_counter() - t0
    assert res.intersects is True

    import jax

    return {
        "snapshot_nodes": len(data),
        "snapshot_verdict_seconds": round(seconds, 3),
        "snapshot_backend": res.stats.get("backend", "scc-guard"),
        "snapshot_device": jax.devices()[0].device_kind,
    }


def phase_auto_race(quick: bool) -> dict:
    """Racing-router overhead rows (ISSUE 1 acceptance): on the
    deterministic fake-latency harness (benchmarks/auto_race.py), `auto`
    must land within 1.2x of the faster engine in BOTH race outcomes —
    CPU-only and engine-noise-free, so the number measures the racing
    machinery itself (thread spin-up, cancel propagation, join)."""
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                    "benchmarks"))
    from auto_race import fake_rows

    from quorum_intersection_tpu.fbas.synth import majority_fbas

    rows = fake_rows(majority_fbas(7 if quick else 9))
    out = {"auto_race_ok": all(
        r["verdict_ok"] and (r["ratio_vs_fast"] or 99) <= 1.2 for r in rows
    )}
    for r in rows:
        key = f"auto_race_{r['outcome']}"
        out[key] = {
            "fast_engine_s": r["fast_engine_s"],
            "auto_race_s": r["auto_race_s"],
            "auto_sequential_s": r["auto_sequential_s"],
            "ratio_vs_fast": r["ratio_vs_fast"],
            "winner": r["winner"],
        }
    return out


def phase_frontier(quick: bool) -> dict:
    """Device-resident frontier vs the native C++ oracle on pruned-search
    workloads — per-round freshness evidence for the crossover story (the
    full decision artifact lives in benchmarks/results/crossover_tpu_r*.txt;
    the round-trip hybrid engine it used to measure was retired in r5).
    Verdicts must agree or the phase reports invalid."""
    import jax

    from quorum_intersection_tpu.backends.cpp import CppOracleBackend
    from quorum_intersection_tpu.backends.tpu.frontier import TpuFrontierBackend
    from quorum_intersection_tpu.fbas.synth import hierarchical_fbas, majority_fbas
    from quorum_intersection_tpu.pipeline import solve

    rows = (
        [("hier-5x3", hierarchical_fbas(5, 3))] if quick
        else [("majority-18", majority_fbas(18)), ("hier-5x3", hierarchical_fbas(5, 3))]
    )
    out = {"frontier_device": jax.devices()[0].device_kind,
           "frontier_verdicts_ok": True}
    for name, data in rows:
        t0 = time.perf_counter()
        cpp_res = solve(data, backend=CppOracleBackend())
        cpp_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        fr_res = solve(data, backend=TpuFrontierBackend())
        fr_s = time.perf_counter() - t0
        ok = cpp_res.intersects == fr_res.intersects
        out[f"frontier_{name}"] = {
            "cpp_seconds": round(cpp_s, 3),
            "frontier_seconds": round(fr_s, 3),
            "frontier_speedup_vs_cpp": round(cpp_s / fr_s, 3) if fr_s > 0 else None,
            "verdict_ok": ok,
            "frontier_states": fr_res.stats.get("states_popped"),
            "frontier_device_iters": fr_res.stats.get("device_iters"),
        }
        if not ok:
            # Emit the row (identifying WHICH workload diverged) instead of
            # crashing the phase — a perf number for a wrong answer is
            # worthless, but the evidence of the divergence is not.
            out["frontier_verdicts_ok"] = False
        # Incremental emit: if a later row hangs past the phase timeout
        # (e.g. a pathological device compile), the parent salvages the
        # rows already completed instead of losing the whole phase.
        print(json.dumps(out), flush=True)
    return out


def phase_pagerank(quick: bool) -> dict:
    """Device PageRank on a dump-scale (~3k-node) trust graph: the sparse
    scatter-add power iteration (`analytics/pagerank.py:pagerank`) vs the
    NumPy re-model, with L∞ parity checked (the C15 semantics pins)."""
    import numpy as np

    from quorum_intersection_tpu.analytics.pagerank import pagerank, pagerank_np
    from quorum_intersection_tpu.fbas.graph import build_graph
    from quorum_intersection_tpu.fbas.schema import parse_fbas
    from quorum_intersection_tpu.fbas.synth import stellar_like_fbas

    data = (
        stellar_like_fbas(n_watchers=300, seed=7) if quick
        else stellar_like_fbas(n_watchers=2800, n_null=150, n_dangling=40, seed=7)
    )
    graph = build_graph(parse_fbas(data))

    import jax

    t0 = time.perf_counter()
    ranks_jax = pagerank(graph)  # includes compile
    jax_first = time.perf_counter() - t0
    t0 = time.perf_counter()
    ranks_jax = pagerank(graph)  # warm
    jax_warm = time.perf_counter() - t0
    t0 = time.perf_counter()
    ranks_np = pagerank_np(graph)
    np_seconds = time.perf_counter() - t0
    linf = float(np.max(np.abs(ranks_jax - ranks_np))) if graph.n else 0.0
    assert linf < 1e-4, f"device/NumPy PageRank diverged: linf={linf}"
    return {
        "pagerank_nodes": graph.n,
        "pagerank_edges": graph.n_edges,
        "pagerank_jax_seconds": round(jax_warm, 3),
        "pagerank_jax_first_seconds": round(jax_first, 3),
        "pagerank_np_seconds": round(np_seconds, 3),
        "pagerank_linf_vs_np": linf,
        "pagerank_device": jax.devices()[0].platform,
    }


# --------------------------------------------------------------------------
# Host-only work (safe to run in the CPU-pinned parent).
# --------------------------------------------------------------------------

def parity_gate() -> dict:
    """Golden verdict parity on the host oracle (cpp, python fallback) —
    never on a device.  Checks the reference's four fixtures when the
    read-only checkout is present, and ALWAYS checks the self-contained
    vendored corpus (`fixtures/MANIFEST.json`), so the gate keeps running
    when this repo is detached from the reference environment."""
    from quorum_intersection_tpu.pipeline import solve

    def verdict(text: str) -> bool:
        try:
            return solve(text, backend="cpp").intersects
        except Exception:  # noqa: BLE001 — no g++ etc.; degrade, don't hang
            return solve(text, backend="python").intersects

    parts = []

    ref = pathlib.Path("/root/reference")
    expected = {
        "correct_trivial.json": True,
        "broken_trivial.json": False,
        "correct.json": True,
        "broken.json": False,
    }
    if ref.exists():
        checked = 0
        for name, want in expected.items():
            path = ref / name
            if not path.exists():
                continue
            if verdict(path.read_text()) is not want:
                return {"parity": f"FAILED on {name}", "parity_ok": False}
            checked += 1
        parts.append(f"{checked}/4 reference")

    fixtures = pathlib.Path(__file__).resolve().parent / "fixtures"
    manifest_path = fixtures / "MANIFEST.json"
    if manifest_path.exists():
        manifest = json.loads(manifest_path.read_text())
        checked = total = 0
        for name, meta in manifest.items():
            if name.endswith(".gz"):
                continue  # dump-scale fixture: scale test, not a parity gate
            total += 1
            if verdict((fixtures / name).read_text()) is not meta["verdict"]:
                return {"parity": f"FAILED on vendored {name}", "parity_ok": False}
            checked += 1
        parts.append(f"{checked}/{total} vendored")

    if not parts:
        return {"parity": "fixtures-unavailable"}
    return {"parity": " + ".join(parts), "parity_ok": True}


def cpu_baseline(n_orgs: int, per_org: int, samples: int) -> dict:
    """Single-core candidates/sec through the same check on the host oracle.

    Prefers the native C++ oracle's candidate checker when available."""
    import numpy as np

    graph, _ = build_workload(n_orgs, per_org)
    rng = np.random.default_rng(0)
    n = graph.n
    masks = rng.random((samples, n)) < 0.5

    try:
        from quorum_intersection_tpu.backends.cpp import native_candidate_rate

        return {"baseline_value": native_candidate_rate(graph, masks),
                "baseline": "cpp-single-core"}
    except Exception:  # noqa: BLE001 — degrade to the Python oracle
        pass

    from quorum_intersection_tpu.fbas.semantics import max_quorum

    t0 = time.perf_counter()
    for row in masks:
        avail = row.tolist()
        candidates = [v for v in range(n) if avail[v]]
        q = max_quorum(graph, candidates, avail)
        qset = set(q)
        comp_avail = [not (row[v] and v in qset) for v in range(n)]
        comp = [v for v in range(n) if comp_avail[v]]
        max_quorum(graph, comp, comp_avail)
    seconds = time.perf_counter() - t0
    return {"baseline_value": samples / seconds, "baseline": "python-single-core"}


# --------------------------------------------------------------------------
# Orchestration.
# --------------------------------------------------------------------------

class Deadline:
    def __init__(self, budget: float) -> None:
        self.t_end = time.monotonic() + budget

    def remaining(self) -> float:
        return self.t_end - time.monotonic()


def run_child(phase: str, deadline: Deadline, timeout: float,
              extra_args: list | None = None, platform: str | None = None,
              salvage: bool = False) -> dict:
    """Run one device phase in a subprocess with a hard kill timeout.

    Returns the child's JSON result, or ``{"error": ...}`` on timeout /
    crash / unparseable output — the parent never blocks on a hung tunnel.
    ``salvage=True`` (phases that emit incrementally): on timeout, the last
    parseable stdout line is returned with a ``partial_error`` marker.

    Each dispatch is a ``bench.<phase>`` telemetry span in the parent's run
    record (one schema with the CLI — docs/OBSERVABILITY.md); the child
    inherits ``QI_METRICS_JSON`` through the environment, so its own
    pipeline/sweep spans land in the same JSONL stream, grouped by pid.
    """
    from quorum_intersection_tpu.utils.telemetry import get_run_record

    with get_run_record().span(
        f"bench.{phase}", platform=platform or "ambient"
    ) as sp:
        result = _run_child_raw(phase, deadline, timeout, extra_args,
                                platform, salvage)
        sp.set(ok="error" not in result)
        if "error" in result:
            sp.set(error=result["error"][:120])
        return result


def _run_child_raw(phase: str, deadline: Deadline, timeout: float,
                   extra_args: list | None = None,
                   platform: str | None = None,
                   salvage: bool = False) -> dict:
    timeout = min(timeout, max(deadline.remaining() - 15.0, 0.0))
    if timeout < MIN_CHILD_TIMEOUT:
        return {"error": "skipped: budget exhausted"}
    env = dict(os.environ)
    if platform is not None:
        env["JAX_PLATFORMS"] = platform
    elif _AMBIENT_JAX_PLATFORMS is not None:
        env["JAX_PLATFORMS"] = _AMBIENT_JAX_PLATFORMS
    else:
        env.pop("JAX_PLATFORMS", None)  # parent pinned cpu; child wants ambient
    # Trace-context propagation (qi-trace): the child's RunRecord adopts
    # this trace_id and records the enclosing bench.<phase> span as its
    # remote parent, so the child's whole tree stitches under it in the
    # exported timeline (and metrics_report's span trees).
    from quorum_intersection_tpu.utils.telemetry import get_run_record

    env["QI_TRACE_CONTEXT"] = get_run_record().trace_context().to_env()
    cmd = [sys.executable, os.path.abspath(__file__), "--phase", phase]
    cmd += extra_args or []
    proc = subprocess.Popen(
        cmd, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env
    )
    def last_parseable(text):
        """Scan stdout BACKWARDS for the last complete JSON line (a stray
        library print or a SIGKILL mid-write can corrupt the literal last
        line without invalidating the rows before it)."""
        for ln in reversed([x for x in (text or "").strip().splitlines() if x.strip()]):
            try:
                return json.loads(ln)
            except json.JSONDecodeError:
                continue
        return None

    def degraded(reason):
        """Salvage: phases that emit incrementally (frontier) leave their last
        completed state on stdout — partial evidence beats none.  The
        `partial_error` key lets the caller mark the phase degraded while
        still merging the data."""
        if salvage:
            salvaged = last_parseable(out)
            if salvaged is not None:
                salvaged["partial_error"] = reason
                return salvaged
        return {"error": reason}

    try:
        out, err = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.kill()  # SIGKILL: the hang is inside native tunnel code
        out, _ = proc.communicate()
        return degraded(f"timeout after {timeout:.0f}s")
    lines = [ln for ln in (out or "").strip().splitlines() if ln.strip()]
    if proc.returncode != 0 or not lines:
        tail = (err or "").strip().splitlines()[-3:]
        return degraded(f"exit {proc.returncode}: {' | '.join(tail) or 'no output'}")
    try:
        return json.loads(lines[-1])
    except json.JSONDecodeError:
        return {"error": f"unparseable child output: {lines[-1][:200]}"}


def emit(headline: dict) -> None:
    """(Re)print the full headline line — the driver keeps the LAST one."""
    print(json.dumps(headline), flush=True)


def orchestrate(args) -> int:
    # Pin the PARENT to CPU before any jax import can touch the tunnel.
    os.environ["JAX_PLATFORMS"] = "cpu"
    from quorum_intersection_tpu.utils.platform import honor_platform_env

    honor_platform_env()
    if args.metrics_json:
        # One stream for the whole bench: the env var (not a flag) carries
        # the sink so every phase CHILD appends its own spans/counters to
        # the same JSONL file the parent's bench.<phase> spans land in.
        os.environ["QI_METRICS_JSON"] = os.path.abspath(args.metrics_json)

    deadline = Deadline(args.budget_seconds)
    shapes = dict(QUICK if args.quick else FULL)
    for k in ("batch", "steps", "chunks"):
        if getattr(args, k) is not None:
            shapes[k] = getattr(args, k)
    tmo = {k: v[1 if args.quick else 0] for k, v in TIMEOUTS.items()}

    headline = {
        "metric": HEADLINE_METRIC,
        "value": 0,
        "unit": "candidates/s",
        "vs_baseline": 0,
        "device": "unknown",
        "phases": {},
        # Per-phase device stamps (VERDICT r3 §next-2): which hardware each
        # device phase ACTUALLY ran on, so a mid-run tunnel flake downgrades
        # one phase's stamp, not the whole artifact's credibility.
        "phase_devices": {},
    }
    phases = headline["phases"]
    phase_devices = headline["phase_devices"]

    def stamp(phase: str, result: dict, key: str, pinned: str | None = None) -> None:
        if "error" in result:
            # "chip-unavailable" only for phases dispatched WITHOUT a cpu
            # platform pin: under the cpu-fallback pin a timeout means
            # genuine CPU slowness, not a tunnel flake (ADVICE r4) — the
            # label exists to keep those two failure modes distinguishable.
            chip = pinned != "cpu" and "timeout" in result["error"]
            kind = "chip-unavailable" if chip else "failed"
            phase_devices[phase] = f"{kind}: {result['error'][:80]}"
        else:
            phase_devices[phase] = result.get(key, "?")

    # 1. Verdict parity on the host oracle (fast, CPU-only, no tunnel risk).
    gate = parity_gate()
    headline.update({k: v for k, v in gate.items() if k != "parity_ok"})
    if gate.get("parity_ok") is False:
        emit(headline)
        return 0  # a parseable failure beats a silent one
    phases["parity"] = "ok"

    # 2. Single-core baseline (host; needed for vs_baseline).  Stashed so a
    # later chip recovery can restore it without re-measuring.
    full_baseline = {
        k: round(v, 1) if isinstance(v, float) else v
        for k, v in cpu_baseline(shapes["n_orgs"], shapes["per_org"],
                                 shapes["samples"]).items()
    }
    headline.update(full_baseline)
    phases["baseline"] = "ok"
    emit(headline)  # first safety line: parity + baseline, value still 0

    # 3. Device liveness probe — bounded retry with backoff (the tunnel
    # hangs when down but is known to recover within a bench window; short
    # attempts spread over time beat one long one).
    attempts: list = []
    probe = {"error": "not attempted"}
    max_attempts = 1 if args.quick else 1 + len(PROBE_RETRY_WAITS)
    for i in range(max_attempts):
        if i > 0:
            wait = PROBE_RETRY_WAITS[i - 1]
            if deadline.remaining() < PROBE_RESERVE_S + wait:
                attempts.append("retry-skipped: budget")
                break
            time.sleep(wait)
        probe = run_child("probe", deadline, tmo["probe"])
        if "error" not in probe:
            break
        attempts.append(probe["error"])
    fallback = "error" in probe

    def to_cpu_shapes() -> None:
        shapes.update({k: v for k, v in CPU_FALLBACK.items()
                       if k in ("n_orgs", "per_org", "batch", "steps",
                                "chunks", "sweep_nodes")})
        shapes.pop("wide_sweep_nodes", None)

    def remeasure_baseline() -> None:
        # The baseline must match the active workload shapes; per-candidate
        # cost scales with graph size, so a stale baseline would inflate
        # vs_baseline by orders of magnitude.
        base = cpu_baseline(shapes["n_orgs"], shapes["per_org"], shapes["samples"])
        headline.update({k: round(v, 1) if isinstance(v, float) else v
                         for k, v in base.items()})

    if fallback:
        tunnel_down = all("timeout" in a for a in attempts if not a.startswith("retry"))
        phases["probe"] = (
            f"chip-unavailable (tunnel): {'; '.join(attempts)}" if tunnel_down
            else "; ".join(attempts)
        )
        to_cpu_shapes()
        headline["device"] = "cpu-fallback"
        remeasure_baseline()
    else:
        phases["probe"] = "ok" if not attempts else (
            f"ok after {len(attempts) + 1} attempts ({'; '.join(attempts)})"
        )
        headline["device"] = probe.get("device", "unknown")
    stamp("probe", probe, "device")
    platform = "cpu" if fallback else None

    def try_recover(stage: str) -> bool:
        """After a fallback: cheap re-probe before a remaining device phase;
        on success the rest of the run moves back to the chip (full device
        shapes restored) and the recovery point is on the record."""
        nonlocal fallback, platform
        if not fallback or args.quick:
            return False
        if deadline.remaining() < RECOVERY_MIN_REMAINING:
            return False
        r = run_child("probe", deadline, RECOVERY_PROBE_TIMEOUT)
        if "error" in r:
            phases["probe"] += f"; re-probe at {stage}: down"
            return False
        fallback, platform = False, None
        shapes.update({k: FULL[k] for k in ("n_orgs", "per_org", "batch",
                                            "steps", "chunks", "sweep_nodes",
                                            "wide_sweep_nodes")})
        phases["probe"] += f"; recovered at {stage}"
        phase_devices["probe"] = r.get("device", "?")
        return True

    # 4. Throughput — the headline value.
    def run_throughput():
        tp_args = ["--n-orgs", str(shapes["n_orgs"]), "--per-org", str(shapes["per_org"]),
                   "--batch", str(shapes["batch"]), "--steps", str(shapes["steps"]),
                   "--chunks", str(shapes["chunks"])]
        return run_child("throughput", deadline, tmo["throughput"], tp_args, platform)

    def merge_throughput(tp: dict) -> None:
        phases["throughput"] = "ok"
        rate = tp["rate"]
        base_rate = headline.get("baseline_value") or 0
        headline.update({
            "value": round(rate, 1),
            "vs_baseline": round(rate / base_rate, 2) if base_rate else None,
            "workload": tp.get("workload"),
            "batch": tp.get("batch"),
            "chunks": tp.get("chunks"),
            "device": tp.get("device", headline["device"]),
        })
        if fallback:
            headline["device"] = "cpu-fallback"

    tp = run_throughput()
    if "error" in tp and not fallback:
        # Tunnel died after a healthy probe: fall back to CPU for the rest
        # (recovery re-probes below may switch back).
        phases["throughput"] = tp["error"]
        fallback, platform = True, "cpu"
        headline["device"] = "cpu-fallback"
        to_cpu_shapes()
        tp = run_throughput()
        remeasure_baseline()
    if "error" in tp:
        phases["throughput"] = tp["error"]
    else:
        merge_throughput(tp)
    stamp("throughput", tp, "device", platform)
    emit(headline)  # the headline number is now safe on the record

    # 5. Exhaustive-sweep time-to-verdict.  If the run fell back earlier,
    # a cheap re-probe here moves it back on-chip the moment the tunnel
    # returns — and re-runs the headline throughput phase there.  The
    # baseline/value swap happens only AFTER the re-run succeeds: if the
    # tunnel dies again mid-re-run, the CPU-fallback numbers (value,
    # vs_baseline, baseline_value, shapes, platform) all stay consistent.
    if try_recover("sweep"):
        tp = run_throughput()
        if "error" in tp:
            fallback, platform = True, "cpu"
            to_cpu_shapes()
            phases["probe"] += "; recovery lost at throughput re-run"
        else:
            headline.update(full_baseline)  # stashed step-2 full-shape rates
            merge_throughput(tp)
            stamp("throughput", tp, "device", platform)
        emit(headline)
    sweep = run_child("sweep", deadline, tmo["sweep"],
                      ["--sweep-nodes", str(shapes["sweep_nodes"])], platform)
    if "error" in sweep:
        phases["sweep"] = sweep["error"]
    else:
        phases["sweep"] = "ok"
        headline.update(sweep)
    stamp("sweep", sweep, "sweep_device", platform)
    emit(headline)

    # 5b. Wide sweep (2^(wide_sweep_nodes-1) candidates): large enough that
    # the fixed session costs (tunnel handshake + program-load, see the
    # sweep breakdown keys) amortize — the end-to-end rate here is the one
    # comparable to the steady-state device rate.  Device mode only: the
    # CPU emulation would need hours for 2^33.
    if (not fallback and not args.quick and "wide_sweep_nodes" in shapes
            and phases.get("sweep") == "ok"):
        wide = run_child("sweep", deadline, tmo["sweep_wide"],
                         ["--sweep-nodes", str(shapes["wide_sweep_nodes"])],
                         platform)
        if "error" in wide:
            phases["sweep_wide"] = wide["error"]
        else:
            phases["sweep_wide"] = "ok"
            headline.update({f"wide_{k}": v for k, v in wide.items()})
        stamp("sweep_wide", wide, "sweep_device", platform)
        emit(headline)

    # 5c. North-star verdict benchmarks (BASELINE.json configs[3..4]):
    # end-to-end time-to-verdict through `auto` vs the single-core native
    # oracle, one child per config (incremental salvage: the auto half
    # emits before the native baseline starts).
    quick_flag = ["--quick"] if (args.quick or fallback) else []
    for cfg in ("256", "1024"):
        key = f"verdict_{cfg}"
        vd = run_child("verdict", deadline, tmo["verdict"],
                       ["--verdict-config", cfg] + quick_flag, platform,
                       salvage=True)
        if "error" in vd:
            phases[key] = vd["error"]
        else:
            partial = vd.pop("partial_error", None)
            status = "ok" if vd.get("verdict_ok") else "verdict-mismatch"
            phases[key] = f"partial({status}): {partial}" if partial else status
            headline[key] = vd
        stamp(key, vd, "device", platform)
        emit(headline)

    # 5d. Racing-router overhead rows (ISSUE 1): deterministic fake-latency
    # harness, always CPU-pinned — no tunnel risk, and the measured number
    # is the racing machinery, not the engines.
    ar = run_child("auto_race", deadline, tmo["auto_race"],
                   ["--quick"] if args.quick else [], "cpu")
    if "error" in ar:
        phases["auto_race"] = ar["error"]
    else:
        phases["auto_race"] = "ok" if ar.get("auto_race_ok") else "over-budget"
        headline.update(ar)
    stamp("auto_race", ar, "device", "cpu")
    emit(headline)

    # 6. Snapshot time-to-verdict (auto backend).
    snap = run_child("snapshot", deadline, tmo["snapshot"], quick_flag, platform)
    if "error" in snap:
        phases["snapshot"] = snap["error"]
    else:
        phases["snapshot"] = "ok"
        headline.update(snap)
    stamp("snapshot", snap, "snapshot_device", platform)
    emit(headline)

    # 7. Device PageRank on a dump-scale graph (differential vs NumPy).
    pr = run_child("pagerank", deadline, tmo["pagerank"], quick_flag, platform)
    if "error" in pr:
        phases["pagerank"] = pr["error"]
    else:
        phases["pagerank"] = "ok"
        headline.update(pr)
    stamp("pagerank", pr, "pagerank_device", platform)
    emit(headline)

    # 8. Frontier vs native oracle on pruned-search workloads (on-chip
    # crossover freshness evidence; VERDICT r2 §next-1, hybrid retired r5).
    if try_recover("frontier"):
        quick_flag = ["--quick"] if (args.quick or fallback) else []
        emit(headline)
    fr = run_child("frontier", deadline, tmo["frontier"], quick_flag, platform,
                   salvage=True)
    if "error" in fr:
        phases["frontier"] = fr["error"]
    else:
        # Per-row verdict agreement gates the phase status: a perf number
        # for a wrong answer must not read as a healthy benchmark.  A
        # salvaged partial phase reports which timeout truncated it.
        status = "ok" if fr.get("frontier_verdicts_ok", True) else "verdict-mismatch"
        partial = fr.pop("partial_error", None)
        phases["frontier"] = f"partial({status}): {partial}" if partial else status
        headline.update(fr)
    stamp("frontier", fr, "frontier_device", platform)
    emit(headline)

    from quorum_intersection_tpu.utils import telemetry

    rec = telemetry.get_run_record()
    rec.gauge("bench.headline_value", headline.get("value"))
    rec.event("bench.done", device=headline.get("device"),
              phases={k: str(v)[:80] for k, v in phases.items()})
    telemetry.finish()
    return 0


def child_main(args) -> int:
    """Dispatch one phase in this (child) process and print its JSON."""
    from quorum_intersection_tpu.utils.platform import honor_platform_env
    from quorum_intersection_tpu.utils.telemetry import get_run_record

    honor_platform_env()  # honors JAX_PLATFORMS=cpu for fallback children
    with get_run_record().span(f"bench.child.{args.phase}"):
        return _child_dispatch(args)


def _child_dispatch(args) -> int:
    if args.phase == "probe":
        out = phase_probe()
    elif args.phase == "throughput":
        out = phase_throughput(args.n_orgs, args.per_org, args.batch,
                               args.steps, args.chunks)
    elif args.phase == "sweep":
        out = phase_sweep(args.sweep_nodes)
    elif args.phase == "verdict":
        out = phase_verdict(args.verdict_config, args.quick)
    elif args.phase == "snapshot":
        out = phase_snapshot(args.quick)
    elif args.phase == "auto_race":
        out = phase_auto_race(args.quick)
    elif args.phase == "pagerank":
        out = phase_pagerank(args.quick)
    elif args.phase == "frontier":
        out = phase_frontier(args.quick)
    else:
        raise SystemExit(f"unknown phase {args.phase!r}")
    print(json.dumps(out), flush=True)
    return 0


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--quick", action="store_true", help="small smoke-test shapes")
    parser.add_argument("--budget-seconds", type=float, default=1500.0,
                        help="total wall-clock bound; phases that no longer fit are skipped")
    parser.add_argument("--metrics-json", default=None, metavar="PATH",
                        help="append run-record telemetry (qi-telemetry/1 "
                             "JSONL, parent AND phase children) to PATH; "
                             "render with tools/metrics_report.py")
    parser.add_argument("--batch", type=int, default=None, help="candidates per block")
    parser.add_argument("--steps", type=int, default=None, help="device programs dispatched")
    parser.add_argument(
        "--chunks", type=int, default=None,
        help="blocks fused per device program (candidates/step = batch × chunks)",
    )
    # Internal: child-phase dispatch (run_child invokes bench.py --phase …).
    parser.add_argument("--phase",
                        choices=("probe", "throughput", "sweep", "verdict",
                                 "snapshot", "pagerank", "frontier",
                                 "auto_race"),
                        default=None, help=argparse.SUPPRESS)
    parser.add_argument("--verdict-config", choices=tuple(VERDICT_CONFIGS),
                        default="256", help=argparse.SUPPRESS)
    parser.add_argument("--n-orgs", type=int, default=FULL["n_orgs"], help=argparse.SUPPRESS)
    parser.add_argument("--per-org", type=int, default=FULL["per_org"], help=argparse.SUPPRESS)
    parser.add_argument("--sweep-nodes", type=int, default=FULL["sweep_nodes"],
                        help=argparse.SUPPRESS)
    args = parser.parse_args()
    if args.batch is None and args.phase is not None:
        args.batch = FULL["batch"]
    if args.steps is None and args.phase is not None:
        args.steps = FULL["steps"]
    if args.chunks is None and args.phase is not None:
        args.chunks = FULL["chunks"]

    if args.phase is not None:
        return child_main(args)
    try:
        return orchestrate(args)
    except Exception as exc:  # noqa: BLE001 — the driver must ALWAYS get a line
        print(json.dumps({
            "metric": HEADLINE_METRIC,
            "value": 0,
            "unit": "candidates/s",
            "vs_baseline": 0,
            "device": "unknown",
            "error": f"orchestrator crashed: {type(exc).__name__}: {exc}",
        }), flush=True)
        return 0


if __name__ == "__main__":
    sys.exit(main())
