"""Benchmark driver — prints ONE JSON line with the headline metric.

Metric (BASELINE.json): **candidate quorums checked/sec/chip** — how many
candidate node-subsets per second the engine can push through the full
check (is-quorum greatest-fixpoint + disjointness probe, i.e. the unit of
work at the heart of the reference's `containsQuorum`-driven search,
`/root/reference/quorum_intersection.cpp:140-177, :348-400`).

Workload: a 256-node hierarchical FBAS (16 orgs × 16 validators, nested
inner sets — the BASELINE.json "synthetic FBAS, nested inner-sets" config),
random candidate subsets.  Baseline: the same checks on one CPU core via the
host oracle semantics (the native C++ oracle when built, else pure Python —
reported in the `baseline` field).

A verdict-parity gate runs first: all four bundled reference fixtures must
produce the reference verdicts or the benchmark refuses to report a number.

Usage::

    python bench.py            # full run (driver mode, real chip)
    python bench.py --quick    # small shapes for smoke-testing
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np


def parity_gate() -> bool:
    """All four golden fixtures must match reference verdicts."""
    import pathlib

    from quorum_intersection_tpu.pipeline import solve

    ref = pathlib.Path("/root/reference")
    expected = {
        "correct_trivial.json": True,
        "broken_trivial.json": False,
        "correct.json": True,
        "broken.json": False,
    }
    if not ref.exists():
        return True  # fixtures unavailable; skip the gate rather than fail
    for name, want in expected.items():
        path = ref / name
        if not path.exists():
            continue
        got = solve(path.read_text(), backend="auto").intersects
        if got is not want:
            print(
                json.dumps(
                    {
                        "metric": "candidate_quorums_checked_per_sec_per_chip",
                        "value": 0,
                        "unit": "candidates/s",
                        "vs_baseline": 0,
                        "error": f"verdict parity FAILED on {name}: got {got}, want {want}",
                    }
                )
            )
            return False
    return True


def build_workload(n_orgs: int, per_org: int):
    from quorum_intersection_tpu.encode.circuit import encode_circuit
    from quorum_intersection_tpu.fbas.graph import build_graph
    from quorum_intersection_tpu.fbas.schema import parse_fbas
    from quorum_intersection_tpu.fbas.synth import hierarchical_fbas

    graph = build_graph(parse_fbas(hierarchical_fbas(n_orgs, per_org)))
    return graph, encode_circuit(graph)


def tpu_throughput(circuit, batch: int, steps: int, chunks: int = 32) -> float:
    """Candidates/sec through the full check (fixpoint + disjoint probe).

    Each device program evaluates ``chunks`` independent sub-batches via
    ``fori_loop`` (amortizing the fixed per-program dispatch overhead — see
    kernels.py module docs) and reduces to one scalar hit count; ``steps``
    programs are dispatched asynchronously and pipelined.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    from quorum_intersection_tpu.backends.tpu.kernels import CircuitArrays, fixpoint

    arrays = CircuitArrays(circuit)
    n = circuit.n
    full = jnp.ones((n,), dtype=arrays.dtype)

    @jax.jit
    def step(key):
        def body(i, acc):
            masks = jax.random.bernoulli(
                jax.random.fold_in(key, i), 0.5, (batch, n)
            ).astype(arrays.dtype)
            q = fixpoint(arrays, masks)
            comp = jnp.clip(full - q, 0, 1).astype(arrays.dtype)
            d = fixpoint(arrays, comp)
            hits = jnp.logical_and(
                q.sum(-1, dtype=jnp.int32) > 0, d.sum(-1, dtype=jnp.int32) > 0
            )
            return acc + hits.sum(dtype=jnp.int32)

        return lax.fori_loop(0, chunks, body, jnp.int32(0))

    keys = jax.random.split(jax.random.PRNGKey(0), steps + 1)
    step(keys[0]).block_until_ready()  # compile + warm up
    t0 = time.perf_counter()
    for i in range(steps):
        hits = step(keys[i + 1])
    hits.block_until_ready()
    seconds = time.perf_counter() - t0
    return batch * chunks * steps / seconds


def sweep_verdict(n_nodes: int) -> dict:
    """Time-to-verdict for a FULL exhaustive sweep of a safe n-node majority
    FBAS (2^(n-1) candidates) through the production sweep backend — the
    headline end-to-end number.  The Python re-model of the reference's B&B
    timed out (>110 s) at n=24 (BASELINE.md); this sweeps n=31's 1.07e9
    candidates exhaustively in seconds."""
    from quorum_intersection_tpu.backends.tpu.sweep import TpuSweepBackend
    from quorum_intersection_tpu.fbas.synth import majority_fbas
    from quorum_intersection_tpu.pipeline import solve

    t0 = time.perf_counter()
    res = solve(majority_fbas(n_nodes), backend=TpuSweepBackend())
    seconds = time.perf_counter() - t0
    assert res.intersects is True
    return {
        "sweep_nodes": n_nodes,
        "sweep_candidates": res.stats["candidates_checked"],
        "sweep_seconds": round(seconds, 2),
        "sweep_device_cand_per_sec": round(res.stats["candidates_per_sec"], 1),
    }


def snapshot_verdict(quick: bool = False) -> dict:
    """Time-to-verdict on a stellarbeat-snapshot-shaped ~150-validator
    network (BASELINE.json north-star config), auto backend."""
    from quorum_intersection_tpu.fbas.synth import stellar_like_fbas
    from quorum_intersection_tpu.pipeline import solve

    data = stellar_like_fbas(n_core_orgs=5, n_watchers=30) if quick else stellar_like_fbas()
    t0 = time.perf_counter()
    res = solve(data, backend="auto")
    seconds = time.perf_counter() - t0
    assert res.intersects is True
    return {
        "snapshot_nodes": len(data),
        "snapshot_verdict_seconds": round(seconds, 3),
        "snapshot_backend": res.stats.get("backend", "scc-guard"),
    }


def cpu_baseline(graph, samples: int) -> tuple:
    """Single-core candidates/sec through the same check on the host oracle.

    Prefers the native C++ oracle's candidate checker when available.
    Returns (rate, which)."""
    rng = np.random.default_rng(0)
    n = graph.n
    masks = rng.random((samples, n)) < 0.5

    try:
        from quorum_intersection_tpu.backends.cpp import native_candidate_rate

        return native_candidate_rate(graph, masks), "cpp-single-core"
    except Exception:
        pass

    from quorum_intersection_tpu.fbas.semantics import max_quorum

    t0 = time.perf_counter()
    for row in masks:
        avail = row.tolist()
        candidates = [v for v in range(n) if avail[v]]
        q = max_quorum(graph, candidates, avail)
        qset = set(q)
        comp_avail = [not (row[v] and v in qset) for v in range(n)]
        comp = [v for v in range(n) if comp_avail[v]]
        max_quorum(graph, comp, comp_avail)
    seconds = time.perf_counter() - t0
    return samples / seconds, "python-single-core"


def main() -> int:
    from quorum_intersection_tpu.utils.platform import honor_platform_env

    honor_platform_env()
    parser = argparse.ArgumentParser()
    parser.add_argument("--quick", action="store_true", help="small smoke-test shapes")
    parser.add_argument("--batch", type=int, default=None, help="candidates per block")
    parser.add_argument("--steps", type=int, default=None, help="device programs dispatched")
    parser.add_argument(
        "--chunks", type=int, default=None,
        help="blocks fused per device program (candidates/step = batch × chunks)",
    )
    args = parser.parse_args()

    if not parity_gate():
        return 1

    if args.quick:
        n_orgs, per_org, batch, steps, chunks, samples = 4, 4, 256, 2, 2, 10
        sweep_nodes = 13
    else:
        # 32k-candidate blocks, 128 blocks per device program: one program is
        # ~4M candidates, big enough that the fixed per-program dispatch
        # overhead on a tunneled chip is noise (kernels.py module docs);
        # all `steps` programs dispatch asynchronously so the tunnel RTT
        # overlaps with device compute (sweep.py MAX_INFLIGHT rationale).
        n_orgs, per_org, batch, steps, chunks, samples = 16, 16, 32768, 24, 128, 40
        sweep_nodes = 31
    if args.batch is not None:
        batch = args.batch
    if args.steps is not None:
        steps = args.steps
    if args.chunks is not None:
        chunks = args.chunks

    graph, circuit = build_workload(n_orgs, per_org)
    tpu_rate = tpu_throughput(circuit, batch, steps, chunks)
    cpu_rate, baseline_kind = cpu_baseline(graph, samples)
    sweep_stats = sweep_verdict(sweep_nodes)
    sweep_stats.update(snapshot_verdict(quick=args.quick))

    import jax

    print(
        json.dumps(
            {
                "metric": "candidate_quorums_checked_per_sec_per_chip",
                "value": round(tpu_rate, 1),
                "unit": "candidates/s",
                "vs_baseline": round(tpu_rate / cpu_rate, 2) if cpu_rate else None,
                "baseline": baseline_kind,
                "baseline_value": round(cpu_rate, 1),
                "workload": f"{graph.n}-node hierarchical FBAS, {circuit.n_units} circuit units",
                "batch": batch,
                "chunks": chunks,
                "device": jax.devices()[0].device_kind,
                "parity": "4/4 fixtures",
                **sweep_stats,
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
