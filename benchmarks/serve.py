"""Open-loop load driver for the snapshot-stream serving layer (ISSUE 8).

Drives a live :class:`quorum_intersection_tpu.serve.ServeEngine` with the
traffic shape the ROADMAP's north star describes — a continuous stream of
stellarbeat snapshots where the overwhelmingly common query is an
unchanged topology — and measures the serving numbers the trend sentinel
tracks (``tools/bench_trend.py``):

- ``serve_verdicts_per_sec`` (headline): completed verdicts over the
  measurement wall;
- ``serve_p50_ms`` / ``serve_p99_ms``: admission→delivery latency
  percentiles over all served requests;
- ``serve_cache_hit_pct``: verdict-cache hits as a % of admitted requests
  (the millions-of-users ≈ millions-of-cache-hits claim, measured);
- shed / deadline-expired / coalesced counts (typed outcomes only — a
  silent drop is a driver failure).

**Open loop**: arrivals follow a fixed-rate clock (``--rate``), never the
completions — so overload actually builds queue depth and exercises the
shedding path instead of self-throttling (closed-loop drivers hide
overload by construction).

Traffic comes from :func:`fbas.synth.churn_trace`: a deterministic
snapshot stream with bounded quorum-set diffs.  Requests walk the trace
forward with temporal locality (most requests repeat the current
snapshot; ``--advance-every`` steps the topology), so cache hits, churn
misses and single-flight coalescing all occur at realistic ratios.

The driver doubles as a parity gate: every served verdict is compared to
the one-shot ``pipeline.solve`` oracle verdict for its snapshot — any
mismatch is exit 1 (the chaos-gate contract, here under pure load).

Usage::

    JAX_PLATFORMS=cpu python benchmarks/serve.py --quick        # CI smoke
    python benchmarks/serve.py --requests 2000 --rate 500 --backend auto
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

HEADLINE_METRIC = "serve_verdicts_per_sec"


def build_traffic(args) -> list:
    """The request stream: a list of (step_index, snapshot) drawn from a
    churn trace with temporal locality."""
    from quorum_intersection_tpu.fbas import synth

    if args.base == "stellar":
        base = synth.stellar_like_fbas(
            n_core_orgs=5, per_org=3, n_watchers=args.nodes,
            seed=args.seed,
        )
    else:
        base = synth.majority_fbas(args.nodes, prefix="SRV")
    steps = max(args.requests // max(args.advance_every, 1), 1)
    trace = synth.churn_trace(base, steps, seed=args.seed, max_diff=2)
    traffic = []
    for i in range(args.requests):
        step = min(i // max(args.advance_every, 1), len(trace) - 1)
        traffic.append((step, trace[step]))
    return traffic


def run_churn_phase(args, record) -> tuple:
    """The qi-delta churn phase: every request is a NEW consecutive churn
    step over a multi-SCC stellar-like base, so the snapshot-level verdict
    cache (PR 8) misses on every structurally changed step and the per-SCC
    store (delta.py) carries the reuse.  Returns ``(row_fields,
    mismatches)``; the headline numbers are ``delta_scc_reuse_pct`` (SCC
    verdict-store hits as a % of lookups — watcher churn should keep the
    core fragment hot) and ``delta_resolve_ratio`` (backend solves per
    trace snapshot; 1.0 = no incremental reuse at all)."""
    from quorum_intersection_tpu.fbas import synth
    from quorum_intersection_tpu.pipeline import solve
    from quorum_intersection_tpu.serve import ServeEngine, ServeError

    steps = args.churn_steps or min(args.requests, 60)
    base = synth.stellar_like_fbas(
        n_core_orgs=3, per_org=2, n_watchers=max(args.nodes, 12),
        n_null=2, n_dangling=1, seed=args.seed + 1,
    )
    trace = synth.churn_trace(base, steps, seed=args.seed)
    expected = [solve(s, backend="python").intersects for s in trace]
    c0, _ = record.snapshot()
    # Same driver flags as the main-phase engine, so the persisted churn
    # row describes the configuration that actually ran.  The journal
    # stays off here: the churn phase measures the per-SCC store, and a
    # second engine replaying the main phase's journal would double-serve
    # its requests.
    engine = ServeEngine(
        backend=args.backend, cache_max=args.cache_max,
        queue_depth=args.queue_depth, batch_max=args.batch_max,
        deadline_s=args.deadline_s,
    )
    engine.start()
    tickets = []
    shed = 0
    t0 = time.perf_counter()
    with record.span("serve.bench_churn", steps=len(trace)):
        for i, snap in enumerate(trace):
            target = t0 + i / args.rate
            now = time.perf_counter()
            if target > now:
                time.sleep(target - now)
            try:
                tickets.append((i, engine.submit(snap)))
            except ServeError:
                shed += 1
        engine.stop(drain=True, timeout=600.0)
    wall_s = time.perf_counter() - t0

    served = 0
    mismatches = []
    for i, ticket in tickets:
        try:
            resp = ticket.result(timeout=60.0)
        except ServeError as exc:
            print(f"churn typed error at step {i}: {exc}", file=sys.stderr)
            continue
        except TimeoutError:
            print(f"CHURN SILENT DROP: step {i} reached no outcome",
                  file=sys.stderr)
            mismatches.append(f"churn step {i}: no outcome (silent drop)")
            continue
        served += 1
        if resp.intersects is not expected[i]:
            mismatches.append(
                f"churn step {i}: served {resp.intersects} != oracle "
                f"{expected[i]}"
            )
    c1, _ = record.snapshot()
    hits = c1.get("delta.scc_hits", 0) - c0.get("delta.scc_hits", 0)
    misses = c1.get("delta.scc_misses", 0) - c0.get("delta.scc_misses", 0)
    solves = c1.get("delta.solves", 0) - c0.get("delta.solves", 0)
    reuse_pct = 100.0 * hits / (hits + misses) if hits + misses else 0.0
    row = {
        "churn_steps": len(trace),
        "churn_served": served,
        "churn_shed": shed,
        "delta_scc_reuse_pct": round(reuse_pct, 2),
        "delta_resolve_ratio": (
            round(solves / len(trace), 4) if trace else 0.0
        ),
        "churn_verdicts_per_sec": (
            round(served / wall_s, 2) if wall_s > 0 else 0.0
        ),
    }
    record.gauge("delta.bench_reuse_pct", row["delta_scc_reuse_pct"])
    return row, mismatches


def run_queries_phase(args, record) -> tuple:
    """The qi-query mixed-workload phase (ISSUE 12): a stream mixing all
    four typed query kinds through one live ServeEngine — the traffic
    shape the query subsystem exists for — with every served verdict
    parity-checked against a direct QueryEngine oracle resolution.
    Returns ``(row_fields, mismatches)``; headline numbers are
    ``query_verdicts_per_sec`` plus a per-kind breakdown
    (``tools/bench_trend.py`` gates them)."""
    from quorum_intersection_tpu.fbas import synth
    from quorum_intersection_tpu.query import Query, QueryEngine
    from quorum_intersection_tpu.serve import ServeEngine, ServeError

    n_each = 5 if args.quick else 15
    base = synth.majority_fbas(max(args.nodes, 7), prefix="QRY")
    fa_ok, fb_ok = synth.two_family_preset(
        core=8, watchers=3, seed=args.seed,
    )
    fa_bad, fb_bad = synth.two_family_preset(
        core=8, watchers=3, broken=True, seed=args.seed,
    )
    metrics = ("top_tier", "pagerank", "blocking_set", "splitting_set")
    workload = []  # (kind, nodes, raw_query)
    for i in range(n_each):
        workload.append(("intersection", base, None))
        if i % 2:
            workload.append(
                ("relaxed", fa_ok,
                 {"kind": "relaxed", "family_b": fb_ok}))
        else:
            workload.append(
                ("relaxed", fa_bad,
                 {"kind": "relaxed", "family_b": fb_bad}))
        workload.append(("whatif", base, {"kind": "whatif", "max_k": 1}))
        workload.append(
            ("analytics", base,
             {"kind": "analytics", "metric": metrics[i % len(metrics)]}))

    # Oracle verdicts per DISTINCT (snapshot, query): direct QueryEngine
    # resolution on the python rung — the parity bar.
    oracle = QueryEngine(backend="python")
    expected = {}
    for kind, nodes, raw in workload:
        key = json.dumps([nodes, raw], sort_keys=True, default=str)
        if key not in expected:
            expected[key] = oracle.resolve(
                nodes, Query.parse(raw)
            ).verdict

    engine = ServeEngine(
        backend=args.backend, cache_max=args.cache_max,
        queue_depth=len(workload) + 8, batch_max=args.batch_max,
    )
    engine.start()
    tickets = []
    typed_errors = 0
    t0 = time.perf_counter()
    with record.span("serve.bench_queries", requests=len(workload)):
        for i, (kind, nodes, raw) in enumerate(workload):
            target = t0 + i / args.rate
            now = time.perf_counter()
            if target > now:
                time.sleep(target - now)
            try:
                tickets.append(
                    (i, kind, engine.submit(nodes, query=raw))
                )
            except (ServeError, ValueError) as exc:
                typed_errors += 1
                print(f"query typed admission error at {i} ({kind}): "
                      f"{exc}", file=sys.stderr)
        engine.stop(drain=True, timeout=600.0)
    wall_s = time.perf_counter() - t0

    served = {k: 0 for k in ("intersection", "relaxed", "whatif",
                             "analytics")}
    mismatches = []
    for i, kind, ticket in tickets:
        knd, nodes, raw = workload[i]
        try:
            resp = ticket.result(timeout=120.0)
        except ServeError as exc:
            typed_errors += 1
            print(f"query typed error at {i} ({kind}): {exc}",
                  file=sys.stderr)
            continue
        except TimeoutError:
            mismatches.append(f"query step {i} ({kind}): no outcome "
                              f"(silent drop)")
            continue
        served[kind] += 1
        key = json.dumps([nodes, raw], sort_keys=True, default=str)
        if resp.intersects is not expected[key]:
            mismatches.append(
                f"query step {i} ({kind}): served {resp.intersects} != "
                f"oracle {expected[key]}"
            )
        if kind != "intersection" and resp.result is None:
            mismatches.append(
                f"query step {i} ({kind}): typed query answered without "
                f"a result payload"
            )
    # No silent caps: this phase injects no faults, so EVERY submitted
    # query of every kind must actually serve — a typed error here means
    # part of the workload escaped the parity check, which must fail the
    # gate rather than shrink its coverage.
    for kind, count in served.items():
        if count != n_each:
            mismatches.append(
                f"query phase: only {count}/{n_each} {kind} queries "
                f"served — the rest were never parity-checked"
            )
    if typed_errors:
        mismatches.append(
            f"query phase: {typed_errors} typed error(s) in a fault-free "
            f"run"
        )
    total = sum(served.values())
    row = {
        "query_requests": len(workload),
        "query_served": total,
        "query_typed_errors": typed_errors,
        "query_verdicts_per_sec": (
            round(total / wall_s, 2) if wall_s > 0 else 0.0
        ),
    }
    for kind, count in served.items():
        row[f"query_{kind}_per_sec"] = (
            round(count / wall_s, 2) if wall_s > 0 else 0.0
        )
    record.gauge("query.bench_verdicts_per_sec",
                 row["query_verdicts_per_sec"])
    return row, mismatches


def run_fleet_phase(args, record) -> tuple:
    """The qi-fleet phase (ISSUE 11): the same zipfian churn stream driven
    through replicated fleets at N ∈ ``--fleet-n``, measuring aggregate
    ``fleet_verdicts_per_sec`` / ``fleet_p99_ms`` / fleet-wide store hit %
    / ``delta_scc_reuse_pct`` — with a kill-one-worker round at the
    largest N ≥ 2 whose zero-lost / zero-duplicated / oracle-parity
    contract is gated like every other phase.  Returns ``(row_fields,
    mismatches)``."""
    from quorum_intersection_tpu.fbas import synth
    from quorum_intersection_tpu.fleet import FleetEngine
    from quorum_intersection_tpu.pipeline import solve
    from quorum_intersection_tpu.serve import ServeError, _percentile

    ns = sorted({int(x) for x in args.fleet_n.split(",") if x.strip()})
    requests = args.fleet_requests or (40 if args.quick else 120)
    # A majority core behind a watcher periphery (the BASELINE benchmark
    # shape): core-dirtying churn steps are heavy re-solves that spread
    # across the ring, watcher-only steps change the snapshot fingerprint
    # (they route anywhere) while the core SCC fragment stays reusable —
    # exactly the traffic the shared store tier exists for.
    base = synth.benchmark_fbas(
        args.fleet_core + 17, args.fleet_core, seed=args.seed,
    )
    # Zipfian temporal skew (fbas/synth.py): hot re-emissions coalesce
    # fleet-wide through one worker's single-flight path; the advancing
    # mutation tail spreads across the ring — the traffic shape the
    # consistent-hash front door exists for.
    trace = synth.churn_trace(
        base, requests - 1, seed=args.seed, skew=args.fleet_skew,
    )
    memo = {}
    expected = []
    for snap in trace:
        key = json.dumps(snap, sort_keys=True)
        if key not in memo:
            memo[key] = solve(snap, backend="python").intersects
        expected.append(memo[key])
    mode = "local" if args.fleet_local else "subprocess"
    mismatches = []
    per_n = {}

    def one_run(n, label, kill_at):
        tmp = tempfile.TemporaryDirectory(prefix=f"qi-fleet-bench-{n}-")
        engine = FleetEngine(
            n, backend=args.backend, worker_mode=mode,
            journal_dir=tmp.name, probe_interval_s=0.2,
            batch_max=args.batch_max, cache_max=args.cache_max,
            # The burst submits the whole stream up front: size every
            # worker's admission queue to hold it, so no request is shed
            # and the oracle-parity check covers the full stream (a shed
            # step would silently escape the gate — the no-silent-caps
            # discipline).
            queue_depth=requests + 8,
        )
        engine.start()
        c0, _ = record.snapshot()
        tickets = []
        t0 = time.perf_counter()
        with record.span("fleet.bench", n=n, requests=requests,
                         phase=label, kill_one=kill_at is not None):
            for i, snap in enumerate(trace):
                if kill_at is not None and i == kill_at:
                    # A REAL mid-run kill (SIGKILL for subprocess workers):
                    # probes / broken pipes discover it, the ring shrinks,
                    # and the dead worker's journal replays on its peers.
                    engine.kill_worker(engine.worker_ids()[0])
                try:
                    tickets.append((i, engine.submit(snap)))
                except ServeError as exc:
                    mismatches.append(
                        f"fleet {label} step {i}: typed admission error {exc}"
                    )
            served = 0
            errors = 0
            lost = 0
            lat = []
            for i, ticket in tickets:
                try:
                    resp = ticket.result(timeout=120.0)
                except ServeError:
                    errors += 1
                    continue
                except TimeoutError:
                    lost += 1
                    mismatches.append(
                        f"fleet {label} step {i}: SILENT DROP (no outcome "
                        f"120s after submission)"
                    )
                    continue
                served += 1
                lat.append(resp.seconds * 1000.0)
                if resp.intersects is not expected[i]:
                    mismatches.append(
                        f"fleet {label} step {i}: served {resp.intersects} "
                        f"!= oracle {expected[i]}"
                    )
        wall = time.perf_counter() - t0
        c1, gauges = record.snapshot()
        engine.stop(drain=True)
        tmp.cleanup()
        lat.sort()
        run = {
            "verdicts_per_sec": round(served / wall, 2) if wall else 0.0,
            "p99_ms": round(_percentile(lat, 99.0), 3),
            "served": served,
            "errors": errors,
            "lost": lost,
            "evictions": int(
                c1.get("fleet.evictions", 0) - c0.get("fleet.evictions", 0)
            ),
            "replays": int(
                c1.get("fleet.replays", 0) - c0.get("fleet.replays", 0)
            ),
            "store_hit_pct": gauges.get("fleet.store_hit_pct", 0.0),
            "delta_scc_reuse_pct": gauges.get(
                "fleet.delta_scc_reuse_pct",
                gauges.get("delta.scc_reuse_pct", 0.0),
            ),
            # qi-pulse: the aggregation plane's fleet-MERGED e2e p99 —
            # computed over the union of the workers' histogram buckets
            # (0.0 until a probe cycle aggregated, or QI_PULSE_AGG=0).
            "e2e_p99_ms": gauges.get("fleet.e2e_p99_ms", 0.0),
        }
        if kill_at is not None and run["evictions"] < 1:
            mismatches.append(
                f"fleet {label}: kill-one round evicted nobody (the kill "
                f"was never discovered)"
            )
        if errors:
            # With the queue sized to the stream a typed error means part
            # of the stream escaped the parity check — loud, never a
            # silent cap on coverage.
            mismatches.append(
                f"fleet {label}: {errors} typed error(s) — those steps "
                f"were never parity-checked"
            )
        return run

    # Clean throughput ladder first (the N=4-beats-N=1 scaling gate reads
    # these), then a dedicated kill-one-of-N round at the largest N >= 2
    # whose zero-lost / zero-duplicated / parity contract is gated but
    # whose failover latency never contaminates the scaling numbers.
    for n in ns:
        per_n[n] = one_run(n, f"n={n}", None)
    kill_n = max((n for n in ns if n >= 2), default=2)
    kill_run = one_run(kill_n, f"kill-one(n={kill_n})", requests // 2)
    n_top = max(ns)
    row = {
        "fleet_n": n_top,
        "fleet_mode": mode,
        "fleet_requests": requests,
        "fleet_skew": args.fleet_skew,
        "fleet_verdicts_per_sec": per_n[n_top]["verdicts_per_sec"],
        "fleet_p99_ms": per_n[n_top]["p99_ms"],
        "fleet_e2e_p99_ms": per_n[n_top]["e2e_p99_ms"],
        "fleet_store_hit_pct": per_n[n_top]["store_hit_pct"],
        "fleet_delta_scc_reuse_pct": per_n[n_top]["delta_scc_reuse_pct"],
        "fleet_kill_evictions": kill_run["evictions"],
        "fleet_kill_replays": kill_run["replays"],
        "fleet_lost": (
            sum(p["lost"] for p in per_n.values()) + kill_run["lost"]
        ),
        "fleet_typed_errors": (
            sum(p["errors"] for p in per_n.values()) + kill_run["errors"]
        ),
    }
    for n, p in per_n.items():
        row[f"fleet_n{n}_verdicts_per_sec"] = p["verdicts_per_sec"]
        row[f"fleet_n{n}_p99_ms"] = p["p99_ms"]
    if 1 in per_n and 4 in per_n:
        # The acceptance gate: aggregate throughput at N=4 must exceed
        # N=1 on the zipfian churn preset (CPU numbers fine).  HARD only
        # in the full preset — a 40-request --quick run on a 2-vCPU CI
        # box sits inside scheduler noise, so there the result is
        # reported (and persisted) but does not fail the smoke.
        row["fleet_scaling_ok"] = (
            per_n[4]["verdicts_per_sec"] > per_n[1]["verdicts_per_sec"]
        )
        if not row["fleet_scaling_ok"]:
            msg = (
                f"fleet scaling: N=4 {per_n[4]['verdicts_per_sec']}/s "
                f"did not exceed N=1 {per_n[1]['verdicts_per_sec']}/s"
            )
            if args.quick:
                print(f"FLEET SCALING (informational under --quick): "
                      f"{msg}", file=sys.stderr)
            else:
                mismatches.append(msg)
    record.gauge("fleet.bench_verdicts_per_sec",
                 row["fleet_verdicts_per_sec"])
    return row, mismatches


def run_mesh_phase(args, record) -> tuple:
    """The qi-mesh phase (ISSUE 19, ``--fleet --fleet-join``): the zipfian
    churn stream through a SOCKET-JOINED fleet — one local worker plus one
    remote peer admitted over the versioned wire handshake — with a
    partition window and both elasticity legs exercised mid-stream:

    - **hedge window**: the joined peer is suspected for the middle third
      of the stream, so every request routed to its arc is hedged to the
      next arc owner; the window closes with a lease renewal (rejoin, not
      eviction) — measured as ``fleet_hedge_pct`` (hedged dispatches over
      served verdicts);
    - **elasticity**: a forced scale-up tick mid-stream (an elastic
      ``e``-prefixed worker joins the ring) and a forced drain-retire tick
      after the stream drains — measured as ``fleet_scale_events``
      (scale-up + scale-down bookings; the phase gates on at least one of
      EACH, and on the retire never breaching ``scale_min``).

    Every served verdict is still oracle-parity-gated and the zero-lost /
    typed-outcomes-only accounting applies — partition and resize must be
    invisible in the answers.  ``--fleet-join auto`` spawns a real
    ``serve --socket`` subprocess to join; ``HOST:PORT`` joins an already
    listening peer.  Returns ``(row_fields, mismatches)``."""
    from quorum_intersection_tpu.fbas import synth
    from quorum_intersection_tpu.fleet import FleetEngine
    from quorum_intersection_tpu.pipeline import solve
    from quorum_intersection_tpu.serve import ServeError, _percentile

    requests = args.fleet_requests or (24 if args.quick else 60)
    base = synth.benchmark_fbas(
        args.fleet_core + 17, args.fleet_core, seed=args.seed + 1,
    )
    trace = synth.churn_trace(
        base, requests - 1, seed=args.seed + 1, skew=args.fleet_skew,
    )
    memo = {}
    expected = []
    for snap in trace:
        key = json.dumps(snap, sort_keys=True)
        if key not in memo:
            memo[key] = solve(snap, backend="python").intersects
        expected.append(memo[key])

    mismatches = []
    tmp = tempfile.TemporaryDirectory(prefix="qi-mesh-bench-")
    peer = None
    if args.fleet_join == "auto":
        # A REAL remote: a serve --socket subprocess with its own journal,
        # joined through the same handshake an operator's peer would use.
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        env["PYTHONUNBUFFERED"] = "1"
        for k in ("QI_METRICS_JSON", "QI_METRICS_PROM", "QI_TRACE_OUT"):
            env.pop(k, None)
        peer = subprocess.Popen(
            [sys.executable, "-u", "-m", "quorum_intersection_tpu",
             "serve", "--socket", "0", "--backend", "python",
             "--journal", os.path.join(tmp.name, "peer.journal")],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL, text=True, env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        addr = None
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            line = peer.stdout.readline()
            if not line:
                break
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            if obj.get("kind") == "listening":
                addr = f"{obj['host']}:{obj['port']}"
                break
        if addr is None:
            peer.kill()
            tmp.cleanup()
            return {}, ["mesh: join peer never announced a listening port"]
    else:
        addr = args.fleet_join

    engine = FleetEngine(
        1, backend=args.backend, worker_mode="local",
        journal_dir=tmp.name, joins=[addr],
        # A long probe interval keeps the bench's suspicion window under
        # the driver's control (no background pong closes it early), and
        # respawn_max=0 keeps the membership deterministic — an eviction
        # here is a phase failure, not something to quietly redial.
        probe_interval_s=30.0, respawn_max=0,
        queue_depth=requests + 8, scale_min=1, scale_max=4,
    )
    engine.start()
    joined = [w for w in engine.worker_ids() if w.startswith("j")]
    if not joined:
        engine.stop(drain=True)
        if peer is not None:
            peer.stdin.close()
            peer.wait(timeout=30.0)
        tmp.cleanup()
        return {}, [f"mesh: no socket peer joined from {addr} (degraded "
                    f"to standalone — the wire tier never formed)"]
    jid = joined[0]
    c0, _ = record.snapshot()
    saved = (engine.scale_up_ms, engine.scale_down_ms)
    tickets = []
    t0 = time.perf_counter()
    with record.span("fleet.mesh_bench", requests=requests, join=addr):
        for i, snap in enumerate(trace):
            if i == requests // 3:
                # Partition opens: the peer stops answering (as far as
                # membership is concerned) but its wire stays up — arc
                # traffic hedges, nothing waits on the suspect alone.
                engine._suspect_worker(jid, "bench partition window")
            if i == (2 * requests) // 3:
                # Partition heals (rejoin, not eviction) and the queue
                # pressure verdict flips to scale-up: one elastic worker.
                engine._renew_lease(jid)
                engine.scale_up_ms = -1.0
                decision = engine.scale_tick(force=True)
                engine.scale_up_ms, engine.scale_down_ms = saved
                if decision != "up":
                    mismatches.append(
                        f"mesh: forced scale-up tick decided {decision!r}"
                    )
            try:
                tickets.append((i, engine.submit(snap)))
            except ServeError as exc:
                mismatches.append(
                    f"mesh step {i}: typed admission error {exc}"
                )
        served = 0
        errors = 0
        lost = 0
        lat = []
        for i, ticket in tickets:
            try:
                resp = ticket.result(timeout=120.0)
            except ServeError:
                errors += 1
                continue
            except TimeoutError:
                lost += 1
                mismatches.append(
                    f"mesh step {i}: SILENT DROP (no outcome 120s after "
                    f"submission)"
                )
                continue
            served += 1
            lat.append(resp.seconds * 1000.0)
            if resp.intersects is not expected[i]:
                mismatches.append(
                    f"mesh step {i}: served {resp.intersects} != oracle "
                    f"{expected[i]}"
                )
    wall = time.perf_counter() - t0
    # The stream drained: queue pressure is gone, so the drain-retire leg
    # must fire — and must take the elastic worker, never the floor.
    engine.scale_up_ms = engine.scale_down_ms = 1e12
    decision = engine.scale_tick(force=True)
    engine.scale_up_ms, engine.scale_down_ms = saved
    if decision != "down":
        mismatches.append(
            f"mesh: forced drain-retire tick decided {decision!r}"
        )
    survivors = engine.worker_ids()
    c1, _ = record.snapshot()
    engine.stop(drain=True)
    if peer is not None:
        try:
            peer.stdin.close()
            peer.wait(timeout=30.0)
        except (OSError, subprocess.TimeoutExpired):
            peer.kill()
    tmp.cleanup()

    hedges = int(c1.get("fleet.hedges", 0) - c0.get("fleet.hedges", 0))
    scale_ups = int(
        c1.get("fleet.scale_ups", 0) - c0.get("fleet.scale_ups", 0)
    )
    scale_downs = int(
        c1.get("fleet.scale_downs", 0) - c0.get("fleet.scale_downs", 0)
    )
    evictions = int(
        c1.get("fleet.evictions", 0) - c0.get("fleet.evictions", 0)
    )
    rejoins = int(
        c1.get("fleet.rejoins", 0) - c0.get("fleet.rejoins", 0)
    )
    if hedges < 1:
        mismatches.append(
            "mesh: partition window produced no hedged dispatches (the "
            "suspect's arc was never exercised)"
        )
    if rejoins < 1:
        mismatches.append("mesh: partition never healed as a rejoin")
    if evictions:
        mismatches.append(
            f"mesh: {evictions} eviction(s) during a heal-able partition "
            f"(suspicion escalated instead of hedging)"
        )
    if len(survivors) < engine.scale_min:
        mismatches.append(
            f"mesh: drain-retire breached scale_min ({survivors})"
        )
    if errors:
        mismatches.append(
            f"mesh: {errors} typed error(s) — those steps were never "
            f"parity-checked"
        )
    lat.sort()
    row = {
        "fleet_join": addr if args.fleet_join != "auto" else "auto",
        "fleet_mesh_requests": requests,
        "fleet_mesh_verdicts_per_sec": (
            round(served / wall, 2) if wall else 0.0
        ),
        "fleet_mesh_p99_ms": round(_percentile(lat, 99.0), 3),
        "fleet_scale_events": scale_ups + scale_downs,
        "fleet_scale_ups": scale_ups,
        "fleet_scale_downs": scale_downs,
        "fleet_hedge_pct": (
            round(100.0 * hedges / served, 2) if served else 0.0
        ),
        "fleet_mesh_rejoins": rejoins,
        "fleet_mesh_lost": lost,
        "fleet_mesh_typed_errors": errors,
    }
    record.gauge("fleet.bench_scale_events", row["fleet_scale_events"])
    record.gauge("fleet.bench_hedge_pct", row["fleet_hedge_pct"])
    return row, mismatches


def run_fuse_phase(args, record) -> tuple:
    """The qi-fuse phase (ISSUE 16): the same quick zipfian mixed stream —
    sweep-sized intersection snapshots of several distinct topologies plus
    what-if queries — driven twice through pack-enabled engines, fusion
    off then on.  Measures MXU-tile utilization (``sweep_pack_fill_pct``:
    verdict-bearing lanes over dispatched 128-lane tiles — the device pads
    every sub-tile program's lane axis to a full tile, so fewer fuller
    packs is the entire win), the cross-request share of fused lanes
    (``fuse_cross_request_lane_pct``), and the fused-vs-unfused solve p99.
    Hard gates (mismatches): per-request verdict parity between the two
    runs and the one-shot oracle, ``fuse.cross_request_lanes > 0``, and
    fill strictly improving with fusion on."""
    from quorum_intersection_tpu.encode.circuit import LANE_TILE
    from quorum_intersection_tpu.fbas import synth
    from quorum_intersection_tpu.pipeline import solve
    from quorum_intersection_tpu.serve import (
        ServeEngine, ServeError, _percentile,
    )

    # The packer only exists on the sweep path: force an auto-routed,
    # pack-enabled engine (the driver default "python" never packs).
    backend = args.backend if args.backend in ("auto", "tpu") else "auto"
    n_req = 10 if args.quick else 24
    bases = {
        n: synth.majority_fbas(n, prefix=f"FUSE{n}") for n in (7, 9, 11, 13)
    }
    sizes = sorted(bases)
    # Deterministic zipf-ish pick order: the hot topology re-emits, the
    # tail rotates — repeats exercise cache/coalescing, distinct
    # fingerprints land in one drain batch and fuse across requests.
    # Every third request is a what-if sweep: the legacy drain expands
    # each one into its OWN partially-filled pack (queries resolve one at
    # a time), which is exactly the under-fill fusion closes.
    picks = (0, 1, 0, 2, 0, 1, 3)
    workload = []
    for i in range(n_req):
        nodes = bases[sizes[picks[i % len(picks)]]]
        query = {"kind": "whatif", "max_k": 1} if i % 3 == 2 else None
        workload.append((nodes, query))
    oracle = {
        n: solve(nodes, backend="python").intersects
        for n, nodes in bases.items()
    }

    def one_run(window_ms):
        n0 = record.event_count()
        c0, _ = record.snapshot()
        engine = ServeEngine(
            backend=backend, pack=True, fuse_window_ms=window_ms,
            batch_max=len(workload) + 2, queue_depth=len(workload) + 8,
            cache_max=args.cache_max,
        )
        # Queue the whole stream BEFORE the drain starts: one popped
        # batch, so the fused run's cross-request window actually sees
        # every distinct topology at once (the --quick preset is far too
        # short for open-loop arrival overlap to do it).  Client ids
        # (qi-cost, ISSUE 17) rotate over three tenants so the per-tenant
        # attribution table has real multi-tenant content in the
        # persisted stream.
        tickets = [
            engine.submit(nodes, query=q, client=f"bench-{i % 3}")
            for i, (nodes, q) in enumerate(workload)
        ]
        t0 = time.perf_counter()
        engine.start()
        responses = [t.result(timeout=300.0) for t in tickets]
        engine.stop(drain=True, timeout=600.0)
        wall = time.perf_counter() - t0
        c1, _ = record.snapshot()
        events = record.events_since(n0)
        useful = 0.0
        tile_lanes = 0
        packs = 0
        for e in events:
            if e["name"] != "sweep.packed":
                continue
            attrs = e["attrs"]
            packs += 1
            useful += attrs["fill_pct"] * attrs["lanes"] / 100.0
            tile_lanes += max(-(-attrs["lanes"] // LANE_TILE), 1) * LANE_TILE
        lat = sorted(r.seconds * 1000.0 for r in responses)
        diff = {
            k: c1.get(k, 0) - c0.get(k, 0)
            for k in ("fuse.packs_formed", "fuse.pack_lanes",
                      "fuse.cross_request_lanes")
        }
        return {
            "responses": responses,
            "wall_s": wall,
            "packs": packs,
            "fill_pct": (
                round(100.0 * useful / tile_lanes, 2) if tile_lanes else 0.0
            ),
            "p99_ms": round(_percentile(lat, 99.0), 3),
            "counters": diff,
        }

    mismatches = []
    cost0, _ = record.snapshot()
    # Unfused first: the fused run then reuses the XLA compile cache, so
    # the p99 comparison favors neither run on compile amortization (both
    # presets solve the same compiled shapes).
    unfused = one_run(0.0)
    fused = one_run(args.fuse_window)
    for i, ((nodes, query), r_plain, r_fused) in enumerate(
        zip(workload, unfused["responses"], fused["responses"])
    ):
        if r_fused.intersects is not r_plain.intersects:
            mismatches.append(
                f"fuse step {i}: fused {r_fused.intersects} != unfused "
                f"{r_plain.intersects}"
            )
        if query is None and r_plain.intersects is not oracle[len(nodes)]:
            mismatches.append(
                f"fuse step {i}: unfused {r_plain.intersects} != oracle "
                f"{oracle[len(nodes)]}"
            )
    if fused["counters"]["fuse.cross_request_lanes"] <= 0:
        mismatches.append(
            "fuse phase: no cross-request lanes — fusion never merged two "
            "requests into one pack"
        )
    if fused["fill_pct"] <= unfused["fill_pct"]:
        mismatches.append(
            f"fuse phase: tile fill did not improve (fused "
            f"{fused['fill_pct']}% <= unfused {unfused['fill_pct']}%)"
        )
    pack_lanes = fused["counters"]["fuse.pack_lanes"]
    cross_pct = (
        100.0 * fused["counters"]["fuse.cross_request_lanes"] / pack_lanes
        if pack_lanes else 0.0
    )

    # ---- qi-cost auto-window arm (ISSUE 17) -----------------------------
    # QI_SERVE_FUSE_WINDOW_MS=auto through the two regimes the controller
    # must tell apart: a BURSTY phase (the whole workload pre-queued, the
    # queue held visibly deep past the first pop) where the decision must
    # pick a short POSITIVE window and match the fixed-window run's tile
    # fill, and a SPARSE phase (one request at a time, queue drained
    # between) where every decision must choose 0.0 and the p99 must not
    # exceed the unfused run's.

    def auto_bursty():
        """Hot-queue arm.  A short tail of DISTINCT requests with an
        already-tiny deadline keeps the queue deep when the first batch
        pops (``batch_max`` = the workload's DISTINCT fingerprints —
        repeats coalesce at admission and never occupy queue slots — so
        the pop leaves exactly the tail behind); the tail then
        deadline-expires at its own pop and never solves — it shapes the
        decision input without adding a single pack to the fill
        accounting."""
        n0 = record.event_count()
        distinct = len({
            json.dumps([nodes, q], sort_keys=True) for nodes, q in workload
        })
        engine = ServeEngine(
            backend=backend, pack=True, fuse_window_ms="auto",
            batch_max=distinct, queue_depth=len(workload) + 16,
            cache_max=args.cache_max,
        )
        tickets = [
            engine.submit(nodes, query=q, client=f"bench-{i % 3}")
            for i, (nodes, q) in enumerate(workload)
        ]
        tail = [
            engine.submit(
                synth.majority_fbas(5, prefix=f"TAIL{j}"),
                deadline_s=0.001, client="bench-tail",
            )
            for j in range(4)
        ]
        # Let the queued burst AGE before the drain starts: the popped
        # batch's queue waits (observed before the window decision) are
        # what push the controller's wait-p99 input into hot-queue
        # territory — a burst that waited ~100ms earns the capped window,
        # exactly like real congestion.
        time.sleep(0.12)
        engine.start()
        responses = [t.result(timeout=300.0) for t in tickets]
        for t in tail:
            try:
                t.result(timeout=300.0)
            except ServeError:
                pass  # DeadlineExceeded is the tail's designed outcome
        engine.stop(drain=True, timeout=600.0)
        events = record.events_since(n0)
        useful = 0.0
        tile_lanes = 0
        for e in events:
            if e["name"] != "sweep.packed":
                continue
            attrs = e["attrs"]
            useful += attrs["fill_pct"] * attrs["lanes"] / 100.0
            tile_lanes += max(-(-attrs["lanes"] // LANE_TILE), 1) * LANE_TILE
        decisions = [
            e["attrs"]["window_ms"] for e in events
            if e["name"] == "serve.fuse_window"
        ]
        return {
            "responses": responses,
            "fill_pct": (
                round(100.0 * useful / tile_lanes, 2) if tile_lanes else 0.0
            ),
            "decisions": decisions,
        }

    def auto_sparse():
        """Drained-queue arm: strictly serial submit→result, so every
        pop leaves an empty queue behind and every window decision must
        be 0.0 — fusion never taxes a stream with nobody to fuse with."""
        n0 = record.event_count()
        engine = ServeEngine(
            backend=backend, pack=True, fuse_window_ms="auto",
            batch_max=args.batch_max, queue_depth=len(workload) + 8,
            cache_max=args.cache_max,
        )
        engine.start()
        lat = []
        for i, (nodes, q) in enumerate(workload):
            resp = engine.submit(
                nodes, query=q, client=f"bench-{i % 3}"
            ).result(timeout=300.0)
            lat.append(resp.seconds * 1000.0)
        engine.stop(drain=True, timeout=600.0)
        decisions = [
            e["attrs"]["window_ms"] for e in record.events_since(n0)
            if e["name"] == "serve.fuse_window"
        ]
        return {
            "p99_ms": round(_percentile(sorted(lat), 99.0), 3),
            "decisions": decisions,
        }

    bursty = auto_bursty()
    sparse = auto_sparse()
    for i, (r_auto, r_plain) in enumerate(
        zip(bursty["responses"], unfused["responses"])
    ):
        if r_auto.intersects is not r_plain.intersects:
            mismatches.append(
                f"fuse auto step {i}: auto-window {r_auto.intersects} != "
                f"unfused {r_plain.intersects}"
            )
    auto_window = max(bursty["decisions"], default=0.0)
    if auto_window <= 0.0:
        mismatches.append(
            "fuse auto: bursty phase never chose a positive window "
            f"(decisions {bursty['decisions']})"
        )
    if bursty["fill_pct"] < fused["fill_pct"]:
        mismatches.append(
            f"fuse auto: bursty fill {bursty['fill_pct']}% fell below the "
            f"fixed-window fill {fused['fill_pct']}%"
        )
    if any(d > 0.0 for d in sparse["decisions"]):
        mismatches.append(
            "fuse auto: sparse phase chose a positive window "
            f"(decisions {sparse['decisions']}) — idle traffic must never "
            "wait on fusion"
        )
    if sparse["p99_ms"] > unfused["p99_ms"]:
        mismatches.append(
            f"fuse auto: sparse p99 {sparse['p99_ms']}ms exceeded the "
            f"unfused p99 {unfused['p99_ms']}ms"
        )
    cost1, _ = record.snapshot()
    lw_total = cost1.get("cost.lane_windows_total", 0) - cost0.get(
        "cost.lane_windows_total", 0)
    lw_attr = cost1.get("cost.lane_windows_attributed", 0) - cost0.get(
        "cost.lane_windows_attributed", 0)
    attributed_pct = round(100.0 * lw_attr / lw_total, 2) if lw_total else 0.0
    if lw_total and lw_attr != lw_total:
        mismatches.append(
            f"fuse phase: only {lw_attr}/{lw_total} lane-windows were "
            f"attributed in a fault-free run"
        )

    row = {
        "fuse_requests": n_req,
        "fuse_window_ms": args.fuse_window,
        "fuse_backend": backend,
        "sweep_pack_fill_pct": fused["fill_pct"],
        "sweep_pack_fill_pct_unfused": unfused["fill_pct"],
        "fuse_cross_request_lane_pct": round(cross_pct, 2),
        "fuse_packs_formed": int(fused["counters"]["fuse.packs_formed"]),
        "fuse_packs_unfused": unfused["packs"],
        "fuse_serve_solve_p99_ms": fused["p99_ms"],
        "fuse_serve_solve_p99_unfused_ms": unfused["p99_ms"],
        "fuse_auto_window_ms": round(auto_window, 3),
        "fuse_auto_fill_pct": bursty["fill_pct"],
        "fuse_auto_sparse_p99_ms": sparse["p99_ms"],
        "cost_attributed_pct": attributed_pct,
    }
    record.gauge("fuse.bench_fill_pct", row["sweep_pack_fill_pct"])
    record.gauge("fuse.bench_cross_request_lane_pct",
                 row["fuse_cross_request_lane_pct"])
    record.gauge("fuse.bench_auto_window_ms", row["fuse_auto_window_ms"])
    record.gauge("cost.bench_attributed_pct", row["cost_attributed_pct"])
    return row, mismatches


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--requests", type=int, default=300,
                        help="total requests to submit (default 300)")
    parser.add_argument("--rate", type=float, default=200.0,
                        help="open-loop arrival rate, requests/sec "
                             "(default 200)")
    parser.add_argument("--advance-every", type=int, default=20,
                        help="requests between churn-trace steps: higher = "
                             "more cache hits (default 20)")
    parser.add_argument("--nodes", type=int, default=9,
                        help="base-topology size knob (majority n / stellar "
                             "watcher count; default 9)")
    parser.add_argument("--base", choices=("majority", "stellar"),
                        default="majority")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--backend", default="python",
                        help="serving backend (default python: the load "
                             "numbers measure the SERVING layer, not engine "
                             "latency; use auto for end-to-end rows)")
    parser.add_argument("--deadline-s", type=float, default=None)
    parser.add_argument("--queue-depth", type=int, default=None)
    parser.add_argument("--batch-max", type=int, default=None)
    parser.add_argument("--cache-max", type=int, default=None)
    parser.add_argument("--journal", default=None,
                        help="exercise the crash-only journal on this path")
    parser.add_argument("--churn", action="store_true",
                        help="append the qi-delta churn phase (ISSUE 9): "
                             "every request advances the churn trace one "
                             "step, so snapshot-level caching never hits "
                             "and the per-SCC store does the work — "
                             "measures delta_scc_reuse_pct / "
                             "delta_resolve_ratio (tools/bench_trend.py "
                             "gates both) with the same per-step oracle "
                             "parity bar")
    parser.add_argument("--churn-steps", type=int, default=None,
                        help="churn-phase trace length (default: "
                             "min(requests, 60))")
    parser.add_argument("--queries", action="store_true",
                        help="append the qi-query mixed-workload phase "
                             "(ISSUE 12): a stream mixing intersection / "
                             "relaxed two-family / whatif / analytics "
                             "queries through one engine, every served "
                             "verdict parity-checked against a direct "
                             "QueryEngine oracle — measures "
                             "query_verdicts_per_sec per kind "
                             "(tools/bench_trend.py gates them)")
    parser.add_argument("--fleet", action="store_true",
                        help="append the qi-fleet phase (ISSUE 11): the "
                             "same zipfian churn stream through replicated "
                             "fleets at each N in --fleet-n, with a "
                             "kill-one-worker round at the largest N >= 2 "
                             "— measures fleet_verdicts_per_sec / "
                             "fleet_p99_ms / fleet_store_hit_pct "
                             "(tools/bench_trend.py gates them) under the "
                             "same oracle-parity + zero-silent-drop bar")
    parser.add_argument("--fleet-n", default="1,2,4", metavar="N,N,...",
                        help="fleet sizes to measure (default 1,2,4; the "
                             "N=4-beats-N=1 scaling gate applies when both "
                             "are present)")
    parser.add_argument("--fleet-requests", type=int, default=None,
                        help="requests per fleet size (default: 40 with "
                             "--quick, else 120)")
    parser.add_argument("--fleet-core", type=int, default=13,
                        help="majority-core size of the fleet traffic base "
                             "topology (default 13)")
    parser.add_argument("--fleet-skew", type=float, default=1.1,
                        help="zipfian temporal skew of the fleet churn "
                             "trace (fbas/synth.py churn_trace; default "
                             "1.1)")
    parser.add_argument("--fuse", action="store_true",
                        help="append the qi-fuse phase (ISSUE 16): the "
                             "quick zipfian mixed intersection+whatif "
                             "stream through a pack-enabled engine, fusion "
                             "off then on — measures sweep_pack_fill_pct / "
                             "fuse_cross_request_lane_pct and the fused-vs-"
                             "unfused solve p99 (tools/bench_trend.py "
                             "gates them), hard-failing unless "
                             "cross-request lanes formed and tile fill "
                             "strictly improved; includes the qi-cost "
                             "auto-window arm (QI_SERVE_FUSE_WINDOW_MS="
                             "auto): bursty traffic must pick a positive "
                             "window and match the fixed-window fill, "
                             "sparse traffic must pick 0 and not exceed "
                             "the unfused p99, and every dispatched "
                             "lane-window must be cost-attributed "
                             "(cost_attributed_pct == 100)")
    parser.add_argument("--fuse-window", type=float, default=25.0,
                        help="fused-run batch-former window in ms "
                             "(QI_SERVE_FUSE_WINDOW_MS equivalent; "
                             "default 25)")
    parser.add_argument("--fleet-join", default=None, metavar="HOST:PORT",
                        help="with --fleet, append the qi-mesh phase "
                             "(ISSUE 19): drive the churn stream through "
                             "a socket-joined fleet (one local worker + "
                             "this remote peer) with a mid-stream "
                             "suspect→hedge→rejoin partition window and "
                             "forced scale-up / drain-retire elasticity "
                             "ticks — measures fleet_hedge_pct and "
                             "fleet_scale_events (tools/bench_trend.py "
                             "tracks both), oracle-parity gated; the "
                             "special value 'auto' spawns a real "
                             "`serve --socket` subprocess to join")
    parser.add_argument("--fleet-local", action="store_true",
                        help="run fleet workers in-process instead of as "
                             "subprocesses (faster smoke, same routing/"
                             "failover paths)")
    parser.add_argument("--quick", action="store_true",
                        help="CI smoke preset: 120 requests at 300/s")
    parser.add_argument("--metrics-json", default=None, metavar="PATH")
    parser.add_argument("--metrics-prom", default=None, metavar="PATH")
    args = parser.parse_args(argv)
    if args.quick:
        args.requests, args.rate = 120, 300.0

    from quorum_intersection_tpu.pipeline import solve
    from quorum_intersection_tpu.serve import (
        DeadlineExceeded,
        Overloaded,
        ServeEngine,
        ServeError,
        _percentile,
    )
    from quorum_intersection_tpu.utils import telemetry

    record = telemetry.get_run_record()
    if args.metrics_json:
        record.add_sink(telemetry.JsonlSink(args.metrics_json))
    if args.metrics_prom:
        record.add_sink(telemetry.PromFileSink(args.metrics_prom))

    traffic = build_traffic(args)

    # Fault-free oracle chain, one solve per DISTINCT snapshot step: the
    # parity bar every served verdict is checked against.
    expected = {}
    for step, snap in traffic:
        if step not in expected:
            expected[step] = solve(snap, backend="python").intersects

    engine = ServeEngine(
        backend=args.backend,
        queue_depth=args.queue_depth,
        batch_max=args.batch_max,
        deadline_s=args.deadline_s,
        cache_max=args.cache_max,
        journal=args.journal,
    )
    engine.start()
    tickets = []  # (step, ticket)
    shed = 0
    t0 = time.perf_counter()
    with record.span("serve.bench", requests=args.requests, rate=args.rate):
        for i, (step, snap) in enumerate(traffic):
            target = t0 + i / args.rate
            now = time.perf_counter()
            if target > now:
                time.sleep(target - now)
            try:
                tickets.append((step, engine.submit(snap)))
            except Overloaded:
                shed += 1  # typed shed: the open-loop driver keeps going
        engine.stop(drain=True, timeout=600.0)
    wall_s = time.perf_counter() - t0

    served = 0
    deadline_expired = 0
    errors = 0
    mismatches = []
    latencies_ms = []
    for step, ticket in tickets:
        try:
            resp = ticket.result(timeout=60.0)
        except DeadlineExceeded:
            deadline_expired += 1
            continue
        except ServeError as exc:
            errors += 1
            print(f"typed error for step {step}: {exc}", file=sys.stderr)
            continue
        except TimeoutError:
            # An unresolved ticket is the exact failure class this gate
            # exists to catch: fall through to the lost accounting below
            # (it is admitted - served - ... ), never a bare traceback.
            print(f"SILENT DROP: step {step} reached no outcome after "
                  f"60s", file=sys.stderr)
            continue
        served += 1
        latencies_ms.append(resp.seconds * 1000.0)
        if resp.intersects is not expected[step]:
            mismatches.append(
                f"step {step}: served {resp.intersects} != oracle "
                f"{expected[step]}"
            )

    counters, gauges = record.snapshot()
    hits = counters.get("serve.cache_hits", 0)
    admitted = len(tickets)
    latencies_ms.sort()

    row = {
        "metric": HEADLINE_METRIC,
        "value": round(served / wall_s, 2) if wall_s > 0 else 0.0,
        "serve_verdicts_per_sec": round(served / wall_s, 2) if wall_s else 0.0,
        # Same nearest-rank estimator as the engine's serve.p50_ms/p99_ms
        # gauges, so the bench rows and the live gauges stay comparable.
        "serve_p50_ms": round(_percentile(latencies_ms, 50.0), 3),
        "serve_p99_ms": round(_percentile(latencies_ms, 99.0), 3),
        # Decomposed stage p99s (qi-pulse, ISSUE 15): bucket-resolution
        # estimates from the serving layer's stage histograms, so the
        # trend sentinel can tell a slowed drain (queue_wait growing)
        # from a slowed engine (solve growing), not just watch e2e move.
        "serve_queue_wait_p99_ms": record.histogram(
            "pulse.queue_wait_ms").quantile_ms(99.0),
        "serve_solve_p99_ms": record.histogram(
            "pulse.solve_ms").quantile_ms(99.0),
        "serve_cache_hit_pct": round(100.0 * hits / admitted, 2) if admitted else 0.0,
        "requests": args.requests,
        "admitted": admitted,
        "served": served,
        "shed": shed,
        "deadline_expired": deadline_expired,
        "typed_errors": errors,
        "coalesced": int(counters.get("serve.coalesced", 0)),
        "cache_evictions": int(counters.get("serve.cache_evictions", 0)),
        "distinct_topologies": len(expected),
        "rate_per_sec": args.rate,
        "wall_s": round(wall_s, 3),
        "backend": args.backend,
        "base": args.base,
        "seed": args.seed,
        "verdict_ok": not mismatches,
        "device": os.environ.get("JAX_PLATFORMS", "ambient"),
    }
    if args.churn:
        churn_row, churn_mismatches = run_churn_phase(args, record)
        row.update(churn_row)
        mismatches.extend(churn_mismatches)
        # The persisted row must agree with the exit code: a churn-phase
        # parity failure flips verdict_ok too, not just the return value.
        row["verdict_ok"] = not mismatches
    if args.queries:
        query_row, query_mismatches = run_queries_phase(args, record)
        row.update(query_row)
        mismatches.extend(query_mismatches)
        row["verdict_ok"] = not mismatches
    if args.fleet:
        fleet_row, fleet_mismatches = run_fleet_phase(args, record)
        row.update(fleet_row)
        mismatches.extend(fleet_mismatches)
        row["verdict_ok"] = not mismatches
        if args.fleet_join:
            mesh_row, mesh_mismatches = run_mesh_phase(args, record)
            row.update(mesh_row)
            mismatches.extend(mesh_mismatches)
            row["verdict_ok"] = not mismatches
    if args.fuse:
        fuse_row, fuse_mismatches = run_fuse_phase(args, record)
        row.update(fuse_row)
        mismatches.extend(fuse_mismatches)
        row["verdict_ok"] = not mismatches
    for m in mismatches:
        print(f"SERVE PARITY MISMATCH: {m}", file=sys.stderr)
    # Accounting invariant: every admitted request reached exactly one
    # typed outcome — a gap is a silent drop, the one failure shedding and
    # deadlines exist to prevent.
    lost = admitted - served - deadline_expired - errors
    if lost:
        print(f"SERVE DRIVER: {lost} request(s) reached no outcome "
              f"(silent drop)", file=sys.stderr)
    record.gauge("serve.bench_verdicts_per_sec", row["value"])
    record.finish()
    print(json.dumps(row), flush=True)
    return 1 if (mismatches or lost) else 0


if __name__ == "__main__":
    sys.exit(main())
