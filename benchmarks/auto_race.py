"""Racing auto-router benchmark: time-to-verdict of the `auto` race vs the
faster engine alone, plus the warm-start compile measurement.

Two modes, one artifact family (``benchmarks/results/auto_race*_r*.txt``):

- **Deterministic harness** (``--fake``, the default off-chip): fake
  engines with pinned latencies replace the oracle and the sweep, so the
  measured quantity is the RACING MACHINERY itself — thread spin-up,
  cancel propagation, join — isolated from engine noise.  Both race
  outcomes run (fast oracle / fast sweep); the acceptance bar is
  ``auto_race_s <= 1.2 x fast_engine_s`` in each (ISSUE 1: the sequential
  chain measured 3.4x at scc 36, sweep_vs_native_tpu_r5.txt).  Fakes
  delegate to the real Python oracle after their pinned delay, so
  ``verdict_ok`` stays a real check, and they poll the real CancelToken —
  cancellation latency is measured, not simulated.

- **Real mode** (``--real``, for the on-chip round): the sweep_vs_native
  row shape with racing on — `auto` end-to-end vs the direct sweep and
  the sequential (`--no-race`-equivalent) router on hierarchical k-of-4
  workloads — so the next on-chip round re-measures the r5 3.4x gap with
  racing enabled.  ``--warm-start`` additionally runs the same sweep
  twice against the persistent compile cache and emits the
  ``sweep_cold_xla_compile_s`` / ``sweep_warm_xla_compile_s`` pair that
  ``backends/calibration.py`` turns into the routing-facing warm ratio.

Usage::

    JAX_PLATFORMS=cpu python benchmarks/auto_race.py --fake    # CPU smoke
    python benchmarks/auto_race.py --real --warm-start         # chip round
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Pinned fake latencies, chosen so thread spin-up (~ms) and the cancel poll
# period are noise against the fast engine yet the total run stays seconds.
FAST_S = 0.25
SLOW_S = 3.0
POLL_S = 0.01


class _FakeEngine:
    """Delay, then delegate to the real Python oracle.

    The delay loop polls the real base.CancelToken every POLL_S, so the
    harness measures genuine cooperative-cancel latency; the delegate solve
    keeps verdicts real (verdict_ok below is not vacuous)."""

    def __init__(self, delay_s: float, name: str, cancel=None,
                 burn_budget: bool = False):
        self.delay_s = delay_s
        self.name = name
        self.cancel = cancel
        self.burn_budget = burn_budget  # raise OracleBudgetExceeded instead

    def check_scc(self, graph, circuit, scc, *, scope_to_scc=False):
        from quorum_intersection_tpu.backends.base import SearchCancelled
        from quorum_intersection_tpu.backends.python_oracle import (
            PythonOracleBackend,
        )

        deadline = time.monotonic() + self.delay_s
        while time.monotonic() < deadline:
            if self.cancel is not None and self.cancel.cancelled:
                raise SearchCancelled(f"fake {self.name} cancelled")
            time.sleep(POLL_S)
        if self.burn_budget:
            from quorum_intersection_tpu.backends.base import (
                OracleBudgetExceeded,
            )

            raise OracleBudgetExceeded(f"fake {self.name} burned its budget")
        res = PythonOracleBackend().check_scc(
            graph, circuit, scc, scope_to_scc=scope_to_scc
        )
        res.stats["backend"] = self.name
        return res


def _fake_auto(outcome: str):
    """An AutoBackend whose engines are latency fakes.

    ``outcome='oracle_fast'``: oracle FAST_S, sweep SLOW_S.
    ``outcome='sweep_fast'``: oracle burns its budget after SLOW_S would
    have elapsed — except the racing sweep (FAST_S) cancels it first; in
    sequential mode the burn happens for real and the sweep runs after.
    """
    from quorum_intersection_tpu.backends.auto import AutoBackend

    oracle_fast = outcome == "oracle_fast"

    class FakeAuto(AutoBackend):
        def _cpu_oracle(self, budget_s=None, cancel=None):
            return _FakeEngine(
                FAST_S if oracle_fast else SLOW_S, "cpp", cancel=cancel,
                burn_budget=not oracle_fast,
            )

        def _sweep(self, cancel=None, engine=None):
            return _FakeEngine(
                SLOW_S if oracle_fast else FAST_S, "tpu-sweep", cancel=cancel
            )

    return FakeAuto


def fake_rows(data) -> list:
    """Both race outcomes on one instance; rows carry the measured ratio."""
    from quorum_intersection_tpu.pipeline import solve

    rows = []
    for outcome in ("oracle_fast", "sweep_fast"):
        cls = _fake_auto(outcome)

        # Fast engine alone: the race's lower bound, measured not assumed.
        fast = (
            cls()._cpu_oracle() if outcome == "oracle_fast"
            else cls()._sweep()
        )
        t0 = time.monotonic()
        solo = solve(data, backend=fast)
        fast_s = time.monotonic() - t0

        t0 = time.monotonic()
        raced = solve(data, backend=cls())
        race_s = time.monotonic() - t0

        t0 = time.monotonic()
        seq = solve(data, backend=cls(race=False))
        seq_s = time.monotonic() - t0

        rows.append({
            "mode": "fake",
            "outcome": outcome,
            "fast_engine_s": round(fast_s, 4),
            "auto_race_s": round(race_s, 4),
            "auto_sequential_s": round(seq_s, 4),
            "ratio_vs_fast": round(race_s / fast_s, 3) if fast_s else None,
            "winner": raced.stats.get("race", {}).get("winner"),
            "verdict_ok": (
                solo.intersects == raced.intersects == seq.intersects
            ),
            "device": "cpu",
        })
    return rows


def real_rows(sizes, warm_start: bool) -> list:
    """sweep_vs_native-comparable rows with racing on, plus the warm-start
    compile pair when requested."""
    import jax

    from quorum_intersection_tpu.backends.auto import AutoBackend
    from quorum_intersection_tpu.backends.tpu.sweep import TpuSweepBackend
    from quorum_intersection_tpu.fbas.synth import hierarchical_fbas
    from quorum_intersection_tpu.pipeline import solve

    device = jax.devices()[0].device_kind
    rows = []
    for scc in sizes:
        assert scc % 4 == 0, "hierarchical_fbas rows are 4 nodes/org"
        data = hierarchical_fbas(scc // 4, 4)

        t0 = time.monotonic()
        sw = solve(data, backend=TpuSweepBackend())
        sweep_s = time.monotonic() - t0

        t0 = time.monotonic()
        raced = solve(data, backend=AutoBackend())
        race_s = time.monotonic() - t0

        t0 = time.monotonic()
        seq = solve(data, backend=AutoBackend(race=False))
        seq_s = time.monotonic() - t0

        row = {
            "mode": "real",
            "scc": scc,
            "device": device,
            "sweep_seconds": round(sweep_s, 3),
            "auto_race_seconds": round(race_s, 3),
            "auto_sequential_seconds": round(seq_s, 3),
            "auto_race_vs_sequential": (
                round(seq_s / race_s, 2) if race_s else None
            ),
            "race": raced.stats.get("race"),
            "verdict_ok": sw.intersects == raced.intersects == seq.intersects,
        }
        if warm_start:
            # A genuinely cold/warm pair needs a FRESH persistent cache and
            # fresh processes: the solves above already compiled this exact
            # canonical shape in this process (and, on a real chip, wrote
            # it into the default persistent cache), so an in-process
            # "cold" run would be a cache hit and the ratio would read
            # ~1.0 / get dropped by calibration's cold<0.1s filter.  Each
            # scc gets its own tmp cache dir so same-bucket sizes cannot
            # cross-contaminate either.
            cold_s, warm_s = _subprocess_warm_pair(data)
            row["sweep_cold_xla_compile_s"] = cold_s
            row["sweep_warm_xla_compile_s"] = warm_s
        rows.append(row)
    return rows


_WARM_CHILD = r"""
import json, sys
from quorum_intersection_tpu.backends.tpu.sweep import TpuSweepBackend
from quorum_intersection_tpu.pipeline import solve
from quorum_intersection_tpu.utils.platform import honor_platform_env

honor_platform_env()
res = solve(sys.stdin.read(), backend=TpuSweepBackend())
print(json.dumps({"xla": res.stats.get("xla_compile_seconds")}))
"""


def _subprocess_warm_pair(data):
    """(cold, warm) xla_compile_seconds for one instance: two child
    processes sharing one fresh compile-cache dir.  QI_COMPILE_CACHE_CPU
    keeps the pair meaningful on the CPU smoke tier too (forces the cache
    on and drops jax's sub-second persistence threshold; harmless on an
    accelerator)."""
    payload = json.dumps(data)
    from quorum_intersection_tpu.utils.telemetry import get_run_record

    with tempfile.TemporaryDirectory(prefix="qi_warm_cache_") as cache_dir:
        env = dict(
            os.environ,
            JAX_COMPILATION_CACHE_DIR=cache_dir,
            QI_COMPILE_CACHE_CPU="1",
            # qi-trace: both the cold and warm child adopt this driver's
            # trace_id, so a --warm-start run exports as one timeline.
            QI_TRACE_CONTEXT=get_run_record().trace_context().to_env(),
        )
        out = []
        for _ in ("cold", "warm"):
            proc = subprocess.run(
                [sys.executable, "-c", _WARM_CHILD],
                input=payload, capture_output=True, text=True,
                timeout=1800, env=env,
                cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            )
            if proc.returncode != 0:
                return None, None
            out.append(json.loads(proc.stdout.strip().splitlines()[-1])["xla"])
    return out[0], out[1]


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--fake", action="store_true",
                        help="deterministic fake-latency harness (default)")
    parser.add_argument("--real", action="store_true",
                        help="real engines on hierarchical workloads")
    parser.add_argument("--warm-start", action="store_true",
                        help="with --real: emit the cold/warm compile pair")
    parser.add_argument("--scc", type=int, nargs="*", default=None,
                        help="|scc| sizes for --real (multiples of 4)")
    parser.add_argument("--metrics-json", default=None, metavar="PATH",
                        help="append run-record telemetry (qi-telemetry/1 "
                             "JSONL — the same schema the CLI and bench.py "
                             "emit) to PATH; warm-start child processes "
                             "inherit the sink via the environment")
    args = parser.parse_args()

    from quorum_intersection_tpu.utils.platform import honor_platform_env

    honor_platform_env()
    if args.metrics_json:
        os.environ["QI_METRICS_JSON"] = os.path.abspath(args.metrics_json)

    from quorum_intersection_tpu.utils import telemetry

    rec = telemetry.get_run_record()
    rows = []
    if args.real:
        sizes = args.scc or [28, 32, 36]
        with rec.span("auto_race.real", sizes=sizes):
            rows += real_rows(sizes, args.warm_start)
    if args.fake or not args.real:
        from quorum_intersection_tpu.fbas.synth import majority_fbas

        with rec.span("auto_race.fake"):
            rows += fake_rows(majority_fbas(9))

    ok = True
    for row in rows:
        print(json.dumps(row), flush=True)
        rec.event("auto_race.row", **row)
        if row.get("ratio_vs_fast") is not None:
            ok = ok and row["ratio_vs_fast"] <= 1.2
        ok = ok and row.get("verdict_ok", False)
    print(f"auto_race: {'OK' if ok else 'DEGRADED'} ({len(rows)} rows)")
    telemetry.finish()
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
