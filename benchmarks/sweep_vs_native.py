"""Time-to-verdict: exhaustive device sweep vs the native oracle at
mid-range |scc| — the measurement behind auto's sweep routing window.

Motivation (round 5).  The r5 on-chip crossover (crossover_tpu_r5.txt)
measures the device-resident FRONTIER losing to the native oracle at
every tractable size (0.03x at scc 24, ~0.19x at scc 28): per-chunk host
seams and per-iteration latency through the tunnel dominate the tiny
(≤32-wide) matmuls, exactly as the r3 hybrid measurement foreshadowed.
The engine that DOES win this regime on the chip is the exhaustive
SWEEP: 2^(|scc|-1) candidates at the measured enumeration rate (626M
cand/s end-to-end on v5e, r3) beats the native B&B's ~1.4M calls/s
whenever the B&B call count is within ~3 orders of the subset-space size
— which holds for the symmetric k-of-n cores of the reference benchmarks
(reference `quorum_intersection.cpp:252-346` enumerates ~4·C(n, n/2)
calls ≈ 2^n·sqrt(2/(pi·n)) on them, see bench.py NATIVE_CALLS_MODEL).

Rows: hierarchical k-of-n networks (`hierarchical_fbas(orgs, 4)`,
|scc| = 4·orgs) at scc 28 / 32 / 36.

- native: the C++ oracle run to completion when the call-count model says
  it fits --native-cap (measured floor + model estimate otherwise, same
  three-way honesty as bench.py phase_verdict).
- sweep: TpuSweepBackend directly (the engine auto falls back to).
- auto:  the full `auto` policy end-to-end — oracle-first with a
  sweep-sized budget, then the sweep — i.e. what a user actually gets.
  Skipped (with the reason recorded) when |scc| exceeds the platform
  sweep limit and auto would run the UNBUDGETED native oracle: the row
  would just re-measure `native`, at hours of wall clock.

Usage:
    JAX_PLATFORMS=cpu python benchmarks/sweep_vs_native.py --quick  # smoke
    python benchmarks/sweep_vs_native.py                            # chip
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Measured B&B call counts for hierarchical_fbas(orgs, 4) — crossover
# artifacts r3-r5 (exact, platform-independent: the search is
# deterministic).  Beyond the table, extrapolated at the last measured
# +4-org growth (139.9M/30.0M ≈ 4.66x), labeled as such.
HIER_CALLS = {16: 184_755, 20: 1_307_504, 24: 1_009_587,
              28: 30_029_267, 32: 139_942_245}
HIER_CALLS_MODEL = (
    "measured table (crossover_cpu/tpu_r3-r5) + 4.66x per +4 orgs beyond 32"
    " — LIKELY AN UNDERESTIMATE there: the measured +4 growth was 29.7x"
    " (24->28) then 4.66x (28->32), and two r5 completion attempts at"
    " scc 36 overran the model's prediction by >2x"
)


def hier_calls_estimate(scc: int) -> float:
    if scc in HIER_CALLS:
        return float(HIER_CALLS[scc])
    return HIER_CALLS[32] * 4.66 ** ((scc - 32) / 4)


def time_solve(data, backend):
    from quorum_intersection_tpu.pipeline import solve

    t0 = time.perf_counter()
    res = solve(data, backend=backend)
    return time.perf_counter() - t0, res


def native_row(data, scc: int, cap_s: float) -> dict:
    """A 2M-call probe measures this box's single-core call rate; the run
    completes unbudgeted when (measured-or-extrapolated total)/rate fits
    --native-cap, else the row reports the probe floor + the labeled
    estimate (bench.py phase_verdict three-way honesty)."""
    from quorum_intersection_tpu.backends.base import OracleBudgetExceeded
    from quorum_intersection_tpu.backends.cpp import CppOracleBackend

    t0 = time.perf_counter()
    try:
        _, res = time_solve(data, CppOracleBackend(budget_calls=2_000_000))
        return {
            "native_seconds": round(time.perf_counter() - t0, 3),
            "native_calls": res.stats.get("bnb_calls"),
            "native_completed": True,
            "_intersects": res.intersects,
        }
    except OracleBudgetExceeded:
        probe_s = time.perf_counter() - t0
    rate = 2_000_000 / probe_s if probe_s > 0 else 1.4e6
    expected = hier_calls_estimate(scc)
    if expected / rate <= cap_s:
        sec, res = time_solve(data, CppOracleBackend())
        return {
            "native_seconds": round(sec, 3),
            "native_calls": res.stats.get("bnb_calls"),
            "native_completed": True,
            "native_minimal_quorums": res.stats.get("minimal_quorums"),
            "_intersects": res.intersects,
        }
    return {
        "native_seconds": round(probe_s, 3),
        "native_calls": 2_000_000,
        "native_completed": False,
        "native_rate": round(rate, 1),
        "native_est_seconds": round(expected / rate, 1),
        "native_est_calls": int(expected),
        "native_est_model": HIER_CALLS_MODEL,
        "_intersects": None,
    }


# int8 MXU peak MACs/s by device-kind substring (bench.py INT8_PEAK_MACS —
# duplicated constant, not an import: bench.py is the driver harness and
# pulls in its whole orchestration surface).  Kinds not listed get no MFU
# cell rather than a wrong one.
INT8_PEAK_MACS = {"v5 lite": 1.97e14, "v5e": 1.97e14}


def kofn(n: int, k: int, prefix: str = "N") -> list:
    """Symmetric k-of-n FBAS: single SCC; broken iff k <= n//2 (the
    broken twin the sweep itself must find — synth's broken pairs are
    guard-decided before any backend runs)."""
    ks = [f"{prefix}{i}" for i in range(n)]
    return [
        {"publicKey": x, "name": x,
         "quorumSet": {"threshold": k, "validators": ks}}
        for x in ks
    ]


def packed_row(scc: int, device: str) -> dict:
    """One lane-packing measurement: K=4 k-of-n problems (two correct, two
    broken) swept packed vs unpacked, with the per-lane-group work
    accounting that makes the MACs-per-verdict claim checkable off-chip:
    MACs = rows actually dispatched x the lane-padded shape model
    (sweep.macs_per_candidate_row), packed totals shared across the pack's
    verdicts.  Wall-clock speedup rides along and — with verdict parity —
    is what gates auto-engagement (calibration.pack_win_max_scc)."""
    from quorum_intersection_tpu.backends.tpu.sweep import (
        TpuSweepBackend,
        macs_per_candidate_row,
    )
    from quorum_intersection_tpu.encode.circuit import encode_circuit
    from quorum_intersection_tpu.fbas.graph import build_graph
    from quorum_intersection_tpu.fbas.schema import parse_fbas
    from quorum_intersection_tpu.pipeline import quorum_bearing_sccs

    n = scc
    datas = [
        kofn(n, n // 2 + 1, "PA"), kofn(n, n // 2, "PB"),
        kofn(n, n // 2 + 1, "PC"), kofn(n, n // 2, "PD"),
    ]
    jobs = []
    for data in datas:
        graph = build_graph(parse_fbas(data))
        circuit = encode_circuit(graph)
        bearing = quorum_bearing_sccs(graph, allow_native=False)
        assert len(bearing) == 1
        jobs.append((graph, circuit, bearing[0][1]))
    k = len(jobs)

    t0 = time.perf_counter()
    unpacked = [TpuSweepBackend().check_scc(g, c, s) for g, c, s in jobs]
    unpacked_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    packed = TpuSweepBackend().check_sccs(jobs)
    packed_s = time.perf_counter() - t0

    verdict_ok = all(
        u.intersects == p.intersects and u.q1 == p.q1 and u.q2 == p.q2
        for u, p in zip(unpacked, packed)
    )
    pstats = packed[0].stats
    packed_macs = (
        pstats["pack_rows_dispatched"] * pstats["pack_macs_per_candidate_row"]
    )
    unpacked_macs = 0.0
    for res in unpacked:
        shape = res.stats.get("padded_shape") or res.stats["device_shape"]
        unpacked_macs += res.stats["candidates_checked"] * macs_per_candidate_row(
            shape[0], shape[1], 0
        )
    row = {
        "scc": scc, "device": device, "pack_jobs": k,
        "pack_groups": pstats["pack_groups"],
        "pack_fill_pct": pstats["pack_fill_pct"],
        "packed_seconds": round(packed_s, 3),
        "unpacked_seconds": round(unpacked_s, 3),
        "packed_speedup_vs_unpacked": round(unpacked_s / packed_s, 2)
        if packed_s else None,
        "packed_macs_per_verdict": round(packed_macs / k, 1),
        "unpacked_macs_per_verdict": round(unpacked_macs / k, 1),
        "packed_macs_ratio": round(packed_macs / unpacked_macs, 4)
        if unpacked_macs else None,
        "verdict_ok": verdict_ok,
    }
    # Packed-MFU estimate for the qi-telemetry stream (ROADMAP telemetry
    # item): shape-model MACs/s against the int8 peak — only on device
    # kinds with a known peak, so a CPU-emulated row carries null here
    # while still carrying the (platform-independent) MACs accounting.
    peak = next(
        (v for key, v in INT8_PEAK_MACS.items() if key in device.lower()), None
    )
    row["sweep_mfu_pct"] = (
        round(packed_macs / packed_s / peak * 100, 3)
        if peak and packed_s else None
    )
    return row


def _bitset_workloads(quick: bool) -> list:
    """(name, correct_snapshot, broken_snapshot) triples for the --bitset
    rows: both vendored fixture pairs (org-nested 15-node SCC + the
    149-node stellar-like snapshot's 21-node SCC), a symmetric k-of-n pair
    (density ~1.0 — the dense-friendly end of the density axis), and the
    ``sparse_giant`` preset (the crossover workload: 24-node core under
    ~10k watcher nodes).  --quick shrinks only the watcher mass — the
    cores, and therefore the sweep work, are identical."""
    from quorum_intersection_tpu.fbas.synth import sparse_giant

    fixdir = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "fixtures")

    def fx(name):
        with open(os.path.join(fixdir, name + ".json")) as fh:
            return json.load(fh)

    giant_nodes = 1_500 if quick else 10_000
    return [
        ("nested_fixture", fx("nested_correct"), fx("nested_broken")),
        ("snapshot_fixture", fx("snapshot_correct"), fx("snapshot_broken")),
        ("kofn16", kofn(16, 9, "KD"), kofn(16, 8, "KD")),
        ("sparse_giant", sparse_giant(giant_nodes),
         sparse_giant(giant_nodes, broken=True)),
    ]


def bitset_row(name: str, correct: list, broken: list, device: str) -> dict:
    """One dense-vs-bitset-vs-oracle measurement (qi-sparse ISSUE 20) on a
    correct+broken snapshot pair.

    Times the SWEEP PHASE only (graph/circuit built once, outside the
    clock — on ``sparse_giant`` the 10k-node front end would otherwise
    drown the engines' difference), runs both engines on both twins, and
    carries the shape model that makes the arithmetic-intensity claim
    checkable off-chip: dense MACs-per-candidate vs bitset
    words-per-candidate on the device shape that actually ran, plus the
    streamed ``sweep_bytes_per_candidate`` (4 bytes per packed word).
    ``scc_density`` is the routing feature calibration consumes
    (``bitset_win_max_density``).  Verdict parity — dense == bitset ==
    host oracle on BOTH twins, witness pair included — gates the row; any
    mismatch marks it INVALID and the driver exits 1.
    """
    from quorum_intersection_tpu.backends.tpu.sweep import (
        TpuSweepBackend,
        bitset_words_per_candidate_row,
        macs_per_candidate_row,
    )
    from quorum_intersection_tpu.encode.circuit import encode_circuit
    from quorum_intersection_tpu.fbas.graph import build_graph
    from quorum_intersection_tpu.fbas.schema import parse_fbas
    from quorum_intersection_tpu.fbas.synth import (
        graph_density,
        scc_qset_density,
    )
    from quorum_intersection_tpu.pipeline import quorum_bearing_sccs

    jobs = {}
    for twin, data in (("correct", correct), ("broken", broken)):
        graph = build_graph(parse_fbas(data))
        circuit = encode_circuit(graph)
        bearing = quorum_bearing_sccs(graph, allow_native=False)
        assert bearing, f"{name}/{twin}: no quorum-bearing SCC"
        # The broken twin of a fixture pair may split into several bearing
        # SCCs; the engine differential runs on the largest (the one that
        # carries the sweep work).
        scc = max((s for _, s in bearing), key=len)
        jobs[twin] = (graph, circuit, scc)

    def device_quiesce():
        """Wait out device work abandoned by the previous timed run.  An
        early-hit verdict returns immediately BY DESIGN, dropping up to
        max_inflight in-flight programs (the driver's bounded discard) —
        but those keep executing on the backend's thread pool, and the
        next engine's compile and dispatches queue behind them (measured:
        a 0.3 s bitset compile stretched to ~18 s behind a dense broken-
        twin's abandoned backlog).  A fresh trivial program round-trips
        fast only once the queue is empty, so spin until it does.
        """
        import jax.numpy as jnp

        while True:
            t0 = time.perf_counter()
            jnp.zeros(()).block_until_ready()
            if time.perf_counter() - t0 < 0.05:
                return

    graph, _, scc = jobs["correct"]
    timings = {}
    results = {}
    for engine in ("xla", "bitset"):
        for twin, (g, c, s) in jobs.items():
            device_quiesce()
            t0 = time.perf_counter()
            res = TpuSweepBackend(engine=engine).check_scc(
                g, c, s, scope_to_scc=False
            )
            timings[(engine, twin)] = time.perf_counter() - t0
            results[(engine, twin)] = res

    # Host-oracle rung of the differential: the reference B&B disjointness
    # search (cpp when a compiler is around, stdlib python otherwise) run on
    # the SAME per-SCC problem.  Verdicts must agree three ways; witness
    # pairs are compared engine-vs-engine only (the oracle's search order
    # legitimately surfaces a different disjoint pair).
    def oracle_intersects(g, s):
        try:
            from quorum_intersection_tpu.backends.cpp import CppOracleBackend
            oracle = CppOracleBackend()
        except Exception:  # noqa: BLE001 — no g++: the python oracle counts
            from quorum_intersection_tpu.backends.python_oracle import (
                PythonOracleBackend,
            )
            oracle = PythonOracleBackend()
        return oracle.check_scc(g, None, s, scope_to_scc=False).intersects

    verdict_ok = True
    for twin in ("correct", "broken"):
        g, _, s = jobs[twin]
        dense = results[("xla", twin)]
        bits = results[("bitset", twin)]
        verdict_ok = verdict_ok and (
            dense.intersects == bits.intersects
            and dense.q1 == bits.q1 and dense.q2 == bits.q2
            and dense.intersects == oracle_intersects(g, s)
        )

    dense_s = timings[("xla", "correct")]
    bits_s = timings[("bitset", "correct")]
    shape = (
        results[("xla", "correct")].stats.get("padded_shape")
        or results[("xla", "correct")].stats["device_shape"]
    )
    macs = macs_per_candidate_row(shape[0], shape[1], 0)
    words = bitset_words_per_candidate_row(shape[0], shape[1], 0)
    dens = graph_density(graph)
    row = {
        "bitset": True, "name": name, "device": device,
        "scc": len(scc),
        "nodes": int(dens["nodes"]),
        "edge_density": round(dens["edge_density"], 6),
        "qset_fanout_mean": round(dens["qset_fanout_mean"], 2),
        "scc_density": round(scc_qset_density(graph, scc), 4),
        "dense_seconds": round(dense_s, 3),
        "bitset_seconds": round(bits_s, 3),
        "bitset_speedup_vs_dense": round(dense_s / bits_s, 2)
        if bits_s else None,
        "broken_dense_seconds": round(timings[("xla", "broken")], 3),
        "broken_bitset_seconds": round(timings[("bitset", "broken")], 3),
        "bitset_cand_per_sec": round(
            results[("bitset", "correct")].stats.get("candidates_per_sec", 0.0)
        ),
        "dense_cand_per_sec": round(
            results[("xla", "correct")].stats.get("candidates_per_sec", 0.0)
        ),
        "dense_macs_per_candidate": macs,
        "bitset_words_per_candidate": words,
        "sweep_bytes_per_candidate": 4 * words,
        "model_intensity_ratio": round(macs / words, 2) if words else None,
        "encoding_stamped": (
            results[("bitset", "correct")].stats.get("encoding") == "bitset"
            and "encoding" not in results[("xla", "correct")].stats
        ),
        "verdict_ok": verdict_ok,
    }
    return row


def pruned_row(core: int, device: str) -> dict:
    """One qi-prune measurement (ISSUE 10) on the ``near_disjoint_cores``
    pair (2*core+1 nodes, one SCC):

    - correct twin: rank-ordered + block-guard-pruned sweep vs the
      natural/unpruned baseline — ``sweep_enumeration_ratio`` and
      ``sweep_windows_pruned`` are the ledger numbers the
      tools/bench_trend.py gates track, wall-clock rides along;
    - broken twin: first-hit window index ordered vs natural — the
      rank-order permutation's win on false verdicts;
    - native column: the oracle's B&B node count for the same SCC vs the
      windows the pruned sweep actually enumerated.

    Verdict parity (both twins, pruned and unpruned, vs the oracle) gates
    the row: any mismatch marks it INVALID and the driver exits 1.
    """
    from quorum_intersection_tpu.backends.tpu.sweep import TpuSweepBackend
    from quorum_intersection_tpu.fbas.synth import near_disjoint_cores

    correct = near_disjoint_cores(core, 1)
    broken = near_disjoint_cores(core, 1, broken=True)
    n = 2 * core + 1

    base_s, base = time_solve(correct, TpuSweepBackend())
    pruned_s, pruned = time_solve(
        correct, TpuSweepBackend(order="rank", prune=True)
    )
    led = pruned.stats.get("cert") or {}
    space = led.get("window_space") or (1 << (n - 1))

    _, nat_broken = time_solve(broken, TpuSweepBackend())
    _, ord_broken = time_solve(
        broken, TpuSweepBackend(order="rank", prune=True)
    )

    from quorum_intersection_tpu.pipeline import solve

    try:
        from quorum_intersection_tpu.backends.cpp import CppOracleBackend

        oracle = solve(correct, backend=CppOracleBackend())
        oracle_engine = "cpp"
    except Exception:  # noqa: BLE001 — no g++: the python oracle still counts
        oracle = solve(correct, backend="python")
        oracle_engine = "python"

    verdict_ok = (
        base.intersects is True
        and pruned.intersects is True
        and oracle.intersects is True
        and nat_broken.intersects is False
        and ord_broken.intersects is False
    )
    return {
        "scc": n, "device": device,
        "unpruned_seconds": round(base_s, 3),
        "pruned_seconds": round(pruned_s, 3),
        "sweep_windows_enumerated": led.get("windows_enumerated"),
        "sweep_windows_pruned": led.get("windows_pruned_guard"),
        "sweep_enumeration_ratio": round(
            (led.get("windows_enumerated") or 0) / space, 6
        ),
        "first_hit_natural": nat_broken.stats.get("hit_index"),
        "first_hit_ordered": ord_broken.stats.get("hit_index"),
        "native_bnb_calls": oracle.stats.get("bnb_calls"),
        "native_engine": oracle_engine,
        "verdict_ok": verdict_ok,
    }


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--quick", action="store_true",
                        help="tiny sizes for a CPU smoke run")
    parser.add_argument("--scc", type=int, nargs="*", default=None,
                        help="|scc| sizes (multiples of 4)")
    parser.add_argument("--native-cap", type=float, default=600.0,
                        help="seconds the native oracle may run to completion")
    parser.add_argument("--packed", action="store_true",
                        help="add lane-packed vs unpacked sweep rows "
                             "(packed MACs-per-verdict accounting + "
                             "pack_fill_pct/sweep_mfu_pct)")
    parser.add_argument("--packed-scc", type=int, nargs="*", default=None,
                        help="|scc| sizes for the --packed rows "
                             "(<= 31: the packable window)")
    parser.add_argument("--metrics-json", default=None, metavar="PATH",
                        help="append the run's qi-telemetry/1 stream "
                             "(sweep.pack_* / sweep.prune_* counters "
                             "included) to PATH")
    parser.add_argument("--bitset", action="store_true",
                        help="add dense-vs-bitset-vs-oracle sweep rows on "
                             "the fixture pairs, a k-of-n pair, and the "
                             "sparse_giant preset (qi-sparse ISSUE 20: "
                             "MACs- vs words-per-candidate shape model, "
                             "crossover point, verdict-parity gated)")
    parser.add_argument("--pruned", action="store_true",
                        help="add rank-ordered + block-guard-pruned sweep "
                             "rows on the near_disjoint_cores pair "
                             "(enumeration ratio, pruned window mass, "
                             "first-hit ordered-vs-natural, native B&B "
                             "node counts for the same SCC)")
    parser.add_argument("--pruned-core", type=int, nargs="*", default=None,
                        help="core sizes for the --pruned rows "
                             "(|scc| = 2*core + 1)")
    args = parser.parse_args()

    if args.metrics_json:
        os.environ["QI_METRICS_JSON"] = os.path.abspath(args.metrics_json)

    from quorum_intersection_tpu.utils.platform import honor_platform_env

    honor_platform_env()

    import jax

    from quorum_intersection_tpu.backends.auto import _platform_sweep_limit
    from quorum_intersection_tpu.backends.tpu.sweep import TpuSweepBackend
    from quorum_intersection_tpu.fbas.synth import hierarchical_fbas

    sizes = args.scc or ([16, 20] if args.quick else [28, 32, 36])
    device = jax.devices()[0].device_kind
    limit = _platform_sweep_limit()
    print(f"device: {device}  (platform sweep limit: {limit})\n")
    print("| scc | native (s) | sweep (s) | auto (s) | sweep speedup | auto speedup | cand/s |")
    print("|---|---|---|---|---|---|---|")

    for scc in sizes:
        assert scc % 4 == 0, "hierarchical_fbas rows are 4 nodes/org"
        data = hierarchical_fbas(scc // 4, 4)
        nat = native_row(data, scc, args.native_cap)

        sw_s, sw_res = time_solve(data, TpuSweepBackend())
        row = {
            "scc": scc, "device": device,
            **{k: v for k, v in nat.items() if not k.startswith("_")},
            "sweep_seconds": round(sw_s, 3),
            "sweep_cand_per_sec": round(
                sw_res.stats.get("candidates_per_sec", 0.0)
            ),
            "sweep_enumeration_total": sw_res.stats.get("enumeration_total"),
        }
        verdicts = {sw_res.intersects}
        if nat["_intersects"] is not None:
            verdicts.add(nat["_intersects"])

        nat_s = nat.get("native_est_seconds") or nat["native_seconds"]
        est = "" if nat["native_completed"] else " (est)"
        row["sweep_speedup_vs_native"] = round(nat_s / sw_s, 2) if sw_s else None

        if scc <= limit:
            au_s, au_res = time_solve(data, "auto")
            verdicts.add(au_res.intersects)
            row.update({
                "auto_seconds": round(au_s, 3),
                "auto_backend": au_res.stats.get("backend"),
                "auto_speedup_vs_native": round(nat_s / au_s, 2) if au_s else None,
            })
            auto_cell = f"{au_s:.2f}"
            auto_speed = f"{row['auto_speedup_vs_native']}x"
        else:
            row["auto_skipped"] = (
                f"|scc|={scc} > sweep limit {limit}: auto would run the "
                "unbudgeted native oracle (the `native` column)"
            )
            auto_cell = "—"
            auto_speed = "—"

        row["verdict_ok"] = len(verdicts) == 1
        flag = "" if row["verdict_ok"] else " **INVALID: verdict mismatch**"
        print(
            f"| {scc} | {nat_s:.2f}{est} | {sw_s:.2f} | {auto_cell} | "
            f"{row['sweep_speedup_vs_native']}x{flag} | {auto_speed} | "
            f"{row['sweep_cand_per_sec']:.3g} |"
        )
        print(json.dumps(row), flush=True)

    if args.packed:
        # Packed sizes stay within the packable window (bits <= 30) and the
        # acceptance regime (n <= 48); --quick keeps CPU emulation seconds.
        packed_sizes = [
            s for s in (
                args.packed_scc or ([12, 14] if args.quick else [24, 31])
            ) if s <= 31
        ]
        print("\n| scc | K | packed (s) | unpacked (s) | speedup | "
              "MACs/verdict ratio | fill % | mfu % |")
        print("|---|---|---|---|---|---|---|---|")
        ok = True
        for scc in packed_sizes:
            row = packed_row(scc, device)
            ok = ok and row["verdict_ok"]
            flag = "" if row["verdict_ok"] else " **INVALID: verdict mismatch**"
            mfu = row["sweep_mfu_pct"]
            print(
                f"| {scc} | {row['pack_jobs']} | {row['packed_seconds']:.2f} | "
                f"{row['unpacked_seconds']:.2f} | "
                f"{row['packed_speedup_vs_unpacked']}x{flag} | "
                f"{row['packed_macs_ratio']} | {row['pack_fill_pct']} | "
                f"{mfu if mfu is not None else '—'} |"
            )
            print(json.dumps(row), flush=True)
        if not ok:
            return 1

    if args.bitset:
        print("\n| workload | scc | density | dense (s) | bitset (s) | "
              "speedup | MACs/cand | words/cand | bytes/cand |")
        print("|---|---|---|---|---|---|---|---|---|")
        ok = True
        wins = []
        for name, correct, broken in _bitset_workloads(args.quick):
            row = bitset_row(name, correct, broken, device)
            ok = ok and row["verdict_ok"]
            flag = "" if row["verdict_ok"] else " **INVALID: verdict mismatch**"
            print(
                f"| {name} | {row['scc']} | {row['scc_density']} | "
                f"{row['dense_seconds']:.2f} | {row['bitset_seconds']:.2f} | "
                f"{row['bitset_speedup_vs_dense']}x{flag} | "
                f"{row['dense_macs_per_candidate']} | "
                f"{row['bitset_words_per_candidate']} | "
                f"{row['sweep_bytes_per_candidate']} |"
            )
            print(json.dumps(row), flush=True)
            if row["verdict_ok"] and (row["bitset_speedup_vs_dense"] or 0) > 1:
                wins.append(row)
        if wins:
            # The crossover summary line the calibration parser's humans
            # read; the parser itself consumes the JSON rows above.
            win_sccs = sorted(r["scc"] for r in wins)
            print(f"\nbitset crossover: wins from scc {min(win_sccs)} "
                  f"(measured wins at {win_sccs})")
            # Trend-gate summary row (tools/bench_trend.py TRACKED): the
            # best winning row's end-to-end rate, the measured crossover
            # point (creeping UP = the encoding stopped winning small
            # SCCs), and the streamed bytes per candidate on the largest
            # measured shape (creeping up = encoding bloat).  `bitset` is
            # deliberately absent so calibration's row parser skips it.
            best = max(wins, key=lambda r: r["bitset_cand_per_sec"])
            widest = max(wins, key=lambda r: r["bitset_words_per_candidate"])
            print(json.dumps({
                "device": device,
                "bitset_candidates_per_sec": best["bitset_cand_per_sec"],
                "bitset_crossover_scc": min(win_sccs),
                "sweep_bytes_per_candidate":
                    widest["sweep_bytes_per_candidate"],
            }), flush=True)
        if not ok:
            return 1

    if args.pruned:
        pruned_cores = args.pruned_core or ([6] if args.quick else [8, 10])
        print("\n| scc | unpruned (s) | pruned (s) | enum ratio | pruned "
              "windows | first-hit nat→ord | native B&B |")
        print("|---|---|---|---|---|---|---|")
        ok = True
        for core in pruned_cores:
            row = pruned_row(core, device)
            ok = ok and row["verdict_ok"]
            flag = "" if row["verdict_ok"] else " **INVALID: verdict mismatch**"
            print(
                f"| {row['scc']} | {row['unpruned_seconds']:.2f} | "
                f"{row['pruned_seconds']:.2f} | "
                f"{row['sweep_enumeration_ratio']}{flag} | "
                f"{row['sweep_windows_pruned']} | "
                f"{row['first_hit_natural']}→{row['first_hit_ordered']} | "
                f"{row['native_bnb_calls']} |"
            )
            print(json.dumps(row), flush=True)
        if not ok:
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
