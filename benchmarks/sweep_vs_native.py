"""Time-to-verdict: exhaustive device sweep vs the native oracle at
mid-range |scc| — the measurement behind auto's sweep routing window.

Motivation (round 5).  The r5 on-chip crossover (crossover_tpu_r5.txt)
measures the device-resident FRONTIER losing to the native oracle at
every tractable size (0.03x at scc 24, ~0.19x at scc 28): per-chunk host
seams and per-iteration latency through the tunnel dominate the tiny
(≤32-wide) matmuls, exactly as the r3 hybrid measurement foreshadowed.
The engine that DOES win this regime on the chip is the exhaustive
SWEEP: 2^(|scc|-1) candidates at the measured enumeration rate (626M
cand/s end-to-end on v5e, r3) beats the native B&B's ~1.4M calls/s
whenever the B&B call count is within ~3 orders of the subset-space size
— which holds for the symmetric k-of-n cores of the reference benchmarks
(reference `quorum_intersection.cpp:252-346` enumerates ~4·C(n, n/2)
calls ≈ 2^n·sqrt(2/(pi·n)) on them, see bench.py NATIVE_CALLS_MODEL).

Rows: hierarchical k-of-n networks (`hierarchical_fbas(orgs, 4)`,
|scc| = 4·orgs) at scc 28 / 32 / 36.

- native: the C++ oracle run to completion when the call-count model says
  it fits --native-cap (measured floor + model estimate otherwise, same
  three-way honesty as bench.py phase_verdict).
- sweep: TpuSweepBackend directly (the engine auto falls back to).
- auto:  the full `auto` policy end-to-end — oracle-first with a
  sweep-sized budget, then the sweep — i.e. what a user actually gets.
  Skipped (with the reason recorded) when |scc| exceeds the platform
  sweep limit and auto would run the UNBUDGETED native oracle: the row
  would just re-measure `native`, at hours of wall clock.

Usage:
    JAX_PLATFORMS=cpu python benchmarks/sweep_vs_native.py --quick  # smoke
    python benchmarks/sweep_vs_native.py                            # chip
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Measured B&B call counts for hierarchical_fbas(orgs, 4) — crossover
# artifacts r3-r5 (exact, platform-independent: the search is
# deterministic).  Beyond the table, extrapolated at the last measured
# +4-org growth (139.9M/30.0M ≈ 4.66x), labeled as such.
HIER_CALLS = {16: 184_755, 20: 1_307_504, 24: 1_009_587,
              28: 30_029_267, 32: 139_942_245}
HIER_CALLS_MODEL = (
    "measured table (crossover_cpu/tpu_r3-r5) + 4.66x per +4 orgs beyond 32"
    " — LIKELY AN UNDERESTIMATE there: the measured +4 growth was 29.7x"
    " (24->28) then 4.66x (28->32), and two r5 completion attempts at"
    " scc 36 overran the model's prediction by >2x"
)


def hier_calls_estimate(scc: int) -> float:
    if scc in HIER_CALLS:
        return float(HIER_CALLS[scc])
    return HIER_CALLS[32] * 4.66 ** ((scc - 32) / 4)


def time_solve(data, backend):
    from quorum_intersection_tpu.pipeline import solve

    t0 = time.perf_counter()
    res = solve(data, backend=backend)
    return time.perf_counter() - t0, res


def native_row(data, scc: int, cap_s: float) -> dict:
    """A 2M-call probe measures this box's single-core call rate; the run
    completes unbudgeted when (measured-or-extrapolated total)/rate fits
    --native-cap, else the row reports the probe floor + the labeled
    estimate (bench.py phase_verdict three-way honesty)."""
    from quorum_intersection_tpu.backends.base import OracleBudgetExceeded
    from quorum_intersection_tpu.backends.cpp import CppOracleBackend

    t0 = time.perf_counter()
    try:
        _, res = time_solve(data, CppOracleBackend(budget_calls=2_000_000))
        return {
            "native_seconds": round(time.perf_counter() - t0, 3),
            "native_calls": res.stats.get("bnb_calls"),
            "native_completed": True,
            "_intersects": res.intersects,
        }
    except OracleBudgetExceeded:
        probe_s = time.perf_counter() - t0
    rate = 2_000_000 / probe_s if probe_s > 0 else 1.4e6
    expected = hier_calls_estimate(scc)
    if expected / rate <= cap_s:
        sec, res = time_solve(data, CppOracleBackend())
        return {
            "native_seconds": round(sec, 3),
            "native_calls": res.stats.get("bnb_calls"),
            "native_completed": True,
            "native_minimal_quorums": res.stats.get("minimal_quorums"),
            "_intersects": res.intersects,
        }
    return {
        "native_seconds": round(probe_s, 3),
        "native_calls": 2_000_000,
        "native_completed": False,
        "native_rate": round(rate, 1),
        "native_est_seconds": round(expected / rate, 1),
        "native_est_calls": int(expected),
        "native_est_model": HIER_CALLS_MODEL,
        "_intersects": None,
    }


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--quick", action="store_true",
                        help="tiny sizes for a CPU smoke run")
    parser.add_argument("--scc", type=int, nargs="*", default=None,
                        help="|scc| sizes (multiples of 4)")
    parser.add_argument("--native-cap", type=float, default=600.0,
                        help="seconds the native oracle may run to completion")
    args = parser.parse_args()

    from quorum_intersection_tpu.utils.platform import honor_platform_env

    honor_platform_env()

    import jax

    from quorum_intersection_tpu.backends.auto import _platform_sweep_limit
    from quorum_intersection_tpu.backends.tpu.sweep import TpuSweepBackend
    from quorum_intersection_tpu.fbas.synth import hierarchical_fbas

    sizes = args.scc or ([16, 20] if args.quick else [28, 32, 36])
    device = jax.devices()[0].device_kind
    limit = _platform_sweep_limit()
    print(f"device: {device}  (platform sweep limit: {limit})\n")
    print("| scc | native (s) | sweep (s) | auto (s) | sweep speedup | auto speedup | cand/s |")
    print("|---|---|---|---|---|---|---|")

    for scc in sizes:
        assert scc % 4 == 0, "hierarchical_fbas rows are 4 nodes/org"
        data = hierarchical_fbas(scc // 4, 4)
        nat = native_row(data, scc, args.native_cap)

        sw_s, sw_res = time_solve(data, TpuSweepBackend())
        row = {
            "scc": scc, "device": device,
            **{k: v for k, v in nat.items() if not k.startswith("_")},
            "sweep_seconds": round(sw_s, 3),
            "sweep_cand_per_sec": round(
                sw_res.stats.get("candidates_per_sec", 0.0)
            ),
            "sweep_enumeration_total": sw_res.stats.get("enumeration_total"),
        }
        verdicts = {sw_res.intersects}
        if nat["_intersects"] is not None:
            verdicts.add(nat["_intersects"])

        nat_s = nat.get("native_est_seconds") or nat["native_seconds"]
        est = "" if nat["native_completed"] else " (est)"
        row["sweep_speedup_vs_native"] = round(nat_s / sw_s, 2) if sw_s else None

        if scc <= limit:
            au_s, au_res = time_solve(data, "auto")
            verdicts.add(au_res.intersects)
            row.update({
                "auto_seconds": round(au_s, 3),
                "auto_backend": au_res.stats.get("backend"),
                "auto_speedup_vs_native": round(nat_s / au_s, 2) if au_s else None,
            })
            auto_cell = f"{au_s:.2f}"
            auto_speed = f"{row['auto_speedup_vs_native']}x"
        else:
            row["auto_skipped"] = (
                f"|scc|={scc} > sweep limit {limit}: auto would run the "
                "unbudgeted native oracle (the `native` column)"
            )
            auto_cell = "—"
            auto_speed = "—"

        row["verdict_ok"] = len(verdicts) == 1
        flag = "" if row["verdict_ok"] else " **INVALID: verdict mismatch**"
        print(
            f"| {scc} | {nat_s:.2f}{est} | {sw_s:.2f} | {auto_cell} | "
            f"{row['sweep_speedup_vs_native']}x{flag} | {auto_speed} | "
            f"{row['sweep_cand_per_sec']:.3g} |"
        )
        print(json.dumps(row), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
