"""Frontier at scc 36 (hier-9x4) vs a native-oracle FLOOR — does the
device-resident B&B win keep growing past the measured crossover?

The native oracle cannot be run to completion here: the r5 attempts
measured the real search exceeding 50 minutes single-core (the call-count
law underestimates above scc 32, see sweep_vs_native.py HIER_CALLS_MODEL).
So this row is explicitly FLOOR-based and verdict-plus-closed-form:

- native: budgeted run to a measured time floor (never a ratio claim
  beyond ">= floor/frontier");
- frontier: completes the full enumeration; its confirmed-minimal count
  is checked against the family's COMBINATORIAL ground truth
  C(orgs, majority) * C(4, 3)^majority — the measured r3-r5 counts obey
  it exactly (7x4: C(7,4)*4^4 = 8,960; 8x4: C(8,5)*4^5 = 57,344), which
  verifies enumeration completeness without the intractable native run.

This row records evidence, not routing: auto's frontier win region only
accepts native-parity rows (backends/calibration.py), and sizes <= the
sweep limit route to the sweep anyway.  The question it answers is
whether the scc-32 win (1.16-1.31x) is a knife-edge or a trend.

MEASURED ANSWER (r5, frontier_scc36_r5.txt): neither completes — the
frontier ran >78 minutes on hier-9x4 without exhausting the tree after
the native oracle failed a 500 s floor; the exhaustive sweep did the
same instance in 120 s.  The --frontier-chunk-cap guard (added after
that run) makes the script self-terminating: it emits an honest
frontier_completed=false row instead of running unbounded.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--orgs", type=int, default=9)
    parser.add_argument("--native-floor", type=float, default=600.0,
                        help="approximate seconds of single-core native search "
                             "to burn as the floor (the budget is sized from a "
                             "2M-call probe whose rate includes solve setup, so "
                             "the MEASURED floor lands somewhat short of this; "
                             "the recorded ratio always uses the measured "
                             "seconds, never this request)")
    parser.add_argument("--pop", type=int, default=2048)
    parser.add_argument("--frontier-chunk-cap", type=int, default=1200,
                        help="stop the frontier after this many device chunks "
                             "and record an honest frontier_completed=false "
                             "row (the default workload measured >78 min "
                             "without completing; unbounded is opt-in via 0)")
    args = parser.parse_args()

    from quorum_intersection_tpu.utils.platform import honor_platform_env

    honor_platform_env()

    import jax

    from quorum_intersection_tpu.backends.base import OracleBudgetExceeded
    from quorum_intersection_tpu.backends.cpp import CppOracleBackend
    from quorum_intersection_tpu.backends.tpu.frontier import TpuFrontierBackend
    from quorum_intersection_tpu.fbas.synth import hierarchical_fbas
    from quorum_intersection_tpu.pipeline import solve

    orgs = args.orgs
    scc = 4 * orgs
    majority = orgs // 2 + 1
    expected_count = math.comb(orgs, majority) * 4 ** majority
    data = hierarchical_fbas(orgs, 4)
    device = jax.devices()[0].device_kind
    print(f"device: {device}  workload: hier-{orgs}x4 (scc {scc})  "
          f"closed-form minimal quorums: {expected_count}", flush=True)

    # Native floor: probe the rate, then burn a floor-sized budget.
    t0 = time.perf_counter()
    try:
        solve(data, backend=CppOracleBackend(budget_calls=2_000_000))
        raise SystemExit("native completed under the probe?! not this family")
    except OracleBudgetExceeded:
        rate = 2_000_000 / (time.perf_counter() - t0)
    floor_calls = int(rate * args.native_floor)
    t0 = time.perf_counter()
    try:
        solve(data, backend=CppOracleBackend(budget_calls=floor_calls))
        native_completed = True
    except OracleBudgetExceeded:
        native_completed = False
    native_floor_s = time.perf_counter() - t0
    print(f"native: {'completed' if native_completed else 'floor'} "
          f"{native_floor_s:.1f}s ({floor_calls} calls budgeted)", flush=True)

    from quorum_intersection_tpu.backends.tpu.frontier import (
        FrontierSearchInterrupted,
    )
    from quorum_intersection_tpu.utils.checkpoint import FrontierCheckpoint

    kw = {"flag_check": "auto", "pop": args.pop}
    ckpt_dir = tempfile.mkdtemp(prefix="frontier_scc36_")
    backend = TpuFrontierBackend(
        **kw,
        checkpoint=FrontierCheckpoint(os.path.join(ckpt_dir, "cap.ckpt")),
        interrupt_after_chunks=args.frontier_chunk_cap or None,
    )
    t0 = time.perf_counter()
    fr, completed = None, True
    try:
        fr = solve(data, backend=backend)
    except FrontierSearchInterrupted:
        completed = False
    fr_s = time.perf_counter() - t0
    count = fr.stats.get("minimal_quorums") if fr else None
    row = {
        "workload": f"hier-{orgs}x4", "scc": scc, "device": device,
        "native_floor_seconds": round(native_floor_s, 1),
        "native_floor_calls": floor_calls,
        "native_completed": native_completed,
        "frontier_seconds": round(fr_s, 1),
        "frontier_completed": completed,
        "frontier_kw": kw,
        "frontier_chunk_cap": args.frontier_chunk_cap,
    }
    if completed:
        row.update({
            "frontier_speedup_floor": (
                round(native_floor_s / fr_s, 2) if not native_completed else None
            ),
            "verdict": fr.intersects,
            "minimal_quorums": count,
            "closed_form_count": expected_count,
            "counts_ok_vs_closed_form": count == expected_count,
            "frontier_stats": {
                k: v for k, v in fr.stats.items() if k != "backend"
            },
        })
    print(json.dumps(row), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
