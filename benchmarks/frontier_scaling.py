"""Frontier pop-block scaling study (device-resident B&B).

Sweeps the ``pop`` block size on fixed workloads and reports states/s with
the compile/steady time split (`first_chunk_seconds` vs `chunk_seconds`),
plus the native-oracle reference time.  The interesting knob on a real
chip: larger pops amortize per-iteration loop overhead but need a wide
frontier to fill (the tree only doubles per iteration), so states/s rises
then flattens.

Usage::

    JAX_PLATFORMS=cpu python benchmarks/frontier_scaling.py --quick  # smoke
    python benchmarks/frontier_scaling.py                            # chip
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--quick", action="store_true")
    args = parser.parse_args()

    from quorum_intersection_tpu.utils.platform import honor_platform_env

    honor_platform_env()

    import jax

    from quorum_intersection_tpu.backends.cpp import CppOracleBackend
    from quorum_intersection_tpu.backends.tpu.frontier import TpuFrontierBackend
    from quorum_intersection_tpu.fbas.synth import hierarchical_fbas, majority_fbas
    from quorum_intersection_tpu.pipeline import solve

    device = jax.devices()[0].device_kind
    print(f"device: {device}\n")

    workloads = (
        [("majority-14", majority_fbas(14))] if args.quick
        else [("majority-18", majority_fbas(18)), ("hier-6x4", hierarchical_fbas(6, 4))]
    )
    pops = [256, 1024] if args.quick else [512, 2048, 8192]

    print("| workload | pop | native (s) | frontier (s) | states/s | states | iters | first-chunk (s) | steady (s) |")
    print("|---|---|---|---|---|---|---|---|---|")
    for name, data in workloads:
        t0 = time.perf_counter()
        cpp_res = solve(data, backend=CppOracleBackend())
        cpp_s = time.perf_counter() - t0
        for pop in pops:
            t0 = time.perf_counter()
            res = solve(data, backend=TpuFrontierBackend(pop=pop))
            fr_s = time.perf_counter() - t0
            ok = res.intersects == cpp_res.intersects
            st = res.stats
            rate = st["states_popped"] / fr_s if fr_s > 0 else 0
            flag = "" if ok else " **INVALID**"
            print(
                f"| {name} | {pop} | {cpp_s:.3f} | {fr_s:.3f}{flag} | "
                f"{rate:,.0f} | {st['states_popped']} | {st['device_iters']} | "
                f"{st.get('first_chunk_seconds')} | {st.get('chunk_seconds')} |"
            )
            print(json.dumps({
                "workload": name, "pop": pop, "device": device,
                "cpp_seconds": round(cpp_s, 4),
                "frontier_seconds": round(fr_s, 4),
                "states_per_sec": round(rate, 1), "verdict_ok": ok,
                "stats": {k: v for k, v in st.items() if k != "backend"},
            }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
