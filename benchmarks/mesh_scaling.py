"""Weak-scaling sweep benchmark on the emulated multi-device CPU mesh
(VERDICT r2 §next-8).

Runs the SAME exhaustive sweep (safe majority k-of-n FBAS, 2^(n-1)
candidates) on 1/2/4/8-device candidate meshes and reports aggregate
throughput per configuration.

CAVEAT (recorded in the results file): the 8 "devices" are XLA
host-platform emulations sharing one host CPU's cores, so absolute scaling
here is bounded by host parallelism and scheduler noise — the point of the
table is (a) the sharded decomposition covers the full enumeration at every
width with verdict parity and (b) throughput does not *degrade* as devices
are added (the collective/orchestration overhead stays negligible).  Real
ICI scaling needs a physical multi-chip slice, which this environment does
not expose (single tunneled chip).

Usage::

    python benchmarks/mesh_scaling.py [--nodes 21] [--out PATH]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

# Hard-pin the CPU emulation: this benchmark is specifically about the
# 8-emulated-device mesh, and the image's ambient JAX_PLATFORMS points at a
# tunneled chip that hangs when the tunnel is down (utils/platform.py).
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--nodes", type=int, default=21,
                        help="majority-FBAS size; enumeration = 2^(nodes-1)")
    parser.add_argument("--out", default=None,
                        help="results file (default benchmarks/results/mesh_scaling_cpu_r3.txt)")
    args = parser.parse_args()

    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    from quorum_intersection_tpu.backends.tpu.sweep import TpuSweepBackend
    from quorum_intersection_tpu.fbas.synth import majority_fbas
    from quorum_intersection_tpu.parallel.mesh import candidate_mesh
    from quorum_intersection_tpu.pipeline import solve

    data = majority_fbas(args.nodes)
    total = 1 << (args.nodes - 1)
    lines = [
        f"# Weak-scaling sweep: safe majority-{args.nodes} FBAS, "
        f"2^{args.nodes - 1} = {total} candidates, emulated CPU devices",
        "# CAVEAT: devices are host-platform emulations sharing one CPU; this",
        "# validates decomposition coverage + orchestration overhead, not ICI.",
        f"# host devices available: {len(jax.devices())}",
        "n_dev  seconds  cand/s_aggregate  cand/s_per_dev  verdict  checked",
    ]
    base_rate = None
    for n_dev in (1, 2, 4, 8):
        if n_dev > len(jax.devices()):
            lines.append(f"{n_dev:>5}  (skipped: only {len(jax.devices())} devices)")
            continue
        mesh = candidate_mesh(n_dev)
        t0 = time.perf_counter()
        res = solve(data, backend=TpuSweepBackend(mesh=mesh))
        seconds = time.perf_counter() - t0
        checked = res.stats["candidates_checked"]
        rate = checked / seconds
        if base_rate is None:
            base_rate = rate
        lines.append(
            f"{n_dev:>5}  {seconds:7.2f}  {rate:16.0f}  {rate / n_dev:14.0f}  "
            f"{str(res.intersects):>7}  {checked}"
        )
        assert res.intersects is True
        assert checked >= total
    lines.append(f"# speedup 8-dev vs 1-dev: {rate / base_rate:.2f}x")

    out = args.out or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "results", "mesh_scaling_cpu_r3.txt"
    )
    os.makedirs(os.path.dirname(out), exist_ok=True)
    with open(out, "w") as f:
        f.write("\n".join(lines) + "\n")
    print("\n".join(lines))
    print(f"\nwritten: {out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
