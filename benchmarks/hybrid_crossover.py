"""Device-search-vs-native-oracle crossover benchmark (VERDICT r1
§next-2; the file name survives the r5 retirement of the hybrid engine it
was born to measure, keeping artifact lineage crossover_*_r1-r5 intact).

Measures end-to-end time-to-verdict of the device-resident frontier
against the native C++ oracle on pruned-search workloads: safe
hierarchical networks and safe majority networks (the B&B worst case).
Emits a markdown table (for the README) and a JSON line per row; the
win-region rows (--large) carry their frontier config + minimal-quorum
count parity and gate auto's routing (backends/calibration.py).

The verdicts must agree row-by-row or the row is marked INVALID — a perf
number for a wrong answer is worthless.

Usage::

    JAX_PLATFORMS=cpu python benchmarks/hybrid_crossover.py --quick  # smoke
    python benchmarks/hybrid_crossover.py                            # real chip
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))


def workloads(quick: bool):
    """Safe networks where the full minimal-quorum enumeration is tractable.

    NB the search cost on safe networks grows exponentially with the SCC —
    a safe 36-node hierarchical network already enumerates ~129k minimal
    quorums at ~16 fixpoints each and takes the NATIVE oracle minutes
    (measured: hier-6x4 = 1M B&B calls = 1.4 s single-core; each +1 org
    multiplies by ~9).  These sizes keep both sides within CI budgets; the
    crossover story extrapolates from the per-fixpoint costs they expose.
    """
    from quorum_intersection_tpu.fbas.synth import hierarchical_fbas, majority_fbas

    rows = [
        ("majority-14", majority_fbas(14), 14),
        ("hier-5x3 (scc 15)", hierarchical_fbas(5, 3), 15),
    ]
    if not quick:
        rows += [
            ("majority-16", majority_fbas(16), 16),
            ("majority-18", majority_fbas(18), 18),
            ("hier-6x4 (scc 24)", hierarchical_fbas(6, 4), 24),
        ]
    return rows


def large_workloads():
    """The frontier win-region sizes (VERDICT r4 §next-1): native cost
    grows ~9× per org (hier-7x4 ≈ 30 s, hier-8x4 ≈ 4.5 min single-core),
    so these rows are opt-in (--large)."""
    from quorum_intersection_tpu.fbas.synth import hierarchical_fbas

    return [
        ("hier-7x4 (scc 28)", hierarchical_fbas(7, 4), 28),
        ("hier-8x4 (scc 32)", hierarchical_fbas(8, 4), 32),
    ]


def time_solve(data, backend) -> tuple:
    from quorum_intersection_tpu.pipeline import solve

    t0 = time.perf_counter()
    res = solve(data, backend=backend)
    return time.perf_counter() - t0, res


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--quick", action="store_true")
    parser.add_argument("--large", action="store_true",
                        help="add hier-7x4/8x4 frontier-vs-native rows "
                             "(native alone is 30 s + ~4.5 min single-core)")
    parser.add_argument("--large-only", action="store_true",
                        help="skip the standard (small) rows; implies --large "
                             "— for re-measuring win-region rows under a "
                             "different frontier config")
    parser.add_argument("--pop", type=int, default=None,
                        help="frontier pop-block override for the large rows")
    parser.add_argument("--flag-check", choices=("auto", "device", "host"),
                        default="auto",
                        help="frontier flag pipeline for the large rows "
                             "(device reproduces the CPU-emulation numbers "
                             "in docs/ROUND4_NOTES.md on a cpu platform)")
    args = parser.parse_args()

    from quorum_intersection_tpu.utils.platform import honor_platform_env

    honor_platform_env()

    import jax

    from quorum_intersection_tpu.backends.cpp import CppOracleBackend
    from quorum_intersection_tpu.backends.tpu.frontier import TpuFrontierBackend

    device = jax.devices()[0].device_kind
    print(f"device: {device}\n")
    print("| workload | native C++ (s) | frontier (s) | frontier speedup | frontier states | flagged |")
    print("|---|---|---|---|---|---|")
    if args.large_only:
        args.large = True
    for name, data, scc in ([] if args.large_only else workloads(args.quick)):
        cpp_s, cpp_res = time_solve(data, CppOracleBackend())
        fr_s, fr_res = time_solve(data, TpuFrontierBackend())
        ok = (cpp_res.intersects == fr_res.intersects)
        speed = cpp_s / fr_s if fr_s > 0 else float("inf")
        flag = "" if ok else " **INVALID: verdict mismatch**"
        print(
            f"| {name} | {cpp_s:.3f} | {fr_s:.3f} | {speed:.2f}x{flag} | "
            f"{fr_res.stats.get('states_popped')} | {fr_res.stats.get('flagged')} |"
        )
        print(json.dumps({
            "workload": name, "scc": scc, "device": device,
            "cpp_seconds": round(cpp_s, 4),
            "frontier_seconds": round(fr_s, 4),
            "frontier_speedup_vs_cpp": round(speed, 3), "verdict_ok": ok,
            "frontier_stats": {k: v for k, v in fr_res.stats.items() if k != "backend"},
            "cpp_bnb_calls": cpp_res.stats.get("bnb_calls"),
        }))

    if args.large:
        frontier_kw = {"flag_check": args.flag_check}
        if args.pop is not None:
            frontier_kw["pop"] = args.pop
        for name, data, scc in large_workloads():
            cpp_s, cpp_res = time_solve(data, CppOracleBackend())
            fr_s, fr_res = time_solve(data, TpuFrontierBackend(**frontier_kw))
            ok = cpp_res.intersects == fr_res.intersects
            # Enumeration completeness, not just the verdict: count parity
            # is the evidence these rows exist for.
            counts_ok = (
                cpp_res.stats.get("minimal_quorums")
                == fr_res.stats.get("minimal_quorums")
            )
            speed = cpp_s / fr_s if fr_s > 0 else float("inf")
            flag = "" if (ok and counts_ok) else " **INVALID**"
            print(
                f"| {name} | {cpp_s:.3f} | {fr_s:.3f} | {speed:.2f}x{flag} | "
                f"{fr_res.stats.get('states_popped')} | {fr_res.stats.get('flagged')} |"
            )
            print(json.dumps({
                "workload": name, "scc": scc, "device": device,
                "cpp_seconds": round(cpp_s, 4),
                "frontier_seconds": round(fr_s, 4),
                "frontier_speedup_vs_cpp": round(speed, 3),
                "verdict_ok": ok, "counts_ok": counts_ok,
                # Machine-readable config: the calibration module only
                # routes wins together with the kwargs they were measured
                # under (backends/calibration.py _frontier_win_min_scc).
                "frontier_kw": frontier_kw,
                "frontier_stats": {k: v for k, v in fr_res.stats.items()
                                   if k != "backend"},
                "cpp_bnb_calls": cpp_res.stats.get("bnb_calls"),
            }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
