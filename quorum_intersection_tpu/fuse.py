"""qi-fuse: cross-request pack fusion at the serve drain (ISSUE 16).

The sweep's cost is enumeration of an NP-hard window space, so the serve
tier only gets faster per-verdict by filling every compiled tile.  Lane
packing (ISSUE 5) fuses K SCC-restricted circuits into one block-diagonal
MXU tile and qi-query (ISSUE 12) lane-packs what-if variants — but the
drain loop still dispatched each request's batch separately, so mixed
traffic showed the device many partially-filled tiles.

:class:`BatchFormer` closes that gap: drain workers from DIFFERENT
requests submit their window work (plain intersection SCCs, what-if
masked variants) and block; the former accumulates units until the
estimated lane tile fills, every registered producer is already waiting
(no more work can arrive), or a deadline-aware timer fires — then ONE
elected producer flushes the whole accumulation as a single
``check_many`` call, whose lane packer sees all requests' circuits at
once.  Results split back per submission in order; each contributing
request keeps its own :class:`~.backends.base.CancelToken`, so a lane
whose request died retires via the sweep's per-group dead-lane machinery
without invalidating co-packed work (the cancelled request's ledger books
the unswept remainder exactly — see docs/PARITY.md §Fusion invariants).

The former is a pure meeting point: it never inspects verdicts and never
reorders a request's own sources, which is why the fused path stays
byte-identical per request to the unfused one (modulo shared-batch
provenance).  Fusion is an optimization, never a precondition for a
verdict — the ``serve.fuse`` fault point degrades the drain in place to
the unfused per-batch path.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence

from quorum_intersection_tpu.backends.base import CancelToken
from quorum_intersection_tpu.encode.circuit import LANE_TILE, ladder_up
from quorum_intersection_tpu.utils.telemetry import get_run_record

# Deterministic-interleaving hook (tools/analyze/schedules.py): the race
# harness swaps in a SyncController to force orderings like a flush
# taking off while a late submit is still queueing.  Production: no-op.
_fuse_sync: Callable[[str], None] = lambda point: None  # noqa: E731

# Hook points, in call order: a producer entering submit
# ("fuse.submit"), the elected flusher the moment it owns a formed batch
# ("fuse.flush.formed"), and the flusher after results are distributed
# ("fuse.flush.done").  All fire OUTSIDE the former's lock.
_POINT_SUBMIT = "fuse.submit"
_POINT_FORMED = "fuse.flush.formed"
_POINT_DONE = "fuse.flush.done"


@dataclass
class FuseUnit:
    """One producer's submission: a request's sources awaiting a flush."""

    sources: List[object]
    origin: str
    cancel: Optional[CancelToken] = None
    # Latest monotonic time this unit may still be HELD in the former —
    # the deadline-aware half of the flush timer.  None: no deadline.
    deadline_t: Optional[float] = None
    lanes: int = 0
    ready: threading.Event = field(default_factory=threading.Event)
    results: Optional[List[object]] = None
    error: Optional[BaseException] = None


def estimate_lanes(source: object, lane_tile: int = LANE_TILE) -> int:
    """Upper-bound lane estimate for one source: the pad-ladder rung of
    its node count (the pack planner can never use more lanes for it than
    it has nodes, rounded up to a compiled shape), capped at one tile.
    Opaque sources (raw JSON text) estimate a full tile — conservative:
    they flush immediately rather than holding a tile they might not
    fill."""
    nodes = getattr(source, "nodes", None)
    if nodes is None:
        return lane_tile
    return min(ladder_up(max(len(nodes), 1)), lane_tile)


class BatchFormer:
    """Accumulate window work from different requests into shared packs.

    ``check_many_fn(sources, cancels, origins)`` is the underlying batch
    solve — in the serve drain it closes over the engine's backend and
    threads per-source cancel tokens and request-id origins down to the
    lane packer (``pipeline.check_many`` → ``check_sccs``).

    Producer protocol::

        former.register()
        try:
            results = former.submit(sources, origin=req_id, cancel=tok)
        finally:
            former.done()

    ``submit`` blocks until the unit's flush lands and returns this
    unit's results, in submission order.  Flush fires on the FIRST of:

    - **full** — pending lane estimates fill the tile;
    - **drain** — every registered producer is blocked in ``submit`` (no
      more work can arrive this round, waiting is pure latency);
    - **timer** — the oldest pending unit has waited ``window_ms``;
    - **deadline** — a pending unit's ``deadline_t`` is earlier than the
      timer would fire.

    Exactly one blocked producer is elected flusher; the rest keep
    waiting on their unit.  A flush failure fans the exception out to
    every unit it carried (each producer re-raises in its own frame).
    """

    def __init__(
        self,
        check_many_fn: Callable[
            [List[object], List[Optional[CancelToken]], List[str]],
            List[object],
        ],
        *,
        window_ms: float,
        lane_tile: int = LANE_TILE,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self._fn = check_many_fn
        self.window_s = max(float(window_ms), 0.0) / 1000.0
        self.lane_tile = lane_tile
        self._clock = clock
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._pending: List[FuseUnit] = []
        self._first_pending_t: Optional[float] = None
        self._producers = 0
        self._waiting = 0
        self._flushing = False
        # Flush reasons in order, for tests and the drain's span attrs.
        self.flush_log: List[str] = []

    # ---- producer lifecycle ----------------------------------------------

    def register(self) -> None:
        """Announce a producer that WILL submit (or call :meth:`done`):
        the drain counts its per-entry workers in, so the former knows
        when everyone is already waiting and holding longer is pointless."""
        with self._cond:
            self._producers += 1

    def done(self) -> None:
        """Producer finished (its submits all returned, or it had no
        work).  May unblock a drain flush."""
        with self._cond:
            self._producers = max(self._producers - 1, 0)
            self._cond.notify_all()

    # ---- submission -------------------------------------------------------

    def submit(
        self,
        sources: Sequence[object],
        *,
        origin: str,
        cancel: Optional[CancelToken] = None,
        deadline_t: Optional[float] = None,
    ) -> List[object]:
        """Queue this request's sources and block until their flush lands.

        Returns this unit's results (aligned with ``sources``).  Raises
        whatever the underlying batch solve raised, in every contributing
        producer's frame."""
        _fuse_sync(_POINT_SUBMIT)
        unit = FuseUnit(
            sources=list(sources), origin=origin, cancel=cancel,
            deadline_t=deadline_t,
            lanes=sum(estimate_lanes(s, self.lane_tile) for s in sources),
        )
        batch: Optional[List[FuseUnit]] = None
        reason = ""
        with self._cond:
            self._pending.append(unit)
            if self._first_pending_t is None:
                self._first_pending_t = self._clock()
            self._cond.notify_all()
            self._waiting += 1
            try:
                while not unit.ready.is_set():
                    reason = self._flush_reason_locked()
                    if reason and not self._flushing:
                        self._flushing = True
                        batch = self._pending
                        self._pending = []
                        self._first_pending_t = None
                        break
                    self._cond.wait(self._wait_timeout_locked())
            finally:
                self._waiting -= 1
        if batch is not None:
            self._flush(batch, reason)
        unit.ready.wait()
        if unit.error is not None:
            raise unit.error
        assert unit.results is not None
        return unit.results

    # ---- flush machinery --------------------------------------------------

    def _flush_reason_locked(self) -> str:
        if not self._pending:
            return ""
        if sum(u.lanes for u in self._pending) >= self.lane_tile:
            return "full"
        if self._producers > 0 and self._waiting >= self._producers:
            return "drain"
        now = self._clock()
        timer_t = (
            self._first_pending_t + self.window_s
            if self._first_pending_t is not None else None
        )
        deadline_t = min(
            (u.deadline_t for u in self._pending if u.deadline_t is not None),
            default=None,
        )
        if deadline_t is not None and (timer_t is None or deadline_t < timer_t):
            if now >= deadline_t:
                return "deadline"
        elif timer_t is not None and now >= timer_t:
            return "timer"
        return ""

    def _wait_timeout_locked(self) -> Optional[float]:
        """Seconds until the earliest timed flush trigger, or None (wait
        for a notify) when nothing is pending."""
        if not self._pending or self._first_pending_t is None:
            return None
        fire_t = self._first_pending_t + self.window_s
        for u in self._pending:
            if u.deadline_t is not None:
                fire_t = min(fire_t, u.deadline_t)
        return max(fire_t - self._clock(), 0.0)

    def _flush(self, batch: List[FuseUnit], reason: str) -> None:
        _fuse_sync(_POINT_FORMED)
        rec = get_run_record()
        sources: List[object] = []
        cancels: List[Optional[CancelToken]] = []
        origins: List[str] = []
        for u in batch:
            sources.extend(u.sources)
            cancels.extend([u.cancel] * len(u.sources))
            origins.extend([u.origin] * len(u.sources))
        rec.event(
            "fuse.flush", reason=reason, units=len(batch),
            requests=len(set(origins)), lanes=sum(u.lanes for u in batch),
        )
        try:
            results = self._fn(sources, cancels, origins)
            if len(results) != len(sources):
                raise RuntimeError(
                    f"fused solve returned {len(results)} results for "
                    f"{len(sources)} sources"
                )
            at = 0
            for u in batch:
                u.results = list(results[at:at + len(u.sources)])
                at += len(u.sources)
        except BaseException as exc:  # noqa: BLE001 — fan out to every unit
            for u in batch:
                if u.results is None:
                    u.error = exc
        finally:
            with self._cond:
                self._flushing = False
                self.flush_log.append(reason)
                for u in batch:
                    u.ready.set()
                self._cond.notify_all()
            _fuse_sync(_POINT_DONE)
