"""quorum_intersection_tpu — a TPU-native framework for deciding the
quorum-intersection property of Federated Byzantine Agreement Systems.

Capability-equivalent to the reference C++ tool ``fixxxedpoint/quorum_intersection``
(see /root/reference/quorum_intersection.cpp), re-designed TPU-first:

- ``fbas``      — stellarbeat JSON frontend, trust graph, Tarjan SCC
- ``encode``    — nested quorum sets flattened into dense threshold-circuit arrays
- ``backends``  — pluggable QuorumChecker backends: pure-Python oracle, native C++
                  oracle, and the JAX/TPU batched-bitmask engine
- ``analytics`` — PageRank power iteration + Graphviz export with SCC coloring
- ``parallel``  — device-mesh / sharding helpers for the candidate-sweep axis
- ``utils``     — logging, run-record telemetry (spans/counters/events, one
                  schema from parse to chip — docs/OBSERVABILITY.md), phase
                  timers, throughput counters, sweep checkpointing
"""

__version__ = "0.1.0"

from quorum_intersection_tpu.fbas.schema import QSet, FbasNode, Fbas, parse_fbas
from quorum_intersection_tpu.fbas.graph import TrustGraph, build_graph
from quorum_intersection_tpu.encode.circuit import Circuit, encode_circuit

__all__ = [
    "QSet",
    "FbasNode",
    "Fbas",
    "parse_fbas",
    "TrustGraph",
    "build_graph",
    "Circuit",
    "encode_circuit",
    "__version__",
]
