"""Command-line interface — flag-compatible superset of the reference CLI
(`/root/reference/quorum_intersection.cpp:744-800`).

Contract parity (SURVEY.md §2.2):

- input is always **stdin**, output always stdout; no file arguments;
- default mode decides quorum intersection and prints ``true``/``false``
  (cpp:790-797), exiting 0 iff intersecting else 1;
- ``-p/--pagerank`` switches to PageRank mode, always exit 0 (cpp:784-788);
- ``-g/--graph`` dumps the SCC-colored Graphviz digraph *before* the verdict
  (cpp:635-637), which still runs;
- ``-v/--verbose`` narrates SCC/quorum findings; ``-t/--trace`` enables
  trace-level logging;
- ``-i/--max_iterations``, ``-m/--dangling_factor``, ``-c/--convergence``
  tune PageRank (defaults 100000 / 0.0001 / 0.0001, cpp:746-765);
- an invalid option prints ``Invalid option!`` plus usage and exits 1
  (cpp:771-775); ``-h/--help`` prints usage and exits 0.

Superset flags (this framework only): ``--backend``, ``--dangling-policy``,
``--scc-select``, ``--scope-scc``, ``--seed``, ``--randomized``, ``--compat``
(reference-bug-compatible shorthand: alias0 dangling + front SCC selection),
``--timing``, ``--no-race`` (sequential auto routing instead of the racing
orchestrator), ``--checkpoint`` (sweep resume), ``--profile-dir`` (jax
profiler trace), ``--metrics-json``/``--metrics-prom`` (run-record telemetry
sinks — docs/OBSERVABILITY.md).

Subcommands (this framework only): ``serve`` — the long-lived
snapshot-stream serving layer (``serve.py``, README §Serving): one JSON
request per stdin line, one JSON response per stdout line, with admission
control, deadlines, load shedding and a crash-only request journal;
``fleet`` — the replicated serve tier (``fleet.py``, README §Fleet): the
same JSONL contract fanned across N serve workers behind a
consistent-hash front door with journal-backed failover; and ``query`` —
a one-shot typed query (``query.py``, README §Queries): relaxed
two-family intersection, what-if removal sweeps, or analytics over a
snapshot on stdin (the same kinds the serve/fleet protocols accept via
the ``"query"`` request field).
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from quorum_intersection_tpu.utils.logging import get_logger, set_trace

log = get_logger("cli")


class _RefCompatParser(argparse.ArgumentParser):
    """argparse with the reference's error contract: ``Invalid option!`` +
    usage on stderr, exit code 1 (cpp:771-775)."""

    def error(self, message: str) -> None:  # type: ignore[override]
        # The reference writes both to cout (cpp:772-774).
        sys.stdout.write("Invalid option!\n")
        self.print_help(sys.stdout)
        raise SystemExit(1)


def build_parser() -> argparse.ArgumentParser:
    p = _RefCompatParser(
        prog="quorum_intersection_tpu",
        description=(
            "Decide the quorum-intersection property of a Stellar FBAS "
            "(stellarbeat /nodes/raw JSON on stdin)."
        ),
        add_help=False,
    )
    p.add_argument("--help", "-h", action="help", help="produce help message")
    p.add_argument("--verbose", "-v", action="store_true", help="print info about the analyzed configuration")
    p.add_argument("--graph", "-g", action="store_true", help="print graphviz representation of the configuration")
    p.add_argument("--trace", "-t", action="store_true", help="print debug information")
    p.add_argument("--pagerank", "-p", action="store_true", help="compute PageRank of the trust graph instead")
    p.add_argument("--max_iterations", "-i", type=int, default=100000, metavar="N",
                   help="maximal number of PageRank iterations (default 100000)")
    p.add_argument("--dangling_factor", "-m", type=float, default=0.0001, metavar="F",
                   help="PageRank dangling factor (default 0.0001)")
    p.add_argument("--convergence", "-c", type=float, default=0.0001, metavar="F",
                   help="PageRank convergence threshold (default 0.0001)")
    # --- superset flags ---
    p.add_argument("--backend", default="auto",
                   choices=["auto", "python", "cpp", "tpu", "tpu-sweep",
                            "tpu-frontier"],
                   help="disjoint-quorum search backend (default auto)")
    p.add_argument("--dangling-policy", default=None, choices=["strict", "alias0"],
                   help="unknown validator refs: strict=never available (default), "
                        "alias0=reference-compatible aliasing to vertex 0 (Q1)")
    p.add_argument("--scc-select", default=None, choices=["quorum-bearing", "front"],
                   help="which SCC to search: the quorum-bearing one (default, Q5 fix) "
                        "or Tarjan component 0 like the reference")
    p.add_argument("--scope-scc", action="store_true",
                   help="scope availability to the searched SCC (principled; default "
                        "reproduces the reference's whole-graph availability, Q6)")
    p.add_argument("--seed", type=int, default=None,
                   help="seed for the randomized branching tie-break (implies --randomized)")
    p.add_argument("--randomized", action="store_true",
                   help="use the reference's randomized branching tie-break instead of "
                        "the deterministic lowest-index rule")
    p.add_argument("--compat", action="store_true",
                   help="reference-bug-compatible mode: --dangling-policy alias0 --scc-select front")
    p.add_argument("--timing", action="store_true",
                   help="print phase timers (and the telemetry summary) to stderr")
    p.add_argument("--metrics-json", metavar="PATH", default=None,
                   help="stream run-record telemetry (spans, counters, "
                        "events — docs/OBSERVABILITY.md) to PATH as JSONL; "
                        "render with tools/metrics_report.py")
    p.add_argument("--metrics-prom", metavar="PATH", default=None,
                   help="write final counters/gauges to PATH as a "
                        "Prometheus-style textfile (node_exporter textfile "
                        "collector format) for soak runs")
    p.add_argument("--trace-out", metavar="PATH", default=None,
                   help="append the run's spans/events to PATH as "
                        "Chrome/Perfetto trace-event JSON (one causal "
                        "timeline incl. both race arms and every ladder "
                        "rung; open in ui.perfetto.dev — "
                        "docs/OBSERVABILITY.md); env twin: QI_TRACE_OUT")
    p.add_argument("--cert-out", metavar="PATH", default=None,
                   help="write the qi-cert/1 verdict certificate to PATH: "
                        "witness pair + per-member slice evidence for "
                        "false, the search-coverage ledger for true, "
                        "provenance always — independently re-validated "
                        "by tools/check_cert.py against the raw input "
                        "(docs/OBSERVABILITY.md §Certificates)")
    p.add_argument("--no-race", action="store_true",
                   help="disable the auto backend's racing orchestrator "
                        "(budgeted oracle vs concurrent sweep spin-up, first "
                        "verdict wins): run the sequential oracle-then-sweep "
                        "chain instead — identical verdicts, no background "
                        "device contact")
    p.add_argument("--checkpoint", metavar="PATH", default=None,
                   help="checkpoint file for long searches (sweep position or "
                        "frontier state): progress is recorded there and an "
                        "interrupted run resumes instead of restarting")
    p.add_argument("--profile-dir", metavar="DIR", default=None,
                   help="record a jax profiler trace of the solve into DIR "
                        "(open with TensorBoard/XProf)")
    p.add_argument("--mesh", metavar="N", default=None,
                   help="shard the device search across N devices ('all' = every "
                        "visible device); applies to auto/tpu/tpu-sweep/"
                        "tpu-frontier")
    p.add_argument("--blocking-set", action="store_true",
                   help="liveness-resilience mode: print a minimal blocking set of "
                        "the quorum-bearing SCC (node failures that halt consensus) "
                        "instead of the intersection verdict")
    p.add_argument("--splitting-set", action="store_true",
                   help="safety-margin mode: print a minimum splitting set (node "
                        "deletions that leave two disjoint quorums) up to "
                        "--splitting-max-k members, instead of the verdict")
    p.add_argument("--splitting-max-k", type=int, default=2, metavar="K",
                   help="splitting-set search depth (subsets up to size K; each "
                        "candidate is a full NP-hard solve — default 2)")
    p.add_argument("--top-tier", action="store_true",
                   help="analysis mode: print the top tier (union of all minimal "
                        "quorums' members — the validators that shape consensus) "
                        "instead of the verdict")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    from quorum_intersection_tpu.utils.platform import honor_platform_env

    honor_platform_env()
    arglist = sys.argv[1:] if argv is None else list(argv)
    if arglist and arglist[0] == "serve":
        # The long-lived serving layer (ISSUE 8): one JSON request per
        # stdin line, one JSON response per stdout line.  Dispatched before
        # the reference-compatible parser because the one-shot contract
        # (stdin = ONE snapshot, exit code = verdict) does not apply to a
        # stream — serve.py owns its own flags and exit semantics.
        from quorum_intersection_tpu.serve import serve_main

        return serve_main(arglist[1:])
    if arglist and arglist[0] == "fleet":
        # The replicated serve tier (ISSUE 11): the same JSONL stream
        # contract as `serve`, fanned across N worker engines behind a
        # consistent-hash front door (fleet.py owns flags and exit
        # semantics, like serve above).
        from quorum_intersection_tpu.fleet import fleet_main

        return fleet_main(arglist[1:])
    if arglist and arglist[0] == "query":
        # One-shot typed query (ISSUE 12): relaxed two-family
        # intersection / what-if removal sweep / analytics over the
        # snapshot on stdin — the stream twin is the serve/fleet
        # protocols' "query" request field (query.py owns flags and exit
        # semantics, like serve above).
        from quorum_intersection_tpu.query import query_main

        return query_main(arglist[1:])
    parser = build_parser()
    args = parser.parse_args(arglist)

    if args.trace:
        set_trace(True)

    from quorum_intersection_tpu.utils import telemetry

    record = telemetry.get_run_record()
    if args.metrics_json:
        record.add_sink(telemetry.JsonlSink(args.metrics_json))
    if args.metrics_prom:
        record.add_sink(telemetry.PromFileSink(args.metrics_prom))
    if args.trace_out:
        record.add_sink(telemetry.ChromeTraceSink(args.trace_out))
    # Crash flight recorder (qi-trace): with QI_FLIGHT_RECORDER set, the
    # get_run_record() call above chained sys.excepthook, so any exception
    # escaping _main dumps the last-N telemetry ring exactly once before
    # the traceback prints — no catch-all needed here.
    try:
        return _main(args, record)
    finally:
        # One flush for every exit path (verdict, analysis modes, errors):
        # final counter/gauge lines land in the JSONL stream and the
        # Prometheus textfile is (re)written.
        record.finish()


def _main(args, record) -> int:
    dangling = args.dangling_policy or ("alias0" if args.compat else "strict")
    scc_select = args.scc_select or ("front" if args.compat else "quorum-bearing")

    if args.cert_out and (
        args.pagerank or args.top_tier or args.splitting_set or args.blocking_set
    ):
        # Analytics modes return before the solve that builds a certificate;
        # reject loudly (same contract as --no-race / --checkpoint below)
        # rather than exiting 0 with the requested file never written.
        sys.stderr.write(
            "--cert-out applies to verdict mode only (certificates are not "
            "produced by --rank/--top-tier/--splitting-set/--blocking-set)\n"
        )
        return 1

    from quorum_intersection_tpu.fbas.schema import parse_fbas
    from quorum_intersection_tpu.fbas.graph import build_graph

    try:
        # Buffered (not streamed): the splitting-set mode re-reads the raw
        # node list, and dumps are at most a few MB.
        with record.span("phase.parse"):
            stdin_text = sys.stdin.read()
            fbas = parse_fbas(stdin_text)
    except ValueError as exc:
        # FbasSchemaError and json.JSONDecodeError both derive from ValueError.
        # (The reference crashes with an uncaught ptree exception here; a clean
        # diagnostic + exit 1 is a deliberate improvement.)
        sys.stderr.write(f"invalid FBAS configuration: {exc}\n")
        return 1

    with record.span("phase.graph"):
        graph = build_graph(fbas, dangling=dangling)

    if args.pagerank:
        from quorum_intersection_tpu.analytics.pagerank import format_pagerank, pagerank_auto

        ranks, engine = pagerank_auto(
            graph,
            m=args.dangling_factor,
            convergence=args.convergence,
            max_iterations=args.max_iterations,
        )
        log.debug("pagerank engine: %s", engine)
        if args.timing:
            sys.stderr.write(f"[stats] pagerank_engine: {engine}\n")
        sys.stdout.write(format_pagerank(graph, ranks))
        return 0  # PageRank mode always exits 0 (cpp:787)

    if args.top_tier:
        from quorum_intersection_tpu.analytics.top_tier import top_tier
        from quorum_intersection_tpu.pipeline import quorum_bearing_sccs

        members: list = []
        quorum_count = 0
        exceeded = False
        bearing = quorum_bearing_sccs(graph)
        for _sid, scc in bearing:
            part, n_min = top_tier(graph, scc)
            if part is None:
                exceeded = True
                break
            members.extend(part)
            quorum_count += n_min
        if not bearing:
            sys.stdout.write("top tier: empty (no quorum exists)\n")
        elif exceeded:
            sys.stdout.write(
                "top tier: not computed (minimal-quorum enumeration exceeded "
                "its call budget)\n"
            )
        else:
            labels = " ".join(graph.label(v) for v in sorted(members))
            sys.stdout.write(
                f"top tier ({len(members)} nodes, {quorum_count} minimal "
                f"quorums): {labels}\n"
            )
        return 0

    if args.splitting_set:
        from quorum_intersection_tpu.analytics.splitting import (
            POOL_LIMIT,
            minimum_splitting_set,
        )
        from quorum_intersection_tpu.pipeline import quorum_bearing_sccs

        raw = json.loads(stdin_text)
        # Candidate pool from the graph already built under the user's
        # dangling policy — no second front-end pass.
        pool: list = []
        for _sid, scc in quorum_bearing_sccs(graph):
            pool.extend(graph.node_ids[v] for v in scc)
        if len(pool) > POOL_LIMIT:
            sys.stdout.write(
                f"splitting set: not computed (candidate pool {len(pool)} > {POOL_LIMIT})\n"
            )
            return 0
        split = minimum_splitting_set(
            raw, max_k=args.splitting_max_k, dangling=dangling, pool=pool
        )
        if split is None:
            sys.stdout.write(
                f"no splitting set with <= {args.splitting_max_k} nodes "
                "(network stays intersecting under any such deletion)\n"
            )
        elif not split:
            sys.stdout.write("minimum splitting set (0 nodes): already split\n")
        else:
            labels = " ".join(split)
            sys.stdout.write(
                f"minimum splitting set ({len(split)} nodes): {labels}\n"
            )
        return 0

    if args.blocking_set:
        from quorum_intersection_tpu.analytics.resilience import (
            EXACT_LIMIT,
            minimal_blocking_set,
            minimum_blocking_size,
        )
        from quorum_intersection_tpu.pipeline import quorum_bearing_sccs

        bearing = quorum_bearing_sccs(graph)
        if not bearing:
            sys.stdout.write("blocking set: none needed (no quorum exists)\n")
            return 0
        # Quorums in different SCCs are independent: halting the WHOLE
        # network means blocking every quorum-bearing SCC, so the minimal
        # set is the union of per-SCC minimal sets and the minimum size is
        # the sum of per-SCC minimums.
        blocking: list = []
        minimum_total: Optional[int] = 0
        for _sid, scc in bearing:
            part = minimal_blocking_set(graph, scc)
            blocking.extend(part)
            minimum = minimum_blocking_size(graph, scc, upper=len(part))
            minimum_total = (
                None if (minimum is None or minimum_total is None)
                else minimum_total + minimum
            )
        labels = " ".join(graph.label(v) for v in blocking)
        sys.stdout.write(f"minimal blocking set ({len(blocking)} nodes): {labels}\n")
        if minimum_total is not None:
            sys.stdout.write(f"minimum blocking size: {minimum_total}\n")
        else:
            sys.stdout.write(
                f"minimum blocking size: not computed (|scc| > {EXACT_LIMIT})\n"
            )
        return 0

    from quorum_intersection_tpu.backends.base import get_backend
    from quorum_intersection_tpu.pipeline import solve_graph

    backend_options = {}
    if args.backend in ("python", "cpp", "auto", "tpu") and (
        args.seed is not None or args.randomized
    ):
        backend_options = {"seed": args.seed, "randomized": True}
    if args.no_race:
        if args.backend not in ("auto", "tpu"):
            sys.stderr.write(
                "--no-race only applies to the auto router "
                "(--backend auto/tpu)\n"
            )
            return 1
        backend_options["race"] = False
    if args.checkpoint is not None:
        if args.backend not in ("auto", "tpu", "tpu-sweep",
                                "tpu-frontier"):
            sys.stderr.write(
                "--checkpoint requires a checkpoint-capable backend "
                "(auto/tpu/tpu-sweep/tpu-frontier)\n"
            )
            return 1
        from quorum_intersection_tpu.utils.checkpoint import (
            FrontierCheckpoint,
            SweepCheckpoint,
        )

        backend_options["checkpoint"] = (
            # Frontier snapshots record (toRemove, dontRemove) node lists;
            # the sweep records a scan position instead.
            FrontierCheckpoint(args.checkpoint)
            if args.backend == "tpu-frontier"
            else SweepCheckpoint(args.checkpoint)
        )
    if args.mesh is not None:
        if args.backend not in ("auto", "tpu", "tpu-sweep",
                                "tpu-frontier"):
            sys.stderr.write(
                "--mesh requires a device backend "
                "(auto/tpu/tpu-sweep/tpu-frontier)\n")
            return 1
        try:
            n_dev = None if args.mesh == "all" else int(args.mesh)
        except ValueError:
            sys.stderr.write(f"--mesh expects a device count or 'all', got {args.mesh!r}\n")
            return 1
        if n_dev is not None and n_dev < 1:
            sys.stderr.write(f"--mesh expects a positive device count, got {n_dev}\n")
            return 1
        try:
            from quorum_intersection_tpu.parallel.mesh import candidate_mesh

            backend_options["mesh"] = candidate_mesh(n_dev)
        except (ImportError, ValueError) as exc:
            # ValueError: more devices requested than visible; ImportError:
            # no jax — same clean one-line contract as backend construction.
            sys.stderr.write(f"--mesh {args.mesh}: {exc}\n")
            return 1
    try:
        backend = get_backend(args.backend, **backend_options)
    except (ImportError, ValueError) as exc:
        sys.stderr.write(f"backend {args.backend!r} unavailable: {exc}\n")
        return 1

    from quorum_intersection_tpu.utils.profiling import profile_trace

    with profile_trace(args.profile_dir):
        result = solve_graph(
            graph,
            backend=backend,
            verbose=args.verbose,
            out=sys.stdout,
            graphviz=args.graph,
            scc_select=scc_select,
            scope_to_scc=args.scope_scc,
        )

    if args.cert_out and result.cert is not None:
        from quorum_intersection_tpu.cert import write_certificate

        # A failed write downgrades to the cert.write_errors counter inside
        # write_certificate — the verdict below is never at stake.
        write_certificate(result.cert, args.cert_out)

    if args.timing:
        # Legacy lines first, byte-compatible with pre-telemetry builds
        # (docs/OBSERVABILITY.md); the run-record summary sink appends the
        # clearly-marked extra [telemetry] lines after them.
        for name, seconds in result.timers.items():
            sys.stderr.write(f"[timing] {name}: {seconds * 1000:.2f} ms\n")
        for key, value in result.stats.items():
            sys.stderr.write(f"[stats] {key}: {value}\n")
        from quorum_intersection_tpu.utils.telemetry import StderrSummarySink

        StderrSummarySink().finish(record)

    sys.stdout.write("true\n" if result.intersects else "false\n")
    return 0 if result.intersects else 1


def run() -> int:
    from quorum_intersection_tpu.utils.pipes import run_with_pipe_hygiene

    return run_with_pipe_hygiene(main)


if __name__ == "__main__":
    sys.exit(run())
