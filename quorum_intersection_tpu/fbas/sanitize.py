"""Configuration sanitizer — capability of the reference's
``fix_quorum_configurations.py`` (all 21 lines of it), made recursive and
dangling-aware.

The reference keeps a node iff its **top-level** quorum set satisfies
``len(validators) + len(innerQuorumSets) >= threshold``
(`/root/reference/fix_quorum_configurations.py:11-15`); it does not recurse
into inner sets and does not touch dangling validator references.  It also
crashes with a ``TypeError`` on any node whose ``quorumSet`` is ``null``
(verified against the reference's own ``correct.json``, which has 26 of them).

This sanitizer:

- treats a ``null``/empty quorum set as *sane* (such nodes are harmless —
  their slice is never satisfiable, SURVEY.md §2.3-Q2 — and real stellarbeat
  snapshots are full of them);
- by default checks sanity **recursively** (an inner set with
  ``threshold > members`` poisons its parent's slice just as surely);
- optionally also flags degenerate ``threshold == 0`` sets (unsatisfiable in
  the reference due to unsigned wraparound, SURVEY.md §2.3-Q3) and dangling
  validator references;
- ``compat=True`` reproduces the reference's exact filter (top-level only,
  ``>=`` check only), except that null-qset nodes are kept instead of crashing.

Usable as a stdin→stdout filter exactly like the reference::

    python -m quorum_intersection_tpu.fbas.sanitize < nodes.json > clean.json
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Iterable, List, Mapping, Optional, Set


def _qset_sane(q: Optional[Mapping], *, recursive: bool,
               flag_zero_threshold: bool) -> bool:
    if q is None or not q:
        return True  # null/empty qset: never satisfiable but harmless
    threshold = q.get("threshold")
    if isinstance(threshold, str):
        # schema.py accepts numeric strings (boost::property_tree stores
        # scalars as strings); the sanitizer must agree or it would silently
        # drop nodes the parser considers valid.
        try:
            threshold = int(threshold)
        except ValueError:
            return False
    if not isinstance(threshold, int) or isinstance(threshold, bool):
        return False
    validators = q.get("validators") or []
    inner = q.get("innerQuorumSets") or []
    if len(validators) + len(inner) < threshold:
        return False
    if flag_zero_threshold and threshold == 0:
        return False
    if recursive:
        return all(
            _qset_sane(iq, recursive=True, flag_zero_threshold=flag_zero_threshold)
            for iq in inner
        )
    return True


def dangling_refs(data: List[Mapping]) -> Set[str]:
    """All validator IDs referenced (at any depth) but not present as nodes."""
    known = {node.get("publicKey") for node in data}
    seen: Set[str] = set()

    def walk(q) -> None:
        if not q:
            return
        for v in q.get("validators") or []:
            if v not in known:
                seen.add(v)
        for iq in q.get("innerQuorumSets") or []:
            walk(iq)

    for node in data:
        walk(node.get("quorumSet"))
    return seen


def sanitize(
    data: List[Mapping],
    *,
    recursive: bool = True,
    flag_zero_threshold: bool = False,
    compat: bool = False,
) -> List[Mapping]:
    """Return the nodes whose quorum configuration is sane.

    ``compat=True`` → the reference's top-level-only ``members >= threshold``
    filter (`fix_quorum_configurations.py:11-12`), null-tolerant.
    """
    if compat:
        recursive = False
        flag_zero_threshold = False
    return [
        node
        for node in data
        if _qset_sane(
            node.get("quorumSet"),
            recursive=recursive,
            flag_zero_threshold=flag_zero_threshold,
        )
    ]


def main(argv: Optional[Iterable[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m quorum_intersection_tpu.fbas.sanitize",
        description="Drop FBAS nodes with insane quorum configurations (stdin → stdout).",
    )
    parser.add_argument(
        "--compat",
        action="store_true",
        help="reference-compatible filter: top-level threshold sanity only",
    )
    parser.add_argument(
        "--flag-zero-threshold",
        action="store_true",
        help="also drop nodes containing a threshold == 0 quorum set",
    )
    parser.add_argument(
        "--report-dangling",
        action="store_true",
        help="report dangling validator references on stderr",
    )
    args = parser.parse_args(list(argv) if argv is not None else None)

    try:
        data = json.load(sys.stdin)
        if not isinstance(data, list):
            raise ValueError(f"top level must be a JSON array, got {type(data).__name__}")
        if args.report_dangling:
            for ref in sorted(dangling_refs(data)):
                print(f"dangling validator reference: {ref}", file=sys.stderr)
        out = sanitize(
            data,
            compat=args.compat,
            flag_zero_threshold=args.flag_zero_threshold,
        )
    except RecursionError:
        # Deep nesting can surface in the json C scanner or in the recursive
        # sanity walks; either way the input is hostile, not a crash.
        sys.stderr.write("invalid FBAS configuration: JSON nesting too deep\n")
        return 1
    except (ValueError, AttributeError, TypeError) as exc:
        # Clean diagnostic + exit 1 on malformed stdin (the reference's
        # 21-line sanitizer tracebacks here).
        sys.stderr.write(f"invalid FBAS configuration: {exc}\n")
        return 1
    json.dump(out, sys.stdout)
    return 0


def run() -> int:
    from quorum_intersection_tpu.utils.pipes import run_with_pipe_hygiene

    return run_with_pipe_hygiene(main)


if __name__ == "__main__":
    sys.exit(run())
