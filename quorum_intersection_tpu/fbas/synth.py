"""Synthetic FBAS generators — the seed corpus for differential testing and
benchmarking (SURVEY.md §4.3, BASELINE.json configs).

All generators emit stellarbeat-style raw dicts (the same shape
:func:`quorum_intersection_tpu.fbas.schema.parse_fbas` accepts), so every
synthetic network also exercises the JSON frontend.

The generators follow the reference fixtures' de-facto test methodology —
*same topology, one knob turned* (SURVEY.md §4.1): each safe generator has a
broken twin differing by a single threshold.
"""

from __future__ import annotations

import copy
import json
import random
from collections import Counter
from typing import TYPE_CHECKING, Dict, List, Optional, Tuple

if TYPE_CHECKING:  # annotation-only: synth stays import-light at runtime
    from quorum_intersection_tpu.fbas.graph import TrustGraph


def _node(key: str, name: str, qset: Dict) -> Dict:
    return {"publicKey": key, "name": name, "quorumSet": qset}


def _qset(threshold: int, validators: List[str], inner: Optional[List] = None) -> Dict:
    return {
        "threshold": threshold,
        "validators": validators,
        "innerQuorumSets": inner or [],
    }


def keys(n: int, prefix: str = "NODE") -> List[str]:
    return [f"{prefix}{i:04d}" for i in range(n)]


def majority_fbas(n: int, *, broken: bool = False, prefix: str = "NODE") -> List[Dict]:
    """Symmetric k-of-n FBAS with k = n//2 + 1 — all quorums intersect.

    ``broken=True`` turns one knob, mirroring the reference's
    ``broken_trivial.json`` methodology (threshold 2→1 on one node,
    `broken_trivial.json:20`): node 0's threshold drops to 1, making {node0}
    a quorum disjoint from any majority of the remaining nodes.
    """
    ks = keys(n, prefix)
    k = n // 2 + 1
    nodes = []
    for i, key in enumerate(ks):
        t = 1 if (broken and i == 0) else k
        nodes.append(_node(key, f"n{i}", _qset(t, list(ks))))
    return nodes


def hierarchical_fbas(
    n_orgs: int, per_org: int, *, broken: bool = False, org_threshold: Optional[int] = None
) -> List[Dict]:
    """Stellar-like tiered FBAS: each node requires a majority of organizations,
    where an organization counts if a majority of its validators are available —
    expressed with one inner quorum set per organization (nesting depth 1,
    matching the bundled fixtures' observed max depth, SURVEY.md §7.3).

    ``broken=True`` gives the first node a degenerate self-only slice
    (threshold 1 over itself), making {node0} a quorum disjoint from the
    surviving org-majority quorum of everyone else.
    """
    org_keys = [keys(per_org, f"ORG{o}N") for o in range(n_orgs)]
    all_nodes: List[Dict] = []
    t_orgs = org_threshold if org_threshold is not None else n_orgs // 2 + 1
    inner = [_qset(per_org // 2 + 1, list(ok)) for ok in org_keys]
    for o in range(n_orgs):
        for i, key in enumerate(org_keys[o]):
            if broken and o == 0 and i == 0:
                all_nodes.append(_node(key, f"org{o}-v{i}", _qset(1, [key])))
            else:
                all_nodes.append(_node(key, f"org{o}-v{i}", _qset(t_orgs, [], list(inner))))
    return all_nodes


def trivial_pair() -> Dict[str, List[Dict]]:
    """Tiny 3-node pass/fail pair, structurally the same test idea as the
    reference's ``correct_trivial.json`` / ``broken_trivial.json`` (2-of-3
    majority; broken twin lowers one threshold to 1)."""
    return {
        "correct": majority_fbas(3, prefix="TRIV"),
        "broken": majority_fbas(3, broken=True, prefix="TRIV"),
    }


def stellar_like_fbas(
    n_core_orgs: int = 7,
    per_org: int = 3,
    n_watchers: int = 100,
    n_null: int = 28,
    n_dangling: int = 7,
    *,
    broken: bool = False,
    seed: int = 0,
) -> List[Dict]:
    """Stellarbeat-snapshot-shaped network (~150 validators with defaults).

    Mirrors the structural statistics of the bundled `correct.json` snapshot
    scaled up (SURVEY.md §4.1): a small strongly-connected core of
    organizations (the quorum-bearing sink SCC), a long tail of watcher
    nodes that trust the core but are not trusted back (many singleton
    SCCs), a block of null-quorumSet nodes, and a sprinkle of dangling
    validator references.  ``broken=True`` turns one knob in the core —
    org 0's validators drop their org-majority threshold to 1-of-{orgs}
    (trust edges unchanged, so the core SCC stays intact), making the org-0
    trio a quorum disjoint from the quorum of the remaining orgs: the
    search inside the SCC, not the SCC guard, must find it.
    """
    rng = random.Random(seed)
    org_keys = [keys(per_org, f"CORE{o}N") for o in range(n_core_orgs)]
    core_flat = [k for ok in org_keys for k in ok]
    inner = [_qset(per_org // 2 + 1, list(ok)) for ok in org_keys]
    t_orgs = n_core_orgs // 2 + 1
    nodes: List[Dict] = []
    for o in range(n_core_orgs):
        for i, key in enumerate(org_keys[o]):
            t = 1 if (broken and o == 0) else t_orgs
            nodes.append(_node(key, f"core{o}-v{i}", _qset(t, [], list(inner))))
    for w in range(n_watchers):
        trusted = rng.sample(core_flat, min(len(core_flat), rng.randint(3, 7)))
        extra = []
        if w < n_dangling:  # dangling refs concentrated in early watchers
            extra = [f"GONE{w:04d}"]
        t = len(trusted) * 2 // 3 + 1
        nodes.append(_node(f"WATCH{w:04d}", f"w{w}", _qset(t, trusted + extra)))
    for z in range(n_null):
        nodes.append(_node(f"NULLQ{z:04d}", f"z{z}", None))
    rng.shuffle(nodes)  # snapshot order is arbitrary; vertex 0 ≠ core
    return nodes


def benchmark_fbas(
    n_total: int,
    core: int,
    *,
    nested_watchers: bool = False,
    broken: bool = False,
    seed: int = 0,
) -> List[Dict]:
    """North-star verdict-benchmark network (BASELINE.json configs 4-5).

    A ``core``-node symmetric k-of-n majority (k = core//2 + 1 — the
    "k-of-n threshold slices" config) forms the quorum-bearing sink SCC;
    the remaining ``n_total - core`` nodes are a periphery of watchers
    trusting random core subsets, null-qset nodes, and a sprinkle of
    dangling refs — the structural shape of a stellarbeat snapshot
    (SURVEY.md §4.1) scaled to the BASELINE node counts.  The verdict
    therefore requires the full in-SCC disjointness search over the core
    (2^(core-1) candidate subsets), which is what the benchmark times.

    ``nested_watchers=True`` (the "1024-node FBAS with nested inner-sets"
    config) gives every watcher a two-level qset: an innerQuorumSet per
    sampled core pair plus direct validators.  ``broken=True`` turns one
    knob in the core (threshold → 1, the `broken_trivial.json:20`
    methodology) for differential twins.
    """
    if core < 3 or core > n_total:
        raise ValueError(f"need 3 <= core <= n_total, got core={core}, n_total={n_total}")
    rng = random.Random(seed)
    nodes = majority_fbas(core, broken=broken, prefix="CORE")
    core_keys = keys(core, "CORE")
    n_periph = n_total - core
    n_null = n_periph // 10
    n_dangling = min(n_periph // 32, 16)
    for w in range(n_periph - n_null):
        trusted = rng.sample(core_keys, min(core, rng.randint(4, 9)))
        if w < n_dangling:
            trusted = trusted + [f"GONE{w:04d}"]
        inner: List[Dict] = []
        if nested_watchers and len(trusted) >= 6:
            # Two-level slice: pairs of trusted core nodes become 1-of-2
            # inner sets (nesting depth 1 below the watcher's own qset).
            split = len(trusted) // 2
            inner = [
                _qset(1, [trusted[split + 2 * j], trusted[split + 2 * j + 1]])
                for j in range((len(trusted) - split) // 2)
            ]
            trusted = trusted[:split]
        t = (len(trusted) + len(inner)) * 2 // 3 + 1
        nodes.append(_node(f"WATCH{w:04d}", f"w{w}", _qset(t, trusted, inner)))
    for z in range(n_null):
        nodes.append(_node(f"NULLQ{z:04d}", f"z{z}", None))
    rng.shuffle(nodes)  # snapshot order is arbitrary; vertex 0 ≠ core
    return nodes


def near_disjoint_cores(
    core: int = 10,
    bridge: int = 1,
    *,
    broken: bool = False,
    seed: int = 0,
    prefix: str = "NDC",
) -> List[Dict]:
    """Adversarial preset (ISSUE 10, ROADMAP scenario diversity): two dense
    cores A and B joined by a THIN bridge — one SCC whose disjointness
    search has deep first-hit windows, exactly where rank-ordered windows
    and block-guard pruning shine.

    Topology (``2*core + bridge`` nodes, a single SCC):

    - ``a ∈ A``: 2-of-[majority-of-A, all-of-bridge] — a quorum touching A
      needs a majority of A AND every bridge node;
    - ``b ∈ B``: same with B (correct twin);
    - ``m ∈ bridge``: 2-of-[majority-of-A, majority-of-B] — the bridge
      pulls in majorities of BOTH cores, so in the correct twin every
      quorum contains the bridge and any two quorums intersect there.

    ``broken=True`` turns one knob on the B side: B's slice relaxes to
    1-of-[sub-majority-of-B, all-of-bridge] (``core // 2``-of-B suffices
    alone), so two disjoint sub-majority halves of B are both quorums —
    while the trust EDGES (and with them the single SCC) are unchanged, so
    the witness must be found by the search INSIDE the full SCC and hides
    deep in the enumeration (B's members are shuffled across the window
    bits; snapshot order is arbitrary).  Guard pruning shines on the
    correct twin: any block whose maximal candidate misses the bridge or
    either core's majority holds no quorum at all.  Same ``(core, bridge,
    seed)`` ⇒ byte-identical snapshot.
    """
    if core < 3 or bridge < 1:
        raise ValueError(
            f"need core >= 3 and bridge >= 1, got core={core}, bridge={bridge}"
        )
    rng = random.Random(seed)
    a_keys = keys(core, f"{prefix}A")
    b_keys = keys(core, f"{prefix}B")
    m_keys = keys(bridge, f"{prefix}M")
    maj = core // 2 + 1
    inner_a = _qset(maj, list(a_keys))
    inner_b = _qset(maj, list(b_keys))
    inner_m = _qset(bridge, list(m_keys))
    nodes: List[Dict] = []
    for key in a_keys:
        nodes.append(_node(key, f"a-{key}", _qset(2, [], [dict(inner_a), dict(inner_m)])))
    for key in b_keys:
        if broken:
            # One knob (the fixture-pair methodology): a sub-majority of B
            # ALONE satisfies the slice — two disjoint halves of B qualify
            # — but the bridge inner set (and its trust edges) stays, so
            # the SCC partition is identical to the correct twin's.
            nodes.append(_node(key, f"b-{key}", _qset(
                1, [], [_qset(max(core // 2, 1), list(b_keys)), dict(inner_m)]
            )))
        else:
            nodes.append(_node(key, f"b-{key}", _qset(2, [], [dict(inner_b), dict(inner_m)])))
    for key in m_keys:
        nodes.append(_node(key, f"m-{key}", _qset(2, [], [dict(inner_a), dict(inner_b)])))
    rng.shuffle(nodes)  # snapshot order is arbitrary; the witness bits spread
    return nodes


def nested_hierarchy(
    n_nodes: int,
    *,
    core_orgs: int = 5,
    per_org: int = 3,
    fanout: int = 6,
    orgs_per_level: int = 64,
    broken: bool = False,
    seed: int = 0,
) -> List[Dict]:
    """Scale preset (qi-query, ROADMAP scenario diversity): a nested
    multi-level org hierarchy that generates honestly at 10k+ nodes.

    Tier 0 is a ``core_orgs × per_org`` org-majority core (the
    quorum-bearing sink SCC, same structure as :func:`hierarchical_fbas`).
    Every later tier is organizations of ``per_org`` validators whose
    slice is a majority over ``fanout`` org inner sets sampled from the
    *previous* tier — nesting depth 2, trust flowing strictly rootward, so
    the tiers are watcher SCCs and the NP-hard search stays confined to
    the core while parse/graph/Tarjan/scan chew through the full node
    count (exactly the front-end load a 10k-node serving request costs).
    Tiers are capped at ``orgs_per_level`` orgs; generation stops at
    ``n_nodes`` (the final org may be partial).

    ``broken=True`` turns the one fixture-pair knob in the core (org 0's
    threshold → 1, the ``stellar_like_fbas`` methodology).  Same
    arguments ⇒ byte-identical snapshot (pinned by seed tests).
    """
    if n_nodes < core_orgs * per_org:
        raise ValueError(
            f"need n_nodes >= {core_orgs * per_org} for the core, "
            f"got {n_nodes}"
        )
    rng = random.Random(seed)
    core_org_keys = [keys(per_org, f"HIER0O{o}N") for o in range(core_orgs)]
    core_inner = [_qset(per_org // 2 + 1, list(ok)) for ok in core_org_keys]
    t_core = core_orgs // 2 + 1
    nodes: List[Dict] = []
    for o in range(core_orgs):
        for i, key in enumerate(core_org_keys[o]):
            t = 1 if (broken and o == 0) else t_core
            nodes.append(
                _node(key, f"t0-org{o}-v{i}", _qset(t, [], list(core_inner)))
            )
    prev_inner = core_inner
    level = 1
    while len(nodes) < n_nodes:
        level_inner: List[Dict] = []
        for o in range(orgs_per_level):
            if len(nodes) >= n_nodes:
                break
            org_keys = keys(per_org, f"HIER{level}O{o}N")
            picked = rng.sample(prev_inner, min(fanout, len(prev_inner)))
            t_up = len(picked) // 2 + 1
            slice_q = _qset(t_up, [], [dict(q) for q in picked])
            for i, key in enumerate(org_keys):
                if len(nodes) >= n_nodes:
                    break
                nodes.append(_node(key, f"t{level}-org{o}-v{i}", slice_q))
            level_inner.append(_qset(per_org // 2 + 1, org_keys))
        prev_inner = level_inner or prev_inner
        level += 1
    rng.shuffle(nodes)  # snapshot order is arbitrary; vertex 0 ≠ core
    return nodes


def two_family_preset(
    core: int = 9,
    watchers: int = 6,
    *,
    broken: bool = False,
    seed: int = 0,
) -> Tuple[List[Dict], List[Dict]]:
    """Adversarial two-family preset (qi-query relaxed mode, Fast Flexible
    Paxos arXiv:2008.02671): ``(family_a, family_b)`` — two quorum-set
    families over ONE node set in ONE vertex order (the relaxed query's
    parse-time contract).

    Family A is the *classic* family: ``k``-of-core majorities
    (``k = core // 2 + 1``).  Family B is the *fast* family: symmetric
    supermajority ``t``-of-core slices with ``t = 3·core//4 + 1`` in the
    correct twin — comfortably above the Fast Paxos safety bound
    ``k + t > core``, so every fast quorum meets every classic quorum.
    ``broken=True`` turns the one knob down to ``t = core - k``: a fast
    quorum of ``t`` core nodes can now dodge a classic quorum of the
    other ``k`` — a cross-family split that is INVISIBLE to family A's
    own single-family verdict (classic majorities still pairwise
    intersect), which is exactly what makes the preset adversarial: fast
    quorums need not intersect each other in Fast Paxos, only the
    cross-family overlap is safety-critical, so no per-family check can
    stand in for the relaxed query.  Watcher nodes (identical in both
    families) trust a core majority and pad the vertex space so the
    witness bits spread across the window order.  Same arguments ⇒
    byte-identical pair.
    """
    if core < 4:
        raise ValueError(f"need core >= 4, got {core}")
    rng = random.Random(seed)
    core_keys = keys(core, "TFC")
    k_classic = core // 2 + 1
    t_fast = (core - k_classic) if broken else (3 * core // 4 + 1)
    t_fast = max(t_fast, 1)
    order = list(range(core + watchers))
    rng.shuffle(order)  # one arbitrary vertex order shared by BOTH families

    def family(threshold: int) -> List[Dict]:
        out: List[Dict] = []
        for ix in order:
            if ix < core:
                key = core_keys[ix]
                out.append(_node(key, f"c{ix}", _qset(threshold, list(core_keys))))
            else:
                w = ix - core
                trusted = rng_w[w]
                out.append(_node(
                    f"TFW{w:04d}", f"w{w}",
                    _qset(len(trusted) // 2 + 1, trusted),
                ))
        return out

    rng_w = [
        rng.sample(core_keys, min(core, 4)) for _ in range(watchers)
    ]
    return family(k_classic), family(t_fast)


def sparse_giant(
    n_nodes: int = 10_000,
    *,
    broken: bool = False,
    seed: int = 7,
) -> List[Dict]:
    """Sparse-giant preset (qi-sparse ISSUE 20): the bench workload behind
    the dense-vs-bitset crossover row.

    A :func:`nested_hierarchy` instance sized so the DENSE block-diagonal
    sweep encoding is measurably memory/MAC-bound: ~10k nodes of watcher
    tiers over an 8-org × 3-validator core — a 24-node quorum-bearing SCC
    (2^23 sweep windows, enough device work that per-candidate arithmetic
    dominates setup) whose restricted member matrix is the sparse regime
    the bitset twin exists for (measured on CPU emulation: ~18x dense →
    bitset, benchmarks/results/).  ``broken=True`` is the usual one-knob
    twin (core org 0's threshold → 1, verdict flips to False).  Same
    arguments ⇒ byte-identical snapshot; the seed is pinned so committed
    crossover artifacts stay comparable across rounds.
    """
    return nested_hierarchy(
        n_nodes, core_orgs=8, per_org=3, fanout=6, orgs_per_level=64,
        broken=broken, seed=seed,
    )


def graph_density(graph: TrustGraph) -> Dict[str, float]:
    """Density/fanout annotation of a built :class:`TrustGraph` (qi-sparse
    ISSUE 20) — the workload-shape numbers the dense-vs-bitset routing and
    the ``--bitset`` bench rows report.

    ``edge_density`` is directed trust-edge fill ``edges / (n * (n-1))``
    (self-loops counted toward edges but not capacity, multiplicity
    preserved — the same edge semantics as ``TrustGraph.succ``);
    ``qset_fanout_*`` summarize per-node successor counts — the row count
    a node contributes to the dense member matrix vs the ~``n/32`` words
    the bitset encoding stores regardless of fanout.
    """
    n = graph.n
    fanouts = [len(s) for s in graph.succ]
    edges = sum(fanouts)
    return {
        "nodes": float(n),
        "edges": float(edges),
        "edge_density": (edges / (n * (n - 1))) if n > 1 else 0.0,
        "qset_fanout_mean": (edges / n) if n else 0.0,
        "qset_fanout_max": float(max(fanouts, default=0)),
        "qset_fanout_min": float(min(fanouts, default=0)),
    }


def scc_qset_density(graph: TrustGraph, scc: List[int]) -> float:
    """Member-matrix fill estimate of one SCC's restricted circuit (qi-sparse
    ISSUE 20): total in-SCC qset references / (qset units × |scc|).

    Walks every SCC node's qset tree counting units (the node slice plus
    each nested inner set) and references (in-SCC validators plus
    inner-unit links) — the graph-side approximation of
    ``nnz(members) / size`` of the dense encoding the sweep would build,
    cheap enough for auto's routing hot path (no circuit encode, no
    restriction).  A symmetric k-of-n core scores ~1.0 (every unit
    references every member — the dense-friendly regime); an org-nested
    core scores well under 0.2 (each inner set references its few members
    — the regime the bitset twin wins).  Dedup of shared inner units is
    deliberately NOT modeled: the estimate is a routing feature measured
    and consumed under the same definition (calibration
    ``bitset_win_max_density``), not a circuit-size claim.
    """
    sset = set(scc)
    units = 0
    refs = 0
    for v in scc:
        stack = [graph.qsets[v]]
        while stack:
            q = stack.pop()
            units += 1
            refs += sum(1 for m in q.members if m in sset)
            for inner in q.inner:
                refs += 1  # the parent's link to the inner unit
                stack.append(inner)
    denom = units * len(scc)
    return (refs / denom) if denom else 0.0


# The default churn mix (the three bounded mutations a live stellarbeat
# feed actually produces — see churn_trace_steps); the restructuring kinds
# scc_split / scc_merge are opt-in via ``kinds`` because they change the
# SCC partition itself, which most load-shaped consumers don't want.
CHURN_KINDS = ("threshold", "swap", "rename")

def _scc_partition(snapshot: List[Dict]) -> Tuple[List[int], List[str]]:
    """``(comp, keys)``: the snapshot's SCC component id per node (JSON
    order) and each node's publicKey — the ground truth churn annotations
    are expressed against.  Uses the real front end (parse → build →
    Tarjan) so annotations agree with what the pipeline will see."""
    from quorum_intersection_tpu.fbas.graph import build_graph, tarjan_scc
    from quorum_intersection_tpu.fbas.schema import parse_fbas

    fbas = parse_fbas(snapshot)
    graph = build_graph(fbas)
    _, comp = tarjan_scc(graph.n, graph.succ)
    return comp, list(graph.node_ids)


def _key_sets(comp: List[int], keys: List[str]) -> List[frozenset]:
    """One partition as member-publicKey sets (the ground-truth currency
    of the restructure annotations)."""
    groups: Dict[int, set] = {}
    for v, c in enumerate(comp):
        groups.setdefault(c, set()).add(keys[v])
    return [frozenset(g) for g in groups.values()]


def _zipf_pick(rng: random.Random, n: int, skew: float) -> int:
    """One draw from a truncated Zipf over ranks ``0..n-1``:
    ``P(r) ∝ 1/(r+1)^skew``.  Rank 0 is the hottest."""
    weights = [(r + 1) ** -skew for r in range(n)]
    total = sum(weights)
    x = rng.random() * total
    acc = 0.0
    for r, w in enumerate(weights):
        acc += w
        if x <= acc:
            return r
    return n - 1


def churn_trace_steps(
    base: List[Dict],
    steps: int,
    seed: int = 0,
    *,
    max_diff: int = 2,
    kinds: Tuple[str, ...] = CHURN_KINDS,
    annotate: bool = True,
    skew: float = 0.0,
) -> Tuple[List[List[Dict]], List[Dict]]:
    """Deterministic snapshot stream with **ground-truth step annotations**
    (qi-delta, ISSUE 9): ``(trace, metas)`` where ``trace`` has
    ``steps + 1`` consecutive snapshots starting at ``base`` and
    ``metas[k]`` describes the mutations that produced ``trace[k + 1]``:

    - ``mutations``: ``[{kind, node, scc_id}, ...]`` — each churned node's
      publicKey and its SCC id in the **predecessor** snapshot's partition
      (merge mutations list both touched nodes);
    - ``affected_scc_ids``: the predecessor-partition SCC ids whose
      structural fingerprint the step invalidated — empty for a pure
      cosmetic-rename step, so incremental tests can assert *exactly*
      which SCCs a delta engine must re-derive;
    - ``partition_changed`` / ``merges`` / ``splits``: whether the SCC
      partition itself restructured (computed independently of
      ``fbas/diff.py`` by comparing member-key sets, so the differ is
      tested against ground truth, not against itself).

    ``kinds`` selects the mutation mix.  Beyond the bounded trio
    (**threshold wobble**, **validator swap**, **cosmetic rename** — see
    :func:`churn_trace`), two restructuring kinds are available:

    - ``scc_merge``: the churned node and a node of another SCC add each
      other as validators — the 2-cycle merges their components;
    - ``scc_split``: a node of a multi-node SCC replaces its quorum set
      with a self-only slice (threshold 1 over itself), splitting off —
      the classic broken-config shape, so expect guard-decided verdicts.

    Either falls back to a threshold wobble when the partition offers no
    candidate (a single SCC to merge, no multi-node SCC to split).

    ``skew`` (qi-fleet, ISSUE 11) adds **zipfian temporal skew**: with
    ``skew > 0`` each emitted step either *advances* the underlying
    bounded-diff mutation chain (rank 0) or *re-emits* a recent chain
    snapshot byte-identically, with rank ``r`` (the r-th most recent)
    drawn ``P(r) ∝ 1/(r+1)^skew`` — the hot-key request distribution the
    fleet bench routes (``benchmarks/serve.py --fleet``: identical
    re-emissions are fleet-wide cache/coalesce hits, the advancing tail
    spreads across workers).  The skew draws come from a separate
    string-seeded RNG, so the mutation chain consumes exactly the same
    ``seed`` stream with or without revisits — ``skew=0.0`` (default) is
    **byte-identical** to the pre-skew generator.  Revisit metas are
    ``{"revisit_of": <trace index>, "mutations": []}`` with empty
    ``affected_scc_ids``: a revisit is a re-emission, not a bounded diff,
    so the per-mutation ground-truth fields do not apply to it.

    Same ``(base, steps, seed, max_diff, kinds, skew)`` ⇒ byte-identical
    trace and metas; annotation never consumes randomness, so
    ``annotate=False`` (what :func:`churn_trace` passes — load-shaped
    consumers pay no parse/Tarjan passes for metas they discard) and the
    default ``kinds`` yield a byte-identical trace with empty metas.
    Nodes with null quorum sets are never churned.  Each snapshot is a
    deep copy: mutating one never aliases another.
    """
    if steps < 0:
        raise ValueError(f"steps must be >= 0, got {steps}")
    if skew < 0:
        raise ValueError(f"skew must be >= 0, got {skew}")
    for kind in kinds:
        if kind not in ("threshold", "swap", "rename", "scc_split",
                        "scc_merge"):
            raise ValueError(f"unknown churn kind {kind!r}")
    rng = random.Random(seed)
    # Separate, string-seeded RNG for the skew draws (sha-based seeding,
    # deterministic across processes): the mutation chain consumes exactly
    # the same `rng` stream whether or not revisits interleave, so a
    # skew>0 trace shares its underlying chain with the skew=0 one.
    rng_skew = random.Random(f"qi-churn-skew:{seed}")
    trace = [copy.deepcopy(base)]
    metas: List[Dict] = []
    # The distinct mutation chain (head = next mutation's base) and each
    # chain snapshot's first trace index — revisit steps re-emit from here.
    chain: List[List[Dict]] = [trace[0]]
    chain_emit_ix: List[int] = [0]
    all_keys = [n.get("publicKey") for n in base if n.get("publicKey")]
    # Predecessor partition: the coordinate system of the annotations and
    # the candidate pool for merge/split.  Computed once per snapshot and
    # carried forward — each step's successor partition (needed for the
    # restructure ground truth anyway) IS the next step's predecessor, so
    # the front end runs once per snapshot, not twice per step — and not
    # at all when nothing needs it (annotate=False with the bounded trio,
    # whose mutations never consult the partition).
    needs_partition = annotate or bool(
        {"scc_split", "scc_merge"}.intersection(kinds)
    )
    comp, keys = _scc_partition(base) if needs_partition else ([], [])
    for step in range(steps):
        if skew > 0:
            r = _zipf_pick(rng_skew, len(chain) + 1, skew)
            if r > 0:
                # Zipfian revisit: re-emit the r-th most recent distinct
                # snapshot byte-identically (a fleet-wide hot key).
                trace.append(copy.deepcopy(chain[-r]))
                if annotate:
                    metas.append({
                        "step": step + 1,
                        "revisit_of": chain_emit_ix[-r],
                        "mutations": [],
                        "affected_scc_ids": [],
                        "partition_changed": False,
                        "merges": 0,
                        "splits": 0,
                    })
                continue
        prev = chain[-1]
        snap = copy.deepcopy(prev)
        mutable = [
            i for i, n in enumerate(snap)
            if isinstance(n.get("quorumSet"), dict)
            and n["quorumSet"].get("validators")
        ]
        scc_of = dict(zip(keys, comp))
        key_of_ix = [n.get("publicKey") for n in snap]
        mutations: List[Dict] = []
        affected: set = set()
        for ix in (
            rng.sample(mutable, min(max_diff, len(mutable))) if mutable else ()
        ):
            node = snap[ix]
            q = node["quorumSet"]
            kind = rng.choice(kinds)
            own_scc = scc_of.get(key_of_ix[ix])
            structural = False
            extra: Dict = {}
            if kind == "scc_merge":
                partner = _merge_partner(
                    rng, snap, mutable, scc_of, key_of_ix, own_scc
                )
                if partner is None:
                    kind = "threshold"  # no second SCC to merge with
                else:
                    other = snap[partner]
                    q["validators"].append(other["publicKey"])
                    other["quorumSet"]["validators"].append(node["publicKey"])
                    partner_scc = scc_of.get(key_of_ix[partner])
                    structural = True
                    extra = {"partner": other["publicKey"],
                             "partner_scc_id": partner_scc}
                    if partner_scc is not None:
                        affected.add(partner_scc)
            if kind == "scc_split":
                # The drawn node may sit in a single-node SCC (most
                # watchers do); redirect to a split-capable node so the
                # requested kind actually restructures, falling back to a
                # wobble only when NO multi-node SCC exists at all.
                if sum(1 for c in comp if c == own_scc) < 2:
                    sizes = Counter(comp)
                    # A split only replaces the whole quorum set, so any
                    # dict-qset member of a multi-node SCC qualifies —
                    # including org-structured cores whose top-level
                    # validator list is empty (all-inner-sets).
                    capable = [
                        j for j, n in enumerate(snap)
                        if isinstance(n.get("quorumSet"), dict)
                        and sizes.get(scc_of.get(key_of_ix[j]), 0) >= 2
                    ]
                    if not capable:
                        kind = "threshold"  # nothing multi-node to split
                    else:
                        ix = rng.choice(capable)
                        node = snap[ix]
                        q = node["quorumSet"]
                        own_scc = scc_of.get(key_of_ix[ix])
                if kind == "scc_split":
                    node["quorumSet"] = _qset(1, [node["publicKey"]])
                    structural = True
            if kind == "threshold":
                lo, hi = 1, max(1, len(q["validators"]))
                old_t = q.get("threshold", 1)
                t = old_t + rng.choice((-1, 1))
                q["threshold"] = min(max(t, lo), hi)
                # A wobble clamped back to its old value mutated nothing.
                structural = q["threshold"] != old_t
            elif kind == "swap":
                vix = rng.randrange(len(q["validators"]))
                old_key = q["validators"][vix]
                new_key = rng.choice(all_keys)
                q["validators"][vix] = new_key
                # SCC-local structure changes only when an endpoint is
                # inside the owner's component or the dropped ref was
                # dangling (strict policy folds dangling into n_dangling,
                # a fingerprinted field); an outside→outside swap leaves
                # the restricted problem identical — though it can still
                # restructure the partition, which the key-set comparison
                # below catches independently.
                structural = old_key != new_key and (
                    scc_of.get(old_key) == own_scc
                    or scc_of.get(new_key) == own_scc
                    or old_key not in scc_of
                )
                extra = {"old_key": old_key, "new_key": new_key}
            elif kind == "rename":
                node["name"] = f"{node.get('name', '')}~{rng.randrange(999)}"
            mutations.append({
                "kind": kind, "node": node.get("publicKey"),
                "scc_id": own_scc, "structural": structural, **extra,
            })
            if structural and own_scc is not None:
                affected.add(own_scc)
        trace.append(snap)
        chain.append(snap)
        chain_emit_ix.append(len(trace) - 1)
        if not needs_partition:
            continue
        old_parts = _key_sets(comp, keys)
        comp, keys = _scc_partition(snap)  # becomes the next step's prev
        if not annotate:
            continue
        # Partition restructure ground truth, by member-key sets (never by
        # fingerprints — see docstring).  A validator swap can restructure
        # the partition as a side effect (a new edge closing a cycle
        # between components); every old SCC that gained or lost members
        # is invalidated even when its own node wasn't churned.
        new_parts = _key_sets(comp, keys)
        new_set = set(new_parts)
        changed = set(old_parts) != new_set
        merges = sum(
            1 for np in new_parts
            if sum(1 for p in old_parts if p & np) >= 2
        )
        splits = sum(
            1 for p in old_parts
            if sum(1 for np in new_parts if p & np) >= 2
        )
        for part in old_parts:
            if part not in new_set:
                sid = old_ix_to_scc_id(part, scc_of)
                if sid is not None:
                    affected.add(sid)
        metas.append({
            "step": step + 1,
            "mutations": mutations,
            "affected_scc_ids": sorted(affected),
            "partition_changed": changed,
            "merges": merges,
            "splits": splits,
        })
    # Determinism belt-and-braces: the trace must be JSON-serializable as
    # produced (the serving layer journals exactly these dicts).
    json.dumps(trace[-1])
    json.dumps(metas)
    return trace, metas


def _merge_partner(
    rng: random.Random,
    snap: List[Dict],
    mutable: List[int],
    scc_of: Dict[str, int],
    key_of_ix: List[Optional[str]],
    own_scc: Optional[int],
) -> Optional[int]:
    """A deterministic merge partner: a mutable node in a different SCC
    (rng draws among the candidates in snapshot order), or ``None``."""
    candidates = [
        j for j in mutable
        if scc_of.get(key_of_ix[j]) is not None
        and scc_of.get(key_of_ix[j]) != own_scc
    ]
    if not candidates or own_scc is None:
        return None
    return rng.choice(candidates)


def old_ix_to_scc_id(
    part: frozenset, scc_of: Dict[str, int]
) -> Optional[int]:
    """The predecessor SCC id of one old partition cell (any member's)."""
    for key in part:
        if key in scc_of:
            return scc_of[key]
    return None


def churn_trace(
    base: List[Dict],
    steps: int,
    seed: int = 0,
    *,
    max_diff: int = 2,
    kinds: Tuple[str, ...] = CHURN_KINDS,
    skew: float = 0.0,
) -> List[List[Dict]]:
    """Deterministic snapshot stream: ``steps + 1`` consecutive snapshots
    starting at ``base``, each differing from its predecessor in at most
    ``max_diff`` nodes' quorum sets (ROADMAP scenario-diversity item; the
    serving layer's realistic traffic — ``benchmarks/serve.py``).

    Per step the generator draws, per churned node, one of three bounded
    mutations a live stellarbeat feed actually produces:

    - **threshold wobble**: a top-level threshold moves ±1, clamped to
      ``[1, members]`` — the most common real churn (validators tuning
      safety margins);
    - **validator swap**: one top-level validator reference is replaced by
      another key drawn from the snapshot (trust-edge churn);
    - **cosmetic rename**: the node's display name changes — a diff the
      sanitized-SCC fingerprint (``serve.snapshot_fingerprint``) must
      ignore, so caches stay hot across it.

    ``kinds`` extends the mix with the restructuring mutations
    ``scc_split`` / ``scc_merge`` (see :func:`churn_trace_steps`, which
    also returns per-step ground-truth annotations — this wrapper is the
    load-shaped view, so it skips the annotation work entirely:
    ``annotate=False`` costs no parse/Tarjan passes with the default
    ``kinds``).  ``skew > 0`` adds zipfian temporal skew — steps
    re-emitting recent snapshots byte-identically with rank probability
    ``∝ 1/(r+1)^skew`` — the hot-key traffic shape the fleet bench needs
    (``benchmarks/serve.py --fleet``); the default ``skew=0.0`` keeps the
    trace byte-identical to the pre-skew generator.

    Same ``(base, steps, seed, skew)`` ⇒ byte-identical trace.  Nodes
    with null quorum sets are never churned (there is nothing bounded to
    mutate).  Each snapshot is a deep copy: mutating one never aliases
    another.
    """
    trace, _ = churn_trace_steps(
        base, steps, seed, max_diff=max_diff, kinds=kinds, annotate=False,
        skew=skew,
    )
    return trace


def random_fbas(
    n: int,
    *,
    seed: int = 0,
    slice_size: Optional[int] = None,
    nested_prob: float = 0.0,
    null_prob: float = 0.0,
    dangling_prob: float = 0.0,
) -> List[Dict]:
    """Random FBAS: each node trusts a random subset, threshold a random
    majority-ish fraction of it.  Knobs add nested inner sets, null qsets and
    dangling references to exercise quirk policies (Q1/Q2)."""
    rng = random.Random(seed)
    ks = keys(n, "RND")
    nodes = []
    for i, key in enumerate(ks):
        if rng.random() < null_prob:
            nodes.append(_node(key, f"r{i}", None))
            continue
        size = slice_size or rng.randint(3, max(3, min(n, 8)))
        size = min(size, n)
        chosen = rng.sample(ks, size)
        if rng.random() < dangling_prob:
            chosen[rng.randrange(len(chosen))] = f"MISSING{rng.randrange(1000):04d}"
        inner: List[Dict] = []
        if rng.random() < nested_prob and size >= 4:
            split = size // 2
            inner = [_qset(max(1, (size - split) // 2 + 1), chosen[split:])]
            chosen = chosen[:split]
        t = max(1, (len(chosen) + len(inner)) * 2 // 3 + 1)
        t = min(t, len(chosen) + len(inner))
        nodes.append(_node(key, f"r{i}", _qset(t, chosen, inner)))
    return nodes
