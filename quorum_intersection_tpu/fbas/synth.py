"""Synthetic FBAS generators — the seed corpus for differential testing and
benchmarking (SURVEY.md §4.3, BASELINE.json configs).

All generators emit stellarbeat-style raw dicts (the same shape
:func:`quorum_intersection_tpu.fbas.schema.parse_fbas` accepts), so every
synthetic network also exercises the JSON frontend.

The generators follow the reference fixtures' de-facto test methodology —
*same topology, one knob turned* (SURVEY.md §4.1): each safe generator has a
broken twin differing by a single threshold.
"""

from __future__ import annotations

import copy
import json
import random
from typing import Dict, List, Optional


def _node(key: str, name: str, qset: Dict) -> Dict:
    return {"publicKey": key, "name": name, "quorumSet": qset}


def _qset(threshold: int, validators: List[str], inner: Optional[List] = None) -> Dict:
    return {
        "threshold": threshold,
        "validators": validators,
        "innerQuorumSets": inner or [],
    }


def keys(n: int, prefix: str = "NODE") -> List[str]:
    return [f"{prefix}{i:04d}" for i in range(n)]


def majority_fbas(n: int, *, broken: bool = False, prefix: str = "NODE") -> List[Dict]:
    """Symmetric k-of-n FBAS with k = n//2 + 1 — all quorums intersect.

    ``broken=True`` turns one knob, mirroring the reference's
    ``broken_trivial.json`` methodology (threshold 2→1 on one node,
    `broken_trivial.json:20`): node 0's threshold drops to 1, making {node0}
    a quorum disjoint from any majority of the remaining nodes.
    """
    ks = keys(n, prefix)
    k = n // 2 + 1
    nodes = []
    for i, key in enumerate(ks):
        t = 1 if (broken and i == 0) else k
        nodes.append(_node(key, f"n{i}", _qset(t, list(ks))))
    return nodes


def hierarchical_fbas(
    n_orgs: int, per_org: int, *, broken: bool = False, org_threshold: Optional[int] = None
) -> List[Dict]:
    """Stellar-like tiered FBAS: each node requires a majority of organizations,
    where an organization counts if a majority of its validators are available —
    expressed with one inner quorum set per organization (nesting depth 1,
    matching the bundled fixtures' observed max depth, SURVEY.md §7.3).

    ``broken=True`` gives the first node a degenerate self-only slice
    (threshold 1 over itself), making {node0} a quorum disjoint from the
    surviving org-majority quorum of everyone else.
    """
    org_keys = [keys(per_org, f"ORG{o}N") for o in range(n_orgs)]
    all_nodes: List[Dict] = []
    t_orgs = org_threshold if org_threshold is not None else n_orgs // 2 + 1
    inner = [_qset(per_org // 2 + 1, list(ok)) for ok in org_keys]
    for o in range(n_orgs):
        for i, key in enumerate(org_keys[o]):
            if broken and o == 0 and i == 0:
                all_nodes.append(_node(key, f"org{o}-v{i}", _qset(1, [key])))
            else:
                all_nodes.append(_node(key, f"org{o}-v{i}", _qset(t_orgs, [], list(inner))))
    return all_nodes


def trivial_pair() -> Dict[str, List[Dict]]:
    """Tiny 3-node pass/fail pair, structurally the same test idea as the
    reference's ``correct_trivial.json`` / ``broken_trivial.json`` (2-of-3
    majority; broken twin lowers one threshold to 1)."""
    return {
        "correct": majority_fbas(3, prefix="TRIV"),
        "broken": majority_fbas(3, broken=True, prefix="TRIV"),
    }


def stellar_like_fbas(
    n_core_orgs: int = 7,
    per_org: int = 3,
    n_watchers: int = 100,
    n_null: int = 28,
    n_dangling: int = 7,
    *,
    broken: bool = False,
    seed: int = 0,
) -> List[Dict]:
    """Stellarbeat-snapshot-shaped network (~150 validators with defaults).

    Mirrors the structural statistics of the bundled `correct.json` snapshot
    scaled up (SURVEY.md §4.1): a small strongly-connected core of
    organizations (the quorum-bearing sink SCC), a long tail of watcher
    nodes that trust the core but are not trusted back (many singleton
    SCCs), a block of null-quorumSet nodes, and a sprinkle of dangling
    validator references.  ``broken=True`` turns one knob in the core —
    org 0's validators drop their org-majority threshold to 1-of-{orgs}
    (trust edges unchanged, so the core SCC stays intact), making the org-0
    trio a quorum disjoint from the quorum of the remaining orgs: the
    search inside the SCC, not the SCC guard, must find it.
    """
    rng = random.Random(seed)
    org_keys = [keys(per_org, f"CORE{o}N") for o in range(n_core_orgs)]
    core_flat = [k for ok in org_keys for k in ok]
    inner = [_qset(per_org // 2 + 1, list(ok)) for ok in org_keys]
    t_orgs = n_core_orgs // 2 + 1
    nodes: List[Dict] = []
    for o in range(n_core_orgs):
        for i, key in enumerate(org_keys[o]):
            t = 1 if (broken and o == 0) else t_orgs
            nodes.append(_node(key, f"core{o}-v{i}", _qset(t, [], list(inner))))
    for w in range(n_watchers):
        trusted = rng.sample(core_flat, min(len(core_flat), rng.randint(3, 7)))
        extra = []
        if w < n_dangling:  # dangling refs concentrated in early watchers
            extra = [f"GONE{w:04d}"]
        t = len(trusted) * 2 // 3 + 1
        nodes.append(_node(f"WATCH{w:04d}", f"w{w}", _qset(t, trusted + extra)))
    for z in range(n_null):
        nodes.append(_node(f"NULLQ{z:04d}", f"z{z}", None))
    rng.shuffle(nodes)  # snapshot order is arbitrary; vertex 0 ≠ core
    return nodes


def benchmark_fbas(
    n_total: int,
    core: int,
    *,
    nested_watchers: bool = False,
    broken: bool = False,
    seed: int = 0,
) -> List[Dict]:
    """North-star verdict-benchmark network (BASELINE.json configs 4-5).

    A ``core``-node symmetric k-of-n majority (k = core//2 + 1 — the
    "k-of-n threshold slices" config) forms the quorum-bearing sink SCC;
    the remaining ``n_total - core`` nodes are a periphery of watchers
    trusting random core subsets, null-qset nodes, and a sprinkle of
    dangling refs — the structural shape of a stellarbeat snapshot
    (SURVEY.md §4.1) scaled to the BASELINE node counts.  The verdict
    therefore requires the full in-SCC disjointness search over the core
    (2^(core-1) candidate subsets), which is what the benchmark times.

    ``nested_watchers=True`` (the "1024-node FBAS with nested inner-sets"
    config) gives every watcher a two-level qset: an innerQuorumSet per
    sampled core pair plus direct validators.  ``broken=True`` turns one
    knob in the core (threshold → 1, the `broken_trivial.json:20`
    methodology) for differential twins.
    """
    if core < 3 or core > n_total:
        raise ValueError(f"need 3 <= core <= n_total, got core={core}, n_total={n_total}")
    rng = random.Random(seed)
    nodes = majority_fbas(core, broken=broken, prefix="CORE")
    core_keys = keys(core, "CORE")
    n_periph = n_total - core
    n_null = n_periph // 10
    n_dangling = min(n_periph // 32, 16)
    for w in range(n_periph - n_null):
        trusted = rng.sample(core_keys, min(core, rng.randint(4, 9)))
        if w < n_dangling:
            trusted = trusted + [f"GONE{w:04d}"]
        inner: List[Dict] = []
        if nested_watchers and len(trusted) >= 6:
            # Two-level slice: pairs of trusted core nodes become 1-of-2
            # inner sets (nesting depth 1 below the watcher's own qset).
            split = len(trusted) // 2
            inner = [
                _qset(1, [trusted[split + 2 * j], trusted[split + 2 * j + 1]])
                for j in range((len(trusted) - split) // 2)
            ]
            trusted = trusted[:split]
        t = (len(trusted) + len(inner)) * 2 // 3 + 1
        nodes.append(_node(f"WATCH{w:04d}", f"w{w}", _qset(t, trusted, inner)))
    for z in range(n_null):
        nodes.append(_node(f"NULLQ{z:04d}", f"z{z}", None))
    rng.shuffle(nodes)  # snapshot order is arbitrary; vertex 0 ≠ core
    return nodes


def churn_trace(
    base: List[Dict],
    steps: int,
    seed: int = 0,
    *,
    max_diff: int = 2,
) -> List[List[Dict]]:
    """Deterministic snapshot stream: ``steps + 1`` consecutive snapshots
    starting at ``base``, each differing from its predecessor in at most
    ``max_diff`` nodes' quorum sets (ROADMAP scenario-diversity item; the
    serving layer's realistic traffic — ``benchmarks/serve.py``).

    Per step the generator draws, per churned node, one of three bounded
    mutations a live stellarbeat feed actually produces:

    - **threshold wobble**: a top-level threshold moves ±1, clamped to
      ``[1, members]`` — the most common real churn (validators tuning
      safety margins);
    - **validator swap**: one top-level validator reference is replaced by
      another key drawn from the snapshot (trust-edge churn);
    - **cosmetic rename**: the node's display name changes — a diff the
      sanitized-SCC fingerprint (``serve.snapshot_fingerprint``) must
      ignore, so caches stay hot across it.

    Same ``(base, steps, seed)`` ⇒ byte-identical trace.  Nodes with null
    quorum sets are never churned (there is nothing bounded to mutate).
    Each snapshot is a deep copy: mutating one never aliases another.
    """
    if steps < 0:
        raise ValueError(f"steps must be >= 0, got {steps}")
    rng = random.Random(seed)
    trace = [copy.deepcopy(base)]
    all_keys = [n.get("publicKey") for n in base if n.get("publicKey")]
    for _ in range(steps):
        snap = copy.deepcopy(trace[-1])
        mutable = [
            i for i, n in enumerate(snap)
            if isinstance(n.get("quorumSet"), dict)
            and n["quorumSet"].get("validators")
        ]
        for ix in (
            rng.sample(mutable, min(max_diff, len(mutable))) if mutable else ()
        ):
            node = snap[ix]
            q = node["quorumSet"]
            kind = rng.choice(("threshold", "swap", "rename"))
            if kind == "threshold":
                lo, hi = 1, max(1, len(q["validators"]))
                t = q.get("threshold", 1) + rng.choice((-1, 1))
                q["threshold"] = min(max(t, lo), hi)
            elif kind == "swap":
                vix = rng.randrange(len(q["validators"]))
                q["validators"][vix] = rng.choice(all_keys)
            else:
                node["name"] = f"{node.get('name', '')}~{rng.randrange(999)}"
        trace.append(snap)
    # Determinism belt-and-braces: the trace must be JSON-serializable as
    # produced (the serving layer journals exactly these dicts).
    json.dumps(trace[-1])
    return trace


def random_fbas(
    n: int,
    *,
    seed: int = 0,
    slice_size: Optional[int] = None,
    nested_prob: float = 0.0,
    null_prob: float = 0.0,
    dangling_prob: float = 0.0,
) -> List[Dict]:
    """Random FBAS: each node trusts a random subset, threshold a random
    majority-ish fraction of it.  Knobs add nested inner sets, null qsets and
    dangling references to exercise quirk policies (Q1/Q2)."""
    rng = random.Random(seed)
    ks = keys(n, "RND")
    nodes = []
    for i, key in enumerate(ks):
        if rng.random() < null_prob:
            nodes.append(_node(key, f"r{i}", None))
            continue
        size = slice_size or rng.randint(3, max(3, min(n, 8)))
        size = min(size, n)
        chosen = rng.sample(ks, size)
        if rng.random() < dangling_prob:
            chosen[rng.randrange(len(chosen))] = f"MISSING{rng.randrange(1000):04d}"
        inner: List[Dict] = []
        if rng.random() < nested_prob and size >= 4:
            split = size // 2
            inner = [_qset(max(1, (size - split) // 2 + 1), chosen[split:])]
            chosen = chosen[:split]
        t = max(1, (len(chosen) + len(inner)) * 2 // 3 + 1)
        t = min(t, len(chosen) + len(inner))
        nodes.append(_node(key, f"r{i}", _qset(t, chosen, inner)))
    return nodes
