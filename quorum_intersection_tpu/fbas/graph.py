"""Trust digraph construction + Tarjan SCC.

Capability parity with the reference's ``buildDependencyGraph``
(`/root/reference/quorum_intersection.cpp:438-473`) and its use of Boost
``strong_components`` (cpp:620-622), with one deliberate semantic fix:

**Dangling validator references (SURVEY.md §2.3-Q1).**  The reference resolves
validator IDs through ``unordered_map::operator[]`` (cpp:456), so an unknown ID
silently default-inserts vertex 0 — unknown validators alias to the *first node
in the JSON file*.  The principled default here is ``dangling="strict"``: an
unknown validator can never be available, which for threshold semantics is
exactly equivalent to dropping it from the member list (each never-available
member decrements the dual fail counter once, cpp:108 — i.e. members-1 with the
same threshold).  ``dangling="alias0"`` reproduces the reference bug bit-for-bit
for differential testing.  Both verdicts agree on all bundled fixtures
(SURVEY.md §2.3-Q1 [verified]).

Parallel edges and self-loops are preserved with multiplicity — one edge per
validator occurrence at every nesting depth (cpp:455-464) — because both the
branching heuristic's in-degree (cpp:224-229) and PageRank's out-degree and
contributions (cpp:561-570) double-count them (SURVEY.md §2.3-Q7).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from quorum_intersection_tpu.fbas.schema import Fbas, QSet

DanglingPolicy = str  # "strict" | "alias0"


@dataclass(frozen=True)
class IndexedQSet:
    """A quorum set with validator IDs resolved to vertex indices.

    ``threshold is None`` still means "never satisfiable" (null qset, Q2).
    Under the strict dangling policy, dropped members are counted in
    ``n_dangling`` so diagnostics can report them.
    """

    threshold: Optional[int]
    members: Tuple[int, ...] = ()
    inner: Tuple["IndexedQSet", ...] = ()
    n_dangling: int = 0


@dataclass
class TrustGraph:
    """Directed trust graph over vertex indices 0..n-1.

    ``succ[i]`` lists successors *with multiplicity* (parallel edges and
    self-loops preserved, Q7).  ``qsets[i]`` is vertex i's indexed quorum set.
    """

    n: int
    succ: List[List[int]]
    qsets: List[IndexedQSet]
    node_ids: List[str] = field(default_factory=list)  # publicKeys
    names: List[str] = field(default_factory=list)  # raw names ("" if unset)
    dangling_refs: int = 0
    # The dangling policy this graph was BUILT under ("strict" | "alias0"):
    # verdict certificates (qi-cert/1) record it so the independent checker
    # evaluates the same FBAS semantics the verdict used.
    dangling: DanglingPolicy = "strict"

    def label(self, v: int) -> str:
        """Display label: name if non-empty else publicKey (cpp:507, :596-597)."""
        return self.names[v] if self.names[v] else self.node_ids[v]

    @property
    def n_edges(self) -> int:
        return sum(len(s) for s in self.succ)

    def in_degrees(self) -> List[int]:
        deg = [0] * self.n
        for srcs in self.succ:
            for d in srcs:
                deg[d] += 1
        return deg


def _index_qset(
    q: QSet,
    index: dict,
    policy: DanglingPolicy,
    out_edges: List[int],
    stats: List[int],
) -> IndexedQSet:
    if q.is_null:
        return IndexedQSet(threshold=None)
    members: List[int] = []
    n_dangling = 0
    for key in q.validators:
        v = index.get(key)
        if v is None:
            stats[0] += 1
            if policy == "alias0":
                # Reference-compatible aliasing to vertex 0 (cpp:456, Q1).
                v = 0
            else:
                n_dangling += 1
                continue  # strict: never-available ≡ dropped member
        members.append(v)
        out_edges.append(v)
    inner = tuple(_index_qset(iq, index, policy, out_edges, stats) for iq in q.inner)
    return IndexedQSet(
        threshold=q.threshold, members=tuple(members), inner=inner, n_dangling=n_dangling
    )


def build_graph(fbas: Fbas, dangling: DanglingPolicy = "strict") -> TrustGraph:
    """Build the trust digraph: one vertex per node (JSON order, cpp:441-446),
    one edge owner→validator per occurrence at every nesting depth (cpp:448-465).
    """
    if dangling not in ("strict", "alias0"):
        raise ValueError(f"unknown dangling policy {dangling!r}")
    n = len(fbas)
    succ: List[List[int]] = []
    qsets: List[IndexedQSet] = []
    stats = [0]
    for node in fbas:
        out_edges: List[int] = []
        qsets.append(_index_qset(node.qset, fbas.index, dangling, out_edges, stats))
        succ.append(out_edges)
    return TrustGraph(
        n=n,
        succ=succ,
        qsets=qsets,
        node_ids=[node.public_key for node in fbas],
        names=[node.name for node in fbas],
        dangling_refs=stats[0],
        dangling=dangling,
    )


def tarjan_scc(n: int, succ: List[List[int]]) -> Tuple[int, List[int]]:
    """Iterative Tarjan strongly-connected components.

    Returns ``(count, comp)`` where ``comp[v]`` is v's component id.
    Components are numbered in completion order, which is *reverse topological
    order of the condensation* — component ids increase from sinks toward
    sources, the same ordering contract Boost's ``strong_components`` gives the
    reference (cpp:643-644 relies on component 0 being "last in topological
    order", i.e. a sink reachable from low-numbered vertices).
    """
    UNVISITED = -1
    comp = [UNVISITED] * n
    low = [0] * n
    disc = [0] * n
    on_stack = [False] * n
    stack: List[int] = []
    timer = 0
    count = 0

    for root in range(n):
        if comp[root] != UNVISITED or disc[root]:
            continue
        # Explicit DFS stack of (vertex, iterator position).
        work = [(root, 0)]
        while work:
            v, pi = work[-1]
            if pi == 0:
                timer += 1
                disc[v] = timer
                low[v] = timer
                stack.append(v)
                on_stack[v] = True
            advanced = False
            edges = succ[v]
            while pi < len(edges):
                w = edges[pi]
                pi += 1
                if not disc[w]:
                    work[-1] = (v, pi)
                    work.append((w, 0))
                    advanced = True
                    break
                if on_stack[w]:
                    low[v] = min(low[v], disc[w])
            if advanced:
                continue
            work.pop()
            if low[v] == disc[v]:
                while True:
                    w = stack.pop()
                    on_stack[w] = False
                    comp[w] = count
                    if w == v:
                        break
                count += 1
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[v])
    return count, comp


def group_sccs(n: int, comp: List[int], count: int) -> List[List[int]]:
    """Group vertices by component id, vertices ascending within each group —
    the same grouping the reference builds at cpp:624-633."""
    sccs: List[List[int]] = [[] for _ in range(count)]
    for v in range(n):
        sccs[comp[v]].append(v)
    return sccs
