"""Reference-faithful FBAS set semantics on the host (pure Python).

These are the two leaf operations everything else builds on:

- :func:`slice_satisfied` — is a node's quorum slice satisfied by an
  availability set?  Parity with ``containsQuorumSlice``
  (`/root/reference/quorum_intersection.cpp:90-138`), including its dual
  early-exit counters and the quirks pinned in SURVEY.md §2.3:
  Q2 (null qset never satisfiable), Q3 (``threshold == 0`` and
  ``threshold > members`` never satisfiable — the reference gets there via
  unsigned wraparound; we state it directly), Q4 (self-availability required).
- :func:`max_quorum` — the greatest fixpoint of
  ``f(X) = {x ∈ X : slice(x) satisfied by X}`` — parity with
  ``containsQuorum`` (cpp:140-177): repeatedly drop nodes whose slice is not
  satisfied until stable; the survivors are the unique largest quorum inside
  the candidate set (or empty).

The host pipeline uses these for the cheap polynomial phases (per-SCC quorum
scan); the Python oracle backend uses them inside the exponential search.  The
TPU backend re-derives the same math as dense threshold-circuit arrays in
``encode.circuit`` / ``backends.tpu`` and is differentially tested against
these functions.
"""

from __future__ import annotations

from typing import Iterable, List, Sequence

from quorum_intersection_tpu.fbas.graph import IndexedQSet, TrustGraph


def slice_satisfied(owner: int, qset: IndexedQSet, avail: Sequence[bool]) -> bool:
    """True iff ``owner``'s slice described by ``qset`` is satisfied by ``avail``.

    Mirrors cpp:90-138: requires self-availability (Q4, cpp:95-98), then counts
    available direct members and recursively satisfied inner sets against the
    threshold with dual early exits (``fail = members − threshold + 1``,
    cpp:100).  A null qset (Q2) or a degenerate threshold (Q3) is never
    satisfiable.
    """
    if qset.threshold is None:  # Q2: null/empty quorumSet
        return False
    if not avail[owner]:  # Q4: self must be available
        return False
    t = qset.threshold
    if t <= 0:
        # Q3, corrected: the reference's behavior for threshold == 0 is
        # *chaotic*, not uniformly unsatisfiable — its `threshold == 0` check
        # sits after the per-member decrements (cpp:105-118), so a
        # zero-threshold slice evaluates TRUE iff its first member is
        # unavailable/unsatisfied (fail-- leaves threshold at 0 → cpp:111
        # fires), FALSE if the first member is available (0 wraps to
        # SIZE_MAX) and FALSE with no members at all.  We deliberately do
        # not reproduce that: threshold <= 0 is normalized to "never
        # satisfiable" everywhere (here and in encode/circuit.py).  No real
        # stellarbeat snapshot contains threshold 0; the sanitizer can flag
        # such inputs (--flag-zero-threshold).
        return False
    fail = len(qset.members) + len(qset.inner) - t + 1
    if fail <= 0:  # Q3: threshold > members can never be met
        return False
    for m in qset.members:
        if avail[m]:
            t -= 1
            if t == 0:
                return True
        else:
            fail -= 1
            if fail == 0:
                return False
    for iq in qset.inner:
        if slice_satisfied(owner, iq, avail):
            t -= 1
            if t == 0:
                return True
        else:
            fail -= 1
            if fail == 0:
                return False
    return False


def max_quorum(
    graph: TrustGraph, candidates: Iterable[int], avail: List[bool]
) -> List[int]:
    """Greatest quorum contained in ``candidates`` under availability ``avail``.

    Parity with ``containsQuorum`` (cpp:140-177): iterate
    ``X ← {x ∈ X : slice(x) ⊆ X}`` to its greatest fixpoint.  ``avail`` is
    temporarily narrowed during the iteration and **restored before returning**
    (cpp:171-173) so callers can reuse their availability vector.  Returns the
    surviving candidates (a quorum — every member's slice is satisfied within
    the set) or ``[]``.
    """
    nodes = list(candidates)
    removed: List[int] = []
    while True:
        before = len(nodes)
        kept: List[int] = []
        for v in nodes:
            if slice_satisfied(v, graph.qsets[v], avail):
                kept.append(v)
            else:
                if avail[v]:
                    avail[v] = False
                    removed.append(v)
        nodes = kept
        if len(nodes) == before:
            break
    for v in removed:
        avail[v] = True
    return nodes


def is_quorum(graph: TrustGraph, members: Sequence[int]) -> bool:
    """True iff ``members`` is itself a quorum (every slice satisfied within)."""
    unique = sorted(set(members))
    if not unique:
        return False
    avail = [False] * graph.n
    for v in unique:
        avail[v] = True
    return len(max_quorum(graph, unique, avail)) == len(unique)
