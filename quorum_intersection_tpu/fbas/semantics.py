"""Reference-faithful FBAS set semantics on the host (pure Python).

These are the two leaf operations everything else builds on:

- :func:`slice_satisfied` — is a node's quorum slice satisfied by an
  availability set?  Parity with ``containsQuorumSlice``
  (`/root/reference/quorum_intersection.cpp:90-138`), including its dual
  early-exit counters and the quirks pinned in SURVEY.md §2.3:
  Q2 (null qset never satisfiable), Q3 (``threshold == 0`` and
  ``threshold > members`` never satisfiable — the reference gets there via
  unsigned wraparound; we state it directly), Q4 (self-availability required).
- :func:`max_quorum` — the greatest fixpoint of
  ``f(X) = {x ∈ X : slice(x) satisfied by X}`` — parity with
  ``containsQuorum`` (cpp:140-177): repeatedly drop nodes whose slice is not
  satisfied until stable; the survivors are the unique largest quorum inside
  the candidate set (or empty).

The host pipeline uses these for the cheap polynomial phases (per-SCC quorum
scan); the Python oracle backend uses them inside the exponential search.  The
TPU backend re-derives the same math as dense threshold-circuit arrays in
``encode.circuit`` / ``backends.tpu`` and is differentially tested against
these functions.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from quorum_intersection_tpu.fbas.graph import IndexedQSet, TrustGraph


def slice_satisfied(owner: int, qset: IndexedQSet, avail: Sequence[bool]) -> bool:
    """True iff ``owner``'s slice described by ``qset`` is satisfied by ``avail``.

    Mirrors cpp:90-138: requires self-availability (Q4, cpp:95-98), then counts
    available direct members and recursively satisfied inner sets against the
    threshold with dual early exits (``fail = members − threshold + 1``,
    cpp:100).  A null qset (Q2) or a degenerate threshold (Q3) is never
    satisfiable.
    """
    if qset.threshold is None:  # Q2: null/empty quorumSet
        return False
    if not avail[owner]:  # Q4: self must be available
        return False
    t = qset.threshold
    if t <= 0:
        # Q3, corrected: the reference's behavior for threshold == 0 is
        # *chaotic*, not uniformly unsatisfiable — its `threshold == 0` check
        # sits after the per-member decrements (cpp:105-118), so a
        # zero-threshold slice evaluates TRUE iff its first member is
        # unavailable/unsatisfied (fail-- leaves threshold at 0 → cpp:111
        # fires), FALSE if the first member is available (0 wraps to
        # SIZE_MAX) and FALSE with no members at all.  We deliberately do
        # not reproduce that: threshold <= 0 is normalized to "never
        # satisfiable" everywhere (here and in encode/circuit.py).  No real
        # stellarbeat snapshot contains threshold 0; the sanitizer can flag
        # such inputs (--flag-zero-threshold).
        return False
    fail = len(qset.members) + len(qset.inner) - t + 1
    if fail <= 0:  # Q3: threshold > members can never be met
        return False
    for m in qset.members:
        if avail[m]:
            t -= 1
            if t == 0:
                return True
        else:
            fail -= 1
            if fail == 0:
                return False
    for iq in qset.inner:
        if slice_satisfied(owner, iq, avail):
            t -= 1
            if t == 0:
                return True
        else:
            fail -= 1
            if fail == 0:
                return False
    return False


def max_quorum(
    graph: TrustGraph, candidates: Iterable[int], avail: List[bool]
) -> List[int]:
    """Greatest quorum contained in ``candidates`` under availability ``avail``.

    Parity with ``containsQuorum`` (cpp:140-177): iterate
    ``X ← {x ∈ X : slice(x) ⊆ X}`` to its greatest fixpoint.  ``avail`` is
    temporarily narrowed during the iteration and **restored before returning**
    (cpp:171-173) so callers can reuse their availability vector.  Returns the
    surviving candidates (a quorum — every member's slice is satisfied within
    the set) or ``[]``.
    """
    nodes = list(candidates)
    removed: List[int] = []
    while True:
        before = len(nodes)
        kept: List[int] = []
        for v in nodes:
            if slice_satisfied(v, graph.qsets[v], avail):
                kept.append(v)
            else:
                if avail[v]:
                    avail[v] = False
                    removed.append(v)
        nodes = kept
        if len(nodes) == before:
            break
    for v in removed:
        avail[v] = True
    return nodes


def cross_family_disjoint_quorum(
    graph_b: TrustGraph, exclude: Sequence[int]
) -> List[int]:
    """Greatest family-B quorum avoiding ``exclude`` — the cross-family
    overlap guard of the relaxed two-family intersection query (qi-query,
    Fast Flexible Paxos arXiv:2008.02671: fast-vs-classic quorum safety
    reduces to "no A-quorum is disjoint from every B-quorum").

    One polynomial fixpoint over family B's graph with the candidate
    A-quorum's members unavailable: nonempty means the pair ``(exclude ∩
    A-quorum, result)`` is a disjoint cross-family witness.  Both graphs
    must index the same node set (same vertex order — the two-family
    contract ``query.py`` enforces at parse time).
    """
    banned = set(exclude)
    candidates = [v for v in range(graph_b.n) if v not in banned]
    avail = [v not in banned for v in range(graph_b.n)]
    return max_quorum(graph_b, candidates, avail)


def relaxed_disjoint_witness(
    graph_a: TrustGraph,
    graph_b: TrustGraph,
    members: Sequence[int],
) -> Tuple[Optional[List[int]], Optional[List[int]], int]:
    """Cross-family disjointness search (host oracle): find an A-quorum
    and a B-quorum over the same node set that do NOT intersect, or prove
    none exists among A-quorums inside ``members``.

    Enumerates every subset ``S`` of ``members`` (the quorum-bearing SCC
    of family A — all minimal A-quorums live inside it, exactly the
    argument the single-family sweep rests on); per window the greatest
    A-quorum within ``S`` is one fixpoint, and each *distinct* nonempty
    A-quorum runs the :func:`cross_family_disjoint_quorum` B-side guard
    once (memoized — many windows collapse to the same greatest quorum).
    Returns ``(qa, qb, windows_enumerated)`` with ``qa``/``qb`` None when
    every A-quorum meets every B-quorum.

    Unlike the single-family search there is no complement symmetry (the
    B-side quorum is not confined to ``members`` under whole-graph
    availability), so all ``2^m - 1`` nonempty windows are enumerated
    rather than ``2^(m-1)`` — the certificate ledger records exactly
    that space and the checker re-verifies the arithmetic
    (docs/PARITY.md §Two-family invariants).
    """
    nodes = list(members)
    m = len(nodes)
    avail = [False] * graph_a.n
    enumerated = 0
    seen: Dict[frozenset, bool] = {}
    for window in range(1, 1 << m):
        enumerated += 1
        chosen = [nodes[i] for i in range(m) if window >> i & 1]
        for v in chosen:
            avail[v] = True
        qa = max_quorum(graph_a, chosen, avail)
        for v in chosen:
            avail[v] = False
        if not qa:
            continue
        key = frozenset(qa)
        if key in seen:
            continue
        qb = cross_family_disjoint_quorum(graph_b, qa)
        seen[key] = bool(qb)
        if qb:
            return sorted(qa), sorted(qb), enumerated
    return None, None, enumerated


def is_quorum(graph: TrustGraph, members: Sequence[int]) -> bool:
    """True iff ``members`` is itself a quorum (every slice satisfied within)."""
    unique = sorted(set(members))
    if not unique:
        return False
    avail = [False] * graph.n
    for v in unique:
        avail[v] = True
    return len(max_quorum(graph, unique, avail)) == len(unique)
