"""Structural snapshot differ over sanitized trust graphs (qi-delta, ISSUE 9).

The serving layer's verdict cache (PR 8) is all-or-nothing per snapshot
fingerprint: one threshold wobble anywhere forces a full re-solve even
though the NP-hard work decomposes per-SCC (arXiv:1902.06493 — all minimal
quorums live inside one SCC, and the per-SCC scan is independent).  This
module supplies the structural half of incremental re-analysis:

- :func:`scc_fingerprint` — an **SCC-local** fingerprint of one component:
  the resolved quorum sets of its members in SCC-vertex order, with member
  references rewritten to SCC-local ranks and out-of-SCC references
  anonymized to a sentinel.  Two SCCs with equal fingerprints present the
  *identical* restricted solve problem, whatever their global vertex
  indices, display names, or position in the snapshot — so cosmetic churn
  (renames from ``synth.churn_trace``), watcher churn outside the
  component, and global index shifts from node insertion all fingerprint
  identically.
- :func:`diff_snapshots` — maps the old snapshot's SCC partition onto the
  new one's and classifies each new SCC as ``unchanged`` (an old SCC with
  the same fingerprint exists), ``dirty`` (members overlap the old
  snapshot but the structure changed — threshold wobble, validator swap,
  or an SCC merge/split restructure), or ``new`` (no member existed
  before), and counts merges (one new SCC spanning >= 2 old ones) and
  splits (one old SCC scattered over >= 2 new ones).

**Soundness note** (why the sentinel is safe): the per-SCC quorum scan
restricts availability to the SCC's members (cpp:645-672 semantics), so an
out-of-SCC reference can never be satisfied — only its *multiplicity*
affects the dual fail counter, never its identity.  The in-SCC
disjointness search is the same under ``scope_to_scc=True``; under the
reference's whole-graph availability (``scope_to_scc=False``, quirk Q6) it
is sound exactly when the SCC is **closed** (no member's quorum set
references an outside node at any nesting depth — true of every sink SCC,
i.e. the quorum-bearing component of every Stellar-like topology).
:func:`scc_fingerprint` therefore also reports closedness, and the verdict
store (``delta.py``) refuses to reuse across snapshots what closedness
cannot justify.
"""

from __future__ import annotations

import hashlib
import json
from collections import Counter
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from quorum_intersection_tpu.fbas.graph import (
    IndexedQSet,
    TrustGraph,
    group_sccs,
    tarjan_scc,
)

# Sentinel rank for a member reference that points outside the SCC: its
# identity cannot matter (see module docstring), its multiplicity can.
OUTSIDE = -1


def _local_qset(
    q: IndexedQSet, rank: Dict[int, int], closed: List[bool]
) -> List[object]:
    """Canonical SCC-local form of one resolved quorum set: threshold,
    member ranks (:data:`OUTSIDE` for non-members, multiplicity and order
    preserved), inner sets, and the strict-policy dropped-dangling count —
    exactly the inputs the restricted scan and search depend on."""
    if q.threshold is None:
        return [None]
    members: List[int] = []
    for v in q.members:
        r = rank.get(v, OUTSIDE)
        if r == OUTSIDE:
            closed[0] = False
        members.append(r)
    return [
        q.threshold,
        members,
        [_local_qset(iq, rank, closed) for iq in q.inner],
        q.n_dangling,
    ]


def scc_fingerprint(
    graph: TrustGraph, members: List[int]
) -> Tuple[str, bool]:
    """``(fingerprint, closed)`` for one SCC of ``graph``.

    ``members`` must be ascending vertex indices (the :func:`group_sccs`
    contract); their rank in that order is the SCC-local coordinate every
    stored scan/verdict fragment is expressed in.  The fingerprint covers
    the dangling policy (strict vs alias0 resolve to different member
    lists with different ``n_dangling`` semantics) but deliberately NOT
    node names or publicKeys: the verdict is structural, and consumers
    project stored local coordinates back through the *new* snapshot's
    member list, so identity churn costs nothing.  ``closed`` is True iff
    no member's quorum set references an outside vertex at any depth.
    """
    rank = {v: i for i, v in enumerate(members)}
    closed = [True]
    payload = {
        "v": 1,
        "dangling": graph.dangling,
        "size": len(members),
        "qsets": [_local_qset(graph.qsets[v], rank, closed) for v in members],
    }
    digest = hashlib.sha256(
        json.dumps(payload, separators=(",", ":")).encode()
    ).hexdigest()[:32]
    return digest, closed[0]


@dataclass
class SccDelta:
    """One new-snapshot SCC's classification against the old snapshot."""

    index: int  # new-snapshot SCC id (Tarjan completion order)
    kind: str  # "unchanged" | "dirty" | "new"
    fingerprint: str
    closed: bool
    size: int
    old_indices: List[int] = field(default_factory=list)  # by member overlap


@dataclass
class SnapshotDiff:
    """The full old→new SCC partition mapping (see module docstring)."""

    deltas: List[SccDelta]
    old_n_sccs: int
    new_n_sccs: int
    unchanged: int = 0
    dirty: int = 0
    new: int = 0
    merges: int = 0  # new SCCs spanning >= 2 old SCCs
    splits: int = 0  # old SCCs scattered over >= 2 new SCCs

    def summary(self) -> Dict[str, int]:
        return {
            "old_sccs": self.old_n_sccs,
            "new_sccs": self.new_n_sccs,
            "unchanged": self.unchanged,
            "dirty": self.dirty,
            "new": self.new,
            "merges": self.merges,
            "splits": self.splits,
        }

    def dirty_or_new(self) -> List[SccDelta]:
        return [d for d in self.deltas if d.kind != "unchanged"]


def _partition(graph: TrustGraph) -> List[List[int]]:
    count, comp = tarjan_scc(graph.n, graph.succ)
    return group_sccs(graph.n, comp, count)


def diff_snapshots(
    old: TrustGraph,
    new: TrustGraph,
    *,
    old_parts: Optional[List[List[int]]] = None,
    old_fps_list: Optional[List[Tuple[str, bool]]] = None,
    new_parts: Optional[List[List[int]]] = None,
    new_fps_list: Optional[List[Tuple[str, bool]]] = None,
) -> SnapshotDiff:
    """Classify every SCC of ``new`` against ``old`` (see module docstring).

    ``unchanged`` is decided purely structurally (fingerprint match against
    the old partition's fingerprint multiset — each old SCC justifies at
    most one new SCC, so a duplicated component still counts once per
    copy); ``old_indices`` is decided by member-publicKey overlap, which is
    what makes merges and splits visible even when every fingerprint
    changed.

    The keyword arguments let a caller that already partitioned and
    fingerprinted either snapshot (the incremental engine does both as its
    structural prefix, and keeps the previous snapshot's) hand the work in
    instead of paying Tarjan + sha256 again — the diff itself then costs
    only the overlap bookkeeping.
    """
    old_sccs = _partition(old) if old_parts is None else old_parts
    new_sccs = _partition(new) if new_parts is None else new_parts
    if old_fps_list is None:
        old_fps_list = [scc_fingerprint(old, m) for m in old_sccs]
    if new_fps_list is None:
        new_fps_list = [scc_fingerprint(new, m) for m in new_sccs]
    old_fps = Counter(fp for fp, _ in old_fps_list)
    old_scc_of: Dict[str, int] = {}
    for sid, m in enumerate(old_sccs):
        for v in m:
            old_scc_of[old.node_ids[v]] = sid
    deltas: List[SccDelta] = []
    claimed: Counter = Counter()  # old scc id → # new SCCs overlapping it
    for sid, members in enumerate(new_sccs):
        fp, closed = new_fps_list[sid]
        old_ids = sorted({
            old_scc_of[new.node_ids[v]]
            for v in members if new.node_ids[v] in old_scc_of
        })
        for oid in old_ids:
            claimed[oid] += 1
        if old_fps[fp] > 0:
            old_fps[fp] -= 1
            kind = "unchanged"
        elif old_ids:
            kind = "dirty"
        else:
            kind = "new"
        deltas.append(SccDelta(
            index=sid, kind=kind, fingerprint=fp, closed=closed,
            size=len(members), old_indices=old_ids,
        ))
    diff = SnapshotDiff(
        deltas=deltas, old_n_sccs=len(old_sccs), new_n_sccs=len(new_sccs),
    )
    for d in deltas:
        if d.kind == "unchanged":
            diff.unchanged += 1
        elif d.kind == "dirty":
            diff.dirty += 1
        else:
            diff.new += 1
        if len(d.old_indices) >= 2:
            diff.merges += 1
    diff.splits = sum(1 for n in claimed.values() if n >= 2)
    return diff


def project(local: Optional[List[int]], members: List[int]) -> Optional[List[int]]:
    """SCC-local ranks → this snapshot's global vertex indices (the inverse
    of the rank map :func:`scc_fingerprint` canonicalizes under)."""
    if local is None:
        return None
    return [members[r] for r in local]


def localize(
    quorum: Optional[List[int]], members: List[int]
) -> Optional[List[int]]:
    """Global vertex indices → SCC-local ranks; ``None`` when any vertex
    falls outside ``members`` (the caller must then not cache — a witness
    that escapes the SCC is exactly the unsoundness closedness guards)."""
    if quorum is None:
        return None
    rank = {v: i for i, v in enumerate(members)}
    local: List[int] = []
    for v in quorum:
        r = rank.get(v)
        if r is None:
            return None
        local.append(r)
    return local
