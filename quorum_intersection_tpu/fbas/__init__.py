"""Frontend: stellarbeat JSON → validated FBAS model → trust graph + SCCs."""

from quorum_intersection_tpu.fbas.schema import QSet, FbasNode, Fbas, parse_fbas
from quorum_intersection_tpu.fbas.graph import TrustGraph, build_graph, tarjan_scc

__all__ = ["QSet", "FbasNode", "Fbas", "parse_fbas", "TrustGraph", "build_graph", "tarjan_scc"]
