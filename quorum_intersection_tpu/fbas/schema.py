"""Stellarbeat ``/nodes/raw`` JSON schema → validated FBAS model.

Capability parity with the reference frontend
(`/root/reference/quorum_intersection.cpp:402-436`):

- each array element must carry ``publicKey`` (cpp:428) and a ``quorumSet`` key
  (cpp:430 — absent key is an error there too);
- ``name`` is optional, defaulting to ``""`` (cpp:429);
- a quorum set carries ``threshold``, ``validators`` and recursive
  ``innerQuorumSets`` (cpp:410-416); unknown keys (``hashKey``, dates, …) are
  ignored;
- a ``null`` / empty ``quorumSet`` maps to :data:`NULL_QSET` — the reference
  default-constructs a qset with an *uninitialized* threshold in this case
  (cpp:405-408) whose observable behavior is "never satisfiable" (SURVEY.md
  §2.3-Q2).  We model that explicitly with ``threshold=None`` instead of UB.

Deliberate lenient superset: inside a non-empty quorum set, a missing
``validators`` or ``innerQuorumSets`` key is treated as the empty list (the
reference throws an uncaught ``ptree_bad_path`` and crashes, cpp:411,414);
real stellarbeat snapshots occasionally omit the empty lists.  ``threshold``
remains required for non-empty quorum sets, as in the reference (cpp:410).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import IO, Iterable, Iterator, Mapping, Optional, Sequence, Union


class FbasSchemaError(ValueError):
    """Raised when the input JSON does not satisfy the FBAS schema."""


# Hostile-input hardening: a quorum set nested deeper than this is rejected
# with a clean schema error instead of exhausting the interpreter stack (the
# reference would crash on such input, cpp:402-418).  Real stellarbeat
# snapshots nest 1-2 levels; 128 is far beyond any legitimate FBAS while
# keeping every downstream recursion (graph indexing, circuit interning,
# native flattening — all capped to the same constant) well inside default
# stack budgets.
MAX_QSET_DEPTH = 128


@dataclass(frozen=True)
class QSet:
    """A (possibly nested) quorum set.

    ``threshold is None`` encodes the reference's null/empty quorum set —
    a slice that can never be satisfied (SURVEY.md §2.3-Q2).
    """

    threshold: Optional[int]
    validators: tuple = ()
    inner: tuple = ()

    @property
    def is_null(self) -> bool:
        return self.threshold is None

    def member_count(self) -> int:
        """Direct member count: validators + inner sets (one vote each)."""
        return len(self.validators) + len(self.inner)

    def max_depth(self) -> int:
        """Nesting depth: 0 for a flat qset, 1 + max over children otherwise."""
        if not self.inner:
            return 0
        return 1 + max(q.max_depth() for q in self.inner)

    def all_validator_refs(self) -> Iterable[str]:
        """Every validator reference at every nesting depth, with repeats.

        Mirrors the reference's edge construction, which adds one trust edge
        per occurrence at every depth (cpp:455-464, SURVEY.md §2.3-Q7).
        """
        for v in self.validators:
            yield v
        for q in self.inner:
            yield from q.all_validator_refs()


NULL_QSET = QSet(threshold=None)


@dataclass(frozen=True)
class FbasNode:
    public_key: str
    name: str
    qset: QSet


@dataclass
class Fbas:
    """A parsed FBAS: ordered node list + public-key index.

    Node order is the JSON array order — vertex ``i`` of the trust graph is
    ``nodes[i]``, matching the reference's ``add_vertex`` order (cpp:441-446).
    """

    nodes: list = field(default_factory=list)

    def __post_init__(self) -> None:
        self.index: dict = {}
        for i, node in enumerate(self.nodes):
            # First occurrence wins on duplicate keys; the reference's
            # idMap[node.nodeID] = v overwrite makes the *last* occurrence win
            # for edge targets (cpp:445) but vertices are still distinct.
            # Duplicate publicKeys are rejected here instead: silently aliased
            # vertices are a foot-gun, and no real snapshot contains them.
            if node.public_key in self.index:
                raise FbasSchemaError(f"duplicate publicKey: {node.public_key!r}")
            self.index[node.public_key] = i

    def __len__(self) -> int:
        return len(self.nodes)

    def __iter__(self) -> "Iterator[FbasNode]":
        return iter(self.nodes)

    def __getitem__(self, i: int) -> FbasNode:
        return self.nodes[i]

    def label(self, i: int) -> str:
        """Display label: name if non-empty else publicKey (cpp:507, :596-597)."""
        node = self.nodes[i]
        return node.name if node.name else node.public_key


def _parse_qset(value: object, where: str, depth: int = 0) -> QSet:
    if depth > MAX_QSET_DEPTH:
        raise FbasSchemaError(
            f"{where}: quorumSet nesting exceeds depth {MAX_QSET_DEPTH}"
        )
    if value is None:
        return NULL_QSET
    if not isinstance(value, Mapping):
        raise FbasSchemaError(f"{where}: quorumSet must be an object or null, got {type(value).__name__}")
    if not value:
        # Empty object → same "never satisfiable" semantics as null (cpp:406-408).
        return NULL_QSET
    if "threshold" not in value:
        raise FbasSchemaError(f"{where}: non-empty quorumSet missing 'threshold'")
    threshold = value["threshold"]
    if isinstance(threshold, str):
        # boost::property_tree stores scalars as strings and converts on get;
        # accept numeric strings for input compatibility.
        try:
            threshold = int(threshold)
        except ValueError:
            raise FbasSchemaError(f"{where}: threshold {threshold!r} is not an integer") from None
    if isinstance(threshold, bool) or not isinstance(threshold, int):
        raise FbasSchemaError(f"{where}: threshold must be an integer, got {threshold!r}")
    validators = value.get("validators")
    if validators is None:
        validators = ()
    if not isinstance(validators, Sequence) or isinstance(validators, (str, bytes)):
        raise FbasSchemaError(f"{where}: validators must be an array")
    for v in validators:
        if not isinstance(v, str):
            raise FbasSchemaError(f"{where}: validator entries must be strings, got {v!r}")
    inner_raw = value.get("innerQuorumSets")
    if inner_raw is None:
        inner_raw = ()
    if not isinstance(inner_raw, Sequence) or isinstance(inner_raw, (str, bytes)):
        raise FbasSchemaError(f"{where}: innerQuorumSets must be an array")
    inner = tuple(
        _parse_qset(q, f"{where}.innerQuorumSets[{i}]", depth + 1)
        for i, q in enumerate(inner_raw)
    )
    return QSet(threshold=threshold, validators=tuple(validators), inner=inner)


def parse_fbas(source: Union[str, bytes, IO, list]) -> Fbas:
    """Parse a stellarbeat ``/nodes/raw`` JSON array into an :class:`Fbas`.

    ``source`` may be a JSON string/bytes, an open text stream (the CLI passes
    stdin, matching the reference's stdin-only contract, cpp:791), or an
    already-decoded list.
    """
    try:
        if isinstance(source, (str, bytes)):
            data = json.loads(source)
        elif isinstance(source, list):
            data = source
        else:
            data = json.load(source)
    except RecursionError:
        # json's C scanner recurses per nesting level; surface the same clean
        # diagnostic as any other malformed input instead of a traceback.
        raise FbasSchemaError("JSON nesting too deep") from None
    if not isinstance(data, list):
        raise FbasSchemaError(f"top level must be a JSON array, got {type(data).__name__}")

    nodes = []
    for i, raw in enumerate(data):
        where = f"nodes[{i}]"
        if not isinstance(raw, Mapping):
            raise FbasSchemaError(f"{where}: must be an object")
        if "publicKey" not in raw:
            raise FbasSchemaError(f"{where}: missing required 'publicKey'")
        public_key = raw["publicKey"]
        if not isinstance(public_key, str):
            raise FbasSchemaError(f"{where}: publicKey must be a string")
        name = raw.get("name") or ""
        if not isinstance(name, str):
            raise FbasSchemaError(f"{where}: name must be a string")
        if "quorumSet" not in raw:
            raise FbasSchemaError(f"{where} ({public_key}): missing required 'quorumSet'")
        qset = _parse_qset(raw["quorumSet"], f"{where}.quorumSet")
        nodes.append(FbasNode(public_key=public_key, name=name, qset=qset))
    return Fbas(nodes)
