"""qi-cost/1: per-request device-cost attribution, tenants, and SLOs.

Quorum intersection is NP-hard, so per-request device cost is exponential
in SCC size and varies by orders of magnitude across a serve stream — and
since qi-fuse packs windows from *different* requests into one MXU tile,
"which request consumed which device time?" stopped having a per-dispatch
answer.  This module is the accounting plane that restores it (ISSUE 17):

- **Attribution** (:func:`pack_lane_shares` / :func:`attribute_pack` /
  :func:`solo_cost` / :func:`reuse_credit`): the sweep pack drain books,
  per origin request, the lanes it occupied (including its integer share
  of pack padding), the windows swept, the MACs under the
  ``macs_per_candidate_row`` shape model, and the dispatch wall pro-rated
  by lane occupancy.  The conserved quantity is **lane·windows**: the sum
  of attributed lane·windows across a pack's origins equals the pack
  total *exactly* (integer shares, asserted at every attribution site).
  Delta-reused SCCs book a reuse *credit*; cancelled/dead lanes stay
  booked to the request that retired them (group ownership is never
  reassigned mid-pack).
- **Tenants** (:class:`TenantTable`): costs ride ``SolveResult.stats`` →
  ``cert.provenance.cost`` → the serve/fleet wire and aggregate per
  client id into a bounded LRU table (``QI_COST_TENANTS_MAX``); the fleet
  front door merges the workers' pong-carried snapshots into a second,
  fleet-wide table (pid-deduped, rebuilt each probe cycle — snapshots are
  cumulative, so merging must replace, never accumulate).
- **SLO plane** (:class:`SloPlane`): declarative targets
  (``QI_SLO="serve_e2e_p99_ms<500,..."``) evaluated lazily (each
  ``/healthz`` / ``/sloz`` scrape and each adaptive fuse-window decision)
  over a :class:`~quorum_intersection_tpu.utils.telemetry.SnapshotRing`
  of metric samples: a target is *burning* when the violating fraction of
  samples is high in BOTH the fast (``QI_SLO_FAST_S``) and slow
  (``QI_SLO_SLOW_S``) windows — the multiwindow burn-rate discipline, so
  a recovered metric stops firing as soon as the fast window clears.
  Transitions emit ``slo.burn`` events; the ``slo.burning`` gauge counts
  currently-burning targets.
- **Closed loop** (:func:`choose_fuse_window`): the first consumer —
  ``QI_SERVE_FUSE_WINDOW_MS=auto`` picks the BatchFormer window each
  flush cycle from the pulse queue-wait p99 and the burn state.

Every step degrades through the ``cost.attribute`` fault point: a wrong
cost must become a *dropped* cost (``cost.attribute_errors`` counter +
``cost.degraded`` event, loud), never a wrong verdict — verdicts, certs
and latency are byte-identical with attribution off.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from quorum_intersection_tpu.utils.env import (
    qi_env, qi_env_float, qi_env_int,
)
from quorum_intersection_tpu.utils.faults import FaultInjected, fault_point
from quorum_intersection_tpu.utils.logging import get_logger
from quorum_intersection_tpu.utils.telemetry import (
    SnapshotRing, get_run_record, register_final_lines,
)

log = get_logger("cost")

COST_SCHEMA = "qi-cost/1"
SLO_SCHEMA = "qi-slo/1"

# Burn-rate thresholds: the violating fraction of ring samples within the
# fast window must reach 1/2 AND within the slow window 1/10 for a target
# to burn — the classic multiwindow discipline (fast window for response
# time, slow window so a single spike cannot page).
FAST_BURN_FRACTION = 0.5
SLOW_BURN_FRACTION = 0.1

# Adaptive fuse-window bounds (milliseconds): the controller never waits
# longer than the cap even under a deep queue, never shorter than the
# floor once it decides to wait at all, and clamps to the burn cap while
# any SLO target is burning (a burning latency budget buys no batching).
AUTO_WINDOW_CAP_MS = 25.0
AUTO_WINDOW_FLOOR_MS = 1.0
AUTO_WINDOW_BURN_CAP_MS = 2.0

# Deterministic-schedule hook (tools/analyze/schedules.py, the qi-fuse
# discipline): when set, called with a named sync point at every adaptive
# window decision so the harness can force queue states under it.
_cost_sync: Optional[Callable[[str], None]] = None


def _sync(point: str) -> None:
    hook = _cost_sync
    if hook is not None:
        hook(point)


# ---- attribution -----------------------------------------------------------


def pack_lane_shares(n_lanes: int, slot: int, k: int) -> List[int]:
    """Integer per-group lane shares summing to ``n_lanes`` exactly.

    A pack of ``k`` groups ladders each member up to ``slot`` lanes
    (``n_raw = k·slot``) and then pads the whole circuit up to the lane
    tile (``pad = n_lanes − k·slot ≥ 0``).  The pad belongs to nobody, so
    it is distributed in integer parts: ``pad // k`` to every group plus
    one extra lane to the first ``pad % k`` groups.  Conservation holds by
    construction — and is asserted anyway, because the invariant is the
    whole point."""
    if k <= 0:
        raise ValueError(f"pack_lane_shares: k must be positive, got {k}")
    pad = n_lanes - k * slot
    if pad < 0:
        raise ValueError(
            f"pack_lane_shares: n_lanes={n_lanes} < k*slot={k * slot}"
        )
    base, extra = divmod(pad, k)
    shares = [slot + base + (1 if gix < extra else 0) for gix in range(k)]
    assert sum(shares) == n_lanes, (shares, n_lanes, slot, k)
    return shares


def attribute_pack(group_origins: Sequence[object], n_lanes: int, slot: int,
                   pack_rows: int, macs_per_row: int,
                   seconds: float) -> Dict[object, Dict[str, object]]:
    """Book one fused pack's device work to its origin requests.

    ``group_origins`` is the origin key of each lane group in pack order
    (a retired/cancelled group keeps its origin — dead lanes book to the
    request that cancelled them).  Returns origin → cost dict; the sum of
    ``lane_windows`` across origins equals ``n_lanes · pack_rows``
    exactly (the qi-cost conservation invariant, asserted)."""
    k = len(group_origins)
    shares = pack_lane_shares(n_lanes, slot, k)
    per_origin: "OrderedDict[object, Dict[str, object]]" = OrderedDict()
    for gix, origin in enumerate(group_origins):
        row = per_origin.get(origin)
        if row is None:
            row = per_origin[origin] = {
                "schema": COST_SCHEMA,
                "fused": True,
                "lanes": 0,
                "groups": 0,
                "windows": int(pack_rows),
                "lane_windows": 0,
                "macs": 0,
                "device_s": 0.0,
            }
        row["lanes"] = int(row["lanes"]) + shares[gix]
        row["groups"] = int(row["groups"]) + 1
    total = 0
    for row in per_origin.values():
        lanes = int(row["lanes"])
        row["lane_windows"] = lanes * int(pack_rows)
        total += int(row["lane_windows"])
        if n_lanes > 0:
            frac = lanes / float(n_lanes)
            row["macs"] = int(round(macs_per_row * int(pack_rows) * frac))
            row["device_s"] = round(float(seconds) * frac, 9)
    assert total == n_lanes * int(pack_rows), (
        "qi-cost conservation violated: "
        f"attributed {total} != pack total {n_lanes * int(pack_rows)}"
    )
    return dict(per_origin)


def solo_cost(n_lanes: int, candidates: int, macs_per_row: int,
              seconds: float) -> Dict[str, object]:
    """The unfused (one request per dispatch) cost: the whole device."""
    return {
        "schema": COST_SCHEMA,
        "fused": False,
        "lanes": int(n_lanes),
        "groups": 1,
        "windows": int(candidates),
        "lane_windows": int(n_lanes) * int(candidates),
        "macs": int(macs_per_row) * int(candidates),
        "device_s": round(float(seconds), 9),
    }


def reuse_credit(cached_cost: Optional[Dict[str, object]]) -> Dict[str, object]:
    """The cost of a delta-reused SCC: zero new device work plus a
    *credit* — the lane·windows the reuse avoided re-sweeping (what the
    cached solve booked, when it carried a cost)."""
    credit = 0
    if isinstance(cached_cost, dict):
        try:
            credit = int(cached_cost.get("lane_windows") or 0)
        except (TypeError, ValueError):
            credit = 0
    return {
        "schema": COST_SCHEMA,
        "fused": False,
        "reused": True,
        "lanes": 0,
        "groups": 0,
        "windows": 0,
        "lane_windows": 0,
        "macs": 0,
        "device_s": 0.0,
        "credit_lane_windows": credit,
    }


def merge_costs(parts: Sequence[Dict[str, object]]) -> Dict[str, object]:
    """Sum cost dicts (a serve request solving several SCCs books one
    combined cost).  ``fused`` is true when any part was fused."""
    out: Dict[str, object] = {
        "schema": COST_SCHEMA, "fused": False, "lanes": 0, "groups": 0,
        "windows": 0, "lane_windows": 0, "macs": 0, "device_s": 0.0,
    }
    credit = 0
    reused = False
    for part in parts:
        if not isinstance(part, dict):
            continue
        out["fused"] = bool(out["fused"]) or bool(part.get("fused"))
        for key in ("lanes", "groups", "windows", "lane_windows", "macs"):
            try:
                out[key] = int(out[key]) + int(part.get(key) or 0)  # type: ignore[arg-type]
            except (TypeError, ValueError):
                pass
        try:
            out["device_s"] = round(
                float(out["device_s"]) + float(part.get("device_s") or 0.0), 9)  # type: ignore[arg-type]
        except (TypeError, ValueError):
            pass
        try:
            credit += int(part.get("credit_lane_windows") or 0)
        except (TypeError, ValueError):
            pass
        reused = reused or bool(part.get("reused"))
    if credit:
        out["credit_lane_windows"] = credit
    if reused:
        out["reused"] = True
    return out


# ---- per-tenant tables -----------------------------------------------------

_TENANT_INT_FIELDS = ("requests", "lane_windows", "macs",
                      "credit_lane_windows")


class TenantTable:
    """Bounded per-client-id cost aggregation (LRU on booking order).

    Capacity comes from ``QI_COST_TENANTS_MAX`` at construction/reset —
    bounded so client-id cardinality cannot grow serve-tier memory;
    evictions count on ``cost.tenants_evicted``, never silent."""

    def __init__(self, capacity: Optional[int] = None) -> None:
        self._lock = threading.Lock()
        self._rows: "OrderedDict[str, Dict[str, object]]" = OrderedDict()
        self._capacity = (int(capacity) if capacity is not None
                          else max(1, qi_env_int("QI_COST_TENANTS_MAX")))

    def book(self, client: str, cost: Optional[Dict[str, object]]) -> None:
        """Accumulate one request's cost under ``client`` (LRU-touch)."""
        tenant = str(client or "anon")
        evicted = 0
        with self._lock:
            row = self._rows.pop(tenant, None)
            if row is None:
                row = {f: 0 for f in _TENANT_INT_FIELDS}
                row["device_s"] = 0.0
            self._rows[tenant] = row
            row["requests"] = int(row["requests"]) + 1  # type: ignore[arg-type]
            if isinstance(cost, dict):
                for key in ("lane_windows", "macs", "credit_lane_windows"):
                    try:
                        row[key] = int(row[key]) + int(cost.get(key) or 0)  # type: ignore[arg-type]
                    except (TypeError, ValueError):
                        pass
                try:
                    row["device_s"] = round(
                        float(row["device_s"])  # type: ignore[arg-type]
                        + float(cost.get("device_s") or 0.0), 9)
                except (TypeError, ValueError):
                    pass
            while len(self._rows) > self._capacity:
                self._rows.popitem(last=False)
                evicted += 1
        if evicted:
            get_run_record().add("cost.tenants_evicted", evicted)

    def replace(self, rows: Dict[str, Dict[str, object]]) -> None:
        """Overwrite with merged snapshots (the fleet front door's move —
        pong snapshots are cumulative, so the merge REPLACES each cycle;
        accumulating them would double-count every prior cycle)."""
        capped = list(rows.items())[-self._capacity:]
        fresh: "OrderedDict[str, Dict[str, object]]" = OrderedDict()
        for tenant, row in capped:
            fresh[str(tenant)] = dict(row)
        with self._lock:
            self._rows = fresh

    def snapshot(self) -> Dict[str, Dict[str, object]]:
        with self._lock:
            return {t: dict(r) for t, r in self._rows.items()}

    def top(self, n: int) -> List[Tuple[str, Dict[str, object]]]:
        """The ``n`` costliest tenants by lane·windows (ties: requests)."""
        snap = self.snapshot()
        ranked = sorted(
            snap.items(),
            key=lambda kv: (int(kv[1].get("lane_windows") or 0),
                            int(kv[1].get("requests") or 0)),
            reverse=True,
        )
        return ranked[:max(0, int(n))]

    def reset(self) -> None:
        with self._lock:
            self._rows.clear()
            self._capacity = max(1, qi_env_int("QI_COST_TENANTS_MAX"))

    def __len__(self) -> int:
        with self._lock:
            return len(self._rows)


def merge_tenant_snapshots(
        parts: Sequence[Dict[str, Dict[str, object]]],
) -> Dict[str, Dict[str, object]]:
    """Sum per-tenant rows across worker snapshots (one per distinct
    worker process — the caller pid-dedupes, this just adds)."""
    merged: Dict[str, Dict[str, object]] = {}
    for part in parts:
        if not isinstance(part, dict):
            continue
        for tenant, row in part.items():
            if not isinstance(row, dict):
                continue
            agg = merged.setdefault(
                str(tenant),
                {**{f: 0 for f in _TENANT_INT_FIELDS}, "device_s": 0.0},
            )
            for key in _TENANT_INT_FIELDS:
                try:
                    agg[key] = int(agg[key]) + int(row.get(key) or 0)  # type: ignore[arg-type]
                except (TypeError, ValueError):
                    pass
            try:
                agg["device_s"] = round(
                    float(agg["device_s"])  # type: ignore[arg-type]
                    + float(row.get("device_s") or 0.0), 9)
            except (TypeError, ValueError):
                pass
    return merged


_TENANTS = TenantTable()
_FLEET_TENANTS = TenantTable()


def tenant_table() -> TenantTable:
    """This process's per-tenant table (what pongs snapshot)."""
    return _TENANTS


def fleet_tenant_table() -> TenantTable:
    """The fleet-merged view (front door only; empty elsewhere)."""
    return _FLEET_TENANTS


# ---- SLO plane -------------------------------------------------------------

# Friendly SLO metric names → the live gauge names they mean.  Beyond the
# aliases, resolution also tries the name verbatim and with '_' read as
# '.' (gauges first, then counters).
_METRIC_ALIASES: Dict[str, str] = {
    "serve_e2e_p99_ms": "serve.p99_ms",
    "serve_e2e_p50_ms": "serve.p50_ms",
}


@dataclass(frozen=True)
class SloTarget:
    """One parsed ``QI_SLO`` clause: ``metric OP bound``."""
    metric: str
    op: str           # '<' (stay under) or '>' (stay over)
    bound: float

    def violated(self, value: float) -> bool:
        return value >= self.bound if self.op == "<" else value <= self.bound


def parse_slo(spec: str) -> List[SloTarget]:
    """Parse ``"serve_e2e_p99_ms<500,pack_fill_pct>60"``; malformed
    clauses log and are skipped (a broken SLO spec must not break
    serving)."""
    targets: List[SloTarget] = []
    for clause in (spec or "").split(","):
        clause = clause.strip()
        if not clause:
            continue
        for op in ("<", ">"):
            metric, sep, bound = clause.partition(op)
            if sep and metric.strip():
                try:
                    targets.append(SloTarget(metric.strip(), op,
                                             float(bound.strip())))
                except ValueError:
                    log.warning("QI_SLO: unparseable bound in %r; skipped",
                                clause)
                break
        else:
            log.warning("QI_SLO: clause %r has no '<'/'>' operator; skipped",
                        clause)
    return targets


def _resolve_metric(name: str, counters: Dict[str, float],
                    gauges: Dict[str, object]) -> Optional[float]:
    candidates = [name]
    alias = _METRIC_ALIASES.get(name)
    if alias:
        candidates.append(alias)
    dotted = name.replace("_", ".")
    if dotted != name:
        candidates.append(dotted)
    for cand in candidates:
        for table in (gauges, counters):
            if cand in table:
                try:
                    return float(table[cand])  # type: ignore[arg-type]
                except (TypeError, ValueError):
                    continue
    return None


class SloPlane:
    """Multiwindow burn-rate evaluation over a metric snapshot ring.

    Lazy: :meth:`evaluate` runs on each ``/healthz`` / ``/sloz`` scrape
    and each adaptive fuse-window decision — no background thread.  Each
    call samples the live gauges/counters for every target's metric,
    records the sample into the ring, and answers per-target fast/slow
    violating fractions.  The clock is injectable so tests replay hours
    in microseconds."""

    def __init__(self, spec: Optional[str] = None,
                 fast_s: Optional[float] = None,
                 slow_s: Optional[float] = None,
                 clock: Optional[Callable[[], float]] = None,
                 ring: Optional[SnapshotRing] = None) -> None:
        self.targets = parse_slo(qi_env("QI_SLO") if spec is None else spec)
        self.fast_s = (qi_env_float("QI_SLO_FAST_S")
                       if fast_s is None else float(fast_s))
        self.slow_s = (qi_env_float("QI_SLO_SLOW_S")
                       if slow_s is None else float(slow_s))
        self._clock = clock or time.monotonic
        self.ring = ring if ring is not None else SnapshotRing(
            clock=self._clock)
        self._burning: set = set()
        self._lock = threading.Lock()

    @property
    def enabled(self) -> bool:
        return bool(self.targets)

    def _ratio(self, target: SloTarget,
               samples: List[Tuple[float, Dict[str, float]]]) -> Tuple[float, int]:
        seen = 0
        bad = 0
        for _, values in samples:
            value = values.get(target.metric)
            if value is None:
                continue
            seen += 1
            if target.violated(value):
                bad += 1
        return ((bad / seen) if seen else 0.0, seen)

    def evaluate(self, now: Optional[float] = None) -> Dict[str, object]:
        """One lazy evaluation cycle: sample → ring → burn rates →
        events/gauge.  Failures degrade through ``cost.attribute`` (a
        broken SLO evaluator must not break the scrape, let alone a
        verdict)."""
        rec = get_run_record()
        status: Dict[str, object] = {
            "schema": SLO_SCHEMA,
            "enabled": self.enabled,
            "fast_window_s": self.fast_s,
            "slow_window_s": self.slow_s,
            "targets": [],
            "burning": 0,
        }
        if not self.enabled:
            return status
        try:
            fault_point("cost.attribute")
            counters, gauges = rec.snapshot()
            sample: Dict[str, float] = {}
            for target in self.targets:
                value = _resolve_metric(target.metric, counters, gauges)
                if value is not None:
                    sample[target.metric] = value
            t = self.ring.record(sample, t=now)
            fast = self.ring.window(self.fast_s, now=t)
            slow = self.ring.window(self.slow_s, now=t)
            burning_now = 0
            rows: List[Dict[str, object]] = []
            with self._lock:
                for target in self.targets:
                    fast_ratio, fast_n = self._ratio(target, fast)
                    slow_ratio, slow_n = self._ratio(target, slow)
                    burning = (fast_n > 0
                               and fast_ratio >= FAST_BURN_FRACTION
                               and slow_ratio >= SLOW_BURN_FRACTION)
                    key = f"{target.metric}{target.op}{target.bound:g}"
                    if burning and key not in self._burning:
                        rec.event(
                            "slo.burn",
                            metric=target.metric, op=target.op,
                            bound=target.bound,
                            value=sample.get(target.metric),
                            fast_ratio=round(fast_ratio, 4),
                            slow_ratio=round(slow_ratio, 4),
                            fast_samples=fast_n, slow_samples=slow_n,
                        )
                        self._burning.add(key)
                    elif not burning:
                        self._burning.discard(key)
                    if burning:
                        burning_now += 1
                    rows.append({
                        "metric": target.metric,
                        "op": target.op,
                        "bound": target.bound,
                        "value": sample.get(target.metric),
                        "fast_ratio": round(fast_ratio, 4),
                        "slow_ratio": round(slow_ratio, 4),
                        "fast_samples": fast_n,
                        "slow_samples": slow_n,
                        "burning": burning,
                    })
            rec.gauge("slo.burning", burning_now)
            status["targets"] = rows
            status["burning"] = burning_now
        except (FaultInjected, OSError, ValueError) as exc:
            rec.add("cost.attribute_errors")
            rec.event("cost.degraded", site="slo.evaluate", error=repr(exc))
            status["degraded"] = True
        return status

    def burning_count(self) -> int:
        with self._lock:
            return len(self._burning)


_SLO_PLANE: Optional[SloPlane] = None
_SLO_LOCK = threading.Lock()


def slo_plane() -> SloPlane:
    """The process-wide lazily-built plane (spec read at first use)."""
    global _SLO_PLANE
    with _SLO_LOCK:
        if _SLO_PLANE is None:
            _SLO_PLANE = SloPlane()
        return _SLO_PLANE


def reset_cost_state() -> None:
    """Test hook: fresh tenant tables and a re-read SLO plane."""
    global _SLO_PLANE
    _TENANTS.reset()
    _FLEET_TENANTS.reset()
    with _SLO_LOCK:
        _SLO_PLANE = None


# ---- adaptive fuse window (the closed loop) --------------------------------


def choose_fuse_window(queue_depth: int, wait_p99_ms: float,
                       burning: bool) -> float:
    """Pick the BatchFormer window for one flush cycle.

    Sparse traffic (nothing queued beyond this batch) → 0.0: latency
    never pays for an empty wait.  Hot queue → a short positive window
    proportional to the observed queue-wait p99 (a quarter of it, so the
    wait the fusion *adds* stays small against the wait the queue already
    *has*), clamped to [floor, cap].  While any SLO target burns, clamp
    to the burn cap — batching throughput never buys back a burning
    latency budget."""
    _sync("cost.window.decide")
    if queue_depth <= 0:
        return 0.0
    window = min(AUTO_WINDOW_CAP_MS,
                 max(AUTO_WINDOW_FLOOR_MS, float(wait_p99_ms) / 4.0))
    if burning:
        window = min(window, AUTO_WINDOW_BURN_CAP_MS)
    return window


# ---- /sloz -----------------------------------------------------------------


def sloz_payload(top_n: int = 10) -> Dict[str, object]:
    """The ``/sloz`` endpoint body: one SLO evaluation plus the costliest
    tenants, local and fleet-merged."""
    status = slo_plane().evaluate()
    status["tenants"] = {
        "local": [{"client": c, **row} for c, row in tenant_table().top(top_n)],
        "fleet": [{"client": c, **row}
                  for c, row in fleet_tenant_table().top(top_n)],
    }
    return status


# ---- stream export ---------------------------------------------------------


def _tenant_final_lines() -> List[Dict[str, object]]:
    """Finish-time JSONL line: this process's per-tenant cost table, so the
    qi-telemetry stream carries attribution next to the counters it
    conserves against (``tools/metrics_report.py --top N`` renders it).
    Silent when nothing was booked — a pre-cost stream stays
    byte-identical."""
    snap = tenant_table().snapshot()
    if not snap:
        return []
    return [{
        "kind": "tenants",
        "schema": COST_SCHEMA,
        "pid": get_run_record().pid,
        "tenants": snap,
    }]


register_final_lines(_tenant_final_lines)
