"""qi-cert/1 — checkable verdict certificates (ISSUE 7 tentpole).

The paper's algorithm answers an NP-hard question with a bare boolean.
qi-telemetry (PR 2) and qi-trace (PR 6) made the *runtime* observable; the
*answer* stayed opaque: a ``false`` verdict's witness pair was rechecked
internally and thrown away, and a ``true`` verdict carried no evidence that
the search actually covered the space it claims.  This module attaches a
certificate to every verdict:

- **``false``**: the two disjoint quorums in graph-space node ids
  (publicKeys + vertex indices) plus per-member **slice-satisfaction
  evidence** — for each witness member, which direct validators inside the
  quorum and how many satisfied inner sets meet its threshold — so the
  witness is auditable without re-running any engine.
- **``true``**: a **coverage ledger** — per searched SCC, windows
  enumerated / pruned-by-guard / skipped-by-pack-fill / cancelled for the
  exhaustive sweep (invariant: they sum to the window space
  ``2^(|scc|-1)``, docs/PARITY.md §Certificate invariants), frontier
  chunks drained for the device-resident B&B, and the branch-and-bound
  node counts echoed from the native/python oracles — so "intersecting"
  is auditable as "exhaustively covered".
- **always**: provenance — which ladder rung/engine/pack produced the
  verdict, the run's ``trace_id``, the routing/calibration/degrade events
  of this solve, and the front-end's sanitation decisions (dangling
  policy + dropped refs).

``tools/check_cert.py`` is the adversarial counterpart: a stdlib-only
checker (no imports from this package) that re-validates a certificate
against the raw stellarbeat JSON with its own minimal quorum-set
evaluator and exits 1 on any unsound witness or ledger arithmetic that
does not sum to the window space.

Certificates are attached to every :class:`pipeline.SolveResult` (the
``cert`` field), written to disk via the CLI ``--cert-out``, and
summarized into the qi-telemetry/1 stream (``cert.*`` events/counters,
docs/OBSERVABILITY.md registry).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

from quorum_intersection_tpu.fbas.graph import IndexedQSet, TrustGraph
from quorum_intersection_tpu.utils.faults import fault_point
from quorum_intersection_tpu.utils.logging import get_logger
from quorum_intersection_tpu.utils.telemetry import get_run_record

log = get_logger("cert")

CERT_SCHEMA = "qi-cert/1"

# Reference witness-pair convention (cpp:372-373), recorded verbatim in
# every witness block so a consumer never has to guess which side was the
# enumerated quorum: q1 is the disjointness-probe result, q2 the
# enumerated/minimal quorum.  Certificate parity across engines is "same
# pair up to this convention" (tests/test_qi_cert.py).
WITNESS_CONVENTION = "q1=disjoint-probe, q2=enumerated (cpp:372-373)"

# The event names a solve's provenance block carries out of the run record:
# routing decisions, race verdicts, ladder transitions, calibration gates,
# engine resolutions, pack builds, and injected faults — the "why this
# engine answered" trail, scoped to the one solve via events_since().
PROVENANCE_EVENTS = frozenset((
    "route.decision",
    "race",
    "degrade",
    "degrade.retry",
    "ladder.quarantined",
    "native.watchdog_cancel",
    "sweep.engine_resolved",
    "sweep.packed",
    "sweep.cancelled",
    "calibration.foreign_artifact_ignored",
    "fault.injected",
))


def _slice_evidence(
    owner: int,
    qset: IndexedQSet,
    member_set: frozenset,
    graph: TrustGraph,
) -> Dict[str, object]:
    """Slice-satisfaction evidence for ``owner``'s quorum slice against a
    witness quorum: which direct members inside the quorum and how many
    recursively-satisfied inner sets meet the threshold.  Mirrors the
    pinned host semantics (fbas/semantics.py slice_satisfied): Q2 null
    qsets never satisfy, Q3 degenerate/unreachable thresholds never
    satisfy, Q4 requires the owner itself inside the quorum."""
    if qset.threshold is None:
        return {"threshold": None, "satisfied": False, "reason": "null qset (Q2)"}
    direct = [v for v in qset.members if v in member_set]
    inner = [
        _slice_evidence(owner, iq, member_set, graph) for iq in qset.inner
    ]
    inner_sat = sum(1 for ev in inner if ev["satisfied"])
    t = qset.threshold
    m_count = len(qset.members) + len(qset.inner)
    satisfied = (
        owner in member_set  # Q4 self-availability
        and 0 < t <= m_count  # Q3 normalization
        and len(direct) + inner_sat >= t
    )
    return {
        "threshold": t,
        "members": m_count,
        "direct_met": [graph.node_ids[v] for v in direct],
        "inner_satisfied": inner_sat,
        "satisfied": satisfied,
    }


def witness_evidence(graph: TrustGraph, quorum: List[int]) -> List[Dict[str, object]]:
    """Per-member slice-satisfaction evidence for one witness quorum —
    the auditable half of a ``false`` certificate, and the validity probe
    ``analytics/splitting.py`` reuses (a candidate set is splitting only
    when every member of both claimed quorums is actually satisfied)."""
    member_set = frozenset(quorum)
    return [
        {
            "id": graph.node_ids[v],
            "index": v,
            **_slice_evidence(v, graph.qsets[v], member_set, graph),
        }
        for v in quorum
    ]


def witness_block(
    graph: TrustGraph, q1: List[int], q2: List[int]
) -> Dict[str, object]:
    """The ``witness`` block of a false certificate: both quorums in
    graph-space node ids plus per-member evidence."""
    return {
        "convention": WITNESS_CONVENTION,
        "q1": [graph.node_ids[v] for v in q1],
        "q2": [graph.node_ids[v] for v in q2],
        "q1_index": list(q1),
        "q2_index": list(q2),
        "evidence": {
            "q1": witness_evidence(graph, q1),
            "q2": witness_evidence(graph, q2),
        },
    }


def ledger_entry(
    graph: TrustGraph, scc: List[int], stats: Dict[str, object],
    scc_index: Optional[int] = None,
) -> Dict[str, object]:
    """One coverage-ledger entry for the SCC a backend searched, from the
    backend's result stats.  Sweep engines contribute the window counters
    maintained in their drive/pack loops (``stats["cert"]``); the frontier
    contributes its chunk/worklist counters; the host oracles echo their
    B&B node counts."""
    entry: Dict[str, object] = {
        "scc_index": scc_index,
        "size": len(scc),
        "nodes": [graph.node_ids[v] for v in scc],
        "backend": stats.get("backend", "?"),
    }
    cert_stats = stats.get("cert")
    if isinstance(cert_stats, dict):
        entry.update(cert_stats)
    # Oracle B&B counts ride along even for backends that predate the
    # explicit cert stats (defense in depth: the ledger never goes empty).
    for key in ("bnb_calls", "minimal_quorums", "fixpoint_calls",
                "native_call_id"):
        if key in stats and key not in entry:
            entry[key] = stats[key]
    if stats.get("packed"):
        entry["packed"] = True
        if "pack_engine" in stats:
            entry["engine"] = stats["pack_engine"]
    return entry


def build_certificate(
    graph: TrustGraph,
    *,
    intersects: bool,
    reason: str,
    n_sccs: int,
    quorum_bearing: int,
    scc_select: str,
    scope_to_scc: bool,
    stats: Dict[str, object],
    q1: Optional[List[int]] = None,
    q2: Optional[List[int]] = None,
    target_scc: Optional[List[int]] = None,
    target_scc_index: Optional[int] = None,
    events: Optional[List[dict]] = None,
    batched: bool = False,
    delta: Optional[Dict[str, object]] = None,
) -> Dict[str, object]:
    """Assemble one ``qi-cert/1`` certificate and emit its telemetry
    summary (``cert.emitted`` event + ``cert.certificates`` counter).

    ``delta`` (qi-delta, ISSUE 9) is the incremental-re-analysis stamp:
    reused vs re-solved SCC counts for this verdict, recorded under
    ``provenance.delta`` so a consumer can tell a composed certificate
    (cached SCC fragments stitched against this snapshot) from a
    from-scratch solve.  Purely provenance: the witness/ledger claims are
    rebuilt against THIS graph either way, so ``tools/check_cert.py``
    validates both identically."""
    rec = get_run_record()
    cert: Dict[str, object] = {
        "schema": CERT_SCHEMA,
        "verdict": bool(intersects),
        "dangling": graph.dangling,
        "scc_select": scc_select,
        "scope_to_scc": bool(scope_to_scc),
        "graph": {"n": graph.n, "edges": graph.n_edges},
        "guard": {
            "n_sccs": n_sccs,
            "quorum_bearing_sccs": quorum_bearing,
            "reason": reason,
        },
        "provenance": {
            "backend": stats.get("backend", reason),
            "trace_id": rec.trace_id,
            "packed": bool(stats.get("packed", False)),
            "batched": bool(batched),
            "native_call_id": stats.get("native_call_id"),
            "race": stats.get("race"),
            "sanitize": {
                "dangling_policy": graph.dangling,
                "dangling_refs": graph.dangling_refs,
            },
            "events": [
                {"name": ev.get("name"), "t_s": ev.get("t_s"),
                 "attrs": ev.get("attrs") or {}}
                for ev in (events or [])
                if ev.get("name") in PROVENANCE_EVENTS
            ],
            # After a MAX_EVENTS overflow the slice above may be empty or
            # clipped; without this flag a consumer cannot distinguish "no
            # routing/degrade events happened" from "the buffer overflowed".
            "events_truncated": rec.events_truncated(),
        },
    }
    if delta is not None:
        cert["provenance"]["delta"] = dict(delta)  # type: ignore[index]
    order = stats.get("order")
    if isinstance(order, dict):
        # Rank-ordered windows (ISSUE 10): which enumeration permutation the
        # sweep ran under (mode/score source/fixed-out node) — provenance
        # only; the witness and every ledger claim are already expressed in
        # graph-space node ids, so the checker needs no decode help here
        # (a pruned ledger carries its own explicit `enumeration` block).
        cert["provenance"]["order"] = dict(order)  # type: ignore[index]
    encoding = stats.get("encoding")
    if isinstance(encoding, str):
        # qi-sparse (ISSUE 20): which adjacency encoding proved the verdict
        # (only the bitset path stamps it — dense certs stay byte-identical
        # to prior releases).  Provenance only: witness/ledger claims are
        # encoding-independent and the checker never reads it.
        cert["provenance"]["encoding"] = encoding  # type: ignore[index]
    cost = stats.get("cost")
    if isinstance(cost, dict):
        # qi-cost/1 (ISSUE 17): which share of the device work this verdict
        # paid for — lane·windows, MACs, pro-rated dispatch wall, delta
        # reuse credits.  Provenance only: the checker ignores it, the
        # serve/fleet wire and the per-tenant tables consume it.
        cert["provenance"]["cost"] = dict(cost)  # type: ignore[index]
    summary: Dict[str, object] = {
        "verdict": bool(intersects),
        "backend": stats.get("backend", reason),
        "reason": reason,
    }
    if intersects:
        entry = ledger_entry(
            graph, target_scc or [], stats, scc_index=target_scc_index
        )
        cert["coverage"] = {"sccs": [entry]}
        for key in ("window_space", "windows_enumerated",
                    "windows_pruned_guard", "windows_skipped_pack_fill",
                    "windows_cancelled", "frontier_chunks_drained",
                    "bnb_calls"):
            if key in entry:
                summary[key] = entry[key]
    elif q1 and q2:
        cert["witness"] = witness_block(graph, q1, q2)
        summary["witness_sizes"] = [len(q1), len(q2)]
    else:
        # Zero quorum-bearing SCCs: no quorum exists at all, so no witness
        # pair is possible — the certificate claims (and the checker
        # re-verifies) graph-wide quorum absence instead.
        cert["no_quorum"] = True
        summary["no_quorum"] = True
    rec.add("cert.certificates")
    rec.event("cert.emitted", **summary)
    return cert


def write_certificate(cert: Dict[str, object], path: str) -> Optional[str]:
    """Write one certificate to ``path`` (atomic tmp+rename).

    The write is a declared fault point (``cert.write``,
    docs/ROBUSTNESS.md): an ``OSError`` — injected disk-full or real —
    downgrades to the ``cert.write_errors`` counter plus a
    ``cert.write_error`` event and returns None.  A certificate is
    evidence about a verdict; failing to record it must never flip or
    cost the verdict itself."""
    rec = get_run_record()
    try:
        fault_point("cert.write")
        tmp = f"{path}.tmp{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(cert, fh, indent=1, default=str)
            fh.write("\n")
        os.replace(tmp, path)
    except OSError as exc:
        rec.add("cert.write_errors")
        rec.event("cert.write_error", path=str(path), error=str(exc))
        log.warning("certificate write failed (%s); verdict unaffected", exc)
        return None
    rec.add("cert.writes")
    rec.event("cert.written", path=str(path))
    return str(path)
