"""Flatten nested quorum sets into a dense **threshold circuit** suitable for
batched TPU evaluation (SURVEY.md §7.3 "Nested qsets on TPU").

The reference evaluates slice satisfaction by recursion over qset objects with
dual early-exit counters (`/root/reference/quorum_intersection.cpp:90-138`).
That recursion is hostile to XLA (dynamic control flow, pointer chasing), so we
re-express the same math as a layered monotone threshold circuit:

- one **unit** per quorum set occurrence; unit ``i < n`` is node *i*'s
  top-level quorum set, inner sets get fresh unit ids;
- ``sat(u) = [ |members(u) ∩ avail| + Σ_{c ∈ children(u)} sat(c) ≥ threshold(u) ]``
- node *i* has a satisfied slice iff ``avail[i] ∧ sat(i)`` — the self-
  availability conjunct is quirk Q4 (cpp:95-98; checking it once at the root is
  equivalent to the reference's per-recursion check because the owner is the
  same at every depth).

Children are strictly deeper than parents, so ``depth+1`` synchronous sweeps of
the update rule computed over *all* units converge exactly — each sweep is two
dense matmuls (``avail @ members`` and ``sat @ childᵀ``), which is precisely
the shape the MXU wants.  Early-exit counters are pointless on TPU: evaluating
everything densely in a batch is the fast path.

Degenerate thresholds are **normalized away at encode time** so device kernels
carry no quirk logic:

- null/empty qset (Q2)      → threshold 1 with zero members: never satisfiable;
- ``threshold == 0`` (Q3)   → ``members + children + 1``: never satisfiable.
  NB the reference's behavior here is *chaotic*, not unsatisfiable: its
  ``threshold == 0`` check sits after the per-member decrements (cpp:105-118),
  so a zero-threshold slice is TRUE iff its first member is unavailable.  We
  deliberately normalize instead of reproducing that (see
  ``fbas/semantics.py:slice_satisfied``);
- ``threshold < 0``         → same normalization (the reference would wrap it
  into an astronomically large unsigned value: never satisfiable);
- ``threshold > members``   → kept as-is (naturally unsatisfiable).

Dangling-reference policy (Q1) is resolved earlier, in
:mod:`quorum_intersection_tpu.fbas.graph`.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List

import numpy as np

from quorum_intersection_tpu.fbas.graph import IndexedQSet, TrustGraph

UNSAT_SENTINEL_DOC = "threshold normalized to members+children+1 ⇒ never satisfiable"


@dataclass
class Circuit:
    """Dense threshold-circuit encoding of a trust graph's quorum sets.

    Array inventory (``U`` = unit count, ``n`` = node count):

    - ``thresholds``  (U,)  int32 — normalized thresholds (see module docs)
    - ``members``     (U,n) uint8 — members[u, v] = 1 iff node v is a direct
      validator of unit u (0/1 — multiplicity is NOT kept here: the reference
      counts a duplicated validator once per occurrence in the *slice* test
      loop (cpp:103-110)... see note below)
    - ``child``       (U,U) uint8 — child[u, c] = 1 iff unit c is an inner set
      of unit u
    - ``unit_depth``  (U,)  int32 — 0 for roots, +1 per nesting level
    - ``depth``       — max(unit_depth)

    **Duplicate-validator note:** the reference iterates the validator list, so
    a validator listed twice contributes two votes (cpp:103-110).  ``members``
    therefore stores *vote counts*, not 0/1 — uint8 counts (a validator listed
    >255 times in one slice would be pathological input).

    CSR views (``mem_indptr``/``mem_indices`` with per-entry ``mem_counts``,
    ``child_indptr``/``child_indices``) feed the native C++ backend the same
    circuit without densification.
    """

    n: int
    n_units: int
    depth: int
    thresholds: np.ndarray
    members: np.ndarray
    child: np.ndarray
    unit_depth: np.ndarray
    mem_indptr: np.ndarray = field(repr=False, default=None)
    mem_indices: np.ndarray = field(repr=False, default=None)
    mem_counts: np.ndarray = field(repr=False, default=None)
    child_indptr: np.ndarray = field(repr=False, default=None)
    child_indices: np.ndarray = field(repr=False, default=None)

    @property
    def lanes(self) -> int:
        """uint32 lanes needed to pack an n-node availability mask."""
        return (self.n + 31) // 32


def encode_circuit(graph: TrustGraph) -> Circuit:
    """Encode every node's quorum set into one shared threshold circuit."""
    n = graph.n
    # First pass: count inner units to size arrays. Roots are units 0..n-1.
    n_units = n
    for q in graph.qsets:
        stack = list(q.inner)
        while stack:
            iq = stack.pop()
            n_units += 1
            stack.extend(iq.inner)

    thresholds = np.zeros(n_units, dtype=np.int32)
    members = np.zeros((n_units, n), dtype=np.uint8)
    child = np.zeros((n_units, n_units), dtype=np.uint8)
    unit_depth = np.zeros(n_units, dtype=np.int32)

    next_unit = [n]

    def fill(unit: int, q: IndexedQSet, depth: int) -> None:
        unit_depth[unit] = depth
        n_members = len(q.members) + len(q.inner)
        if q.threshold is None:
            # Q2: null qset — threshold 1 over zero members: never satisfiable.
            thresholds[unit] = 1
            return
        if q.threshold <= 0:
            # Q3 normalization: never satisfiable.
            thresholds[unit] = n_members + 1
        else:
            thresholds[unit] = min(q.threshold, np.iinfo(np.int32).max)
        for v in q.members:
            if members[unit, v] == np.iinfo(np.uint8).max:
                raise ValueError(f"validator {v} listed >255 times in one quorum set")
            members[unit, v] += 1
        for iq in q.inner:
            cu = next_unit[0]
            next_unit[0] += 1
            child[unit, cu] = 1
            fill(cu, iq, depth + 1)

    for i, q in enumerate(graph.qsets):
        fill(i, q, 0)
    assert next_unit[0] == n_units

    # CSR views for the native backend.
    mem_lists: List[np.ndarray] = []
    mem_count_lists: List[np.ndarray] = []
    child_lists: List[np.ndarray] = []
    mem_indptr = np.zeros(n_units + 1, dtype=np.int32)
    child_indptr = np.zeros(n_units + 1, dtype=np.int32)
    for u in range(n_units):
        midx = np.nonzero(members[u])[0].astype(np.int32)
        mem_lists.append(midx)
        mem_count_lists.append(members[u, midx].astype(np.int32))
        cidx = np.nonzero(child[u])[0].astype(np.int32)
        child_lists.append(cidx)
        mem_indptr[u + 1] = mem_indptr[u] + len(midx)
        child_indptr[u + 1] = child_indptr[u] + len(cidx)
    mem_indices = np.concatenate(mem_lists) if mem_lists else np.zeros(0, np.int32)
    mem_counts = np.concatenate(mem_count_lists) if mem_count_lists else np.zeros(0, np.int32)
    child_indices = np.concatenate(child_lists) if child_lists else np.zeros(0, np.int32)

    return Circuit(
        n=n,
        n_units=n_units,
        depth=int(unit_depth.max(initial=0)),
        thresholds=thresholds,
        members=members,
        child=child,
        unit_depth=unit_depth,
        mem_indptr=mem_indptr,
        mem_indices=mem_indices.astype(np.int32),
        mem_counts=mem_counts.astype(np.int32),
        child_indptr=child_indptr,
        child_indices=child_indices.astype(np.int32),
    )


def node_sat_np(circuit: Circuit, avail: np.ndarray) -> np.ndarray:
    """NumPy reference evaluator: which nodes have a satisfied slice?

    ``avail``: (..., n) bool.  Returns (..., n) bool.  This is the
    specification the JAX kernels are differentially tested against; it must
    agree with :func:`quorum_intersection_tpu.fbas.semantics.slice_satisfied`.
    """
    avail_f = avail.astype(np.int32)
    base = avail_f @ circuit.members.T.astype(np.int32)  # (..., U)
    sat = np.zeros(avail.shape[:-1] + (circuit.n_units,), dtype=np.int32)
    child_t = circuit.child.T.astype(np.int32)
    for _ in range(circuit.depth + 1):
        sat = ((base + sat @ child_t) >= circuit.thresholds).astype(np.int32)
    return (sat[..., : circuit.n] & avail_f).astype(bool)


def max_quorum_np(circuit: Circuit, avail: np.ndarray) -> np.ndarray:
    """Greatest-fixpoint quorum inside ``avail`` (..., n) — NumPy reference for
    the device fixpoint kernel (parity with cpp:140-177 restricted-availability
    semantics: candidates and availability are the same set here)."""
    cur = avail.astype(bool).copy()
    while True:
        nxt = node_sat_np(circuit, cur)
        if np.array_equal(nxt, cur):
            return cur
        cur = nxt
