"""Flatten nested quorum sets into a dense **threshold circuit** suitable for
batched TPU evaluation (SURVEY.md §7.3 "Nested qsets on TPU").

The reference evaluates slice satisfaction by recursion over qset objects with
dual early-exit counters (`/root/reference/quorum_intersection.cpp:90-138`).
That recursion is hostile to XLA (dynamic control flow, pointer chasing), so we
re-express the same math as a monotone threshold-circuit DAG:

- one **unit** per *distinct* quorum set: unit ``i < n`` is node *i*'s
  top-level quorum set; identical inner sets are interned and shared, with a
  repeated inner set contributing its multiplicity as a child vote count;
- ``sat(u) = [ |members(u) ∩ avail| + Σ_{c ∈ children(u)} sat(c) ≥ threshold(u) ]``
- node *i* has a satisfied slice iff ``avail[i] ∧ sat(i)`` — the self-
  availability conjunct is quirk Q4 (cpp:95-98; checking it once at the root is
  equivalent to the reference's per-recursion check because the owner is the
  same at every depth).

The shared circuit is an acyclic DAG; ``depth+1`` synchronous sweeps of the
update rule computed over *all* units converge exactly, where ``depth`` is the
DAG **height** (after sweep *k*, every unit of height < *k* is correct — by
induction on height).  Each sweep is two dense matmuls (``avail @ members``
and ``sat @ childᵀ``), which is precisely the shape the MXU wants.  Early-exit counters are pointless on TPU: evaluating
everything densely in a batch is the fast path.

Degenerate thresholds are **normalized away at encode time** so device kernels
carry no quirk logic:

- null/empty qset (Q2)      → threshold 1 with zero members: never satisfiable;
- ``threshold == 0`` (Q3)   → ``members + children + 1``: never satisfiable.
  NB the reference's behavior here is *chaotic*, not unsatisfiable: its
  ``threshold == 0`` check sits after the per-member decrements (cpp:105-118),
  so a zero-threshold slice is TRUE iff its first member is unavailable.  We
  deliberately normalize instead of reproducing that (see
  ``fbas/semantics.py:slice_satisfied``);
- ``threshold < 0``         → same normalization (the reference would wrap it
  into an astronomically large unsigned value: never satisfiable);
- ``threshold > members``   → kept as-is (naturally unsatisfiable).

Dangling-reference policy (Q1) is resolved earlier, in
:mod:`quorum_intersection_tpu.fbas.graph`.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from quorum_intersection_tpu.fbas.graph import IndexedQSet, TrustGraph

UNSAT_SENTINEL_DOC = "threshold normalized to members+children+1 ⇒ never satisfiable"


@dataclass
class Circuit:
    """Dense threshold-circuit encoding of a trust graph's quorum sets.

    Array inventory (``U`` = unit count, ``n`` = node count):

    - ``thresholds``  (U,)  int32 — normalized thresholds (see module docs)
    - ``members``     (U,n) uint8 — vote count of node v in unit u's validator
      list: the reference iterates the list, so a validator listed twice
      contributes two votes (cpp:103-110); >255 repeats is rejected as
      pathological input
    - ``child``       (U,U) uint8 — vote count of inner-set unit c within
      unit u (identical inner sets intern to one unit, so a duplicated inner
      set shows up as multiplicity here; same 255 cap)
    - ``unit_depth``  (U,)  int32 — DAG **height** of each unit: 0 for units
      with no children, ``1 + max(child heights)`` otherwise
    - ``depth``       — max height; ``depth+1`` synchronous sweeps evaluate
      the circuit exactly

    (The native C++ backend does not read this dense encoding — it flattens
    the quorum-set trees itself from the :class:`TrustGraph`,
    ``backends/cpp/__init__.py`` ``FlatGraph`` — so no sparse views live here.)
    """

    n: int
    n_units: int
    depth: int
    thresholds: np.ndarray
    members: np.ndarray
    child: np.ndarray
    unit_depth: np.ndarray

    @property
    def lanes(self) -> int:
        """uint32 lanes needed to pack an n-node availability mask."""
        return (self.n + 31) // 32


def _check_qset_depth(qsets: List[IndexedQSet]) -> None:
    """Iterative depth guard: the interning recursion below (and the frozen
    dataclass hashes it triggers) must never see a tree deeper than the
    schema-level cap — graphs built through ``parse_fbas`` are pre-capped,
    but programmatically constructed ones are not."""
    from quorum_intersection_tpu.fbas.schema import MAX_QSET_DEPTH

    for root in qsets:
        stack = [(root, 0)]
        while stack:
            q, d = stack.pop()
            if d > MAX_QSET_DEPTH:
                raise ValueError(
                    f"quorumSet nesting exceeds depth {MAX_QSET_DEPTH}"
                )
            stack.extend((iq, d + 1) for iq in q.inner)


def encode_circuit(graph: TrustGraph) -> Circuit:
    """Encode every node's quorum set into one shared threshold circuit.

    Identical inner quorum sets are **interned** — real FBAS configurations
    repeat the same org-level inner sets across every validator of the
    network (a 256-node 16-org network would otherwise carry 16×256 copies of
    16 distinct units).  Sharing keeps the circuit a DAG; the sweep count
    needed for convergence becomes the DAG *height* (longest unit→leaf path),
    stored per unit in ``unit_depth`` with ``depth = max height``.
    """
    n = graph.n
    _check_qset_depth(graph.qsets)

    thresholds_l: List[int] = []
    member_rows: List[dict] = []  # unit → {vertex: vote count}
    child_rows: List[List[int]] = []  # unit → child unit ids
    heights: List[int] = []
    interned: dict = {}

    def new_unit() -> int:
        thresholds_l.append(0)
        member_rows.append({})
        child_rows.append([])
        heights.append(0)
        return len(thresholds_l) - 1

    def fill(unit: int, q: IndexedQSet) -> None:
        n_members = len(q.members) + len(q.inner)
        if q.threshold is None:
            # Q2: null qset — threshold 1 over zero members: never satisfiable.
            thresholds_l[unit] = 1
            return
        if q.threshold <= 0:
            # Q3 normalization: never satisfiable.
            thresholds_l[unit] = n_members + 1
        else:
            thresholds_l[unit] = min(q.threshold, np.iinfo(np.int32).max)
        row = member_rows[unit]
        for v in q.members:
            row[v] = row.get(v, 0) + 1
            if row[v] > np.iinfo(np.uint8).max:
                raise ValueError(f"validator {v} listed >255 times in one quorum set")
        h = 0
        for iq in q.inner:
            cu = intern(iq)
            child_rows[unit].append(cu)
            h = max(h, heights[cu] + 1)
        heights[unit] = h

    def intern(q: IndexedQSet) -> int:
        unit = interned.get(q)
        if unit is None:
            unit = new_unit()
            fill(unit, q)
            interned[q] = unit
        return unit

    # Roots first: unit i is node i's top-level quorum set (kernels rely on
    # this layout); their inner sets are interned/shared below.
    for _ in range(n):
        new_unit()
    for i, q in enumerate(graph.qsets):
        fill(i, q)

    n_units = len(thresholds_l)
    thresholds = np.asarray(thresholds_l, dtype=np.int32)
    members = np.zeros((n_units, n), dtype=np.uint8)
    child = np.zeros((n_units, n_units), dtype=np.uint8)
    unit_depth = np.asarray(heights, dtype=np.int32)
    for u in range(n_units):
        for v, count in member_rows[u].items():
            members[u, v] = count
        for cu in child_rows[u]:
            if child[u, cu] == np.iinfo(np.uint8).max:
                raise ValueError(
                    f"inner quorum set repeated >255 times in one quorum set (unit {u})"
                )
            child[u, cu] += 1

    return Circuit(
        n=n,
        n_units=n_units,
        depth=int(unit_depth.max(initial=0)),
        thresholds=thresholds,
        members=members,
        child=child,
        unit_depth=unit_depth,
    )


# Rank-ordered windows (ISSUE 10): the sweep's verdict-equivalence proof
# (backends/tpu/sweep.py module docs) holds for ANY ordering of the SCC —
# any single node may be fixed out of the enumeration and any assignment of
# the rest to index bits is exhaustive.  The ordering is therefore a free
# perf knob: candidates composed of low-bit nodes occupy low window
# indices, so putting the nodes most likely to form a (minimal) quorum at
# the LOW bits shrinks the expected first-hit window of a `false` verdict,
# while low-rank nodes ride the high bits.  Scores: top-tier membership
# (union of minimal quorums, budget-bounded) first, PageRank second, and a
# deterministic node-index tie-break so two runs in one process order
# identically.  Witness decode is order-transparent — the sweep keeps the
# permuted graph-space id list and maps hit bits back through it before the
# host recheck — and the permutation is stamped into cert provenance.

# B&B call budget for the top-tier score component: bounded so ordering
# setup stays a fraction of any sweep it precedes; exceeding it (or any
# analytics failure) silently drops the component, leaving PageRank.
RANK_ORDER_TOP_TIER_BUDGET = 200_000


def rank_order_nodes(
    graph: TrustGraph,
    scc: Sequence[int],
    *,
    top_tier_budget: int = RANK_ORDER_TOP_TIER_BUDGET,
) -> Tuple[List[int], Dict[str, object]]:
    """Rank-order an SCC for sweep enumeration: ``(ordered, meta)``.

    ``ordered[0]`` is the node fixed OUT of the enumeration (the
    lowest-ranked member — it occupies "bit infinity"); ``ordered[1 + j]``
    is enumeration bit *j*, descending rank, so the highest-ranked nodes
    occupy the lowest window bits.  ``meta`` is the provenance stamp
    (mode/source/fixed node id) certificates carry.
    """
    from quorum_intersection_tpu.analytics.pagerank import pagerank_np

    ranks = pagerank_np(graph)
    tier: frozenset = frozenset()
    source = "pagerank"
    try:
        from quorum_intersection_tpu.analytics.top_tier import top_tier

        members, _ = top_tier(graph, list(scc), budget_calls=top_tier_budget)
        if members:
            tier = frozenset(members)
            source = "pagerank+top-tier"
    # qi-lint: allow(degrade-via-ladder) — scoring heuristic, not a rung;
    # any failure (no native build, budget blown) degrades to PageRank-only
    except Exception:  # noqa: BLE001 — ordering is a heuristic, never fatal
        pass
    best_first = sorted(
        scc, key=lambda v: (0 if v in tier else 1, -float(ranks[v]), v)
    )
    ordered = [best_first[-1]] + best_first[:-1]
    meta: Dict[str, object] = {
        "mode": "rank",
        "source": source,
        "fixed": graph.node_ids[ordered[0]],
        # The full permutation in graph-space ids (bit j = bit_nodes[j]),
        # so ANY ordered certificate — pruned or not — lets a consumer
        # reconstruct the enumeration (e.g. interpret stats["hit_index"]
        # or audit the ordering claim); scores alone are not recoverable
        # from a cert.
        "bit_nodes": [graph.node_ids[v] for v in ordered[1:]],
    }
    return ordered, meta


# Canonical pad ladder for device sweeps (backends/tpu/sweep.py warm-start
# compile path): node and unit counts round UP to the nearest rung so the
# compiled program shapes — which key the persistent XLA compilation cache —
# collapse from "one per exact (n, n_units)" to a handful of buckets.  Rungs
# are sub-tile below 128 (XLA pads the lane axis to 128 anyway, so the extra
# columns are free) and tile-multiples above; beyond the ladder the exact
# size is kept (snapshot-scale circuits are already restricted to their SCC
# before padding applies).
PAD_LADDER = (8, 16, 24, 32, 48, 64, 96, 128, 192, 256, 384, 512, 768, 1024)


def ladder_up(x: int) -> int:
    """Smallest :data:`PAD_LADDER` rung holding ``x`` (identity beyond the
    ladder) — the rounding primitive shared by :func:`pad_targets`, the
    frontier's compile-shape bucketing, and the lane-packing slot planner."""
    for rung in PAD_LADDER:
        if x <= rung:
            return rung
    return x


def pad_targets(n: int, n_units: int) -> tuple:
    """Canonical padded ``(n, n_units)`` for one circuit: each dimension
    rounds up to the smallest :data:`PAD_LADDER` rung that holds it (identity
    beyond the ladder).  Two structural invariants the kernels read off the
    shapes are preserved: ``n_units >= n`` (they slice ``sat[..., :n]``, so
    every padded node index needs a unit row) and the STRICT ``n_units > n``
    of a circuit with inner units (``CircuitArrays.has_inner`` — collapsing
    it to equality would silently skip the child-propagation matmuls)."""
    n_pad = ladder_up(n)
    if n_units <= n:
        return n_pad, n_pad
    return n_pad, ladder_up(max(n_units, n_pad + 1))


def pad_circuit(circuit: Circuit, n_to: int, units_to: int) -> Circuit:
    """Grow a circuit to ``(n_to, units_to)`` with inert padding — equal
    satisfaction semantics for every availability row supported on the
    original ``n`` nodes (pinned by differential tests vs
    :func:`node_sat_np` / :func:`max_quorum_np`).

    Padding is doubly inert: padded node COLUMNS carry zero votes in every
    unit (a padded node's availability influences nothing), and padded unit
    ROWS get the Q2 never-satisfiable encoding (threshold 1 over zero
    members), so ``sat[..., n:n_to]`` is identically 0 regardless of input.
    Callers must keep padded nodes out of every availability input (the
    sweep decode does so structurally: its ``pos`` table maps only real
    nodes; masks are zero-extended).
    """
    if n_to == circuit.n and units_to == circuit.n_units:
        return circuit
    if n_to < circuit.n or units_to < max(circuit.n_units, n_to):
        raise ValueError(
            f"pad target ({n_to}, {units_to}) below circuit shape "
            f"({circuit.n}, {circuit.n_units})"
        )
    if circuit.n_units > circuit.n and units_to <= n_to:
        raise ValueError(
            "padding would collapse n_units > n — the inner-unit marker "
            "the device kernels key child propagation on"
        )
    thresholds = np.ones(units_to, dtype=np.int32)  # Q2: unsatisfiable filler
    thresholds[: circuit.n_units] = circuit.thresholds
    members = np.zeros((units_to, n_to), dtype=np.uint8)
    members[: circuit.n_units, : circuit.n] = circuit.members
    child = np.zeros((units_to, units_to), dtype=np.uint8)
    child[: circuit.n_units, : circuit.n_units] = circuit.child
    unit_depth = np.zeros(units_to, dtype=np.int32)
    unit_depth[: circuit.n_units] = circuit.unit_depth
    return Circuit(
        n=n_to,
        n_units=units_to,
        depth=circuit.depth,
        thresholds=thresholds,
        members=members,
        child=child,
        unit_depth=unit_depth,
    )


def restrict_circuit_pair(circuit: Circuit, scc: List[int]) -> tuple:
    """Project the circuit onto the SCC's columns, folding the constant
    contribution of non-SCC nodes into thresholds — both folds at once:
    ``(scoped, q6)``, identical members/child/unit layout.

    Device searches (sweep, frontier) only ever evaluate availability rows
    whose support lies inside the SCC; every other node's availability is a
    CONSTANT for the whole search — 0 for the candidate-scoped Q-side
    fixpoints, 1 for the Q6 whole-graph-availability probes (cpp:354).
    Constants fold: a unit's non-SCC member votes become a threshold
    reduction, and a unit with no SCC node in its transitive support has a
    statically known satisfaction that folds into its parents the same way.
    What remains is an equivalent circuit over ``len(scc)`` nodes — for a
    1024-node snapshot with a 34-node core, the fixpoint matmuls shrink
    from (B,1024)x(1024,U) to (B,34)x(34,U'), a ~30x MXU-work reduction at
    identical semantics.  The dynamic-unit classification is fold-
    independent, so the two variants share every array except thresholds —
    searches that scope their Q-side but probe under Q6 (sweep_step, the
    frontier's flag filter) take one of each.

    Equivalence (pinned by differential tests): for any availability row
    ``a`` with support ⊆ scc,
    ``fixpoint(full, a, frozen)[scc] == fixpoint(restricted, a[scc])``
    where ``frozen`` is the constant outside-availability row of the
    matching fold.  Thresholds may legitimately become <= 0 here
    ("satisfied by constants alone") — the kernels' ``>=`` compare needs
    no special casing.  New node *j* is ``scc[j]``; root-unit layout
    (unit j = node j's qset) is preserved.
    """
    n, U = circuit.n, circuit.n_units
    s = len(scc)
    scc_arr = np.asarray(scc, dtype=np.int64)
    in_s = np.zeros(n, dtype=bool)
    in_s[scc_arr] = True

    members = circuit.members.astype(np.int64)
    child = circuit.child.astype(np.int64)
    const_votes = members[:, ~in_s].sum(axis=1)  # Q6 fold; scoped fold is 0
    has_s_member = members[:, scc_arr].sum(axis=1) > 0

    # Bottom-up (children are always deeper-interned units, so ascending
    # height order visits children first): classify units as dynamic (an
    # SCC node somewhere in the transitive support) and evaluate static
    # units' constant satisfaction under each fold.
    order = np.argsort(circuit.unit_depth, kind="stable")
    dynamic = has_s_member.copy()
    static_sat = {True: np.zeros(U, dtype=bool), False: np.zeros(U, dtype=bool)}
    for u in order:
        kids = np.nonzero(child[u])[0]
        if kids.size and dynamic[kids].any():
            dynamic[u] = True
        if not dynamic[u]:
            for q6 in (False, True):
                votes = const_votes[u] if q6 else 0
                if kids.size:
                    votes += int((child[u, kids] * static_sat[q6][kids]).sum())
                static_sat[q6][u] = votes >= circuit.thresholds[u]

    thr = {q6: circuit.thresholds.astype(np.int64).copy() for q6 in (False, True)}
    for u in np.nonzero(dynamic)[0]:
        kids = np.nonzero(child[u])[0]
        sk = kids[~dynamic[kids]] if kids.size else kids
        for q6 in (False, True):
            if q6:
                thr[q6][u] -= const_votes[u]
            if sk.size:
                thr[q6][u] -= int((child[u, sk] * static_sat[q6][sk]).sum())

    # Keep every SCC root (in scc order — the new root layout) plus the
    # dynamic units reachable from them.  Static children folded above;
    # dynamic units unreachable from SCC roots are dead weight.
    keep: List[int] = [int(v) for v in scc_arr]
    keep_set = set(keep)
    stack = list(keep)
    while stack:
        u = stack.pop()
        for c in np.nonzero(child[u])[0]:
            c = int(c)
            if dynamic[c] and c not in keep_set:
                keep_set.add(c)
                keep.append(c)
                stack.append(c)
    remap = {u: i for i, u in enumerate(keep)}

    U2 = len(keep)
    i32 = np.iinfo(np.int32)
    members2 = np.zeros((U2, s), dtype=np.uint8)
    child2 = np.zeros((U2, U2), dtype=np.uint8)
    thresholds2 = {q6: np.zeros(U2, dtype=np.int32) for q6 in (False, True)}
    for u in keep:
        i = remap[u]
        for q6 in (False, True):
            thresholds2[q6][i] = int(np.clip(thr[q6][u], i32.min + 1, i32.max))
        members2[i] = circuit.members[u, scc_arr]
        for c in np.nonzero(child[u])[0]:
            c = int(c)
            if dynamic[c]:
                child2[i, remap[c]] = circuit.child[u, c]

    depth2 = np.zeros(U2, dtype=np.int32)
    for u in sorted(keep, key=lambda x: int(circuit.unit_depth[x])):
        i = remap[u]
        kids = np.nonzero(child2[i])[0]
        depth2[i] = 0 if kids.size == 0 else int(depth2[kids].max()) + 1

    def build(q6: bool) -> Circuit:
        return Circuit(
            n=s,
            n_units=U2,
            depth=int(depth2.max(initial=0)),
            thresholds=thresholds2[q6],
            members=members2,
            child=child2,
            unit_depth=depth2,
        )

    return build(False), build(True)


def restrict_two_family(
    circuit_a: Circuit, circuit_b: Circuit, scc: List[int]
) -> tuple:
    """Two-circuit restriction for the relaxed two-family query (qi-query,
    Fast Flexible Paxos arXiv:2008.02671): project BOTH families' circuits
    onto the same SCC columns in the same member order —
    ``(a_scoped, b_scoped, b_q6)``.

    Both circuits must be encoded over the identical node set (the
    two-family contract: one vertex order, two quorum-set families), so
    one ``scc`` index list projects both.  ``a_scoped`` is family A's
    candidate-scoped restriction — the enumeration side: the greatest
    A-quorum inside a window mask is one :func:`max_quorum_np` fixpoint,
    vectorizable over whole window batches.  ``b_scoped`` is family B's
    scoped twin — the FAST overlap guard: a B-quorum found inside
    ``scc ∖ qa`` under scoped availability is a real B-quorum (scoped
    availability only under-approximates), so a nonempty scoped fixpoint
    is an immediate disjointness witness without leaving the restricted
    coordinates.  ``b_q6`` is B's whole-graph-availability fold — the
    sound SLOW guard's device twin for B-quorums that lean on nodes
    outside the SCC (the host ``cross_family_disjoint_quorum`` remains
    the reference the kernels are differentially tested against).

    Same equivalence contract as :func:`restrict_circuit_pair` (which
    this composes), pinned per family by ``tests/test_qi_query.py``.
    """
    if circuit_a.n != circuit_b.n:
        raise ValueError(
            f"two-family circuits must share one node set; got "
            f"{circuit_a.n} != {circuit_b.n} nodes"
        )
    a_scoped, _a_q6 = restrict_circuit_pair(circuit_a, scc)
    b_scoped, b_q6 = restrict_circuit_pair(circuit_b, scc)
    return a_scoped, b_scoped, b_q6


def node_sat_np(circuit: Circuit, avail: np.ndarray) -> np.ndarray:
    """NumPy reference evaluator: which nodes have a satisfied slice?

    ``avail``: (..., n) bool.  Returns (..., n) bool.  This is the
    specification the JAX kernels are differentially tested against; it must
    agree with :func:`quorum_intersection_tpu.fbas.semantics.slice_satisfied`.
    """
    avail_f = avail.astype(np.int32)
    base = avail_f @ circuit.members.T.astype(np.int32)  # (..., U)
    sat = np.zeros(avail.shape[:-1] + (circuit.n_units,), dtype=np.int32)
    child_t = circuit.child.T.astype(np.int32)
    for _ in range(circuit.depth + 1):
        sat = ((base + sat @ child_t) >= circuit.thresholds).astype(np.int32)
    return (sat[..., : circuit.n] & avail_f).astype(bool)


def max_quorum_np(circuit: Circuit, avail: np.ndarray) -> np.ndarray:
    """Greatest-fixpoint quorum inside ``avail`` (..., n) — NumPy reference for
    the device fixpoint kernel (parity with cpp:140-177 restricted-availability
    semantics: candidates and availability are the same set here)."""
    cur = avail.astype(bool).copy()
    while True:
        nxt = node_sat_np(circuit, cur)
        if np.array_equal(nxt, cur):
            return cur
        cur = nxt


# ---------------------------------------------------------------------------
# Lane packing (ISSUE 5): the MXU multiplies 128x128 tiles, so a 31-node
# circuit occupies a sliver of the lane axis and XLA's "free" padding (see
# PAD_LADDER above) is 100% wasted compute.  A PackedCircuit tiles K
# independent circuits side-by-side along the lane axis into ONE circuit
# with block-diagonal structure, so one batched sweep resolves K verdicts
# per matmul instead of one.
#
# Packing invariants (pinned by tests/test_lane_packing.py; docs/PARITY.md):
#
# - **block-diagonal inertness**: group g's units carry votes ONLY from
#   group g's lane columns, and the child matrix links only units of the
#   same group — cross-blocks are identically zero, so each group's
#   satisfaction/fixpoint is computed exactly as it would be alone (the
#   fused fixpoint is the product of the per-group fixpoints);
# - **root-unit layout**: lane ``g*slot + j`` (j < n_g) is group g's node j
#   AND unit ``g*slot + j`` is its root unit, preserving the ``sat[..., :n]``
#   slice contract of the kernels; padded lane slots get the Q2
#   never-satisfiable filler from :func:`pad_circuit`;
# - **decode-map contract**: :meth:`PackedCircuit.decode_tables` is the ONE
#   source of the per-lane-group decode — per-lane enumeration bit position
#   (group-local bit j toggles local node j+1, node 0 fixed out exactly as
#   in the unpacked sweep), lane→group id, the packed scc mask, and the
#   (n, K) group-indicator used for per-group hit reduction.
#
# Members must be SCC-restricted circuits (encode.restrict_circuit_pair):
# restriction guarantees the root-unit layout and folds all outside
# availability into thresholds, so the packed block needs no frozen row.

# One MXU tile along the lane axis: the packing budget one pack tries to
# fill (a pack of K slot-wide groups targets K*slot <= LANE_TILE).
LANE_TILE = 128


@dataclass
class PackedCircuit:
    """K independent circuits fused into one block-diagonal :class:`Circuit`.

    ``circuit`` is the scoped (Q-side) fusion; ``circuit_d`` the Q6-fold
    (D-probe) twin sharing every array except thresholds (None when every
    member was scope-to-scc).  ``slot`` is the uniform lane width per group;
    group g's real nodes live at lanes ``[g*slot, g*slot + sizes[g])``.
    """

    circuit: Circuit
    circuit_d: Optional[Circuit]
    groups: int
    slot: int
    sizes: Tuple[int, ...]
    # Pack provenance (qi-fuse): the originating request id per lane group,
    # aligned with ``sizes``.  None for single-origin packs formed outside
    # the serve drain — the pre-fusion behavior.
    origins: Optional[Tuple[str, ...]] = None

    @property
    def origin_count(self) -> int:
        """Distinct contributing origins (0 when provenance is untracked)."""
        return len(set(self.origins)) if self.origins else 0

    @property
    def fill_pct(self) -> float:
        """Pack occupancy: verdict-bearing lanes / padded lane width."""
        return 100.0 * float(sum(self.sizes)) / float(max(self.circuit.n, 1))

    def lane_base(self, g: int) -> int:
        return g * self.slot

    def decode_tables(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        """Per-lane-group decode map: ``(pos, scc_mask, lane_group, group_ind)``.

        - ``pos``        (n,) int32 — enumeration bit position per lane
          (31 = not enumerated, the :func:`...kernels.bit_positions`
          convention): group g's local node j >= 1 decodes bit j-1 of that
          group's candidate index; local node 0 is fixed out of the
          enumeration exactly as in the unpacked sweep;
        - ``scc_mask``   (n,) float32 — 1 on every real lane;
        - ``lane_group`` (n,) int32 — owning group per lane (padded lanes
          map to group 0; their ``pos`` of 31 decodes them to 0 regardless);
        - ``group_ind``  (n, K) float32 — indicator used to reduce per-lane
          fixpoint survivors into per-group counts with one matmul.
        """
        n = self.circuit.n
        pos = np.full((n,), 31, dtype=np.int32)
        scc_mask = np.zeros((n,), dtype=np.float32)
        lane_group = np.zeros((n,), dtype=np.int32)
        group_ind = np.zeros((n, self.groups), dtype=np.float32)
        for g, size in enumerate(self.sizes):
            base = g * self.slot
            scc_mask[base : base + size] = 1.0
            lane_group[base : base + size] = g
            group_ind[base : base + size, g] = 1.0
            for j in range(1, size):
                pos[base + j] = j - 1
        return pos, scc_mask, lane_group, group_ind


def plan_packs(sizes: Sequence[int], lane_tile: int = LANE_TILE) -> List[List[int]]:
    """Greedy pack plan: indices into ``sizes`` grouped so each pack's
    ``K * slot`` fits one lane tile, where ``slot`` is the ladder rung of
    the pack's LARGEST member (descending-size order keeps slots tight —
    mixed-size packs waste at most the rung gap per lane group).  Jobs wider
    than a tile get a singleton pack (K=1 degenerates to the padded sweep).
    """
    order = sorted(range(len(sizes)), key=lambda i: (-sizes[i], i))
    packs: List[List[int]] = []
    cur: List[int] = []
    capacity = 0
    for i in order:
        if cur and len(cur) < capacity:
            cur.append(i)
            continue
        slot = ladder_up(max(int(sizes[i]), 1))
        capacity = max(1, lane_tile // slot)
        cur = [i]
        packs.append(cur)
    return packs


def pack_circuits(
    members: Sequence[Tuple[Circuit, Optional[Circuit]]],
    lane_tile: int = LANE_TILE,
    origins: Optional[Sequence[str]] = None,
) -> PackedCircuit:
    """Fuse K ``(scoped, q6_or_None)`` circuit pairs into one
    :class:`PackedCircuit` (invariants in the section comment above).

    Every member must have root-unit layout (unit j = node j's quorum set
    for j < n — what :func:`encode_circuit` and
    :func:`restrict_circuit_pair` produce) and a Q6 twin, when present,
    sharing the scoped member's shapes.  The fused block rounds up to the
    canonical :data:`PAD_LADDER` shape, so packed programs ride the same
    warm-start compile-cache discipline as the unpacked sweep.
    """
    if not members:
        raise ValueError("pack_circuits needs at least one circuit")
    if origins is not None and len(origins) != len(members):
        raise ValueError(
            f"{len(origins)} origins for {len(members)} members — pack "
            f"provenance must be lane-group-aligned"
        )
    sizes = tuple(c.n for c, _ in members)
    for c, d in members:
        if d is not None and (d.n != c.n or d.n_units != c.n_units):
            raise ValueError(
                f"q6 twin shape {(d.n, d.n_units)} does not match scoped "
                f"member {(c.n, c.n_units)}"
            )
    k = len(members)
    slot = ladder_up(max(max(sizes), 1))
    if k > 1 and k * slot > lane_tile:
        raise ValueError(
            f"{k} groups of slot {slot} exceed the {lane_tile}-lane tile; "
            f"plan packs with plan_packs()"
        )
    n_raw = k * slot
    inner_total = sum(c.n_units - c.n for c, _ in members)
    u_raw = n_raw + inner_total

    thresholds = np.ones(u_raw, dtype=np.int32)  # Q2 filler in padded slots
    thresholds_d = np.ones(u_raw, dtype=np.int32)
    members_m = np.zeros((u_raw, n_raw), dtype=np.uint8)
    child = np.zeros((u_raw, u_raw), dtype=np.uint8)
    unit_depth = np.zeros(u_raw, dtype=np.int32)
    any_d = any(d is not None for _, d in members)

    inner_base = n_raw
    for g, (c, d) in enumerate(members):
        base = g * slot
        n_g = c.n
        umap = np.concatenate([
            np.arange(base, base + n_g, dtype=np.int64),
            np.arange(inner_base, inner_base + (c.n_units - n_g), dtype=np.int64),
        ])
        thresholds[umap] = c.thresholds
        thresholds_d[umap] = c.thresholds if d is None else d.thresholds
        members_m[np.ix_(umap, np.arange(base, base + n_g))] = c.members
        child[np.ix_(umap, umap)] = c.child
        unit_depth[umap] = c.unit_depth
        inner_base += c.n_units - n_g

    depth = max(c.depth for c, _ in members)
    fused = Circuit(
        n=n_raw, n_units=u_raw, depth=depth, thresholds=thresholds,
        members=members_m, child=child, unit_depth=unit_depth,
    )
    fused_d: Optional[Circuit] = None
    if any_d:
        # The Q6 twin shares every array except thresholds — the same
        # aliasing restrict_circuit_pair uses for the unpacked pair.
        fused_d = Circuit(
            n=n_raw, n_units=u_raw, depth=depth, thresholds=thresholds_d,
            members=members_m, child=child, unit_depth=unit_depth,
        )

    n_to, units_to = pad_targets(n_raw, u_raw)
    fused = pad_circuit(fused, n_to, units_to)
    if fused_d is not None:
        fused_d = pad_circuit(fused_d, n_to, units_to)
    return PackedCircuit(
        circuit=fused, circuit_d=fused_d, groups=k, slot=slot, sizes=sizes,
        origins=tuple(origins) if origins is not None else None,
    )


# ---------------------------------------------------------------------------
# Bitset encoding (ISSUE 20 qi-sparse): the same threshold circuit as packed
# uint32 membership words, for the intersect-and-popcount sweep kernels
# (backends/tpu/kernels.py bitset_* / pallas_sweep.pallas_bitset_program_
# factory).  The dense encoding pays one MAC per (node, unit) pair whether or
# not the node votes anywhere; on a sparse graph (qset fanout ≪ n) that is
# almost entirely multiplied zeros.  A bitset row covers 32 nodes per word,
# so the per-unit vote count becomes ceil(n/32) AND+popcount lane ops —
# density-independent too, but 32× narrower, which is what makes the sparse
# engine win once n outgrows a few MXU tiles (benchmarks/sweep_vs_native.py
# --bitset measures the crossover; backends/calibration.py carries it).
#
# Invariants (pinned by tests/test_qi_sparse.py):
#
# - **exact-shape encoding**: word counts derive from the circuit as given
#   (``words = ceil(n/32)``, ``unit_words = ceil(n_units/32)``) — the driver
#   pads circuits up the canonical PAD_LADDER *before* encoding, so bitset
#   program shapes bucket by ladder rung exactly like the dense path
#   (a 48-node rung is 2 words, 128 is 4, ... — one compiled shape each);
# - **thresholds verbatim**: thresholds, unit_depth, and the inner-qset DAG
#   structure are the dense circuit's arrays unchanged — only the vote
#   MATRICES change representation, so restriction folds (including ≤ 0
#   thresholds) and the Q2/Q3 normalizations carry over untouched;
# - **multiplicity gate**: a membership bit can encode a vote count of 0 or
#   1 only.  Circuits with repeated validators / repeated inner sets
#   (members or child counts > 1 — pathological but legal input) are not
#   bitset-encodable; callers gate on :func:`bitset_supported` and the
#   sweep driver resolves such circuits back to the dense engine.

BITSET_WORD_BITS = 32


def pack_mask_words(mask: np.ndarray, words: int) -> np.ndarray:
    """Pack 0/1 rows ``(..., m)`` into uint32 words ``(..., words)``.

    Bit ``j % 32`` of word ``j // 32`` is column *j* (LSB-first within a
    word, matching the kernels' ``(idx >> pos) & 1`` decode convention).
    Values are truthiness-packed (any nonzero → bit set)."""
    mask = np.asarray(mask)
    m = mask.shape[-1]
    if m > words * BITSET_WORD_BITS:
        raise ValueError(f"{m} columns do not fit {words} uint32 words")
    padded = np.zeros(mask.shape[:-1] + (words * BITSET_WORD_BITS,), dtype=np.uint64)
    padded[..., :m] = mask != 0
    shifts = np.uint64(1) << np.arange(BITSET_WORD_BITS, dtype=np.uint64)
    packed = (padded.reshape(mask.shape[:-1] + (words, BITSET_WORD_BITS)) * shifts).sum(
        axis=-1
    )
    return packed.astype(np.uint32)


def unpack_mask_words(packed: np.ndarray, m: int) -> np.ndarray:
    """Inverse of :func:`pack_mask_words`: ``(..., words)`` uint32 →
    ``(..., m)`` uint8 0/1 (the round-trip the encoding tests pin)."""
    packed = np.asarray(packed, dtype=np.uint32)
    j = np.arange(m)
    return (
        (packed[..., j // BITSET_WORD_BITS] >> (j % BITSET_WORD_BITS).astype(np.uint32))
        & np.uint32(1)
    ).astype(np.uint8)


def bitset_supported(circuit: Circuit) -> bool:
    """Can this circuit's vote matrices be represented as bitsets?
    True iff every member and child vote count is 0/1 (see section note)."""
    return (
        int(circuit.members.max(initial=0)) <= 1
        and int(circuit.child.max(initial=0)) <= 1
    )


@dataclass(frozen=True)
class BitsetCircuit:
    """Bitset twin of :class:`Circuit`: identical thresholds/DAG, packed
    uint32 vote rows.

    - ``member_words`` (U, words)      — bit *v* of unit *u*'s row set iff
      node *v* votes in unit *u* (``circuit.members[u, v] == 1``);
    - ``child_words``  (U, unit_words) — bit *c* set iff unit *c* is a
      child of unit *u*; ``None`` when the circuit has no inner units;
    - ``thresholds`` / ``unit_depth`` / ``depth`` — the dense arrays
      verbatim (restriction folds included).
    """

    n: int
    n_units: int
    depth: int
    words: int
    unit_words: int
    thresholds: np.ndarray
    member_words: np.ndarray
    child_words: Optional[np.ndarray]
    unit_depth: np.ndarray

    def decode_members(self) -> np.ndarray:
        """(U, n) uint8 dense member matrix — must equal the source
        circuit's ``members`` exactly (round-trip invariant)."""
        return unpack_mask_words(self.member_words, self.n)

    def decode_child(self) -> Optional[np.ndarray]:
        """(U, U) uint8 dense child matrix (None when no inner units)."""
        if self.child_words is None:
            return None
        return unpack_mask_words(self.child_words, self.n_units)


def bitset_encode(circuit: Circuit) -> BitsetCircuit:
    """Encode a (0/1-vote) circuit into its :class:`BitsetCircuit` twin.

    Raises ``ValueError`` for circuits with vote multiplicities > 1 — the
    sweep driver gates on :func:`bitset_supported` first, so reaching the
    raise from the drivers indicates a routing bug (or an injected
    ``sweep.bitset`` fault exercising the in-place dense degrade)."""
    if not bitset_supported(circuit):
        raise ValueError(
            "circuit has vote multiplicities > 1; the bitset encoding is "
            "0/1-vote only — use the dense engine"
        )
    words = (circuit.n + BITSET_WORD_BITS - 1) // BITSET_WORD_BITS
    unit_words = (circuit.n_units + BITSET_WORD_BITS - 1) // BITSET_WORD_BITS
    has_inner = circuit.n_units > circuit.n
    return BitsetCircuit(
        n=circuit.n,
        n_units=circuit.n_units,
        depth=circuit.depth,
        words=max(words, 1),
        unit_words=max(unit_words, 1),
        thresholds=circuit.thresholds.astype(np.int32),
        member_words=pack_mask_words(circuit.members, max(words, 1)),
        child_words=(
            pack_mask_words(circuit.child, max(unit_words, 1)) if has_inner else None
        ),
        unit_depth=circuit.unit_depth,
    )
