"""Encoding: nested quorum sets → dense threshold-circuit arrays."""

from quorum_intersection_tpu.encode.circuit import Circuit, encode_circuit, node_sat_np, max_quorum_np

__all__ = ["Circuit", "encode_circuit", "node_sat_np", "max_quorum_np"]
