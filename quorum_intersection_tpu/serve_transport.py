"""qi-serve transports — the engine/transport seam (ISSUE 11).

PR 8 built :class:`quorum_intersection_tpu.serve.ServeEngine` but fused it
to one transport: a stdio loop inside ``serve_main``.  The ROADMAP's fleet
item names the engine/transport split as the seam — this module is that
split.  The engine stays transport-agnostic (submit → Ticket → callback);
everything that turns bytes into requests and outcomes into bytes lives
here, once, shared by every way an engine can be driven:

- **stdio** (:func:`serve_main`): the existing CLI contract, byte-for-byte
  — one JSON request per stdin line, one JSON response per stdout line in
  completion order, EOF drains and exits 0 (``tests/test_serve.py`` pins
  it; the split must not churn a single expectation);
- **sockets** (:class:`SocketServeServer`): the same JSONL conversation
  over TCP (127.0.0.1), many concurrent connections sharing ONE engine —
  each connection gets its own :class:`JsonlSession`, so its responses
  never interleave with another client's;
- **the fleet supervisor** (``fleet.py``): worker subprocesses run this
  module's stdio loop over pipes, and the front door's
  :class:`~quorum_intersection_tpu.fleet.LocalWorker` reuses
  :func:`ticket_response` directly — both worker kinds answer in exactly
  the shape this module emits, so the front door cannot tell them apart.

Protocol (one JSON value per line, ``qi-serve/1``):

- request: a raw stellarbeat node array, or ``{"request_id", "nodes"}``
  optionally with ``"deadline_s"`` (per-request budget — the fleet front
  door forwards its clients' budgets this way);
- response: ``{"request_id", "verdict", "cached", "seconds"}`` or
  ``{"request_id", "error": {"code", "message"}}``; with certificates
  enabled (``--emit-certs``, the fleet workers' mode) the verdict line
  additionally carries ``"cert"`` and ``"stats"`` — off by default so the
  pre-split byte contract holds;
- probe: ``{"ping": token}`` → ``{"pong": token, ...}`` with the worker's
  readiness and a small counter/gauge snapshot (:func:`pong_payload`) —
  the fleet's health probes and its fleet-wide ``/healthz`` aggregation
  ride this instead of N scrape ports.
"""

from __future__ import annotations

import argparse
import base64
import hashlib
import hmac
import json
import os
import socketserver
import sys
import threading
from typing import Dict, Iterator, List, Optional, TextIO

from quorum_intersection_tpu.cost import tenant_table
from quorum_intersection_tpu.serve import (
    ServeEngine,
    ServeError,
    ServeResponse,
    Ticket,
)
from quorum_intersection_tpu.utils.env import qi_env
from quorum_intersection_tpu.utils.faults import FaultInjected
from quorum_intersection_tpu.utils.logging import get_logger
from quorum_intersection_tpu.utils.telemetry import get_run_record

log = get_logger("serve.transport")

PROTOCOL_SCHEMA = "qi-serve/1"

# qi-mesh (ISSUE 19): the versioned join handshake a multi-host fleet
# front door performs before a socket worker enters its ring.  Bump on
# any wire-incompatible change — a mismatch is a TYPED reject
# (hello_err), never a silently skewed mesh.
MESH_PROTOCOL = 1

# Journal-ship framing (qi-mesh): chunk payload size before base64.  Each
# chunk line carries its own byte length (length-prefixed framing on top
# of JSONL) and the end line carries the stream digest — the receiver
# fsyncs BEFORE acknowledging, so an acked ship is durable.
SHIP_CHUNK_BYTES = 64 * 1024


def package_fingerprint() -> str:
    """The wire-compatibility fingerprint the join handshake compares:
    package version + every schema string a mesh peer must agree on.  Two
    hosts with different fingerprints get a typed reject instead of a
    protocol skew that only surfaces as lost or wrong work."""
    from quorum_intersection_tpu import __version__

    basis = "|".join((
        str(__version__), PROTOCOL_SCHEMA, f"mesh/{MESH_PROTOCOL}",
    ))
    return hashlib.sha256(basis.encode("utf-8")).hexdigest()[:16]


def fleet_token_digest() -> str:
    """SHA-256 digest of the shared mesh secret (``QI_FLEET_TOKEN``) —
    the wire never carries the raw token.  Empty token ⇒ empty digest:
    unauthenticated loopback mode, and both sides must agree on it."""
    token = qi_env("QI_FLEET_TOKEN")
    if not token:
        return ""
    return hashlib.sha256(token.encode("utf-8")).hexdigest()

# The counter/gauge slice one pong carries: enough for the fleet front
# door to aggregate health (store hit %, delta reuse, queue depth) without
# opening N scrape ports — docs/OBSERVABILITY.md §Fleet.
PONG_COUNTERS = (
    "serve.requests",
    "serve.verdicts",
    "serve.errors",
    "serve.cache_hits",
    "fleet.store_hits",
    "fleet.store_misses",
    "fleet.store_errors",
    "delta.scc_hits",
    "delta.scc_misses",
)
PONG_GAUGES = (
    "serve.queue_depth",
    "delta.scc_reuse_pct",
    "delta.store_size",
)

# The pulse histograms one pong ships (qi-pulse, ISSUE 15): the worker's
# own per-stage latency distributions, merged bucket-wise by the fleet
# front door's aggregation plane.  Deliberately the serve-side stage set
# only — a front door's fleet.* merged views must never ride a pong back
# into another aggregation (no fleet-of-fleets double counting).
PONG_PULSE = (
    "pulse.queue_wait_ms",
    "pulse.cache_ms",
    "pulse.delta_ms",
    "pulse.solve_ms",
    "pulse.respond_ms",
    "pulse.e2e_ms",
)


def pong_payload(token: object) -> Dict[str, object]:
    """The ``{"ping": token}`` reply: readiness + a health snapshot +
    the worker's pulse histogram snapshots (the aggregation plane's
    transport — piggybacked here instead of N scrape ports)."""
    rec = get_run_record()
    counters, gauges = rec.snapshot()
    hists = rec.histograms_snapshot()
    replay = gauges.get("serve.replay_complete")
    payload = {
        "pong": token,
        "schema": PROTOCOL_SCHEMA,
        "pid": os.getpid(),
        "ready": bool(replay) if replay is not None else True,
        "counters": {k: counters.get(k, 0) for k in PONG_COUNTERS},
        "gauges": {k: gauges.get(k, 0) for k in PONG_GAUGES},
        "pulse": {k: hists[k] for k in PONG_PULSE if k in hists},
    }
    # qi-cost (ISSUE 17): the worker's cumulative per-tenant cost table
    # rides the pong like the pulse histograms — the fleet front door
    # pid-dedupes and REBUILDS its merged view each cycle (cumulative
    # snapshots must replace, never accumulate).  Same deliberate rule as
    # PONG_PULSE: only the LOCAL table ships, never a fleet-merged one.
    tenants = tenant_table().snapshot()
    if tenants:
        payload["cost"] = tenants
    return payload


def ticket_response(
    ticket: Ticket, *, emit_certs: bool = False
) -> Dict[str, object]:
    """One RESOLVED ticket → its JSONL response object (the single place
    the outcome→wire shape lives; LocalWorker and both loop transports
    share it so a fleet front door sees one shape from every worker)."""
    try:
        resp: ServeResponse = ticket.result(timeout=0)
    except ServeError as exc:
        return {"request_id": ticket.request_id,
                "error": {"code": exc.code, "message": str(exc)}}
    except Exception as exc:  # noqa: BLE001 — an untyped failure still gets a response line
        # Typed non-serve failures (the query layer's QueryError rides
        # here) keep their machine-readable code; anything else is
        # "internal" — still a response line, never a silent drop.
        return {"request_id": ticket.request_id,
                "error": {"code": str(getattr(exc, "code", "internal")),
                          "message": str(exc)}}
    line: Dict[str, object] = {
        "request_id": resp.request_id,
        "verdict": resp.intersects,
        "cached": resp.cached,
        "seconds": round(resp.seconds, 6),
    }
    if resp.trace is not None:
        # Wire trace echo (qi-pulse): the request's carried context rides
        # the response so the fleet front door (and any client) can join
        # the verdict to its distributed trace.
        line["trace"] = resp.trace
    if resp.result is not None:
        # Typed-query payload (qi-query/1): verdict stays the boolean
        # summary, the structured table/witness/report rides alongside.
        line["result"] = resp.result
    if resp.cost is not None:
        # qi-cost/1 (ISSUE 17): what this verdict cost on the device —
        # absent on cache hits, degraded attribution and legacy backends
        # (the byte-compatible pre-cost response shape).
        line["cost"] = resp.cost
    if emit_certs:
        line["cert"] = resp.cert
        line["stats"] = resp.stats
    return line


class JsonlSession:
    """One JSONL conversation against one engine.

    Owns the write lock (responses from concurrent ticket callbacks never
    interleave bytes) and the outstanding-ticket count, so a transport can
    drain a single connection without stopping the shared engine.
    """

    def __init__(self, engine: ServeEngine, writer: TextIO,
                 *, emit_certs: bool = False) -> None:
        self._engine = engine
        self._writer = writer
        self._emit_certs = emit_certs
        self._lock = threading.Lock()
        self._outstanding = 0
        self._drained = threading.Condition(self._lock)

    def emit(self, obj: Dict[str, object]) -> None:
        """Write one response line; a vanished client (closed socket) is
        logged and dropped — its verdict is already cached and journaled,
        so a reconnect-and-retry is a cache hit, never lost work."""
        try:
            with self._lock:
                self._writer.write(json.dumps(obj, default=str) + "\n")
                self._writer.flush()
        except (OSError, ValueError) as exc:
            log.warning("response write failed (client gone?): %s", exc)

    def _on_done(self, ticket: Ticket) -> None:
        self.emit(ticket_response(ticket, emit_certs=self._emit_certs))
        with self._drained:
            self._outstanding -= 1
            self._drained.notify_all()

    # ---- qi-mesh handshake + journal shipping (ISSUE 19) -----------------

    def _handle_hello(self, hello: object) -> None:
        """The versioned join handshake: protocol + package fingerprint +
        shared-secret digest must all match, or the peer gets a TYPED
        ``hello_err`` — a mesh must never run skewed silently.  A valid
        hello may announce the front door's store gateway; the engine then
        reads through to it on every fragment miss (fetch-on-miss,
        publish-on-solve)."""
        rec = get_run_record()
        hello = hello if isinstance(hello, dict) else {}

        def _reject(code: str, message: str) -> None:
            rec.add("serve.hello_rejects")
            rec.event("serve.hello_rejected", code=code)
            log.warning("mesh hello rejected (%s): %s", code, message)
            self.emit({"hello_err": {"code": code, "message": message}})

        schema = hello.get("schema")
        protocol = hello.get("protocol")
        if schema != PROTOCOL_SCHEMA or protocol != MESH_PROTOCOL:
            _reject(
                "protocol_mismatch",
                f"peer speaks {schema!r}/mesh-{protocol!r}, this worker "
                f"speaks {PROTOCOL_SCHEMA!r}/mesh-{MESH_PROTOCOL}",
            )
            return
        fingerprint = hello.get("fingerprint")
        if fingerprint != package_fingerprint():
            _reject(
                "fingerprint_mismatch",
                f"peer package fingerprint {fingerprint!r} != "
                f"{package_fingerprint()!r} — upgrade one side; a skewed "
                f"mesh is refused, not guessed at",
            )
            return
        token = hello.get("token")
        if not hmac.compare_digest(
            str(token or ""), fleet_token_digest(),
        ):
            _reject("bad_token", "QI_FLEET_TOKEN digest mismatch")
            return
        store = hello.get("store")
        if isinstance(store, dict):
            # The front door's store gateway: attach the remote fragment
            # tier (fetch-on-miss, publish-on-solve).  Safe by
            # construction — fragments re-verify through the checker, so
            # a torn/corrupt/forged shipped fragment is just a miss.
            from quorum_intersection_tpu.delta import RemoteStoreClient

            client = RemoteStoreClient(
                str(store.get("host") or "127.0.0.1"),
                int(store.get("port") or 0),
            )
            self._engine.attach_remote_store(client)
        rec.event("serve.hello_ok", peer=str(hello.get("peer") or ""))
        _, gauges = rec.snapshot()
        replay = gauges.get("serve.replay_complete")
        self.emit({"hello_ok": {
            "schema": PROTOCOL_SCHEMA,
            "protocol": MESH_PROTOCOL,
            "fingerprint": package_fingerprint(),
            "pid": os.getpid(),
            "ready": bool(replay) if replay is not None else True,
            "replay": self._engine.replay_report,
        }})

    def _handle_ship(self, ship: object) -> None:
        """Stream this worker's crash-only journal to the requesting peer:
        chunked + length-prefixed (each ``ship_chunk`` carries its own
        byte length, the ``ship_end`` line the stream digest), so the
        receiver can fsync-then-ack and a torn stream is detected, never
        replayed.  The journal file itself is append-fsynced by
        construction — shipping reads a consistent prefix."""
        rec = get_run_record()
        ship = ship if isinstance(ship, dict) else {}
        if not hmac.compare_digest(
            str(ship.get("token") or ""), fleet_token_digest(),
        ):
            rec.add("serve.hello_rejects")
            rec.event("serve.hello_rejected", code="bad_token")
            self.emit({"ship_err": {"code": "bad_token",
                                    "message": "QI_FLEET_TOKEN digest "
                                               "mismatch"}})
            return
        path = self._engine.journal_path
        if path is None:
            self.emit({"ship_err": {"code": "no_journal",
                                    "message": "this worker runs without "
                                               "a request journal"}})
            return
        try:
            raw = path.read_bytes()
        except OSError as exc:
            self.emit({"ship_err": {"code": "journal_unreadable",
                                    "message": str(exc)}})
            return
        chunks = 0
        for off in range(0, len(raw), SHIP_CHUNK_BYTES):
            piece = raw[off:off + SHIP_CHUNK_BYTES]
            self.emit({"ship_chunk": {
                "seq": chunks,
                "len": len(piece),
                "data": base64.b64encode(piece).decode("ascii"),
            }})
            chunks += 1
        self.emit({"ship_end": {
            "chunks": chunks,
            "bytes": len(raw),
            "sha256": hashlib.sha256(raw).hexdigest(),
        }})
        rec.add("serve.journal_ships")
        rec.event("serve.journal_shipped", chunks=chunks, bytes=len(raw))

    def handle_line(self, n: int, line: str) -> None:
        """One request line → submit (or ping/typed rejection), non-blocking."""
        line = line.strip()
        if not line:
            return
        request_id: Optional[str] = None
        try:
            obj = json.loads(line)
            if isinstance(obj, dict) and "ping" in obj:
                self.emit(pong_payload(obj["ping"]))
                return
            if isinstance(obj, dict) and "hello" in obj:
                self._handle_hello(obj["hello"])
                return
            if isinstance(obj, dict) and "ship_journal" in obj:
                self._handle_ship(obj["ship_journal"])
                return
            if isinstance(obj, dict) and "ship_ack" in obj:
                # The receiving peer fsynced the shipped journal: the
                # hand-off is durable on the inheriting side.
                get_run_record().event("serve.ship_acked")
                return
            nodes = obj
            deadline_s: Optional[float] = None
            query: Optional[object] = None
            trace: Optional[str] = None
            if isinstance(obj, dict):
                request_id = obj.get("request_id")
                nodes = obj.get("nodes")
                raw_deadline = obj.get("deadline_s")
                if raw_deadline is not None:
                    deadline_s = float(raw_deadline)
                # qi-query/1 (ISSUE 12): absent ⇒ intersection, the
                # byte-compatible legacy request.
                query = obj.get("query")
                # qi-pulse (ISSUE 15): optional wire trace context
                # "trace_id:span_id[:pid]" — absent ⇒ the engine's own
                # trace, the byte-compatible legacy request.
                raw_trace = obj.get("trace")
                trace = raw_trace if isinstance(raw_trace, str) else None
                # qi-cost (ISSUE 17): optional client id — the tenant this
                # request's device cost books to.  Absent ⇒ "anon", the
                # byte-compatible legacy request.
                raw_client = obj.get("client")
                client = raw_client if isinstance(raw_client, str) else None
            else:
                client = None
            if not isinstance(nodes, list):
                raise ValueError("expected a node array or "
                                 '{"request_id", "nodes"}')
            ticket = self._engine.submit(
                nodes, request_id=request_id, deadline_s=deadline_s,
                query=query, trace=trace, client=client,
            )
        except ServeError as exc:
            self.emit({"request_id": request_id or f"line-{n + 1}",
                       "error": {"code": exc.code, "message": str(exc)}})
            return
        except (ValueError, TypeError, FaultInjected) as exc:
            # A typed QueryError keeps its own code (unknown_query /
            # invalid_query / ...); other parse failures stay "invalid".
            self.emit({"request_id": request_id or f"line-{n + 1}",
                       "error": {"code": str(getattr(exc, "code", "invalid")),
                                 "message": str(exc)}})
            return
        with self._drained:
            self._outstanding += 1
        ticket.add_done_callback(self._on_done)

    def wait_drained(self, timeout: Optional[float] = None) -> bool:
        """Block until every submitted ticket of THIS session delivered."""
        with self._drained:
            return self._drained.wait_for(
                lambda: self._outstanding == 0, timeout=timeout,
            )


def run_jsonl_loop(session: JsonlSession, reader: TextIO) -> None:
    """Feed ``reader``'s lines through ``session`` until EOF (the caller
    decides whether EOF drains the engine or just this conversation) —
    the one request-loop shared by the stdio CLI, the socket handler and
    the fleet CLI."""
    for n, line in enumerate(reader):
        session.handle_line(n, line)


class SocketServeServer:
    """JSONL-over-TCP twin of the stdio loop: one shared engine, many
    concurrent connections (one :class:`JsonlSession` each).  Binds
    ``QI_SERVE_BIND`` (default loopback, like the metrics endpoint) — a
    routable bind address is the multi-host fleet's explicit opt-in and
    should ride with a non-empty ``QI_FLEET_TOKEN``.  ``port=0`` binds
    ephemeral; read ``.port``.
    """

    def __init__(self, engine: ServeEngine, *, host: Optional[str] = None,
                 port: int = 0, emit_certs: bool = False) -> None:
        outer = self
        host = host or qi_env("QI_SERVE_BIND") or "127.0.0.1"

        class _Handler(socketserver.StreamRequestHandler):
            def handle(self) -> None:
                reader = _utf8_lines(self.rfile)
                writer = _Utf8Writer(self.wfile)
                session = JsonlSession(
                    outer.engine, writer, emit_certs=outer.emit_certs,
                )
                try:
                    run_jsonl_loop(session, reader)  # type: ignore[arg-type]
                except (OSError, ValueError) as exc:
                    # A client that connects and dies mid-line (reset,
                    # torn read) ends THIS session with a typed error —
                    # the acceptor loop and every other connection stay
                    # up, and any work the dead client already submitted
                    # still drains below (its verdicts are cached and
                    # journaled; a reconnect-and-retry is a cache hit).
                    rec = get_run_record()
                    rec.add("serve.errors")
                    rec.event("serve.session_error", error=str(exc))
                    log.warning(
                        "socket session ended mid-line (%s); acceptor "
                        "unaffected", exc,
                    )
                # Connection EOF drains the CONNECTION, not the engine:
                # every response this client is owed goes out before the
                # socket closes; other clients' work is untouched.
                session.wait_drained(timeout=None)

        self.engine = engine
        self.emit_certs = emit_certs
        self._httpd = socketserver.ThreadingTCPServer(
            (host, port), _Handler, bind_and_activate=True,
        )
        self._httpd.daemon_threads = True
        self.host = host
        self.port = int(self._httpd.server_address[1])
        # qi-lint: allow(cancel-token-plumbed) — daemon accept loop, no solve work; stop() shuts it down
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="qi-serve-socket",
            daemon=True,
        )
        self._thread.start()
        log.info("serve socket transport on %s:%d", host, self.port)

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()


def _utf8_lines(raw: object) -> Iterator[str]:
    """Decode a binary line reader lazily — tiny shim so the socket handler
    can share ``JsonlSession`` with the text-mode stdio loop."""
    for line in raw:  # type: ignore[attr-defined]
        yield line.decode("utf-8", errors="replace")


class _Utf8Writer:
    """Text façade over a binary socket file (write + flush only)."""

    def __init__(self, raw: object) -> None:
        self._raw = raw

    def write(self, text: str) -> int:
        self._raw.write(text.encode("utf-8"))  # type: ignore[attr-defined]
        return len(text)

    def flush(self) -> None:
        self._raw.flush()  # type: ignore[attr-defined]


# ---- CLI subcommand ---------------------------------------------------------


def build_serve_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m quorum_intersection_tpu serve",
        description=(
            "Long-lived snapshot-verdict service: one JSON request per "
            "stdin line (a raw stellarbeat node array, or "
            '{"request_id": ..., "nodes": [...]}), one JSON response per '
            "stdout line in completion order.  EOF drains the queue and "
            "exits 0."
        ),
    )
    p.add_argument("--journal", metavar="PATH", default=None,
                   help="crash-only request journal (env twin: "
                        "QI_SERVE_JOURNAL): accepted requests are "
                        "journaled before solving; a hard kill + restart "
                        "replays unfinished work")
    p.add_argument("--deadline-s", type=float, default=None, metavar="F",
                   help="per-request deadline budget in seconds (env twin: "
                        "QI_SERVE_DEADLINE_S; 0 = none)")
    p.add_argument("--queue-depth", type=int, default=None, metavar="N",
                   help="admission-queue bound; over-depth requests are "
                        "shed with a typed 'overloaded' error (env twin: "
                        "QI_SERVE_QUEUE_DEPTH)")
    p.add_argument("--batch-max", type=int, default=None, metavar="N",
                   help="most requests one drain cycle batches into "
                        "pipeline.check_many (env twin: QI_SERVE_BATCH_MAX)")
    p.add_argument("--cache-max", type=int, default=None, metavar="N",
                   help="verdict-cache capacity (env twin: "
                        "QI_SERVE_CACHE_MAX)")
    p.add_argument("--backend", default="auto",
                   choices=["auto", "python", "cpp", "tpu", "tpu-sweep",
                            "tpu-frontier"],
                   help="search backend for served solves (default auto)")
    p.add_argument("--dangling-policy", default="strict",
                   choices=["strict", "alias0"],
                   help="unknown validator refs (default strict)")
    p.add_argument("--scc-select", default="quorum-bearing",
                   choices=["quorum-bearing", "front"],
                   help="which SCC to search (default quorum-bearing)")
    p.add_argument("--scope-scc", action="store_true",
                   help="scope availability to the searched SCC")
    p.add_argument("--no-delta", action="store_true",
                   help="disable incremental re-analysis (qi-delta): every "
                        "snapshot re-solves from scratch instead of reusing "
                        "per-SCC verdict fragments (env twin: "
                        "QI_DELTA_CACHE_MAX=0)")
    p.add_argument("--replay-only", action="store_true",
                   help="replay the journal, print the report, exit "
                        "(restart-recovery probe; no requests accepted)")
    p.add_argument("--emit-certs", action="store_true",
                   help="verdict responses carry their qi-cert/1 "
                        "certificate and solve stats (the fleet workers' "
                        "mode; off by default for wire compatibility)")
    p.add_argument("--socket", type=int, default=None, metavar="PORT",
                   help="ALSO serve the same JSONL protocol over TCP on "
                        "PORT (0 = ephemeral; the bound port is "
                        "announced as a {\"kind\": \"listening\"} line); "
                        "stdin EOF still drains and exits")
    p.add_argument("--bind", metavar="ADDR", default=None,
                   help="bind address of the --socket transport (env "
                        "twin: QI_SERVE_BIND; default 127.0.0.1 — a "
                        "routable address is the multi-host fleet opt-in "
                        "and should ride with QI_FLEET_TOKEN)")
    p.add_argument("--metrics-json", metavar="PATH", default=None,
                   help="stream qi-telemetry/1 JSONL to PATH")
    p.add_argument("--metrics-prom", metavar="PATH", default=None,
                   help="write final counters/gauges to PATH "
                        "(Prometheus textfile)")
    return p


def serve_main(argv: Optional[List[str]] = None) -> int:
    """The ``serve`` subcommand body (dispatched from cli.py)."""
    from quorum_intersection_tpu.utils import telemetry

    args = build_serve_parser().parse_args(argv)
    record = telemetry.get_run_record()
    if args.metrics_json:
        record.add_sink(telemetry.JsonlSink(args.metrics_json))
    if args.metrics_prom:
        record.add_sink(telemetry.PromFileSink(args.metrics_prom))
    engine = ServeEngine(
        backend=args.backend,
        queue_depth=args.queue_depth,
        batch_max=args.batch_max,
        deadline_s=args.deadline_s,
        cache_max=args.cache_max,
        journal=args.journal,
        dangling=args.dangling_policy,
        scc_select=args.scc_select,
        scope_to_scc=args.scope_scc,
        delta=False if args.no_delta else None,
    )
    session = JsonlSession(engine, sys.stdout, emit_certs=args.emit_certs)
    server: Optional[SocketServeServer] = None
    try:
        report = engine.start()
        if report is not None:
            session.emit({"kind": "replay", **report})
        if args.replay_only:
            return 0
        if args.socket is not None:
            server = SocketServeServer(
                engine, host=args.bind, port=args.socket,
                emit_certs=args.emit_certs,
            )
            session.emit({"kind": "listening", "host": server.host,
                          "port": server.port})
        run_jsonl_loop(session, sys.stdin)
        # No drain bound at EOF: every accepted request gets its response
        # line before exit, however long its solve runs (deadlines, not
        # timeouts, are the latency control here).
        engine.stop(drain=True, timeout=None)
        session.wait_drained(timeout=None)
        return 0
    finally:
        if server is not None:
            server.stop()
        engine.stop(drain=False, timeout=5.0)
        record.finish()
