"""One registry for every named fault point the framework can inject at.

The robustness claims of this pipeline — "a disk-full checkpoint write never
kills the run", "a hung native call degrades instead of wedging", "a dead
coordinator falls back to single-process loudly" — used to be assumptions:
the ``except`` sites existed but nothing could *trigger* them on demand, so
they were dead code until production found them first.  This module makes
every such failure reproducible, the same move PR 3 made for thread
interleavings (``tools/analyze/schedules.py``: deterministic schedules, not
sleeps): faults are **declared**, **named**, and fired by **seeded,
deterministic activation schedules** instead of hoping an overfull disk or a
flaky device shows up in CI.

Mirrors the :mod:`quorum_intersection_tpu.utils.env` registry discipline:

- every injectable boundary calls :func:`fault_point` with a name declared
  in the catalog below — an undeclared name raises ``KeyError`` immediately
  (a fault point that is not in the catalog does not exist);
- the catalog IS the documentation (docs/ROBUSTNESS.md renders it), so a
  new boundary cannot ship without a description;
- with no plan installed and ``QI_FAULTS`` unset, :func:`fault_point` is a
  dict lookup and a ``None`` check — negligible on every production path.

Activation comes from either source:

- ``QI_FAULTS`` (env registry): ``point=mode[:seconds][@hit[+]]`` rules,
  comma-separated — e.g. ``QI_FAULTS="checkpoint.write=oserror@3"`` fires a
  disk-full ``OSError`` on the third checkpoint write;
  ``QI_FAULTS="native.call=hang:0.5@1"`` hangs the first native entry for
  half a second.  ``@N`` fires on exactly the Nth hit, ``@N+`` from the Nth
  hit onward; omitted means every hit.
- :func:`install_plan` — tests and the chaos soak install a
  :class:`FaultPlan` programmatically; :func:`sample_plan` draws one from a
  seeded RNG (same seed ⇒ same plan ⇒ same firing sequence, the
  determinism contract ``tests/test_fault_schedules.py`` pins).

Modes map to the failure they simulate:

- ``error``   — generic failed dispatch/compile: raises :class:`FaultInjected`;
- ``oom``     — transient device OOM: raises :class:`TransientDeviceFault`
  (message carries ``RESOURCE_EXHAUSTED``, the marker the degradation
  ladder's retry classifier keys on);
- ``oserror`` — disk full: raises ``OSError(ENOSPC)`` (checkpoint I/O);
- ``hang``    — blocks for ``seconds`` (bounded by :data:`HANG_CAP_S`), the
  native-watchdog trigger;
- ``preempt`` — sweep-window preemption: raises :class:`FaultPreempted`.

Every firing lands in the run record (``fault.injected`` event +
``faults.injected`` counter) and in the plan's ``fired`` log, so a chaos run
can prove which faults actually exercised which paths.
"""

from __future__ import annotations

import errno
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from quorum_intersection_tpu.utils.env import qi_env
from quorum_intersection_tpu.utils.logging import get_logger

log = get_logger("utils.faults")

# Upper bound on an injected hang: a typo'd QI_FAULTS must not wedge a run
# for hours — the watchdog the hang exists to exercise trips in well under
# this, and the (non-daemon) hung thread unwinds on its own afterwards.
HANG_CAP_S = 30.0


# ---- typed injected failures ----------------------------------------------


class FaultInjected(RuntimeError):
    """An injected fault fired at a declared point.

    Typed (never a bare ``RuntimeError`` raised ad hoc) so the chaos soak
    can tell a LOUD injected failure from an untyped crash: the acceptance
    criterion is "verdict equals the fault-free chain or a typed error" —
    this class and its subclasses are the typed errors.
    """

    def __init__(self, point: str, mode: str, hit: int,
                 detail: str = "") -> None:
        self.point = point
        self.mode = mode
        self.hit = hit
        msg = f"injected fault at {point} (mode={mode}, hit {hit})"
        if detail:
            msg = f"{msg}: {detail}"
        super().__init__(msg)


class TransientDeviceFault(FaultInjected):
    """Simulated transient device failure (OOM / allocation pressure).

    The message carries ``RESOURCE_EXHAUSTED`` so the degradation ladder's
    transient classifier treats it exactly like the real XLA error string —
    the retry-with-backoff path is exercised by the same predicate
    production errors hit.
    """

    def __init__(self, point: str, hit: int) -> None:
        super().__init__(
            point, "oom", hit,
            "RESOURCE_EXHAUSTED: simulated device out-of-memory",
        )


class FaultPreempted(FaultInjected):
    """Simulated sweep-window preemption (the scheduler revoked the chip)."""

    def __init__(self, point: str, hit: int) -> None:
        super().__init__(point, "preempt", hit, "window preempted")


# ---- the catalog -----------------------------------------------------------

_POINTS: Dict[str, str] = {}


def _declare(name: str, description: str) -> str:
    _POINTS[name] = description
    return name


CHECKPOINT_WRITE = _declare(
    "checkpoint.write",
    "Checkpoint save (utils/checkpoint.py atomic write): oserror simulates "
    "a full disk — the hardened writer downgrades it to the "
    "checkpoint.save_errors counter, never a crashed run.",
)
NATIVE_CALL = _declare(
    "native.call",
    "Entry into the native C++ search (backends/cpp check_scc): error "
    "simulates a crashed library call, hang a wedged one — the auto "
    "router's watchdog trips the CancelToken and quarantines the rung.",
)
NATIVE_BUILD = _declare(
    "native.build",
    "g++ compile of the native oracle/CLI (backends/cpp _compile): error "
    "simulates a broken toolchain; the ladder degrades to the Python "
    "oracle.",
)
SWEEP_COMPILE = _declare(
    "sweep.compile",
    "Synchronous XLA trace+compile of a sweep program shape "
    "(backends/tpu/sweep.py dispatch): error simulates a compile failure.",
)
SWEEP_DISPATCH = _declare(
    "sweep.dispatch",
    "Device dispatch of one sweep program (backends/tpu/sweep.py): oom "
    "simulates RESOURCE_EXHAUSTED — the transient class the ladder "
    "retries with backoff before degrading.",
)
SWEEP_WINDOW = _declare(
    "sweep.window",
    "Sweep window loop (backends/tpu/sweep.py, once per dispatched "
    "window): preempt simulates losing the chip mid-enumeration.",
)
SWEEP_PACK = _declare(
    "sweep.pack",
    "Lane-pack assembly of a fused multi-problem sweep block "
    "(backends/tpu/sweep.py check_sccs, before any pack is built): error "
    "simulates a packing failure — the auto router's DegradationLadder "
    "degrades to the unpacked per-problem sweep, verdicts unchanged.",
)
SWEEP_PRUNE = _declare(
    "sweep.prune",
    "Block-guard prune planning of the exhaustive sweep "
    "(backends/tpu/sweep.py _plan_pruning, fired once per drive/pack "
    "before any guard is evaluated): error simulates a broken guard path "
    "— the sweep degrades IN PLACE to the unpruned enumeration "
    "(sweep.prune_degraded event + sweep.prune_errors counter), verdicts "
    "unchanged; pruning is an optimization, never a precondition for a "
    "verdict.",
)
SWEEP_BITSET = _declare(
    "sweep.bitset",
    "Bitset kernel-twin construction of the exhaustive sweep "
    "(backends/tpu/sweep.py, fired before the bitset program factory is "
    "built, solo and packed drives alike): error simulates a broken "
    "sparse encoding — the sweep degrades IN PLACE to the dense "
    "block-diagonal encoding (sweep.bitset_degraded event + "
    "sweep.bitset_errors counter), verdict, witness and ledger "
    "unchanged; the bitset twin only changes the fixpoint's arithmetic, "
    "never its result.",
)
FRONTIER_CHUNK = _declare(
    "frontier.chunk",
    "Frontier device-chunk dispatch (backends/tpu/frontier.py): oom/error "
    "simulate a device failure mid-search.",
)
DISTRIBUTED_INIT = _declare(
    "distributed.init",
    "Coordinator join (parallel/distributed.py initialize): error "
    "simulates a dead/unreachable coordinator — bounded retry under "
    "QI_DIST_INIT_TIMEOUT_S, then a loud single-process degrade.",
)
CERT_WRITE = _declare(
    "cert.write",
    "Verdict-certificate write (cert.py write_certificate, CLI "
    "--cert-out): oserror simulates a full disk — the write downgrades to "
    "the cert.write_errors counter and the run keeps its verdict; a "
    "certificate is evidence about a verdict, never a precondition for "
    "one.",
)
SERVE_ADMIT = _declare(
    "serve.admit",
    "Request admission into the serving layer (serve.py ServeEngine."
    "submit): error simulates a broken admission path — the request is "
    "rejected with a typed error, never silently dropped; the queue and "
    "every already-admitted request are unaffected.",
)
SERVE_CACHE = _declare(
    "serve.cache",
    "Verdict-cache lookup/insert (serve.py): error simulates a corrupted "
    "cache — the engine bypasses the cache for that request "
    "(serve.cache_errors counter) and solves from scratch; a cache is an "
    "optimization, never a precondition for a verdict.",
)
SERVE_JOURNAL = _declare(
    "serve.journal",
    "Request-journal append (serve.py RequestJournal): oserror simulates "
    "a full disk — the write downgrades to the serve.journal_errors "
    "counter and the request proceeds UN-journaled (loud: replay "
    "protection is lost for it, the verdict is not).",
)
SERVE_DRAIN = _declare(
    "serve.drain",
    "Admission-queue drain into pipeline.check_many (serve.py drain "
    "loop): error simulates a broken batch path — the engine degrades to "
    "per-request solves; hang simulates a wedged drain (the kill-and-"
    "replay soak's window for a mid-stream hard kill).",
)
SERVE_RESPOND = _declare(
    "serve.respond",
    "Verdict delivery to a waiting client (serve.py): error simulates a "
    "failed response write — the client receives the typed error (never "
    "a silent drop) while the verdict itself is already cached and "
    "journal-marked done, so a retry is a cache hit.",
)
SERVE_FUSE = _declare(
    "serve.fuse",
    "Cross-request batch-former setup in the drain (serve.py _drain_batch, "
    "fired once per drained batch while QI_SERVE_FUSE_WINDOW_MS is "
    "positive, before any fused dispatch): error simulates a broken "
    "former — the batch degrades in place to the unfused per-batch path "
    "(serve.fuse_faults counter + serve.fuse_degraded event), verdicts "
    "unchanged; fusion is an optimization, never a precondition for a "
    "verdict.",
)
DELTA_DIFF = _declare(
    "delta.diff",
    "Snapshot diff / SCC-fingerprint path of the incremental re-analysis "
    "engine (delta.py DeltaEngine.check_many): error simulates a broken "
    "differ — the engine degrades to the full re-solve chain "
    "(pipeline.check_many), verdicts unchanged; incremental re-analysis "
    "is an optimization, never a precondition for a verdict.",
)
FLEET_ROUTE = _declare(
    "fleet.route",
    "Consistent-hash routing decision of the fleet front door (fleet.py "
    "FleetEngine.submit): error simulates a broken ring lookup — the "
    "request degrades to the first live worker (fleet.route_errors "
    "counter, loud; only fleet-wide coalescing locality is lost), never "
    "a dropped request.",
)
FLEET_PROBE = _declare(
    "fleet.probe",
    "Worker health probe of the fleet supervisor (fleet.py probe loop): "
    "error simulates a broken probe path — the cycle is recorded "
    "inconclusive (fleet.probe_errors counter) and NO eviction happens "
    "on an injected failure; eviction requires a dead process or "
    "consecutive real probe timeouts, so a probe fault can cost health "
    "freshness, never a spurious failover.",
)
FLEET_REPLAY = _declare(
    "fleet.replay",
    "Dead-worker journal inheritance (fleet.py FleetEngine failover): "
    "error/oserror simulate an unreadable journal — failover degrades to "
    "re-routing the front door's own in-flight tickets only "
    "(fleet.replay_errors counter, loud: journal-only orphans of a "
    "crashed front door are not recovered this round), never a wrong or "
    "duplicated verdict.",
)
FLEET_STORE = _declare(
    "fleet.store",
    "Shared SCC-fragment store tier (delta.py SharedSccStore get/put, "
    "the fleet workers' read-through second level): error/oserror "
    "simulate a dead shared tier — the store degrades to local-LRU-only "
    "(fleet.store_errors counter, loud; fleet-wide reuse is lost, the "
    "verdict is not), and an unparseable/forged fragment is a miss, "
    "never trusted.",
)
QUERY_DISPATCH = _declare(
    "query.dispatch",
    "Typed-query dispatch (query.py QueryEngine.resolve, fired once per "
    "non-intersection query before any resolver runs): error simulates a "
    "broken query layer — the request degrades to a typed QueryError "
    "(query.errors counter + query.degraded event), NEVER a wrong or "
    "silently-absent verdict; the boolean intersection path does not "
    "route through this point, so injected query faults cannot touch "
    "the byte-compatible legacy protocol.",
)
PULSE_AGGREGATE = _declare(
    "pulse.aggregate",
    "Fleet metrics aggregation cycle (fleet.py _aggregate_health, fired "
    "once per probe cycle before the workers' pong-carried pulse "
    "histograms merge): error simulates a broken aggregation plane — the "
    "cycle degrades to per-worker-only metrics (pulse.agg_errors counter "
    "+ pulse.agg_degraded event, loud; the fleet-wide /metrics view goes "
    "stale, per-worker scrapes and every verdict are untouched).",
)
COST_ATTRIBUTE = _declare(
    "cost.attribute",
    "Per-request device-cost attribution (cost.py qi-cost: the sweep pack "
    "drain's per-origin booking, the serve tenant-table booking, SLO "
    "burn-rate evaluation and the fleet cost merge): error simulates a "
    "broken accounting plane — the step degrades to NO cost (cost."
    "attribute_errors counter + cost.degraded event, loud; a wrong cost "
    "must become a dropped cost, never a wrong verdict — verdicts, certs "
    "and latency are byte-identical with attribution off).",
)
FLEET_JOIN = _declare(
    "fleet.join",
    "Socket-worker join handshake of the multi-host fleet (fleet.py "
    "SocketWorker / FleetEngine start, qi-mesh): error simulates an "
    "unreachable or rejecting peer — the join degrades to bounded "
    "backoff+jitter retries and then to a standalone fleet without that "
    "peer (fleet.join_errors counter + fleet.join_degraded event, loud; "
    "capacity is lost, no verdict is), and a protocol/fingerprint/token "
    "mismatch is always a typed reject, never a silently skewed mesh.",
)
FLEET_LEASE = _declare(
    "fleet.lease",
    "Heartbeat-lease evaluation of the fleet probe loop (fleet.py, "
    "qi-mesh): error simulates a broken lease clock / partitioned probe "
    "plane — the cycle degrades to SUSPECT-ONLY (fleet.lease_errors "
    "counter + fleet.lease_degraded event): a worker may be routed "
    "around and hedged, but an injected lease failure never evicts it, "
    "so a partition can cost locality, never a spurious journal "
    "inheritance; a dead process is still evicted immediately.",
)
FLEET_HEDGE = _declare(
    "fleet.hedge",
    "Hedged dispatch to a suspected worker's next arc owner (fleet.py "
    "FleetEngine._hedge_dispatch, qi-mesh): error simulates a broken "
    "hedging path — the request degrades to a SINGLE dispatch to the "
    "next live arc owner (fleet.hedge_errors counter + "
    "fleet.hedge_degraded event, loud; hedge latency cover is lost, "
    "exactly-once resolution is not — duplicates are already deduplicated "
    "by wire request id).",
)
FLEET_SHIP = _declare(
    "fleet.ship",
    "Cross-host journal shipping at failover/drain (fleet.py "
    "FleetEngine._ship_journal ↔ serve_transport.py ship_journal, "
    "qi-mesh): error/oserror simulate a dead wire or a torn stream — "
    "shipping degrades to LOCAL-JOURNAL-ONLY and loud "
    "(fleet.ship_errors counter + fleet.ship_degraded event: the "
    "journal stays on the worker host for a later local replay), while "
    "the front door's own in-flight tickets still re-route — never a "
    "wrong or duplicated verdict, and a shipped journal is fsynced "
    "before it is ever acknowledged.",
)
FLEET_SCALE = _declare(
    "fleet.scale",
    "Elasticity decision/actuation of the fleet supervisor (fleet.py "
    "FleetEngine._apply_scale, qi-mesh): error simulates a broken "
    "autoscaler — the fleet degrades to its FROZEN current size "
    "(fleet.scale_errors counter + fleet.scale_degraded event, loud; "
    "capacity stops tracking load, no verdict and no in-flight request "
    "is touched — a retire drains through journal inheritance or does "
    "not happen).",
)
STORE_FETCH = _declare(
    "store.fetch",
    "Remote SCC-fragment fetch/publish over the store-gateway wire "
    "(delta.py RemoteStoreClient, qi-mesh): error/oserror simulate a "
    "partitioned or lying store peer — the lookup degrades to a LOCAL "
    "SOLVE (store.fetch_errors counter + store.fetch_degraded event, "
    "loud; fleet-wide reuse is lost, the verdict is not), and a "
    "torn/corrupt/forged shipped fragment fails shape validation and is "
    "just a miss — fragments re-verify through the checker, so the wire "
    "is never trusted.",
)
TELEMETRY_DUMP = _declare(
    "telemetry.dump",
    "Flight-recorder dump write (utils/telemetry.py dump_flight_recorder): "
    "oserror simulates a full disk at the worst moment — mid-crash — and "
    "the dump downgrades to the telemetry.dump_errors counter; a crash "
    "dump must never be the crash.",
)


def registry() -> Dict[str, str]:
    """The declared catalog, name → description (docs generators)."""
    return dict(_POINTS)


# ---- rules and plans -------------------------------------------------------


@dataclass(frozen=True)
class FaultRule:
    """One activation rule: fire ``mode`` at ``point`` on selected hits."""

    point: str
    mode: str  # error | oom | oserror | hang | preempt
    first: int = 1  # first hit (1-based) the rule fires on
    every: bool = True  # True: every hit >= first; False: exactly `first`
    seconds: float = 0.5  # hang duration (hang mode only)

    def __post_init__(self) -> None:
        if self.point not in _POINTS:
            raise KeyError(
                f"{self.point!r} is not a declared fault point; add it to "
                f"quorum_intersection_tpu/utils/faults.py"
            )
        if self.mode not in ("error", "oom", "oserror", "hang", "preempt"):
            raise ValueError(f"unknown fault mode {self.mode!r}")
        if self.first < 1:
            raise ValueError(f"fault hit index must be >= 1, got {self.first}")

    def applies(self, hit: int) -> bool:
        return hit >= self.first if self.every else hit == self.first

    def spec(self) -> str:
        """Round-trippable ``point=mode[:seconds][@hit[+]]`` form."""
        mode = self.mode if self.mode != "hang" else f"hang:{self.seconds:g}"
        hits = f"@{self.first}" + ("+" if self.every else "")
        return f"{self.point}={mode}{hits}"


class FaultPlan:
    """An installed set of rules plus per-point hit counters and a firing
    log.  Thread-safe: the race's worker threads hit points concurrently."""

    def __init__(self, rules: Sequence[FaultRule], label: str = "") -> None:
        self.rules: Tuple[FaultRule, ...] = tuple(rules)
        self.label = label or ",".join(r.spec() for r in self.rules)
        self._lock = threading.Lock()
        self.counts: Dict[str, int] = {}
        # Firing log [(point, mode, hit), ...] — the determinism contract's
        # observable: same plan + same workload ⇒ identical log.
        self.fired: List[Tuple[str, str, int]] = []

    def hit(self, point: str) -> None:
        """Count a hit at ``point``; fire the first applicable rule."""
        with self._lock:
            n = self.counts.get(point, 0) + 1
            self.counts[point] = n
            rule = next(
                (r for r in self.rules
                 if r.point == point and r.applies(n)),
                None,
            )
            if rule is not None:
                self.fired.append((point, rule.mode, n))
        if rule is None:
            return
        self._fire(rule, n)

    def _fire(self, rule: FaultRule, n: int) -> None:
        from quorum_intersection_tpu.utils.telemetry import (
            dump_flight_recorder,
            get_run_record,
        )

        rec = get_run_record()
        rec.add("faults.injected")
        rec.event(
            "fault.injected", point=rule.point, mode=rule.mode, hit=n,
        )
        log.info("fault injected: %s (mode=%s, hit %d)", rule.point,
                 rule.mode, n)
        # Crash flight recorder (ISSUE 6): every injected fault carries its
        # last-N telemetry context out to disk BEFORE the failure is raised.
        # The dump is reentrancy-guarded, so a rule on `telemetry.dump`
        # itself cannot recurse (it fires inside the guarded dump instead,
        # exercising the dump's own degradation path).
        dump_flight_recorder(f"fault:{rule.point}:{rule.mode}")
        if rule.mode == "hang":
            time.sleep(min(max(rule.seconds, 0.0), HANG_CAP_S))
            return
        if rule.mode == "oom":
            raise TransientDeviceFault(rule.point, n)
        if rule.mode == "preempt":
            raise FaultPreempted(rule.point, n)
        if rule.mode == "oserror":
            raise OSError(
                errno.ENOSPC,
                f"injected disk full at {rule.point} (hit {n})",
            )
        raise FaultInjected(rule.point, rule.mode, n)


# ---- active plan -----------------------------------------------------------

_PLAN: Optional[FaultPlan] = None
# Parsed-QI_FAULTS cache keyed by the raw spec string, so the env path does
# not reparse per hit while still honoring a monkeypatched environment the
# moment the string changes (the env registry's no-caching contract).
_env_cache: Tuple[str, Optional[FaultPlan]] = ("", None)


def install_plan(plan: FaultPlan) -> FaultPlan:
    """Install ``plan`` process-wide (tests / chaos soak); returns it."""
    global _PLAN
    _PLAN = plan
    log.info("fault plan installed: %s", plan.label)
    return plan


def clear_plan() -> None:
    """Remove any installed plan (the env-spec path stays live)."""
    global _PLAN
    _PLAN = None


def active_plan() -> Optional[FaultPlan]:
    """The plan :func:`fault_point` currently consults, if any."""
    global _env_cache
    if _PLAN is not None:
        return _PLAN
    raw = qi_env("QI_FAULTS").strip()
    if not raw:
        return None
    if _env_cache[0] != raw:
        _env_cache = (raw, parse_faults(raw))
    return _env_cache[1]


def fault_point(name: str) -> None:
    """Declare-and-maybe-fire: called at every injectable boundary.

    Raises ``KeyError`` for an undeclared name even with no plan installed
    — the runtime twin of the env registry's ``qi_env``: a fault point that
    is not in the catalog does not exist, so a typo'd call site fails in
    the first test that reaches it, not silently never-injectable.
    """
    if name not in _POINTS:
        raise KeyError(
            f"{name!r} is not a declared fault point; add it to "
            f"quorum_intersection_tpu/utils/faults.py"
        )
    plan = active_plan()
    if plan is not None:
        plan.hit(name)


# ---- QI_FAULTS parsing -----------------------------------------------------


def parse_faults(spec: str) -> FaultPlan:
    """Parse a ``QI_FAULTS`` spec into a plan.

    Grammar (rules comma- or semicolon-separated)::

        rule    := point "=" mode [":" seconds] ["@" hit ["+"]]
        mode    := "error" | "oom" | "oserror" | "hang" | "preempt"

    Examples: ``checkpoint.write=oserror@3`` (third write only),
    ``native.call=hang:0.5@1`` (first call hangs 0.5 s),
    ``sweep.dispatch=oom`` (every dispatch).
    """
    rules: List[FaultRule] = []
    for chunk in spec.replace(";", ",").split(","):
        chunk = chunk.strip()
        if not chunk:
            continue
        if "=" not in chunk:
            raise ValueError(
                f"malformed QI_FAULTS rule {chunk!r} (expected "
                f"point=mode[:seconds][@hit[+]])"
            )
        point, _, rhs = chunk.partition("=")
        first, every = 1, True
        if "@" in rhs:
            rhs, _, hits = rhs.partition("@")
            hits = hits.strip()
            if hits.endswith("+"):
                hits = hits[:-1]
            else:
                every = False
            first = int(hits)
        seconds = 0.5
        if ":" in rhs:
            rhs, _, secs = rhs.partition(":")
            seconds = float(secs)
        rules.append(FaultRule(
            point=point.strip(), mode=rhs.strip(), first=first,
            every=every, seconds=seconds,
        ))
    return FaultPlan(rules, label=spec)


# ---- seeded chaos sampling -------------------------------------------------

# What the chaos soak can draw: every entry simulates a production failure
# on a path the auto router's degradation ladder (or the crash-only
# checkpoint writer) must absorb without flipping the verdict.  Hang rules
# stay sub-second — the soak enables a short QI_NATIVE_WATCHDOG_S so the
# watchdog, not the sleep, bounds the stall.
_CHAOS_CHOICES: Tuple[Tuple[str, str, float], ...] = (
    (SWEEP_DISPATCH, "oom", 0.0),
    (SWEEP_WINDOW, "preempt", 0.0),
    (SWEEP_COMPILE, "error", 0.0),
    (NATIVE_CALL, "error", 0.0),
    (NATIVE_CALL, "hang", 0.8),
    (NATIVE_BUILD, "error", 0.0),
    (CHECKPOINT_WRITE, "oserror", 0.0),
    (FRONTIER_CHUNK, "oom", 0.0),
)


# What the serving-layer chaos soak can draw (tools/soak.py --serve
# --chaos): every serve.* boundary plus the engine-side points a served
# solve routes through, so one seeded window exercises admission, cache,
# journal, drain and respond alongside the ladder the drain degrades
# through.  serve.drain hang stays sub-second here; the kill-and-replay
# round uses its own explicit long-hang rule instead of a sampled one.
_SERVE_CHAOS_CHOICES: Tuple[Tuple[str, str, float], ...] = (
    (SERVE_ADMIT, "error", 0.0),
    (SERVE_CACHE, "error", 0.0),
    (SERVE_JOURNAL, "oserror", 0.0),
    (SERVE_DRAIN, "error", 0.0),
    (SERVE_DRAIN, "hang", 0.2),
    (SERVE_RESPOND, "error", 0.0),
    (DELTA_DIFF, "error", 0.0),
    (NATIVE_CALL, "error", 0.0),
    (SWEEP_DISPATCH, "oom", 0.0),
)


# What the fleet chaos soak can draw (tools/soak.py --fleet --chaos): the
# fleet.* boundaries plus the serve.*/delta.* points a routed request
# crosses inside its worker — one seeded window exercises routing, probing,
# failover replay and the shared store tier alongside the per-worker
# degradations.  qi-mesh (ISSUE 19) adds the multi-host boundaries: join,
# lease, hedge, ship, scale and the remote fragment fetch.
_FLEET_CHAOS_CHOICES: Tuple[Tuple[str, str, float], ...] = (
    (FLEET_ROUTE, "error", 0.0),
    (FLEET_PROBE, "error", 0.0),
    (FLEET_REPLAY, "error", 0.0),
    (FLEET_STORE, "error", 0.0),
    (FLEET_STORE, "oserror", 0.0),
    (FLEET_JOIN, "error", 0.0),
    (FLEET_LEASE, "error", 0.0),
    (FLEET_HEDGE, "error", 0.0),
    (FLEET_SHIP, "error", 0.0),
    (FLEET_SCALE, "error", 0.0),
    (STORE_FETCH, "error", 0.0),
    (STORE_FETCH, "oserror", 0.0),
    (SERVE_CACHE, "error", 0.0),
    (SERVE_JOURNAL, "oserror", 0.0),
    (DELTA_DIFF, "error", 0.0),
)

# What the socket-mesh soak round draws (tools/soak.py --fleet --chaos,
# qi-mesh): only the wire-tier boundaries — join, lease and journal ship —
# so every mesh instance exercises the adversarial-wire degradations while
# the per-request oracle parity gate stays the same.
_MESH_CHAOS_CHOICES: Tuple[Tuple[str, str, float], ...] = (
    (FLEET_JOIN, "error", 0.0),
    (FLEET_LEASE, "error", 0.0),
    (FLEET_SHIP, "error", 0.0),
)


def sample_mesh_plan(seed: int) -> FaultPlan:
    """Draw a deterministic socket-mesh fault schedule from ``seed`` — the
    qi-mesh twin of :func:`sample_fleet_plan`, restricted to the wire-tier
    boundaries (``fleet.join`` / ``fleet.lease`` / ``fleet.ship``)."""
    rng = random.Random(seed * 53 + 11)
    n_rules = 1 if rng.random() < 0.5 else 2
    picks = rng.sample(range(len(_MESH_CHAOS_CHOICES)), n_rules)
    rules = []
    for ix in picks:
        point, mode, seconds = _MESH_CHAOS_CHOICES[ix]
        first = 1 if rng.random() < 0.6 else rng.randint(2, 3)
        every = rng.random() < 0.6
        rules.append(FaultRule(
            point=point, mode=mode, first=first, every=every,
            seconds=seconds,
        ))
    return FaultPlan(rules, label=f"mesh-chaos(seed={seed})")


def sample_fleet_plan(seed: int) -> FaultPlan:
    """Draw a deterministic fleet-tier fault schedule from ``seed`` — the
    fleet twin of :func:`sample_serve_plan`, drawing from the fleet.*
    boundaries (same seed ⇒ same rules ⇒ same firing sequence)."""
    rng = random.Random(seed * 31 + 7)
    n_rules = 1 if rng.random() < 0.5 else 2
    picks = rng.sample(range(len(_FLEET_CHAOS_CHOICES)), n_rules)
    rules = []
    for ix in picks:
        point, mode, seconds = _FLEET_CHAOS_CHOICES[ix]
        first = 1 if rng.random() < 0.6 else rng.randint(2, 3)
        every = rng.random() < 0.6
        rules.append(FaultRule(
            point=point, mode=mode, first=first, every=every,
            seconds=seconds,
        ))
    return FaultPlan(rules, label=f"fleet-chaos(seed={seed})")


def sample_serve_plan(seed: int) -> FaultPlan:
    """Draw a deterministic serving-layer fault schedule from ``seed`` —
    the serve twin of :func:`sample_plan`, drawing from the serve.*
    boundaries (same seed ⇒ same rules ⇒ same firing sequence)."""
    rng = random.Random(seed)
    n_rules = 1 if rng.random() < 0.6 else 2
    picks = rng.sample(range(len(_SERVE_CHAOS_CHOICES)), n_rules)
    rules = []
    for ix in picks:
        point, mode, seconds = _SERVE_CHAOS_CHOICES[ix]
        first = 1 if rng.random() < 0.6 else rng.randint(2, 3)
        every = rng.random() < 0.5
        rules.append(FaultRule(
            point=point, mode=mode, first=first, every=every,
            seconds=seconds,
        ))
    return FaultPlan(rules, label=f"serve-chaos(seed={seed})")


def sample_plan(seed: int) -> FaultPlan:
    """Draw a deterministic fault schedule from ``seed``.

    Same seed ⇒ same rules in the same order with the same hit selectors —
    the chaos soak's reproducibility contract (re-running ``--chaos --seed
    N`` replays the identical schedule).
    """
    rng = random.Random(seed)
    n_rules = 1 if rng.random() < 0.7 else 2
    picks = rng.sample(range(len(_CHAOS_CHOICES)), n_rules)
    rules = []
    for ix in picks:
        point, mode, seconds = _CHAOS_CHOICES[ix]
        # Bias toward the first hit and toward every-hit rules: small soak
        # instances touch most points only once or twice, and a rule that
        # never fires soaks nothing.
        first = 1 if rng.random() < 0.6 else rng.randint(2, 3)
        every = rng.random() < 0.7
        rules.append(FaultRule(
            point=point, mode=mode, first=first, every=every,
            seconds=seconds,
        ))
    return FaultPlan(rules, label=f"chaos(seed={seed})")
