"""Unified run-record telemetry: spans + counters from parse to chip.

One schema across the CLI, the racing auto router, the sweep, and both
benchmark drivers (ISSUE 2 tentpole).  The observability story used to be
fragments — ``PhaseTimers`` dicts, ad-hoc ``[stats]`` stderr lines, race
stats buried in ``res.stats["race"]`` — none of them machine-readable in one
stream.  This module is the single cross-cutting layer they all feed:

- **Spans**: named, nested wall-clock intervals (monotonic start/end,
  parent id, free-form attributes).  ``PhaseTimers.phase`` opens one per
  pipeline phase, the auto router wraps its routing decision and the race
  in them, benchmark drivers wrap their phases.
- **Counters / gauges**: typed process-wide accumulators (candidates
  checked, sweep windows dispatched/cancelled, compile-cache hits/misses,
  oracle budget consumed, checkpoint saves/restores).  ``add`` is
  lock-protected — the race's two threads increment concurrently.
- **Events**: point-in-time records (race verdicts, routing decisions,
  per-window sweep progress, checkpoint activity).

Sinks are pluggable and attach to the process-wide :class:`RunRecord`:

- :class:`JsonlSink` — streaming JSONL event file (CLI ``--metrics-json``,
  env ``QI_METRICS_JSON``); every span end / event is written as it
  happens, so a crashed run still leaves a parseable prefix.
- :class:`PromFileSink` — Prometheus-style textfile exporter for soak
  runs (CLI ``--metrics-prom``, env ``QI_METRICS_PROM``): counters and
  gauges rewritten atomically at finish, ready for node_exporter's
  textfile collector.
- :class:`StderrSummarySink` — the human summary (``[telemetry]`` lines),
  appended after the legacy ``[timing]``/``[stats]`` output which stays
  byte-compatible (docs/OBSERVABILITY.md).

Schema (``qi-telemetry/1``, one JSON object per line):

    {"kind": "meta",    "schema": "qi-telemetry/1", "pid": ..., "argv0": ..., "t_wall": ...}
    {"kind": "span",    "name": "phase.search", "span_id": 3, "parent_id": 1,
     "start_s": 0.01, "seconds": 1.2, "attrs": {...}}
    {"kind": "event",   "name": "sweep.window", "t_s": 0.5, "span_id": 3, "attrs": {...}}
    {"kind": "counter", "name": "sweep.candidates_checked", "value": 1048576}
    {"kind": "gauge",   "name": "sweep.candidates_per_sec", "value": 2.1e9}

``t_s``/``start_s`` are seconds since the record's creation (monotonic);
``t_wall`` in the meta line anchors them to wall-clock.  Multi-process runs
(the bench driver's phase children, CLI subprocesses under the test suite)
append to one file; consumers group by ``pid``.  ``tools/metrics_report.py``
renders a stream into per-phase / per-window tables.

Since ISSUE 6 (qi-trace) the record also carries **cross-boundary trace
identity and crash forensics**:

- every record mints (or inherits via ``QI_TRACE_CONTEXT``) a
  :class:`TraceContext` ``trace_id`` stamped on every span/event line, so a
  race loser's spans, a native call, a packed-sweep window and a bench
  child's rows all stitch into ONE causal timeline;
- :class:`ChromeTraceSink` (CLI ``--trace-out``, env ``QI_TRACE_OUT``)
  exports that timeline in Chrome/Perfetto trace-event JSON;
- a bounded, lock-protected **flight-recorder ring** of the last
  :data:`FLIGHT_RECORDER_N` span/event lines is always on;
  :func:`dump_flight_recorder` writes it crash-only (fsync-before-rename,
  the checkpoint discipline) on fault firing, watchdog trip, ladder
  degrade/quarantine, or unhandled exception (``QI_FLIGHT_RECORDER``);
- ``QI_METRICS_PORT`` starts the live ``/healthz`` + ``/metrics`` endpoint
  (:mod:`quorum_intersection_tpu.utils.metrics_server`).

Since ISSUE 15 (**qi-pulse**) the record is also the home of fleet-wide
*request* observability:

- :class:`Histogram` — a first-class **mergeable** latency histogram
  (fixed log-spaced buckets, lock-protected, exact count/sum): the serving
  tier's per-stage latency distributions (``pulse.queue_wait_ms`` …
  ``pulse.e2e_ms``) are histograms, not windowed percentiles, so the fleet
  front door can add workers' buckets together and compute p99 over the
  UNION of samples instead of the max of per-worker gauges.  Rendered in
  the JSONL stream as ``{"kind": "histogram", ...}`` lines and on
  ``/metrics`` / the textfile in Prometheus histogram format by the shared
  :func:`prom_lines` encoder.
- :meth:`RunRecord.adopted` — per-REQUEST trace adoption: a serve worker
  handed a wire ``"trace"`` field (``trace_id:span_id[:pid]``, the
  ``QI_TRACE_CONTEXT`` format) scopes its spans/events for that request
  under the front door's request span, so one fleet request is ONE trace
  across processes (the span lines carry ``remote_parent_span`` /
  ``remote_parent_pid`` and ``tools/metrics_report.py`` grafts on them).
- :func:`dump_exemplar` — slow-request exemplars: a request whose
  end-to-end latency exceeds ``QI_PULSE_SLOW_MS`` dumps a ``qi-exemplar/1``
  record (stage breakdown + flight-recorder tail + trace identity) through
  the same crash-only write path as the flight recorder.
"""

from __future__ import annotations

import atexit
import bisect
import io
import json
import math
import os
import sys
import threading
import time
import uuid
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import (
    Callable, Deque, Dict, Iterator, List, Optional, Protocol, Sequence,
    Tuple,
)

from quorum_intersection_tpu.utils.env import qi_env
from quorum_intersection_tpu.utils.logging import get_logger

log = get_logger("utils.telemetry")

SCHEMA = "qi-telemetry/1"
FLIGHT_SCHEMA = "qi-flight/1"
PULSE_SCHEMA = "qi-pulse/1"
EXEMPLAR_SCHEMA = "qi-exemplar/1"

# Latency window behind the serve/fleet p50/p99 *gauges*: big enough to
# smooth scheduler noise, small enough that the gauges track the CURRENT
# load shape (a 10-minute-old latency spike must age out of a live
# /metrics scrape).  One home since ISSUE 15 — serve.py and fleet.py used
# to carry private copies.
LATENCY_WINDOW = 512

# Default Histogram bucket bounds (upper edges, milliseconds): log-spaced
# from sub-ms cache hits to the minute-class NP-hard blowups deadlines
# exist for.  Fixed and shared fleet-wide — bucket-wise addition is only
# sound when every worker buckets identically (merge_wire enforces it).
DEFAULT_HIST_BOUNDS_MS: Tuple[float, ...] = (
    0.25, 0.5, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0,
    512.0, 1024.0, 2048.0, 4096.0, 8192.0, 16384.0, 32768.0, 65536.0,
)


def hist_bounds() -> Tuple[float, ...]:
    """The process's histogram bucket ladder: ``QI_PULSE_BUCKETS`` (a
    comma-separated ascending list of upper edges in ms) overrides the
    default; a malformed override logs and falls back — a typo'd knob must
    cost resolution, never a request."""
    raw = qi_env("QI_PULSE_BUCKETS")
    if not raw:
        return DEFAULT_HIST_BOUNDS_MS
    try:
        bounds = tuple(float(part) for part in raw.split(",") if part.strip())
        if not bounds or list(bounds) != sorted(set(bounds)):
            # STRICTLY ascending: a duplicate edge would render duplicate
            # le labels and Prometheus rejects the whole scrape.
            raise ValueError("bounds must be non-empty, strictly ascending")
        return bounds
    except ValueError as exc:
        log.warning("malformed QI_PULSE_BUCKETS (%s); using defaults", exc)
        return DEFAULT_HIST_BOUNDS_MS


def percentile(sorted_samples: List[float], pct: float) -> float:
    """Nearest-rank percentile of an ascending sample list (0 if empty):
    ``ceil(pct/100 * N)`` — a true ceiling, because ``round(x + 0.5)``
    banker's-rounds exact-integer ranks one slot too high (p99 of exactly
    100 samples would report the maximum).  Moved here from serve.py
    (ISSUE 15 dedupe); ``serve._percentile`` re-exports it."""
    if not sorted_samples:
        return 0.0
    rank = max(math.ceil(pct / 100.0 * len(sorted_samples)) - 1, 0)
    return sorted_samples[min(rank, len(sorted_samples) - 1)]


class Histogram:
    """Mergeable fixed-bucket latency histogram (``qi-pulse/1``).

    Buckets are **non-cumulative** per-bucket counts over the fixed upper
    edges in ``bounds`` plus one overflow bucket; ``count``/``sum`` are
    exact.  Lock-protected: the drain thread, the transport threads and
    the probe loop all observe concurrently.  Merging is bucket-wise
    addition over *snapshots* (:meth:`snapshot` / :meth:`merge_wire`) —
    never over live instances, so no code path ever holds two histogram
    locks at once.

    A bounded raw-sample window (``LATENCY_WINDOW``) rides along for the
    byte-compatible ``serve.p50_ms``-family gauges: the window percentile
    is exactly the estimator those gauges always used, while the buckets
    are what crosses the wire and merges fleet-wide.
    """

    def __init__(self, name: str,
                 bounds: Optional[Sequence[float]] = None,
                 window: int = LATENCY_WINDOW) -> None:
        self.name = name
        self.bounds: Tuple[float, ...] = (
            tuple(bounds) if bounds is not None else hist_bounds()
        )
        self._lock = threading.Lock()
        self._counts = [0] * (len(self.bounds) + 1)
        self._count = 0
        self._sum = 0.0
        self._window: Optional[Deque[float]] = (
            deque(maxlen=window) if window > 0 else None
        )

    def observe(self, value_ms: float) -> None:
        """Record one sample (milliseconds)."""
        ix = bisect.bisect_left(self.bounds, value_ms)
        with self._lock:
            self._counts[ix] += 1
            self._count += 1
            self._sum += value_ms
            if self._window is not None:
                self._window.append(value_ms)

    def snapshot(self) -> Dict[str, object]:
        """The wire form: ``{schema, bounds, counts, count, sum}`` —
        what pongs carry and what :meth:`merge_wire` adds together."""
        with self._lock:
            return {
                "schema": PULSE_SCHEMA,
                "bounds": list(self.bounds),
                "counts": list(self._counts),
                "count": self._count,
                "sum": round(self._sum, 6),
            }

    def set_from_wire(self, wire: Dict[str, object]) -> None:
        """Overwrite this histogram with a merged wire snapshot — the
        fleet front door publishes each aggregation cycle's merge this
        way.  The raw-sample window does not cross the wire and is
        cleared (merged views answer quantiles from buckets)."""
        bounds = tuple(float(b) for b in wire.get("bounds") or ())
        counts = [int(c) for c in wire.get("counts") or ()]
        if bounds != self.bounds or len(counts) != len(self._counts):
            raise ValueError(
                f"histogram {self.name!r}: wire bounds do not match "
                f"(merging differently-bucketed histograms is unsound)"
            )
        with self._lock:
            self._counts = counts
            self._count = int(wire.get("count") or 0)
            self._sum = float(wire.get("sum") or 0.0)
            if self._window is not None:
                self._window.clear()

    def window_percentile(self, pct: float) -> float:
        """Exact nearest-rank percentile over the bounded raw-sample
        window — the estimator behind the byte-compatible p50/p99 gauges
        (sort outside the lock, the serve delivery-path discipline)."""
        with self._lock:
            samples = list(self._window) if self._window is not None else []
        samples.sort()
        return percentile(samples, pct)

    def quantile_ms(self, pct: float) -> float:
        """Bucket-resolution quantile estimate: the upper edge of the
        bucket holding the nearest-rank sample (the overflow bucket
        answers the largest finite edge).  This is what a MERGED view can
        honestly answer — raw samples never cross the wire."""
        with self._lock:
            total = self._count
            counts = list(self._counts)
        if total <= 0:
            return 0.0
        rank = max(math.ceil(pct / 100.0 * total), 1)
        seen = 0
        for ix, n in enumerate(counts):
            seen += n
            if seen >= rank:
                return self.bounds[min(ix, len(self.bounds) - 1)]
        return self.bounds[-1]

    @staticmethod
    def merge_wire(parts: Sequence[Dict[str, object]]) -> Dict[str, object]:
        """Bucket-wise addition of wire snapshots — mergeable by
        construction: ``merge([h(A), h(B)]) == h(A + B)`` exactly, the
        property tests/test_qi_pulse.py pins.  Raises ``ValueError`` on
        mismatched bucket ladders (adding them would be silently wrong)."""
        if not parts:
            return {
                "schema": PULSE_SCHEMA, "bounds": [], "counts": [],
                "count": 0, "sum": 0.0,
            }
        bounds = list(parts[0].get("bounds") or ())
        counts = [0] * len(list(parts[0].get("counts") or ()))
        count = 0
        total = 0.0
        for part in parts:
            if list(part.get("bounds") or ()) != bounds:
                raise ValueError(
                    "histogram merge: bucket bounds differ across parts"
                )
            part_counts = list(part.get("counts") or ())
            if len(part_counts) != len(counts):
                raise ValueError(
                    "histogram merge: bucket count vectors differ in length"
                )
            for ix, n in enumerate(part_counts):
                counts[ix] += int(n)
            count += int(part.get("count") or 0)
            total += float(part.get("sum") or 0.0)
        return {
            "schema": PULSE_SCHEMA, "bounds": bounds, "counts": counts,
            "count": count, "sum": round(total, 6),
        }

    def to_line(self) -> Dict[str, object]:
        """The JSONL stream line (``kind: histogram``)."""
        snap = self.snapshot()
        snap.pop("schema", None)
        return {"kind": "histogram", "name": self.name, **snap}


class SnapshotRing:
    """Bounded ring of timestamped metric snapshots (``qi-cost/1`` SLO
    plane).

    Each :meth:`record` call appends ``(t, values)`` where ``values`` is a
    flat name→float view of whatever the caller sampled (gauges, derived
    histogram percentiles, cost rates).  :meth:`window` answers the samples
    whose timestamps fall within the trailing ``seconds`` — the multi-window
    burn-rate evaluator's only read.  Lock-protected (scrape threads and the
    serve drain both record); the clock is injectable so burn-rate tests can
    replay hours in microseconds.
    """

    def __init__(self, maxlen: int = 4096,
                 clock: Optional[object] = None) -> None:
        self._lock = threading.Lock()
        self._ring: Deque[Tuple[float, Dict[str, float]]] = deque(
            maxlen=maxlen)
        self._clock = clock if callable(clock) else time.monotonic

    def record(self, values: Dict[str, float],
               t: Optional[float] = None) -> float:
        """Append one snapshot; returns the timestamp used."""
        now = float(t) if t is not None else float(self._clock())  # type: ignore[operator]
        snap = {str(k): float(v) for k, v in values.items()}
        with self._lock:
            self._ring.append((now, snap))
        return now

    def window(self, seconds: float,
               now: Optional[float] = None) -> List[Tuple[float, Dict[str, float]]]:
        """Samples within the trailing ``seconds`` (oldest first)."""
        end = float(now) if now is not None else float(self._clock())  # type: ignore[operator]
        cutoff = end - float(seconds)
        with self._lock:
            return [(t, dict(v)) for t, v in self._ring if t >= cutoff]

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()


# Finish-time line providers (qi-cost, ISSUE 17): package-level modules
# (cost.py's per-tenant table) register a callable here and its lines ride
# the JSONL stream next to the counter/gauge/histogram dump — utils/ never
# imports package engines, the dependency points the other way.  Providers
# are best-effort by the telemetry contract: one that raises is skipped.
_FINAL_LINE_PROVIDERS: List[Callable[[], List[dict]]] = []


def register_final_lines(provider: Callable[[], List[dict]]) -> None:
    """Register a finish-time JSONL line provider (idempotent)."""
    if provider not in _FINAL_LINE_PROVIDERS:
        _FINAL_LINE_PROVIDERS.append(provider)


# In-memory retention caps: a 2^44 sweep drains millions of windows; the
# JSONL sink streams them all, but the in-process lists (used by tests and
# the stderr summary) stay bounded.  Overflow is counted, never silent.
MAX_SPANS = 100_000
MAX_EVENTS = 100_000
# Flight-recorder depth: the last N span/event lines every process retains
# for crash dumps.  Small enough that the always-on ring is noise (a deque
# append per emitted line), large enough that a dump shows the whole
# degrade cascade that led to it, not just its final line.
FLIGHT_RECORDER_N = 512


@dataclass(frozen=True)
class TraceContext:
    """Cross-boundary trace identity (ISSUE 6 tentpole).

    One ``trace_id`` per RUN — minted at pipeline entry (record creation)
    and threaded through every boundary: race worker threads adopt it
    implicitly (one record per process), subprocess children inherit it via
    the ``QI_TRACE_CONTEXT`` env hook (``to_env``/``from_env`` round-trip),
    carrying the parent's current span id + pid so the exporter can stitch
    processes into one timeline.
    """

    trace_id: str
    span_id: Optional[int] = None
    pid: Optional[int] = None

    def to_env(self) -> str:
        """``trace_id:span_id:pid`` for the QI_TRACE_CONTEXT env hook."""
        return f"{self.trace_id}:{self.span_id or 0}:{self.pid or os.getpid()}"

    @staticmethod
    def from_env(raw: str) -> Optional["TraceContext"]:
        """Parse a ``to_env`` string; None when empty/blank.  Lenient on
        malformed tails — a garbled context must cost linkage, not a run."""
        parts = (raw or "").strip().split(":")
        if not parts or not parts[0]:
            return None
        span_id: Optional[int] = None
        pid: Optional[int] = None
        try:
            if len(parts) > 1:
                span_id = int(parts[1]) or None
            if len(parts) > 2:
                pid = int(parts[2]) or None
        except ValueError:
            pass
        return TraceContext(trace_id=parts[0], span_id=span_id, pid=pid)


class Sink(Protocol):
    """What the record needs from a sink: streaming lines + a final flush."""

    def emit(self, line: dict) -> None: ...

    def finish(self, record: "RunRecord") -> None: ...


def _jsonable(value: object) -> object:
    """Best-effort JSON coercion — telemetry must never crash a solve."""
    if isinstance(value, (str, int, float, bool)) or value is None:
        return value
    if isinstance(value, dict):
        return {str(k): _jsonable(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_jsonable(v) for v in value]
    return str(value)


@dataclass
class Span:
    """One finished-or-open span.  Mutate attributes via :meth:`set`."""

    name: str
    span_id: int
    parent_id: Optional[int]
    start_s: float
    seconds: Optional[float] = None
    attrs: Dict[str, object] = field(default_factory=dict)
    # Trace identity (ISSUE 6): the run's trace_id plus the OS thread/process
    # the span ran on — what the Perfetto exporter needs to place it on the
    # right track and what lets a consumer assert "one run, one trace".
    trace_id: str = ""
    tid: int = 0
    pid: int = 0
    # Wire-carried remote parent (ISSUE 15, qi-pulse): a thread-root span
    # opened under RunRecord.adopted() parents under ANOTHER process's
    # span — the fleet front door's request span — via these fields;
    # tools/metrics_report.py grafts cross-process trees on them.  Absent
    # (None) on every pre-pulse span, so old streams render unchanged.
    remote_parent_span: Optional[int] = None
    remote_parent_pid: Optional[int] = None

    def set(self, **attrs: object) -> "Span":
        self.attrs.update(attrs)
        return self

    def to_line(self) -> dict:
        line = {
            "kind": "span",
            "name": self.name,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start_s": round(self.start_s, 6),
            "seconds": None if self.seconds is None else round(self.seconds, 6),
            "trace_id": self.trace_id,
            "pid": self.pid,
            "tid": self.tid,
            "attrs": _jsonable(self.attrs),
        }
        if self.remote_parent_span is not None:
            line["remote_parent_span"] = self.remote_parent_span
            line["remote_parent_pid"] = self.remote_parent_pid
        return line


class JsonlSink:
    """Streaming JSONL sink (append mode: multi-process runs share a file)."""

    def __init__(self, path: str) -> None:
        self.path = str(path)
        self._lock = threading.Lock()
        self._fh: Optional[io.TextIOBase] = None

    def _handle(self) -> io.TextIOBase:
        if self._fh is None:
            self._fh = open(self.path, "a", buffering=1, encoding="utf-8")
        return self._fh

    def emit(self, line: dict) -> None:
        try:
            with self._lock:
                self._handle().write(json.dumps(line, default=str) + "\n")
        except OSError as exc:  # telemetry must never cost the verdict
            log.info("metrics JSONL write failed: %s", exc)

    def finish(self, record: "RunRecord") -> None:
        for line in record.final_lines():
            self.emit(line)
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None


def _prom_metric(name: str) -> str:
    clean = "".join(c if c.isalnum() else "_" for c in name)
    return f"qi_{clean}"


def prom_lines(record: "RunRecord") -> List[str]:
    """Prometheus text encoding of a record's counters/gauges/span rollups.

    The ONE encoder behind both the textfile sink below and the live
    ``/metrics`` endpoint (utils/metrics_server.py) — deterministic (sorted)
    output, so two scrapes of an unchanged record are byte-identical.
    """
    lines: List[str] = []
    counters, gauges = record.snapshot()
    for name, value in sorted(counters.items()):
        m = _prom_metric(name)
        lines.append(f"# TYPE {m} counter")
        lines.append(f"{m} {value}")
    for name, value in sorted(gauges.items()):
        if not isinstance(value, (int, float)):
            continue
        m = _prom_metric(name)
        lines.append(f"# TYPE {m} gauge")
        lines.append(f"{m} {value}")
    for name, total, count in record.span_rollup():
        m = _prom_metric(f"span_{name}_seconds")
        lines.append(f"# TYPE {m} counter")
        lines.append(f"{m} {round(total, 6)}")
        lines.append(f"# TYPE {m}_count counter")
        lines.append(f"{m}_count {count}")
    # qi-pulse histograms (ISSUE 15): Prometheus histogram convention —
    # cumulative le buckets, _sum, _count — from the non-cumulative wire
    # snapshots, deterministically sorted like everything above.
    for name, snap in sorted(record.histograms_snapshot().items()):
        m = _prom_metric(name)
        lines.append(f"# TYPE {m} histogram")
        cumulative = 0
        for bound, n in zip(snap["bounds"], snap["counts"]):
            cumulative += int(n)
            lines.append(f'{m}_bucket{{le="{bound:g}"}} {cumulative}')
        lines.append(f'{m}_bucket{{le="+Inf"}} {snap["count"]}')
        lines.append(f"{m}_sum {snap['sum']}")
        lines.append(f"{m}_count {snap['count']}")
    return lines


class PromFileSink:
    """Prometheus textfile exporter: counters/gauges rewritten atomically at
    finish — point node_exporter's textfile collector at the file for soak
    runs (tools/soak.py)."""

    def __init__(self, path: str) -> None:
        self.path = str(path)

    def emit(self, line: dict) -> None:  # streaming is a no-op for textfiles
        pass

    def finish(self, record: "RunRecord") -> None:
        tmp = f"{self.path}.tmp{os.getpid()}"
        try:
            with open(tmp, "w", encoding="utf-8") as fh:
                fh.write("\n".join(prom_lines(record)) + "\n")
            os.replace(tmp, self.path)
        except OSError as exc:
            log.info("metrics textfile write failed: %s", exc)


class ChromeTraceSink:
    """Chrome/Perfetto trace-event JSON exporter (ISSUE 6 tentpole).

    Spans become complete (``"ph": "X"``) duration events on their real
    OS-thread track, telemetry events become instant (``"i"``) marks, and
    each process contributes a ``process_name`` metadata record naming its
    argv0 + pid + trace_id — so a whole run, including the losing race arm
    and every bench subprocess child appending to the same file, opens in
    ui.perfetto.dev / ``chrome://tracing`` as ONE timeline.

    The enclosing JSON array is deliberately left unterminated: the
    trace-event "JSON Array Format" tolerates a missing ``]``, so every
    event is appended and flushed as it happens and a crashed run still
    leaves a loadable trace (the JsonlSink crash-tolerance discipline).
    Timestamps are wall-clock microseconds (the meta line's ``t_wall``
    anchor plus record-relative ``start_s``/``t_s``), so events from
    different processes align without any cross-process clock plumbing.
    """

    def __init__(self, path: str) -> None:
        self.path = str(path)
        self._lock = threading.Lock()
        self._fh: Optional[io.TextIOBase] = None
        self._pid = os.getpid()
        self._t_wall = time.time()  # refined by the meta line on attach

    def _open(self) -> io.TextIOBase:
        # Exactly ONE process writes the opening "[": O_EXCL creation
        # decides the winner, so concurrently launched children sharing a
        # QI_TRACE_OUT file cannot both prepend it (a second "[" mid-stream
        # would corrupt the array for every consumer).  The tell()==0
        # fallback covers a pre-existing empty file, where only this
        # process's own lock matters.
        try:
            fd = os.open(self.path, os.O_WRONLY | os.O_CREAT | os.O_EXCL, 0o644)
            with os.fdopen(fd, "w", encoding="utf-8") as first:
                first.write("[\n")
        except FileExistsError:
            pass
        fh = open(self.path, "a", buffering=1, encoding="utf-8")
        if fh.tell() == 0:
            fh.write("[\n")
        return fh

    def _write(self, obj: dict) -> None:
        try:
            with self._lock:
                if self._fh is None:
                    self._fh = self._open()
                self._fh.write(json.dumps(obj, default=str) + ",\n")
        except OSError as exc:  # telemetry must never cost the verdict
            log.info("trace-event write failed: %s", exc)

    def _ts_us(self, rel_s: object) -> float:
        return round((self._t_wall + float(rel_s or 0.0)) * 1e6, 1)

    def emit(self, line: dict) -> None:
        kind = line.get("kind")
        if kind == "meta":
            try:
                self._t_wall = float(line.get("t_wall") or self._t_wall)
            except (TypeError, ValueError):
                pass
            self._write({
                "ph": "M", "name": "process_name", "pid": self._pid,
                "tid": 0,
                "args": {"name": (
                    f"{line.get('argv0') or 'python'} (pid {self._pid}, "
                    f"trace {line.get('trace_id', '?')})"
                )},
            })
        elif kind == "span" and line.get("seconds") is not None:
            self._write({
                "ph": "X", "cat": "span", "name": line.get("name", "?"),
                "pid": self._pid, "tid": int(line.get("tid") or 0),
                "ts": self._ts_us(line.get("start_s")),
                "dur": max(round(float(line["seconds"]) * 1e6, 1), 1.0),
                "args": line.get("attrs") or {},
            })
        elif kind == "event":
            self._write({
                "ph": "i", "cat": "event", "name": line.get("name", "?"),
                "pid": self._pid, "tid": int(line.get("tid") or 0),
                "ts": self._ts_us(line.get("t_s")), "s": "t",
                "args": line.get("attrs") or {},
            })
        # counters/gauges stay in the JSONL stream; the timeline shows flow

    def finish(self, record: "RunRecord") -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None


class StderrSummarySink:
    """Human stderr summary at finish — the ``[telemetry]`` lines the CLI
    appends after the (byte-compatible) legacy ``[timing]``/``[stats]``
    output."""

    def emit(self, line: dict) -> None:
        pass

    def finish(self, record: "RunRecord") -> None:
        for line in record.summary_lines():
            sys.stderr.write(line + "\n")


class RunRecord:
    """Process-wide telemetry record.  Thread-safe; sinks pluggable."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._local = threading.local()
        self.t0 = time.monotonic()
        self.t_wall = time.time()
        self.pid = os.getpid()
        # Trace identity (ISSUE 6): inherit the parent process's context
        # from QI_TRACE_CONTEXT (bench children, distributed workers) or
        # mint a fresh trace_id — every span/event line this record emits
        # carries it, so one RUN is one trace across threads and processes.
        self.parent_ctx: Optional[TraceContext] = TraceContext.from_env(
            qi_env("QI_TRACE_CONTEXT")
        )
        self.trace_id: str = (
            self.parent_ctx.trace_id if self.parent_ctx is not None
            else uuid.uuid4().hex[:16]
        )
        self.spans: List[Span] = []
        self.events: List[dict] = []
        self.counters: Dict[str, float] = {}
        self.gauges: Dict[str, object] = {}
        # qi-pulse (ISSUE 15): named mergeable histograms.  The dict is
        # guarded by self._lock (get-or-create only); each Histogram
        # guards its own buckets with its own lock, and no path holds
        # both at once (snapshots are taken outside the record lock).
        self._histograms: Dict[str, Histogram] = {}
        self.dropped = 0
        self.events_dropped = 0
        self._next_id = 0
        self._sinks: List[Sink] = []
        self._finished = False
        # Crash flight recorder (ISSUE 6): bounded ring of the last
        # FLIGHT_RECORDER_N emitted span/event lines, always on, guarded by
        # its own lock (never nested with self._lock — the emit path takes
        # them strictly in sequence).
        self._flight_lock = threading.Lock()
        self._flight: Deque[dict] = deque(maxlen=FLIGHT_RECORDER_N)
        # Always-present counters (acceptance: one solve's stream carries the
        # compile-cache hit/miss pair even when the cache saw no traffic).
        self.declare("compile_cache.hits")
        self.declare("compile_cache.misses")

    def trace_context(self) -> TraceContext:
        """The context to export at a process boundary (QI_TRACE_CONTEXT):
        this trace plus the calling thread's current span as the remote
        parent, so a child's whole tree hangs under the span that spawned
        it."""
        return TraceContext(self.trace_id, self.current_span_id, self.pid)

    def snapshot(self) -> Tuple[Dict[str, float], Dict[str, object]]:
        """Consistent copies of (counters, gauges) — the read API for the
        live endpoint and the Prometheus encoder (no caller ever needs to
        touch the record's lock)."""
        with self._lock:
            return dict(self.counters), dict(self.gauges)

    def histogram(self, name: str,
                  bounds: Optional[Sequence[float]] = None) -> Histogram:
        """Get-or-create the named mergeable histogram (qi-pulse).  The
        registry lookup holds the record lock; the returned instance is
        observed under its OWN lock only."""
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms[name] = Histogram(name, bounds)
        return h

    def histograms_snapshot(self) -> Dict[str, Dict[str, object]]:
        """Wire snapshots of every histogram, name-keyed.  The registry
        copy is taken under the record lock; each per-histogram snapshot
        is taken afterwards under that histogram's own lock — the two
        locks are never nested."""
        with self._lock:
            hists = dict(self._histograms)
        return {name: h.snapshot() for name, h in hists.items()}

    @contextmanager
    def adopted(self, ctx: Optional["TraceContext"]) -> Iterator[None]:
        """Per-request wire-trace adoption (ISSUE 15, qi-pulse): while
        active on this thread, spans and events carry ``ctx.trace_id``
        instead of the process's own, and a thread-ROOT span records
        ``ctx.span_id``/``ctx.pid`` as its remote parent — so a serve
        worker's admit/solve/ladder/native spans for one request all hang
        under the fleet front door's request span, across the process
        boundary.  ``None`` (or a blank trace) is a no-op, keeping every
        pre-pulse call path byte-identical."""
        if ctx is None or not ctx.trace_id:
            yield
            return
        prev = getattr(self._local, "adopt", None)
        prev_first = getattr(self._local, "adopt_first", False)
        self._local.adopt = ctx
        # The FIRST span of an adoption scope stamps the remote parent
        # even when it has a local parent (an in-process fleet worker's
        # admit span sits under fleet.request locally; a journal replay's
        # solve sits under serve.replay): the wire link is what joins the
        # recovered/in-process work to the original request's span.
        self._local.adopt_first = True
        try:
            yield
        finally:
            self._local.adopt = prev
            self._local.adopt_first = prev_first

    def flight_tail(self) -> List[dict]:
        """Copy of the flight-recorder ring, oldest first."""
        with self._flight_lock:
            return list(self._flight)

    def event_count(self) -> int:
        """Current in-memory event count — the snapshot anchor for
        :meth:`events_since` (qi-cert provenance slicing)."""
        with self._lock:
            return len(self.events)

    def events_since(self, n: int) -> List[dict]:
        """Copies of the events recorded after snapshot position ``n``
        (an :meth:`event_count` result).  The qi-cert builder uses the
        slice to stamp one solve's routing/degrade/calibration decisions
        into its certificate without consuming the whole run's stream.
        Bounded by MAX_EVENTS: once the in-memory cap overflows, later
        solves see an empty slice (the JSONL stream still has the lines);
        :meth:`events_truncated` tells the cert builder to say so."""
        with self._lock:
            return [dict(ev) for ev in self.events[n:]]

    def events_truncated(self) -> bool:
        """Whether any event line was dropped from the in-memory buffer
        (MAX_EVENTS overflow).  Once true, an empty/short
        :meth:`events_since` slice no longer means "nothing happened" —
        qi-cert stamps this into provenance so a certificate consumer can
        tell a quiet solve from a clipped audit trail."""
        with self._lock:
            return self.events_dropped > 0

    # ---- sinks -----------------------------------------------------------

    def add_sink(self, sink: "Sink") -> None:
        with self._lock:
            self._sinks.append(sink)
        # Every sink gets its own meta/schema header on attach — a sink
        # added after the env sink must still open with the schema line
        # (metrics_report groups multi-process streams by the meta pids).
        meta = {
            "kind": "meta",
            "schema": SCHEMA,
            "pid": self.pid,
            "argv0": os.path.basename(sys.argv[0]) if sys.argv else "",
            "t_wall": round(self.t_wall, 3),
            "trace_id": self.trace_id,
        }
        if self.parent_ctx is not None:
            # Cross-process stitch point: which span of which parent process
            # spawned this one (the exporter and metrics_report use it to
            # hang a child's tree under its parent's bench.<phase> span).
            meta["parent_span"] = self.parent_ctx.span_id
            meta["parent_pid"] = self.parent_ctx.pid
        try:
            sink.emit(meta)
        except Exception as exc:  # noqa: BLE001 — never cost the verdict
            log.info("telemetry sink failed: %s", exc)

    def _emit(self, line: dict) -> None:
        # Flight recorder first (bounded deque append under its own lock —
        # the always-on cost of crash forensics is this one line), then the
        # pluggable sinks, outside any lock.
        if line.get("kind") in ("span", "event"):
            with self._flight_lock:
                self._flight.append(line)
        for sink in list(self._sinks):
            try:
                sink.emit(line)
            except Exception as exc:  # noqa: BLE001 — never cost the verdict
                log.info("telemetry sink failed: %s", exc)

    # ---- spans -----------------------------------------------------------

    def _stack(self) -> List[int]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    @property
    def current_span_id(self) -> Optional[int]:
        stack = self._stack()
        return stack[-1] if stack else None

    @contextmanager
    def span(self, name: str, parent_id: Optional[int] = None,
             **attrs: object) -> Iterator[Span]:
        """Open a nested span.  Nesting is per-thread (a worker thread's
        spans are roots unless ``parent_id`` carries one across)."""
        stack = self._stack()
        with self._lock:
            self._next_id += 1
            sid = self._next_id
        adopt: Optional[TraceContext] = getattr(self._local, "adopt", None)
        local_parent = parent_id if parent_id is not None else (
            stack[-1] if stack else None
        )
        # Wire-adopted thread roots — and the FIRST span of an adoption
        # scope even with a local parent — graft under the remote request
        # span (qi-pulse); later nested spans keep their local parent and
        # inherit the graft transitively.
        graft = adopt is not None and (
            local_parent is None or getattr(self._local, "adopt_first", False)
        )
        if adopt is not None:
            self._local.adopt_first = False
        sp = Span(
            name=name,
            span_id=sid,
            parent_id=local_parent,
            start_s=time.monotonic() - self.t0,
            attrs=dict(attrs),
            trace_id=adopt.trace_id if adopt is not None else self.trace_id,
            tid=threading.get_native_id(),
            pid=self.pid,
            remote_parent_span=adopt.span_id if graft else None,
            remote_parent_pid=adopt.pid if graft else None,
        )
        stack.append(sid)
        try:
            yield sp
        finally:
            stack.pop()
            sp.seconds = (time.monotonic() - self.t0) - sp.start_s
            with self._lock:
                if len(self.spans) < MAX_SPANS:
                    self.spans.append(sp)
                else:
                    self.dropped += 1
            self._emit(sp.to_line())

    # ---- events / counters / gauges -------------------------------------

    def event(self, name: str, **attrs: object) -> None:
        adopt: Optional[TraceContext] = getattr(self._local, "adopt", None)
        ev = {
            "kind": "event",
            "name": name,
            "t_s": round(time.monotonic() - self.t0, 6),
            "span_id": self.current_span_id,
            "trace_id": adopt.trace_id if adopt is not None else self.trace_id,
            "pid": self.pid,
            "tid": threading.get_native_id(),
            "attrs": _jsonable(attrs),
        }
        with self._lock:
            if len(self.events) < MAX_EVENTS:
                self.events.append(ev)
            else:
                self.dropped += 1
                self.events_dropped += 1
        self._emit(ev)

    def declare(self, name: str) -> None:
        """Ensure a counter exists (zero) so it is emitted even untouched."""
        with self._lock:
            self.counters.setdefault(name, 0)

    def add(self, name: str, n: float = 1) -> None:
        """Atomic counter increment (the race's two threads both call in)."""
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + n

    def gauge(self, name: str, value: object) -> None:
        with self._lock:
            self.gauges[name] = value

    # ---- rollups / finish -------------------------------------------------

    def span_rollup(self) -> List[Tuple[str, float, int]]:
        """``[(name, total_seconds, count), ...]`` sorted by total desc."""
        with self._lock:
            totals: Dict[str, List[float]] = {}
            for sp in self.spans:
                if sp.seconds is None:
                    continue
                cur = totals.setdefault(sp.name, [0.0, 0])
                cur[0] += sp.seconds
                cur[1] += 1
        return sorted(
            ((name, t, int(c)) for name, (t, c) in totals.items()),
            key=lambda row: -row[1],
        )

    def final_lines(self) -> List[dict]:
        """Counter/gauge/histogram lines emitted once at finish."""
        with self._lock:
            counters = dict(self.counters)
            gauges = dict(self.gauges)
            hists = dict(self._histograms)
            dropped = self.dropped
        lines = [
            {"kind": "counter", "name": name, "value": value}
            for name, value in sorted(counters.items())
        ]
        lines += [
            {"kind": "gauge", "name": name, "value": _jsonable(value)}
            for name, value in sorted(gauges.items())
        ]
        for name in sorted(hists):
            hist_line = hists[name].to_line()
            if hist_line["count"]:  # untouched histograms stay silent
                lines.append(hist_line)
        if dropped:
            lines.append({"kind": "counter", "name": "telemetry.dropped",
                          "value": dropped})
        for provider in list(_FINAL_LINE_PROVIDERS):
            try:
                lines.extend(provider())
            except Exception as exc:  # noqa: BLE001 — never cost the dump
                log.info("final-line provider failed: %s", exc)
        return lines

    def summary_lines(self) -> List[str]:
        """Human summary: span rollup + non-zero counters + gauges."""
        out = []
        for name, total, count in self.span_rollup():
            suffix = f" (x{count})" if count > 1 else ""
            out.append(f"[telemetry] span {name}: {total * 1000:.2f} ms{suffix}")
        with self._lock:
            counters = dict(self.counters)
            gauges = dict(self.gauges)
        for name, value in sorted(counters.items()):
            out.append(f"[telemetry] counter {name}: {value}")
        for name, value in sorted(gauges.items()):
            out.append(f"[telemetry] gauge {name}: {value}")
        return out

    def finish(self) -> None:
        """Flush counters/gauges and close sinks (idempotent)."""
        with self._lock:
            if self._finished:
                return
            self._finished = True
            sinks = list(self._sinks)
        for sink in sinks:
            try:
                sink.finish(self)
            except Exception as exc:  # noqa: BLE001
                log.info("telemetry sink finish failed: %s", exc)


# ---- process-wide record --------------------------------------------------

_global: Optional[RunRecord] = None
_global_lock = threading.Lock()


def _attach_env_sinks(record: RunRecord) -> None:
    """Honor QI_METRICS_JSON / QI_METRICS_PROM / QI_TRACE_OUT: the env-var
    hooks the test suite, CI and the bench drivers use — every process in a
    run appends to one shared stream without any flag plumbing."""
    jsonl = qi_env("QI_METRICS_JSON")
    if jsonl:
        record.add_sink(JsonlSink(jsonl))
    prom = qi_env("QI_METRICS_PROM")
    if prom:
        record.add_sink(PromFileSink(prom))
    trace = qi_env("QI_TRACE_OUT")
    if trace:
        record.add_sink(ChromeTraceSink(trace))


_crash_hook_installed = False


def _install_crash_hook() -> None:
    """With QI_FLIGHT_RECORDER set, chain ``sys.excepthook`` so an unhandled
    exception dumps the flight-recorder ring BEFORE the interpreter prints
    the traceback — the forensic record survives the crash it describes."""
    global _crash_hook_installed
    if _crash_hook_installed or not qi_env("QI_FLIGHT_RECORDER"):
        return
    _crash_hook_installed = True
    prev = sys.excepthook

    def hook(exc_type, exc, tb):  # nested: exempt from the typing ratchet
        dump_flight_recorder(f"unhandled:{exc_type.__name__}")
        prev(exc_type, exc, tb)

    sys.excepthook = hook


def _maybe_start_metrics_server() -> None:
    """Start the live /healthz + /metrics endpoint when QI_METRICS_PORT > 0
    (best-effort: a taken port on a bench child logs and moves on)."""
    if qi_env("QI_METRICS_PORT") in ("", "0"):
        return
    try:
        from quorum_intersection_tpu.utils.metrics_server import (
            maybe_start_from_env,
        )

        maybe_start_from_env()
    except Exception as exc:  # noqa: BLE001 — observability never costs the verdict
        log.info("metrics server unavailable: %s", exc)


def get_run_record() -> RunRecord:
    """The process-wide :class:`RunRecord` (created lazily; env sinks
    attached on first use; flushed at interpreter exit)."""
    global _global
    if _global is None:
        with _global_lock:
            if _global is None:
                record = RunRecord()
                _attach_env_sinks(record)
                atexit.register(record.finish)
                _global = record
        _install_crash_hook()
        _maybe_start_metrics_server()
    return _global


def reset_run_record() -> RunRecord:
    """Replace the process-wide record with a fresh one (tests; the old
    record is finished first so its sinks flush)."""
    global _global
    with _global_lock:
        old, _global = _global, None
    if old is not None:
        old.finish()
    return get_run_record()


def finish() -> None:
    """Finish the process-wide record if one exists (idempotent)."""
    if _global is not None:
        _global.finish()


# ---- crash flight recorder -------------------------------------------------

_dump_state = threading.local()


def _write_crash_only(target: str, payload: dict, rec: "RunRecord") -> bool:
    """One crash-only dump write (tmp + flush + fsync + rename +
    best-effort dir fsync), behind the ``telemetry.dump`` fault point.
    Shared by the flight recorder and the qi-pulse slow-request exemplars
    — any failure downgrades to the ``telemetry.dump_errors`` counter and
    returns False: a forensic dump must never be the crash."""
    try:
        from quorum_intersection_tpu.utils.faults import fault_point

        # Injectable boundary: the dump write itself can hit a full disk
        # mid-crash; it downgrades to a counter, never a second crash.
        fault_point("telemetry.dump")
        tmp = f"{target}.tmp{rec.pid}"
        with open(tmp, "w", encoding="utf-8") as fh:
            fh.write(json.dumps(payload, default=str))
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, target)
        try:
            dir_fd = os.open(
                os.path.dirname(os.path.abspath(target)), os.O_RDONLY
            )
            try:
                os.fsync(dir_fd)
            finally:
                os.close(dir_fd)
        except OSError:
            pass  # directory fsync is best-effort (utils/checkpoint.py)
    except Exception as exc:  # noqa: BLE001 — a crash dump must never be the crash
        rec.add("telemetry.dump_errors")
        log.warning("crash-only dump failed (%s); run continues", exc)
        return False
    return True


def dump_exemplar(payload: dict, path: Optional[str] = None) -> Optional[str]:
    """Dump one slow-request exemplar (``qi-exemplar/1``, ISSUE 15).

    Fired by the serving layer when a request's end-to-end latency
    exceeds ``QI_PULSE_SLOW_MS``: the caller's stage breakdown + trace
    identity, augmented here with the flight-recorder tail — the same
    forensic ring a crash dump carries, so a slow request's last-N
    spans/events are inspectable without reproducing the slowness.

    Writes to ``path``, or ``<QI_FLIGHT_RECORDER>.exemplar`` when the
    flight recorder has a destination (the exemplar rides the crash-dump
    path and its knob); with neither, the ``pulse.exemplar`` event and
    ``pulse.exemplars`` counter still fire and no file is written.
    Crash-only discipline and reentrancy guard shared with
    :func:`dump_flight_recorder`.  Returns the path written, or None.
    """
    rec = get_run_record()
    rec.add("pulse.exemplars")
    rec.event(
        "pulse.exemplar",
        request_id=payload.get("request_id"),
        e2e_ms=payload.get("e2e_ms"),
        trace_id=payload.get("trace_id"),
    )
    flight = qi_env("QI_FLIGHT_RECORDER")
    target = path or (f"{flight}.exemplar" if flight else "")
    if not target:
        return None
    if getattr(_dump_state, "active", False):
        return None  # one dump per trigger chain is enough
    _dump_state.active = True
    try:
        full = {
            "schema": EXEMPLAR_SCHEMA,
            "pid": rec.pid,
            "t_wall": round(time.time(), 3),
            **payload,
            "tail": rec.flight_tail(),
        }
        if not _write_crash_only(target, full, rec):
            return None
        rec.add("telemetry.dumps")
        return str(target)
    finally:
        _dump_state.active = False


def dump_flight_recorder(reason: str, path: Optional[str] = None) -> Optional[str]:
    """Dump the flight-recorder ring crash-only: the last-N span/event lines
    plus a counter/gauge snapshot, written with the checkpoint discipline
    (tmp + flush + fsync + rename + best-effort dir fsync).

    Called at every forensic trigger — fault firing (utils/faults.py),
    watchdog trip / ladder degrade / quarantine (backends/auto.py), and
    unhandled exceptions (the chained excepthook).  No-op unless ``path`` or
    ``QI_FLIGHT_RECORDER`` names a destination.  Reentrancy-guarded: a
    trigger firing INSIDE a dump (an injected ``telemetry.dump`` fault's own
    event) never recurses.  Returns the path written, or None.
    """
    target = path or qi_env("QI_FLIGHT_RECORDER")
    if not target:
        return None
    if getattr(_dump_state, "active", False):
        return None  # one dump per trigger chain is enough
    _dump_state.active = True
    try:
        rec = get_run_record()
        counters, gauges = rec.snapshot()
        payload = {
            "schema": FLIGHT_SCHEMA,
            "reason": reason,
            "pid": rec.pid,
            "trace_id": rec.trace_id,
            "t_wall": round(time.time(), 3),
            "t_s": round(time.monotonic() - rec.t0, 6),
            "counters": counters,
            "gauges": _jsonable(gauges),
            "tail": rec.flight_tail(),
        }
        if not _write_crash_only(str(target), payload, rec):
            return None
        rec.add("telemetry.dumps")
        rec.event("telemetry.dumped", path=str(target), reason=reason)
        return str(target)
    finally:
        _dump_state.active = False
