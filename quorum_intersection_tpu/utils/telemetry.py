"""Unified run-record telemetry: spans + counters from parse to chip.

One schema across the CLI, the racing auto router, the sweep, and both
benchmark drivers (ISSUE 2 tentpole).  The observability story used to be
fragments — ``PhaseTimers`` dicts, ad-hoc ``[stats]`` stderr lines, race
stats buried in ``res.stats["race"]`` — none of them machine-readable in one
stream.  This module is the single cross-cutting layer they all feed:

- **Spans**: named, nested wall-clock intervals (monotonic start/end,
  parent id, free-form attributes).  ``PhaseTimers.phase`` opens one per
  pipeline phase, the auto router wraps its routing decision and the race
  in them, benchmark drivers wrap their phases.
- **Counters / gauges**: typed process-wide accumulators (candidates
  checked, sweep windows dispatched/cancelled, compile-cache hits/misses,
  oracle budget consumed, checkpoint saves/restores).  ``add`` is
  lock-protected — the race's two threads increment concurrently.
- **Events**: point-in-time records (race verdicts, routing decisions,
  per-window sweep progress, checkpoint activity).

Sinks are pluggable and attach to the process-wide :class:`RunRecord`:

- :class:`JsonlSink` — streaming JSONL event file (CLI ``--metrics-json``,
  env ``QI_METRICS_JSON``); every span end / event is written as it
  happens, so a crashed run still leaves a parseable prefix.
- :class:`PromFileSink` — Prometheus-style textfile exporter for soak
  runs (CLI ``--metrics-prom``, env ``QI_METRICS_PROM``): counters and
  gauges rewritten atomically at finish, ready for node_exporter's
  textfile collector.
- :class:`StderrSummarySink` — the human summary (``[telemetry]`` lines),
  appended after the legacy ``[timing]``/``[stats]`` output which stays
  byte-compatible (docs/OBSERVABILITY.md).

Schema (``qi-telemetry/1``, one JSON object per line):

    {"kind": "meta",    "schema": "qi-telemetry/1", "pid": ..., "argv0": ..., "t_wall": ...}
    {"kind": "span",    "name": "phase.search", "span_id": 3, "parent_id": 1,
     "start_s": 0.01, "seconds": 1.2, "attrs": {...}}
    {"kind": "event",   "name": "sweep.window", "t_s": 0.5, "span_id": 3, "attrs": {...}}
    {"kind": "counter", "name": "sweep.candidates_checked", "value": 1048576}
    {"kind": "gauge",   "name": "sweep.candidates_per_sec", "value": 2.1e9}

``t_s``/``start_s`` are seconds since the record's creation (monotonic);
``t_wall`` in the meta line anchors them to wall-clock.  Multi-process runs
(the bench driver's phase children, CLI subprocesses under the test suite)
append to one file; consumers group by ``pid``.  ``tools/metrics_report.py``
renders a stream into per-phase / per-window tables.
"""

from __future__ import annotations

import atexit
import io
import json
import os
import sys
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Protocol, Tuple

from quorum_intersection_tpu.utils.env import qi_env
from quorum_intersection_tpu.utils.logging import get_logger

log = get_logger("utils.telemetry")

SCHEMA = "qi-telemetry/1"

# In-memory retention caps: a 2^44 sweep drains millions of windows; the
# JSONL sink streams them all, but the in-process lists (used by tests and
# the stderr summary) stay bounded.  Overflow is counted, never silent.
MAX_SPANS = 100_000
MAX_EVENTS = 100_000


class Sink(Protocol):
    """What the record needs from a sink: streaming lines + a final flush."""

    def emit(self, line: dict) -> None: ...

    def finish(self, record: "RunRecord") -> None: ...


def _jsonable(value: object) -> object:
    """Best-effort JSON coercion — telemetry must never crash a solve."""
    if isinstance(value, (str, int, float, bool)) or value is None:
        return value
    if isinstance(value, dict):
        return {str(k): _jsonable(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_jsonable(v) for v in value]
    return str(value)


@dataclass
class Span:
    """One finished-or-open span.  Mutate attributes via :meth:`set`."""

    name: str
    span_id: int
    parent_id: Optional[int]
    start_s: float
    seconds: Optional[float] = None
    attrs: Dict[str, object] = field(default_factory=dict)

    def set(self, **attrs: object) -> "Span":
        self.attrs.update(attrs)
        return self

    def to_line(self) -> dict:
        return {
            "kind": "span",
            "name": self.name,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start_s": round(self.start_s, 6),
            "seconds": None if self.seconds is None else round(self.seconds, 6),
            "attrs": _jsonable(self.attrs),
        }


class JsonlSink:
    """Streaming JSONL sink (append mode: multi-process runs share a file)."""

    def __init__(self, path: str) -> None:
        self.path = str(path)
        self._lock = threading.Lock()
        self._fh: Optional[io.TextIOBase] = None

    def _handle(self) -> io.TextIOBase:
        if self._fh is None:
            self._fh = open(self.path, "a", buffering=1, encoding="utf-8")
        return self._fh

    def emit(self, line: dict) -> None:
        try:
            with self._lock:
                self._handle().write(json.dumps(line, default=str) + "\n")
        except OSError as exc:  # telemetry must never cost the verdict
            log.info("metrics JSONL write failed: %s", exc)

    def finish(self, record: "RunRecord") -> None:
        for line in record.final_lines():
            self.emit(line)
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None


class PromFileSink:
    """Prometheus textfile exporter: counters/gauges rewritten atomically at
    finish — point node_exporter's textfile collector at the file for soak
    runs (tools/soak.py)."""

    def __init__(self, path: str) -> None:
        self.path = str(path)

    def emit(self, line: dict) -> None:  # streaming is a no-op for textfiles
        pass

    @staticmethod
    def _metric(name: str) -> str:
        clean = "".join(c if c.isalnum() else "_" for c in name)
        return f"qi_{clean}"

    def finish(self, record: "RunRecord") -> None:
        lines: List[str] = []
        with record._lock:
            counters = dict(record.counters)
            gauges = dict(record.gauges)
        for name, value in sorted(counters.items()):
            m = self._metric(name)
            lines.append(f"# TYPE {m} counter")
            lines.append(f"{m} {value}")
        for name, value in sorted(gauges.items()):
            if not isinstance(value, (int, float)):
                continue
            m = self._metric(name)
            lines.append(f"# TYPE {m} gauge")
            lines.append(f"{m} {value}")
        for name, total, count in record.span_rollup():
            m = self._metric(f"span_{name}_seconds")
            lines.append(f"# TYPE {m} counter")
            lines.append(f"{m} {round(total, 6)}")
            lines.append(f"# TYPE {m}_count counter")
            lines.append(f"{m}_count {count}")
        tmp = f"{self.path}.tmp{os.getpid()}"
        try:
            with open(tmp, "w", encoding="utf-8") as fh:
                fh.write("\n".join(lines) + "\n")
            os.replace(tmp, self.path)
        except OSError as exc:
            log.info("metrics textfile write failed: %s", exc)


class StderrSummarySink:
    """Human stderr summary at finish — the ``[telemetry]`` lines the CLI
    appends after the (byte-compatible) legacy ``[timing]``/``[stats]``
    output."""

    def emit(self, line: dict) -> None:
        pass

    def finish(self, record: "RunRecord") -> None:
        for line in record.summary_lines():
            sys.stderr.write(line + "\n")


class RunRecord:
    """Process-wide telemetry record.  Thread-safe; sinks pluggable."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._local = threading.local()
        self.t0 = time.monotonic()
        self.t_wall = time.time()
        self.spans: List[Span] = []
        self.events: List[dict] = []
        self.counters: Dict[str, float] = {}
        self.gauges: Dict[str, object] = {}
        self.dropped = 0
        self._next_id = 0
        self._sinks: List[Sink] = []
        self._finished = False
        # Always-present counters (acceptance: one solve's stream carries the
        # compile-cache hit/miss pair even when the cache saw no traffic).
        self.declare("compile_cache.hits")
        self.declare("compile_cache.misses")

    # ---- sinks -----------------------------------------------------------

    def add_sink(self, sink: "Sink") -> None:
        with self._lock:
            self._sinks.append(sink)
        # Every sink gets its own meta/schema header on attach — a sink
        # added after the env sink must still open with the schema line
        # (metrics_report groups multi-process streams by the meta pids).
        try:
            sink.emit({
                "kind": "meta",
                "schema": SCHEMA,
                "pid": os.getpid(),
                "argv0": os.path.basename(sys.argv[0]) if sys.argv else "",
                "t_wall": round(self.t_wall, 3),
            })
        except Exception as exc:  # noqa: BLE001 — never cost the verdict
            log.info("telemetry sink failed: %s", exc)

    def _emit(self, line: dict) -> None:
        for sink in list(self._sinks):
            try:
                sink.emit(line)
            except Exception as exc:  # noqa: BLE001 — never cost the verdict
                log.info("telemetry sink failed: %s", exc)

    # ---- spans -----------------------------------------------------------

    def _stack(self) -> List[int]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    @property
    def current_span_id(self) -> Optional[int]:
        stack = self._stack()
        return stack[-1] if stack else None

    @contextmanager
    def span(self, name: str, parent_id: Optional[int] = None,
             **attrs: object) -> Iterator[Span]:
        """Open a nested span.  Nesting is per-thread (a worker thread's
        spans are roots unless ``parent_id`` carries one across)."""
        stack = self._stack()
        with self._lock:
            self._next_id += 1
            sid = self._next_id
        sp = Span(
            name=name,
            span_id=sid,
            parent_id=parent_id if parent_id is not None else (
                stack[-1] if stack else None
            ),
            start_s=time.monotonic() - self.t0,
            attrs=dict(attrs),
        )
        stack.append(sid)
        try:
            yield sp
        finally:
            stack.pop()
            sp.seconds = (time.monotonic() - self.t0) - sp.start_s
            with self._lock:
                if len(self.spans) < MAX_SPANS:
                    self.spans.append(sp)
                else:
                    self.dropped += 1
            self._emit(sp.to_line())

    # ---- events / counters / gauges -------------------------------------

    def event(self, name: str, **attrs: object) -> None:
        ev = {
            "kind": "event",
            "name": name,
            "t_s": round(time.monotonic() - self.t0, 6),
            "span_id": self.current_span_id,
            "attrs": _jsonable(attrs),
        }
        with self._lock:
            if len(self.events) < MAX_EVENTS:
                self.events.append(ev)
            else:
                self.dropped += 1
        self._emit(ev)

    def declare(self, name: str) -> None:
        """Ensure a counter exists (zero) so it is emitted even untouched."""
        with self._lock:
            self.counters.setdefault(name, 0)

    def add(self, name: str, n: float = 1) -> None:
        """Atomic counter increment (the race's two threads both call in)."""
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + n

    def gauge(self, name: str, value: object) -> None:
        with self._lock:
            self.gauges[name] = value

    # ---- rollups / finish -------------------------------------------------

    def span_rollup(self) -> List[Tuple[str, float, int]]:
        """``[(name, total_seconds, count), ...]`` sorted by total desc."""
        with self._lock:
            totals: Dict[str, List[float]] = {}
            for sp in self.spans:
                if sp.seconds is None:
                    continue
                cur = totals.setdefault(sp.name, [0.0, 0])
                cur[0] += sp.seconds
                cur[1] += 1
        return sorted(
            ((name, t, int(c)) for name, (t, c) in totals.items()),
            key=lambda row: -row[1],
        )

    def final_lines(self) -> List[dict]:
        """Counter/gauge lines emitted once at finish."""
        with self._lock:
            counters = dict(self.counters)
            gauges = dict(self.gauges)
            dropped = self.dropped
        lines = [
            {"kind": "counter", "name": name, "value": value}
            for name, value in sorted(counters.items())
        ]
        lines += [
            {"kind": "gauge", "name": name, "value": _jsonable(value)}
            for name, value in sorted(gauges.items())
        ]
        if dropped:
            lines.append({"kind": "counter", "name": "telemetry.dropped",
                          "value": dropped})
        return lines

    def summary_lines(self) -> List[str]:
        """Human summary: span rollup + non-zero counters + gauges."""
        out = []
        for name, total, count in self.span_rollup():
            suffix = f" (x{count})" if count > 1 else ""
            out.append(f"[telemetry] span {name}: {total * 1000:.2f} ms{suffix}")
        with self._lock:
            counters = dict(self.counters)
            gauges = dict(self.gauges)
        for name, value in sorted(counters.items()):
            out.append(f"[telemetry] counter {name}: {value}")
        for name, value in sorted(gauges.items()):
            out.append(f"[telemetry] gauge {name}: {value}")
        return out

    def finish(self) -> None:
        """Flush counters/gauges and close sinks (idempotent)."""
        with self._lock:
            if self._finished:
                return
            self._finished = True
            sinks = list(self._sinks)
        for sink in sinks:
            try:
                sink.finish(self)
            except Exception as exc:  # noqa: BLE001
                log.info("telemetry sink finish failed: %s", exc)


# ---- process-wide record --------------------------------------------------

_global: Optional[RunRecord] = None
_global_lock = threading.Lock()


def _attach_env_sinks(record: RunRecord) -> None:
    """Honor QI_METRICS_JSON / QI_METRICS_PROM: the env-var hook the test
    suite and CI use (tools/ci_tier1.sh) — every process in a run appends to
    one shared stream without any flag plumbing."""
    jsonl = qi_env("QI_METRICS_JSON")
    if jsonl:
        record.add_sink(JsonlSink(jsonl))
    prom = qi_env("QI_METRICS_PROM")
    if prom:
        record.add_sink(PromFileSink(prom))


def get_run_record() -> RunRecord:
    """The process-wide :class:`RunRecord` (created lazily; env sinks
    attached on first use; flushed at interpreter exit)."""
    global _global
    if _global is None:
        with _global_lock:
            if _global is None:
                record = RunRecord()
                _attach_env_sinks(record)
                atexit.register(record.finish)
                _global = record
    return _global


def reset_run_record() -> RunRecord:
    """Replace the process-wide record with a fresh one (tests; the old
    record is finished first so its sinks flush)."""
    global _global
    with _global_lock:
        old, _global = _global, None
    if old is not None:
        old.finish()
    return get_run_record()


def finish() -> None:
    """Finish the process-wide record if one exists (idempotent)."""
    if _global is not None:
        _global.finish()
