"""Persistent XLA compilation cache.

A sweep run compiles one program per ramp level it reaches
(`backends/tpu/sweep.py` STEPS_RAMP) — several seconds each on a tunneled
chip, re-paid on every fresh process because jit caches die with it.  The
persistent cache amortizes those compiles across processes/runs: warm-cache
time-to-verdict on a 2^30 sweep drops by the full compile budget.

Opt-out with ``QI_NO_COMPILE_CACHE=1``; relocate with
``JAX_COMPILATION_CACHE_DIR`` (jax's own env var, which jax reads itself —
we only install a default when the user hasn't chosen).
"""

from __future__ import annotations

import os
from pathlib import Path

from quorum_intersection_tpu.utils.logging import get_logger

log = get_logger("utils.compile_cache")

_installed = False


def enable_compilation_cache() -> None:
    """Install a persistent compilation cache (idempotent, best-effort)."""
    global _installed
    if _installed or os.environ.get("QI_NO_COMPILE_CACHE"):
        return
    _installed = True
    try:
        import jax

        if os.environ.get("JAX_COMPILATION_CACHE_DIR"):
            return  # user configured jax directly; nothing to do
        cache_dir = Path(
            os.environ.get("XDG_CACHE_HOME", Path.home() / ".cache")
        ) / "quorum_intersection_tpu" / "jax_cache"
        cache_dir.mkdir(parents=True, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", str(cache_dir))
        # Cache every kernel: sweep programs are few and large-ish, and the
        # default min-entry/compile-time thresholds would skip the small
        # early-ramp programs that gate a resumed run's first results.
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        log.debug("persistent compilation cache at %s", cache_dir)
    except Exception as exc:  # noqa: BLE001 - cache is an optimization only
        log.info("compilation cache unavailable: %s", exc)
