"""Persistent XLA compilation cache.

A sweep run compiles one program per ramp level it reaches
(`backends/tpu/sweep.py` STEPS_RAMP) — several seconds each on a tunneled
chip, re-paid on every fresh process because jit caches die with it.  The
persistent cache amortizes those compiles across processes/runs: warm-cache
time-to-verdict on a 2^30 sweep drops by the full compile budget.

Opt-out with ``QI_NO_COMPILE_CACHE=1``; relocate with
``JAX_COMPILATION_CACHE_DIR`` (jax's own env var, which jax reads itself —
we only install a default when the user hasn't chosen).

``QI_COMPILE_CACHE_CPU=1`` forces the cache ON for the CPU backend and
drops jax's min-compile-time threshold to zero — the warm-start acceptance
test pins the cache-hit behavior on the CPU tier, where compiles are
sub-second and the same-host SIGILL caveat below does not apply (the test
reloads its own artifacts).  Not for production CPU use.
"""

from __future__ import annotations

import os
from pathlib import Path

from quorum_intersection_tpu.utils.env import qi_env_flag
from quorum_intersection_tpu.utils.logging import get_logger
from quorum_intersection_tpu.utils.telemetry import get_run_record

log = get_logger("utils.compile_cache")

_installed = False
_listener_installed = False


def _install_cache_listener() -> None:
    """Forward jax's own compilation-cache monitoring events into the run
    record: jax emits ``/jax/compilation_cache/cache_hits`` /
    ``cache_misses`` through ``jax.monitoring`` on every lookup, so the
    telemetry counters are the real cache behavior, not a re-derivation.
    Best-effort — the monitoring module is jax-internal surface."""
    global _listener_installed
    if _listener_installed:
        return
    _listener_installed = True
    try:
        from jax import monitoring

        def _on_event(event: str, **kwargs) -> None:
            if "/compilation_cache/" not in event:
                return
            if "cache_hits" in event:
                get_run_record().add("compile_cache.hits")
            elif "cache_misses" in event:
                get_run_record().add("compile_cache.misses")

        monitoring.register_event_listener(_on_event)
    except Exception as exc:  # noqa: BLE001 — counters are diagnostics only
        log.debug("compile-cache event listener unavailable: %s", exc)


def enable_compilation_cache() -> None:
    """Install a persistent compilation cache (idempotent, best-effort)."""
    global _installed
    if _installed or qi_env_flag("QI_NO_COMPILE_CACHE"):
        return
    _installed = True
    _install_cache_listener()
    try:
        import jax

        force_cpu = qi_env_flag("QI_COMPILE_CACHE_CPU")
        if os.environ.get("JAX_COMPILATION_CACHE_DIR"):
            if force_cpu:
                # The user-chosen dir rides jax's own env handling; only the
                # sub-second-compile threshold needs dropping on CPU.
                jax.config.update(
                    "jax_persistent_cache_min_compile_time_secs", 0.0
                )
            return  # user configured jax directly; nothing else to do
        if jax.default_backend() == "cpu" and not force_cpu:
            # CPU AOT artifacts encode the compile host's machine features;
            # reloading them on a different host risks SIGILL (observed via
            # cpu_aot_loader warnings), and CPU compiles are sub-second —
            # the cache only pays for itself on the accelerator path.
            return
        cache_dir = Path(
            os.environ.get("XDG_CACHE_HOME", Path.home() / ".cache")
        ) / "quorum_intersection_tpu" / "jax_cache"
        cache_dir.mkdir(parents=True, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", str(cache_dir))
        get_run_record().event("compile_cache.enabled", dir=str(cache_dir))
        # JAX's default thresholds (min compile time ~1 s) are kept: every
        # ramp program on a real chip compiles for multiple seconds and is
        # cached, while the sub-second kernels test suites churn through are
        # skipped — bounding cache growth across runs.  The forced-CPU test
        # path drops the threshold so its sub-second compiles cache too.
        if force_cpu:
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        log.debug("persistent compilation cache at %s", cache_dir)
    except Exception as exc:  # noqa: BLE001 - cache is an optimization only
        log.info("compilation cache unavailable: %s", exc)
