"""Profiler hook — structured device traces for the solver phases.

The reference's only observability is boost::log trace spew
(`/root/reference/quorum_intersection.cpp:735-742`); the TPU-native
equivalent (SURVEY.md §5 "tracing/profiling") is a `jax.profiler` trace the
user can open in TensorBoard/XProf: device kernel timelines and HBM usage.

Usage: ``with profile_trace(dir):`` around any solve; no-op when ``dir`` is
falsy, so callers can pass the CLI flag straight through.
"""

from __future__ import annotations

from contextlib import contextmanager
from typing import Iterator, Optional

from quorum_intersection_tpu.utils.logging import get_logger
from quorum_intersection_tpu.utils.telemetry import get_run_record

log = get_logger("utils.profiling")


@contextmanager
def profile_trace(trace_dir: Optional[str]) -> Iterator[None]:
    """Record a ``jax.profiler`` trace into ``trace_dir`` (no-op if falsy).

    The trace dir and its wall-clock window are correlated into the run
    record (``profile.trace`` span + ``profile.trace_dir`` gauge), so a
    JSONL stream names the XProf artifact that covers the same solve."""
    if not trace_dir:
        yield
        return
    try:
        import jax
    except ImportError as exc:  # jax-less install + pure-CPU backend
        log.warning("profiling disabled: jax unavailable (%s)", exc)
        yield
        return

    log.info("recording jax profiler trace to %s", trace_dir)
    rec = get_run_record()
    rec.gauge("profile.trace_dir", str(trace_dir))
    # Correlation marker (qi-trace): the XProf timeline carries a named
    # TraceAnnotation with this run's trace_id, and the profile.trace span
    # carries the same id — so the device trace and the qi-telemetry /
    # Perfetto timeline join on one key.
    annotation = getattr(jax.profiler, "TraceAnnotation", None)
    with rec.span("profile.trace", dir=str(trace_dir), trace_id=rec.trace_id):
        with jax.profiler.trace(str(trace_dir)):
            if annotation is None:
                yield
            else:
                with annotation(f"qi-trace:{rec.trace_id}"):
                    yield
