"""Downstream-pipe hygiene for stdin/stdout CLI entry points."""

from __future__ import annotations

import os
import sys
from typing import Callable


def run_with_pipe_hygiene(main: Callable[[], int]) -> int:
    """Run a CLI ``main``; a closed stdout (e.g. ``… | head``) exits 1
    quietly instead of dumping a BrokenPipeError traceback."""
    try:
        return main()
    except BrokenPipeError:
        devnull = os.open(os.devnull, os.O_WRONLY)
        os.dup2(devnull, sys.stdout.fileno())
        return 1
