"""Platform selection hygiene.

This image's sitecustomize force-appends the axon TPU platform to
``jax.config.jax_platforms`` at interpreter start, which silently overrides a
user-set ``JAX_PLATFORMS=cpu`` (axon wins priority and grabs the tunneled
chip — or hangs when the tunnel is down).  Call :func:`honor_platform_env`
before the first backend query to make the env var mean what it says.
"""

from __future__ import annotations

import os


def is_cpu_platform() -> bool:
    """True when JAX's default backend is the CPU (or JAX is absent/broken).

    The single shared probe for platform-dependent tuning (sweep limits,
    batch sizes, engine routing) — callers must not re-implement it,
    or their exception policies drift apart.
    """
    return backend_kind() == "cpu"


def backend_kind() -> str:
    """JAX's default backend name ("cpu", "tpu", "gpu", ...; "cpu" when JAX
    is absent/broken).  The one place the jax probe lives —
    :func:`is_cpu_platform` and the routing device-match gate both resolve
    through it, so exception/platform policy can't drift between them."""
    try:
        import jax

        return str(jax.default_backend())
    except Exception:  # noqa: BLE001 - no jax ⇒ no accelerator either
        return "cpu"


def honor_platform_env() -> None:
    """Re-pin jax onto the platforms named by ``JAX_PLATFORMS`` when the
    ambient config would override them (no-op otherwise; safe pre-query)."""
    want = os.environ.get("JAX_PLATFORMS")
    if not want or "axon" in want:
        return
    try:
        import jax

        if "axon" in (jax.config.jax_platforms or ""):
            jax.config.update("jax_platforms", want)
    except ImportError:  # pure-CPU installs have nothing to pin
        pass
