"""Live observability endpoint — ``/healthz`` + ``/metrics`` over stdlib HTTP.

The first brick of the snapshot-stream serving layer (ROADMAP): before the
long-lived query service exists, the pipeline already answers the two
questions a fleet scheduler asks of any service — *is it healthy* and *what
are its numbers* — from any run that sets ``QI_METRICS_PORT`` (env registry,
utils/env.py):

- ``GET /healthz`` → JSON (``qi-health/1``): degradation-ladder rung,
  quarantined rungs, in-flight lane packs, degrade/fault counters, trace_id
  — everything sourced from the process-wide RunRecord's gauges/counters,
  so the endpoint never reaches into engine internals;
- ``GET /readyz`` → JSON (``qi-ready/1``, ISSUE 8): the serving layer's
  admission picture — queue depth, shed state, journal-replay progress —
  with proper readiness semantics: **503** while a restarted instance is
  still replaying its crashed predecessor's journal (a scheduler must not
  route traffic at it yet), 200 once replay completes or when no serving
  engine runs in this process (liveness and readiness then coincide).
  ``/healthz`` deliberately stays pure liveness: a replaying process is
  alive (don't restart it — that would loop the replay) but not ready.
  Since ISSUE 11 the same 503 discipline covers the fleet front door:
  not ready while any dead worker's journal is still replaying on its
  inheriting peers (``fleet.replay_complete``), and ``/healthz`` exposes
  the aggregated ``fleet_workers_live`` / ``fleet_ring_size`` /
  ``fleet_store_hit_pct`` gauges;
- ``GET /metrics`` → the Prometheus text encoding of the same record,
  produced by the ONE encoder the textfile sink uses
  (:func:`quorum_intersection_tpu.utils.telemetry.prom_lines`) — scrape it
  directly instead of (or alongside) the ``QI_METRICS_PROM`` textfile.

Both endpoints render deterministically (sorted keys/metrics), so
concurrent scrapes of an unchanged record are byte-identical —
``tests/test_qi_trace.py`` pins it.  stdlib-only (``http.server``), bound
to 127.0.0.1, served from a daemon thread: observability must never hold a
verdict process alive or open the solve to the network.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from quorum_intersection_tpu.utils.env import qi_env_int
from quorum_intersection_tpu.utils.logging import get_logger
from quorum_intersection_tpu.utils.telemetry import get_run_record, prom_lines

log = get_logger("utils.metrics_server")

HEALTH_SCHEMA = "qi-health/1"
READY_SCHEMA = "qi-ready/1"


def healthz_payload() -> dict:
    """The /healthz body: run identity + the degradation picture.

    Sourced purely from the run record's counters/gauges snapshot (the
    ladder and the packed sweep keep ``ladder.rung`` /
    ``ladder.quarantined_rungs`` / ``sweep.packs_in_flight`` current), so
    the endpoint stays byte-stable between state changes and has no lock
    interaction with the engines.
    """
    # qi-cost (ISSUE 17): a /healthz scrape is one of the SLO plane's lazy
    # evaluation triggers (no background thread anywhere) — evaluate FIRST
    # so the snapshot below reads a fresh slo.burning gauge.  Imported
    # here, not at module top: this module sits under utils/ and must not
    # import package-level engines at import time.
    from quorum_intersection_tpu.cost import slo_plane
    slo = slo_plane()
    if slo.enabled:
        slo.evaluate()
    rec = get_run_record()
    counters, gauges = rec.snapshot()
    return {
        "schema": HEALTH_SCHEMA,
        "status": "ok",
        "pid": rec.pid,
        "trace_id": rec.trace_id,
        "started_t_wall": round(rec.t_wall, 3),
        "ladder_rung": gauges.get("ladder.rung"),
        "quarantined_rungs": gauges.get("ladder.quarantined_rungs", []),
        "packs_in_flight": gauges.get("sweep.packs_in_flight", 0),
        "degrades": counters.get("ladder.degrades", 0),
        "retries": counters.get("ladder.retries", 0),
        "faults_injected": counters.get("faults.injected", 0),
        "flight_dumps": counters.get("telemetry.dumps", 0),
        # qi-delta (ISSUE 9): per-SCC reuse efficiency + store occupancy —
        # a reuse_pct collapsing to 0 under steady churn is a fingerprint
        # bug (or a store sized below the working set), visible from any
        # fleet scrape without attaching a debugger.
        "delta_scc_reuse_pct": gauges.get("delta.scc_reuse_pct", 0.0),
        "delta_store_size": gauges.get("delta.store_size", 0),
        # qi-fleet (ISSUE 11): the front door's aggregated fleet picture —
        # workers on the ring vs workers answering probes, and the shared
        # SCC-fragment tier's hit rate (a collapse to 0 under steady
        # traffic means the shared store died and every worker degraded to
        # local-LRU-only — loud in the fleet.store_errors counter too).
        "fleet_workers_live": gauges.get("fleet.workers_live", 0),
        "fleet_ring_size": gauges.get("fleet.ring_size", 0),
        "fleet_store_hit_pct": gauges.get("fleet.store_hit_pct", 0.0),
        # qi-pulse (ISSUE 15): the aggregation plane's fleet-wide tail
        # latency — p99 over the UNION of the workers' merged pulse.e2e_ms
        # histograms, not the max of per-worker gauges.  0.0 until the
        # first aggregation cycle lands (or with QI_PULSE_AGG=0).
        "fleet_e2e_p99_ms": gauges.get("fleet.e2e_p99_ms", 0.0),
        # qi-cost (ISSUE 17): the SLO burn picture — how many declared
        # targets are burning in BOTH the fast and slow windows right now
        # (0 with no QI_SLO targets), and the attribution health counters
        # (/sloz has the full per-target ratios and the tenant tables).
        "slo_burning": gauges.get("slo.burning", 0),
        "cost_attribute_errors": counters.get("cost.attribute_errors", 0),
    }


def sloz_payload() -> dict:
    """The /sloz body (``qi-slo/1``): one lazy SLO evaluation (per-target
    bounds, values, fast/slow burn ratios, burning flags) plus the
    costliest tenants — local table and fleet-merged table."""
    from quorum_intersection_tpu.cost import sloz_payload as _sloz
    return _sloz()


def readyz_payload() -> tuple:
    """The /readyz body + status code: ``(payload, http_status)``.

    Readiness is the SERVING-layer question (liveness stays /healthz):
    sourced from the ``serve.*`` gauges ``ServeEngine`` keeps current.
    Not ready (503) exactly while a journal replay is in progress —
    ``serve.replay_complete`` was published as 0 at engine start and
    flips to 1 once the crashed predecessor's work is re-solved.  A
    process with no serving engine publishes neither gauge and reports
    ready: for the one-shot CLI, alive == ready.
    """
    rec = get_run_record()
    counters, gauges = rec.snapshot()
    replay = gauges.get("serve.replay_complete")
    replaying = replay is not None and not replay
    # qi-fleet (ISSUE 11): the front door is not ready until EVERY live
    # worker finished its journal replay (fleet.replay_complete is 0 from
    # fleet start / failover begin until the inherited work re-solved) —
    # a scheduler must not route traffic at a fleet still recovering a
    # dead worker's unfinished requests.
    fleet_replay = gauges.get("fleet.replay_complete")
    fleet_replaying = fleet_replay is not None and not fleet_replay
    not_ready = replaying or fleet_replaying
    payload = {
        "schema": READY_SCHEMA,
        "status": "replaying" if not_ready else "ready",
        "pid": rec.pid,
        "trace_id": rec.trace_id,
        "serving": "serve.queue_depth" in gauges,
        "replay_complete": None if replay is None else bool(replay),
        "fleet_replay_complete": (
            None if fleet_replay is None else bool(fleet_replay)
        ),
        "fleet_workers_live": gauges.get("fleet.workers_live", 0),
        "queue_depth": gauges.get("serve.queue_depth", 0),
        "shed_state": gauges.get("serve.shed_state", 0),
        "shed_total": counters.get("serve.shed", 0),
        "requests": counters.get("serve.requests", 0),
        "verdicts": counters.get("serve.verdicts", 0),
    }
    return payload, (503 if not_ready else 200)


class _Handler(BaseHTTPRequestHandler):
    """Request handler for the two read-only endpoints."""

    server_version = "qi-metrics/1"

    def _respond(self, code: int, content_type: str, body: bytes) -> None:
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self) -> None:  # noqa: N802 — http.server's required name
        path = self.path.split("?", 1)[0]
        if path == "/metrics":
            body = ("\n".join(prom_lines(get_run_record())) + "\n").encode()
            self._respond(200, "text/plain; version=0.0.4", body)
        elif path == "/healthz":
            body = (
                json.dumps(healthz_payload(), sort_keys=True) + "\n"
            ).encode()
            self._respond(200, "application/json", body)
        elif path == "/readyz":
            payload, status = readyz_payload()
            body = (json.dumps(payload, sort_keys=True) + "\n").encode()
            self._respond(status, "application/json", body)
        elif path == "/sloz":
            body = (
                json.dumps(sloz_payload(), sort_keys=True) + "\n"
            ).encode()
            self._respond(200, "application/json", body)
        else:
            self._respond(404, "text/plain", b"not found\n")

    def log_message(self, format: str, *args: object) -> None:
        # Route scrape access logs to the qi logger at debug, never stderr —
        # a scraper must not interleave noise into --timing output.
        log.debug("metrics scrape: " + format, *args)


class MetricsServer:
    """One live endpoint server, bound to 127.0.0.1.

    ``port=0`` binds an ephemeral port (tests); read it back via ``.port``.
    The serving thread is a daemon — interpreter exit never waits on a
    scraper — and :meth:`stop` shuts it down deterministically.
    """

    def __init__(self, port: int = 0, host: str = "127.0.0.1") -> None:
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = int(self._httpd.server_address[1])
        # qi-lint: allow(cancel-token-plumbed) — daemon scrape server, no solve work; stop() shuts it down
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="qi-metrics-server",
            daemon=True,
        )
        self._thread.start()
        log.info("metrics endpoint serving on http://%s:%d "
                 "(/healthz, /readyz, /sloz, /metrics)", host, self.port)

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()


_server: Optional[MetricsServer] = None
_server_lock = threading.Lock()


def maybe_start_from_env() -> Optional[MetricsServer]:
    """Start the process-wide server once when ``QI_METRICS_PORT`` > 0.

    Best-effort by contract: a port already taken (a bench child inheriting
    the parent's env) logs and returns None — a scrape endpoint is never
    worth a verdict.
    """
    global _server
    with _server_lock:
        if _server is not None:
            return _server
        port = qi_env_int("QI_METRICS_PORT", 0)
        if port <= 0:
            return None
        try:
            _server = MetricsServer(port=port)
        except OSError as exc:
            log.info("metrics endpoint not started on port %d: %s", port, exc)
            return None
        return _server


def stop_server() -> None:
    """Stop the env-started server if one is running (tests)."""
    global _server
    with _server_lock:
        if _server is not None:
            _server.stop()
            _server = None
