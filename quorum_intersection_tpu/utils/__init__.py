"""Cross-cutting utilities: trace logging, phase timers, throughput counters,
and the unified run-record telemetry layer (spans/counters/events + sinks)."""

from quorum_intersection_tpu.utils.logging import get_logger, set_trace
from quorum_intersection_tpu.utils.telemetry import RunRecord, get_run_record
from quorum_intersection_tpu.utils.timers import PhaseTimers, Throughput

__all__ = [
    "get_logger",
    "set_trace",
    "PhaseTimers",
    "Throughput",
    "RunRecord",
    "get_run_record",
]
