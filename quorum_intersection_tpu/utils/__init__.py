"""Cross-cutting utilities: trace logging, phase timers, throughput counters."""

from quorum_intersection_tpu.utils.logging import get_logger, set_trace
from quorum_intersection_tpu.utils.timers import PhaseTimers, Throughput

__all__ = ["get_logger", "set_trace", "PhaseTimers", "Throughput"]
