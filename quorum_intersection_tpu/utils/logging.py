"""Severity-filtered logging — the capability of the reference's boost::log
setup (`/root/reference/quorum_intersection.cpp:735-742`): default level INFO,
``-t/--trace`` drops the filter to TRACE-equivalent (DEBUG here).  Solver
internals log at trace level just as the reference saturates its solver with
``BOOST_LOG_TRIVIAL(trace)`` messages.

Environment knobs (ISSUE 2 satellite):

- ``QI_LOG_LEVEL`` — initial level by name (``DEBUG``/``INFO``/``WARNING``/
  ``ERROR``/``CRITICAL``) or numeric value; ``-t`` still overrides it at the
  CLI.  Before this, only ``-t`` could move the filter at all — soak/CI runs
  had no way to quiet INFO or get DEBUG without a flag.
- ``QI_LOG_JSON=1`` — opt-in JSON formatter: each log line becomes one JSON
  object (``{"kind": "log", "level": ..., "logger": ..., "msg": ...,
  "t_wall": ...}``) so log lines and ``qi-telemetry/1`` events interleave
  cleanly in one machine-readable stream (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import json
import logging
import sys

from quorum_intersection_tpu.utils.env import qi_env, qi_env_flag

_ROOT_NAME = "quorum_intersection_tpu"
_configured = False


class _JsonFormatter(logging.Formatter):
    """One JSON object per log line — telemetry-stream compatible."""

    def format(self, record: logging.LogRecord) -> str:
        line = {
            "kind": "log",
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
            "t_wall": round(record.created, 3),
        }
        if record.exc_info:
            line["exc"] = self.formatException(record.exc_info)
        return json.dumps(line, default=str)


def _env_level() -> int:
    """Level named by QI_LOG_LEVEL (default INFO; bad values ignored)."""
    raw = qi_env("QI_LOG_LEVEL").strip()
    if not raw:
        return logging.INFO
    if raw.isdigit():
        return int(raw)
    level = logging.getLevelName(raw.upper())
    return level if isinstance(level, int) else logging.INFO


def _configure() -> None:
    global _configured
    if _configured:
        return
    logger = logging.getLogger(_ROOT_NAME)
    handler = logging.StreamHandler(sys.stderr)
    if qi_env_flag("QI_LOG_JSON"):
        handler.setFormatter(_JsonFormatter())
    else:
        handler.setFormatter(logging.Formatter("[%(levelname)s] %(name)s: %(message)s"))
    logger.addHandler(handler)
    logger.setLevel(_env_level())
    logger.propagate = False
    _configured = True


def get_logger(name: str = "") -> logging.Logger:
    _configure()
    return logging.getLogger(f"{_ROOT_NAME}.{name}" if name else _ROOT_NAME)


def set_trace(enabled: bool = True) -> None:
    """Enable trace-level (DEBUG) logging, the analog of the reference's
    ``-t`` (overrides ``QI_LOG_LEVEL``; disabling restores the env level)."""
    _configure()
    logging.getLogger(_ROOT_NAME).setLevel(
        logging.DEBUG if enabled else _env_level()
    )
