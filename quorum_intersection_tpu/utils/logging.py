"""Severity-filtered logging — the capability of the reference's boost::log
setup (`/root/reference/quorum_intersection.cpp:735-742`): default level INFO,
``-t/--trace`` drops the filter to TRACE-equivalent (DEBUG here).  Solver
internals log at trace level just as the reference saturates its solver with
``BOOST_LOG_TRIVIAL(trace)`` messages.
"""

from __future__ import annotations

import logging
import sys

_ROOT_NAME = "quorum_intersection_tpu"
_configured = False


def _configure() -> None:
    global _configured
    if _configured:
        return
    logger = logging.getLogger(_ROOT_NAME)
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(logging.Formatter("[%(levelname)s] %(name)s: %(message)s"))
    logger.addHandler(handler)
    logger.setLevel(logging.INFO)
    logger.propagate = False
    _configured = True


def get_logger(name: str = "") -> logging.Logger:
    _configure()
    return logging.getLogger(f"{_ROOT_NAME}.{name}" if name else _ROOT_NAME)


def set_trace(enabled: bool = True) -> None:
    """Enable trace-level (DEBUG) logging, the analog of the reference's ``-t``."""
    _configure()
    logging.getLogger(_ROOT_NAME).setLevel(logging.DEBUG if enabled else logging.INFO)
