"""One registry for every ``QI_*`` environment variable the framework reads.

Before this module, each env knob was an ad-hoc ``os.environ.get`` scattered
through the codebase — docs/OBSERVABILITY.md and the README listed what the
author *remembered*, not what the code *read*, and the two drifted (the
static-analysis ISSUE 3 motivation).  Now every read goes through
:func:`qi_env` against a declared :class:`EnvVar`, so:

- the registry below IS the documentation — ``python -m tools.analyze``'s
  ``no-bare-env-read`` lint rule flags any ``os.environ`` read of a ``QI_*``
  key outside this module, and :func:`qi_env` raises on undeclared names, so
  a new knob cannot ship without a description;
- defaults live in exactly one place (the call sites stop hand-carrying
  them);
- ``registry()`` gives tooling (docs generators, ``--help`` epilogues) the
  machine-readable catalog.

stdlib-only and import-free of the rest of the package: ``utils/logging.py``
reads :data:`QI_LOG_LEVEL` here during its own bootstrap, so this module
must sit below everything else in the import graph.

Reads are deliberately **not cached**: tests monkeypatch ``os.environ`` and
expect the next read to see it, exactly as the scattered ``environ.get``
calls behaved.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, Optional, Tuple


@dataclass(frozen=True)
class EnvVar:
    """One declared environment knob: its name, default, and contract."""

    name: str
    default: Optional[str]
    description: str


_REGISTRY: Dict[str, EnvVar] = {}


def _declare(name: str, default: Optional[str], description: str) -> EnvVar:
    var = EnvVar(name=name, default=default, description=description)
    _REGISTRY[name] = var
    return var


# ---- the catalog -----------------------------------------------------------

QI_LOG_LEVEL = _declare(
    "QI_LOG_LEVEL", "",
    "Initial log level by name (DEBUG/INFO/WARNING/ERROR/CRITICAL) or "
    "numeric value; the CLI's -t still overrides it (utils/logging.py).",
)
QI_LOG_JSON = _declare(
    "QI_LOG_JSON", "",
    "Truthy: one JSON object per log line, interleavable with the "
    "qi-telemetry/1 stream (utils/logging.py).",
)
QI_METRICS_JSON = _declare(
    "QI_METRICS_JSON", "",
    "Path of a qi-telemetry/1 JSONL stream every process appends to "
    "(utils/telemetry.py env sink; the CLI flag --metrics-json plumbs the "
    "same sink explicitly).",
)
QI_METRICS_PROM = _declare(
    "QI_METRICS_PROM", "",
    "Path of a Prometheus textfile rewritten at process finish "
    "(utils/telemetry.py env sink; CLI flag --metrics-prom).",
)
QI_NO_COMPILE_CACHE = _declare(
    "QI_NO_COMPILE_CACHE", "",
    "Truthy: disable the persistent XLA compilation cache "
    "(utils/compile_cache.py).",
)
QI_COMPILE_CACHE_CPU = _declare(
    "QI_COMPILE_CACHE_CPU", "",
    "Truthy: force the compile cache ON for the CPU backend and drop jax's "
    "min-compile-time threshold to zero — warm-start tests only, not for "
    "production CPU use (utils/compile_cache.py).",
)
QI_FRONTIER_CKPT_INTERVAL_S = _declare(
    "QI_FRONTIER_CKPT_INTERVAL_S", "5.0",
    "Frontier checkpoint write cadence in seconds — exists so process-death "
    "tests can shrink the cadence of a CLI child they cannot construct "
    "in-process (backends/tpu/frontier.py).",
)
QI_SANITIZER = _declare(
    "QI_SANITIZER", "asan",
    "Which sanitizer the instrumented native build uses: 'asan' "
    "(address+undefined, the default), 'tsan' (thread), or 'none' "
    "(sanitized builds refused with a clear error) — "
    "backends/cpp/build_native_cli(sanitize=True).",
)
QI_TEST_PLATFORM = _declare(
    "QI_TEST_PLATFORM", "cpu",
    "Platform the test suite pins via JAX_PLATFORMS before jax loads: "
    "'cpu' (default), 'tpu', or 'axon' (tests/conftest.py).",
)
QI_FAULTS = _declare(
    "QI_FAULTS", "",
    "Deterministic fault-injection rules, comma-separated "
    "point=mode[:seconds][@hit[+]] (utils/faults.py — the declared "
    "fault-point catalog and grammar live there; docs/ROBUSTNESS.md "
    "renders it).  Empty: no injection, fault points are near-free.",
)
QI_NATIVE_WATCHDOG_S = _declare(
    "QI_NATIVE_WATCHDOG_S", "0",
    "Deadline in seconds for one in-process native oracle call under the "
    "auto router: past it a monitor trips the CancelToken, and a call "
    "that STILL does not return quarantines the native rung for the run "
    "(backends/auto.py).  0 (default): watchdog off, calls run on the "
    "caller's thread exactly as before.",
)
QI_RETRY_MAX = _declare(
    "QI_RETRY_MAX", "2",
    "Bounded retry budget per degradation-ladder rung for TRANSIENT "
    "device errors (RESOURCE_EXHAUSTED/OOM class): retries with "
    "exponential backoff + deterministic jitter before the ladder "
    "degrades to the next rung (backends/auto.py DegradationLadder).",
)
QI_DIST_INIT_TIMEOUT_S = _declare(
    "QI_DIST_INIT_TIMEOUT_S", "20",
    "Total time budget for joining the multi-process JAX runtime: "
    "coordinator-join failures retry with backoff under this deadline "
    "before degrading loudly to single-process "
    "(parallel/distributed.py initialize; event "
    "distributed.init_degraded).",
)
QI_TRACE_OUT = _declare(
    "QI_TRACE_OUT", "",
    "Path of a Chrome/Perfetto trace-event JSON file the run appends its "
    "spans and events to (utils/telemetry.py ChromeTraceSink; CLI flag "
    "--trace-out).  Multi-process runs share one file — open it in "
    "ui.perfetto.dev to see the whole run as one timeline.",
)
QI_TRACE_CONTEXT = _declare(
    "QI_TRACE_CONTEXT", "",
    "Inherited trace context 'trace_id:span_id:pid' a parent process "
    "exports before spawning children (bench.py phase children, "
    "benchmarks/auto_race.py warm pairs, distributed workers): the child's "
    "RunRecord adopts the trace_id instead of minting its own, so every "
    "process of one run shares a single causal trace "
    "(utils/telemetry.py TraceContext).",
)
QI_FLIGHT_RECORDER = _declare(
    "QI_FLIGHT_RECORDER", "",
    "Path the crash flight recorder dumps to: a bounded ring buffer of the "
    "last spans/events is always on, and on fault firing, watchdog trip, "
    "ladder degrade/quarantine, or unhandled exception its tail plus a "
    "counter/gauge snapshot is written crash-only with fsync-before-rename "
    "(utils/telemetry.py dump_flight_recorder).  Empty: dumps disabled, "
    "the ring still records.",
)
QI_METRICS_PORT = _declare(
    "QI_METRICS_PORT", "0",
    "TCP port of the live observability endpoint (127.0.0.1): /healthz "
    "serves ladder rung, quarantine state and in-flight packs as JSON, "
    "/readyz serves the serving layer's admission picture (503 while "
    "journal replay is in progress), /metrics serves the Prometheus "
    "encoding of the run record (utils/metrics_server.py).  0 (default): "
    "no server.",
)
QI_SERVE_DEADLINE_S = _declare(
    "QI_SERVE_DEADLINE_S", "0",
    "Default per-request deadline budget in seconds for the serving layer "
    "(serve.py): past it an in-flight solve is cancelled through the "
    "CancelToken lattice and the request returns a typed DeadlineExceeded "
    "with its partial-coverage certificate.  0 (default): no deadline.",
)
QI_SERVE_QUEUE_DEPTH = _declare(
    "QI_SERVE_QUEUE_DEPTH", "64",
    "Admission-queue depth bound of the serving layer (serve.py): a "
    "request arriving with this many solve units already queued is shed "
    "with a typed Overloaded rejection instead of growing the queue "
    "without bound.",
)
QI_SERVE_BATCH_MAX = _declare(
    "QI_SERVE_BATCH_MAX", "8",
    "Most solve units one serving drain cycle hands to "
    "pipeline.check_many at once (serve.py): queued compatible requests "
    "accumulate into one batched backend call (which lane-packs "
    "sweep-sized problems together).",
)
QI_SERVE_CACHE_MAX = _declare(
    "QI_SERVE_CACHE_MAX", "1024",
    "Verdict-cache capacity of the serving layer (serve.py): distinct "
    "snapshot fingerprints retained before LRU eviction "
    "(serve.cache_evictions counter).",
)
QI_DELTA_CACHE_MAX = _declare(
    "QI_DELTA_CACHE_MAX", "4096",
    "Per-SCC verdict-store capacity of the incremental re-analysis "
    "subsystem (delta.py): SCC-local scan and verdict fragments retained "
    "before LRU eviction (delta.store_evictions counter).  0 disables "
    "qi-delta entirely — the serving layer then re-solves every snapshot "
    "from scratch, exactly the pre-delta behavior.",
)
QI_SWEEP_ORDER = _declare(
    "QI_SWEEP_ORDER", "",
    "Enumeration-order mode of the exhaustive sweep "
    "(backends/tpu/sweep.py): 'rank' applies the rank-order permutation "
    "(PageRank + top-tier scores, deterministic tie-break) so low-rank "
    "nodes occupy high window bits and the expected first-hit window of a "
    "false verdict shrinks; empty/'natural' (default) keeps the SCC's "
    "natural order.  Verdicts are order-independent (pinned by "
    "tests/test_qi_prune.py); the permutation is stamped into cert "
    "provenance.",
)
QI_SWEEP_PRUNE = _declare(
    "QI_SWEEP_PRUNE", "",
    "Device-side block-guard pruning of the exhaustive sweep "
    "(backends/tpu/sweep.py): any value other than empty or '0' skips "
    "window blocks whose maximal candidate contains no quorum (one "
    "greatest-fixpoint guard per 2^k-window block), booking them as "
    "checkable (prefix, k, rule) entries under the certificate's "
    "windows_pruned_guard ledger term (tools/check_cert.py re-verifies "
    "every block).  Empty/'0' (default): unpruned brute force.",
)
QI_SWEEP_ENGINE = _declare(
    "QI_SWEEP_ENGINE", "",
    "Kernel-engine request of the exhaustive sweep "
    "(backends/tpu/sweep.py): 'bitset' evaluates candidates by "
    "intersect-and-popcount over packed u32 words (qi-sparse — the "
    "sparse-graph twin, auto-routed by the measured density crossover "
    "when this knob is unset), 'pallas' the fused single-kernel engine; "
    "empty or anything else (default) the XLA block-diagonal matmul "
    "path.  A constructor-supplied engine wins over the knob; every "
    "request still flows through resolve_engine's documented precedence "
    "(sweep.engine_resolved event), so forcing an engine a circuit "
    "cannot honor degrades with a typed reason, never an error.  "
    "Verdicts are engine-independent (tests/test_qi_sparse.py).",
)
QI_FLEET_WORKERS = _declare(
    "QI_FLEET_WORKERS", "2",
    "Worker count of the replicated serve tier (fleet.py; CLI twin: "
    "python -m quorum_intersection_tpu fleet -n N): N ServeEngine "
    "workers behind the consistent-hash front door.",
)
QI_FLEET_STORE_DIR = _declare(
    "QI_FLEET_STORE_DIR", "",
    "Directory of the shared SCC-fragment store tier (delta.py "
    "SharedSccStore): set in a serve worker's environment (the fleet "
    "supervisor exports it to every worker it spawns), the per-process "
    "SccVerdictStore reads through to it on every miss and writes every "
    "banked fragment back, so one worker's solve composes into every "
    "worker's certs.  Empty (default): local LRU only.",
)
QI_FLEET_VNODES = _declare(
    "QI_FLEET_VNODES", "32",
    "Virtual nodes per worker on the fleet's consistent-hash ring "
    "(fleet.py HashRing): more vnodes smooth the key distribution; "
    "join/leave still moves only ~1/N of the fingerprint space.",
)
QI_FLEET_PROBE_INTERVAL_S = _declare(
    "QI_FLEET_PROBE_INTERVAL_S", "0.5",
    "Seconds between fleet health-probe cycles (fleet.py probe loop): "
    "each cycle pings every live worker over its own JSONL pipe and "
    "aggregates the pong snapshots into the fleet /healthz gauges.",
)
QI_FLEET_PROBE_FAILS = _declare(
    "QI_FLEET_PROBE_FAILS", "2",
    "Consecutive failed health probes before the fleet front door evicts "
    "a worker from the ring and replays its unfinished journal on the "
    "peers inheriting its hash range (fleet.py); a dead process is "
    "evicted immediately regardless.",
)
QI_FLEET_STORE_MAX_MB = _declare(
    "QI_FLEET_STORE_MAX_MB", "0",
    "Size budget (megabytes) of the shared SCC-fragment store directory "
    "(delta.py SharedSccStore): past it a publish triggers an "
    "LRU-by-mtime sweep deleting the stalest fragments until the "
    "directory fits again (delta.store_evictions counter + "
    "delta.store_gc event — loud, the fragments re-solve on next miss).  "
    "0 (default): unbounded, the pre-GC behavior.",
)
QI_FLEET_RESPAWN_MAX = _declare(
    "QI_FLEET_RESPAWN_MAX", "2",
    "Replacement workers the fleet supervisor may spawn per worker SLOT "
    "after an eviction (fleet.py): each respawn re-inserts a fresh "
    "worker into the consistent-hash ring with bounded exponential "
    "backoff (fleet.respawns counter), so a long-lived fleet does not "
    "shrink until restart.  0: never respawn (the pre-respawn behavior).",
)
QI_QUERY_WHATIF_LIMIT = _declare(
    "QI_QUERY_WHATIF_LIMIT", "512",
    "Most removal subsets one what-if query may expand (query.py): the "
    "k-subset frontier over the candidate validators is truncated at "
    "this bound with a loud result field (truncated: true) — a typed "
    "cap, never an unbounded batch from one request.",
)
QI_PULSE_SLOW_MS = _declare(
    "QI_PULSE_SLOW_MS", "0",
    "Slow-request exemplar threshold in milliseconds (serve.py, qi-pulse): "
    "a served request whose end-to-end latency exceeds it fires the "
    "pulse.exemplar event + pulse.exemplars counter and dumps a "
    "qi-exemplar/1 record (stage breakdown + flight-recorder tail + trace "
    "identity) to <QI_FLIGHT_RECORDER>.exemplar via the crash-only dump "
    "path (utils/telemetry.py dump_exemplar).  0 (default): exemplars off.",
)
QI_PULSE_AGG = _declare(
    "QI_PULSE_AGG", "1",
    "Fleet metrics aggregation plane (fleet.py, qi-pulse): while truthy "
    "and not '0', each health-probe cycle merges the workers' pong-carried "
    "pulse.* histogram snapshots bucket-wise into the front door's "
    "fleet.pulse.* histograms (served on /metrics) and the fleet-wide "
    "fleet.e2e_p99_ms gauge.  '0': per-worker metrics only, the pre-pulse "
    "behavior.",
)
QI_PULSE_BUCKETS = _declare(
    "QI_PULSE_BUCKETS", "",
    "Histogram bucket override (utils/telemetry.py hist_bounds): a "
    "comma-separated ASCENDING list of bucket upper edges in milliseconds "
    "replacing the default log-spaced ladder for every histogram the "
    "process creates.  Must be identical across a fleet — bucket-wise "
    "merging refuses mismatched ladders.  Empty (default): the built-in "
    "ladder; malformed values log and fall back.",
)
QI_SERVE_JOURNAL = _declare(
    "QI_SERVE_JOURNAL", "",
    "Path of the serving layer's crash-only request journal (serve.py): "
    "accepted requests are journaled (fsync per entry) before solving and "
    "marked done after, so a hard kill + restart replays in-flight work "
    "with no lost or duplicated verdicts.  Empty (default): journaling "
    "off (the CLI serve subcommand's --journal flag sets it explicitly).",
)
QI_SERVE_FUSE_WINDOW_MS = _declare(
    "QI_SERVE_FUSE_WINDOW_MS", "0",
    "Cross-request pack-fusion window in milliseconds (serve.py qi-fuse): "
    "while positive, the drain accumulates window work from DIFFERENT "
    "requests — intersection SCCs, what-if variants — into one shared "
    "batch former (fuse.py BatchFormer) and dispatches when the estimated "
    "lane tile fills or this deadline-aware timer fires, so mixed traffic "
    "fills compiled MXU tiles instead of dispatching partial packs per "
    "request.  'auto' (qi-cost): the window is chosen each flush cycle by "
    "cost.choose_fuse_window from the pulse queue-wait p99 and the SLO "
    "burn state — hot queue ⇒ short positive window, sparse traffic ⇒ 0 "
    "so latency never pays for an empty wait; every decision is a "
    "serve.fuse_window event and the active value a serve.fuse_window_ms "
    "gauge.  0 (default): fusion off, the byte-compatible legacy drain.",
)
QI_COST_TENANTS_MAX = _declare(
    "QI_COST_TENANTS_MAX", "256",
    "Per-tenant cost table capacity (cost.py qi-cost): the per-client-id "
    "device-cost aggregation tables (local and fleet-merged) keep at most "
    "this many tenants, evicting least-recently-booked beyond it (evictions "
    "are counted on cost.tenants_evicted, never silent).  Bounds serve-tier "
    "memory against client-id cardinality attacks.",
)
QI_SLO = _declare(
    "QI_SLO", "",
    "Declarative SLO targets (cost.py SloPlane): a comma-separated list of "
    "'metric<bound' / 'metric>bound' clauses, e.g. "
    "'serve_e2e_p99_ms<500,pack_fill_pct>60'.  Metric names resolve "
    "against the live gauge registry ('_' also matches '.'); each scrape "
    "of /healthz or /sloz and each adaptive fuse-window decision "
    "evaluates multi-window burn rates (QI_SLO_FAST_S / QI_SLO_SLOW_S) "
    "and emits slo.burn events + the slo.burning gauge.  Empty (default): "
    "SLO plane off.",
)
QI_SLO_FAST_S = _declare(
    "QI_SLO_FAST_S", "300",
    "Fast burn-rate window in seconds (cost.py SloPlane): a target is "
    "fast-burning when at least half the ring samples within this window "
    "violate its bound.  Default 300 (5 minutes).",
)
QI_SLO_SLOW_S = _declare(
    "QI_SLO_SLOW_S", "3600",
    "Slow burn-rate window in seconds (cost.py SloPlane): a target is "
    "slow-burning when at least a tenth of the ring samples within this "
    "window violate its bound; 'burning' requires BOTH windows, so a "
    "recovered metric stops firing as soon as the fast window clears.  "
    "Default 3600 (1 hour).",
)
QI_FLEET_TOKEN = _declare(
    "QI_FLEET_TOKEN", "",
    "Shared secret of the multi-host fleet mesh (qi-mesh): every socket "
    "join handshake (fleet.py SocketWorker ↔ serve_transport.py hello) "
    "and every store-gateway session carries a SHA-256 digest of it; a "
    "digest mismatch is a TYPED reject (hello_err code bad_token / "
    "store_err), never a silent skew.  Empty (default): unauthenticated "
    "loopback mode — both sides must agree on emptiness too.",
)
QI_SERVE_BIND = _declare(
    "QI_SERVE_BIND", "127.0.0.1",
    "Bind address of the serve socket transport and the fleet's store "
    "gateway (serve_transport.py SocketServeServer, fleet.py "
    "StoreGateway; CLI twin: serve --bind).  Default loopback — binding "
    "a routable address is the explicit multi-host opt-in and should "
    "ride with a non-empty QI_FLEET_TOKEN.",
)
QI_FLEET_LEASE_S = _declare(
    "QI_FLEET_LEASE_S", "3.0",
    "Heartbeat lease duration in seconds (fleet.py probe loop, qi-mesh): "
    "every answered ping renews a worker's lease; QI_FLEET_PROBE_FAILS "
    "consecutive misses only SUSPECT it (routed around, requests hedged "
    "to the next arc owner), and eviction + journal inheritance waits "
    "for the lease to lapse — a slow link is not a dead worker.  A dead "
    "process is still evicted immediately.",
)
QI_FLEET_SCALE_INTERVAL_S = _declare(
    "QI_FLEET_SCALE_INTERVAL_S", "0",
    "Seconds between elasticity-supervisor evaluations (fleet.py "
    "_scale_tick, qi-mesh): each evaluation turns the fleet-merged "
    "pulse queue-wait p99 and the SloPlane burn state into a spawn / "
    "retire / hold decision.  0 (default): elasticity off, the fixed-"
    "size PR 11 fleet.",
)
QI_FLEET_SCALE_UP_MS = _declare(
    "QI_FLEET_SCALE_UP_MS", "250",
    "Scale-up threshold (fleet.py elasticity supervisor): when the "
    "fleet-merged pulse.queue_wait_ms p99 exceeds this many ms — or any "
    "declared SLO is burning — and the fleet is below "
    "QI_FLEET_SCALE_MAX, one replacement-machinery spawn is scheduled "
    "(fleet.scale_ups counter + fleet.scaled event).",
)
QI_FLEET_SCALE_DOWN_MS = _declare(
    "QI_FLEET_SCALE_DOWN_MS", "20",
    "Scale-down threshold (fleet.py elasticity supervisor): when the "
    "fleet-merged pulse.queue_wait_ms p99 is below this many ms, no SLO "
    "is burning, and the fleet is above QI_FLEET_SCALE_MIN, one worker "
    "is retired by DRAINING through the journal-inheritance path — "
    "routed around first, gracefully drained, its journal inherited — "
    "never a dropped request.",
)
QI_FLEET_SCALE_MIN = _declare(
    "QI_FLEET_SCALE_MIN", "1",
    "Fleet-size floor of the elasticity supervisor (fleet.py): scale-"
    "down decisions never retire below this many live workers.",
)
QI_FLEET_SCALE_MAX = _declare(
    "QI_FLEET_SCALE_MAX", "8",
    "Fleet-size ceiling of the elasticity supervisor (fleet.py): scale-"
    "up decisions never spawn past this many live workers — a burn "
    "spiral must not fork-bomb the host.",
)


# ---- reads -----------------------------------------------------------------


def qi_env(name: str) -> str:
    """The declared variable's value (its registered default when unset).

    Raises ``KeyError`` for an undeclared name — the runtime twin of the
    ``no-bare-env-read`` lint rule: a knob that is not in the catalog above
    does not exist.
    """
    var = _REGISTRY.get(name)
    if var is None:
        raise KeyError(
            f"{name!r} is not a declared QI_* environment variable; "
            f"add it to quorum_intersection_tpu/utils/env.py"
        )
    value = os.environ.get(var.name)
    return (var.default or "") if value is None else value


def qi_env_flag(name: str) -> bool:
    """Boolean read: any non-empty value counts as set (the semantics every
    pre-registry call site used — ``QI_LOG_JSON=0`` is still truthy, and the
    docs say 'set'/'unset', not '1'/'0')."""
    return bool(qi_env(name))


def qi_env_float(name: str, fallback: Optional[float] = None) -> float:
    """Float read; malformed values fall back to the registered default
    (or ``fallback`` when the default itself is unparseable)."""
    raw = qi_env(name)
    try:
        return float(raw)
    except ValueError:
        default = _REGISTRY[name].default
        try:
            return float(default if default is not None else "")
        except ValueError:
            if fallback is None:
                raise
            return fallback


def qi_env_int(name: str, fallback: Optional[int] = None) -> int:
    """Integer read; malformed values fall back to the registered default
    (or ``fallback`` when the default itself is unparseable)."""
    raw = qi_env(name)
    try:
        return int(raw)
    except ValueError:
        default = _REGISTRY[name].default
        try:
            return int(default if default is not None else "")
        except ValueError:
            if fallback is None:
                raise
            return fallback


def registry() -> Tuple[EnvVar, ...]:
    """The full declared catalog, in declaration order."""
    return tuple(_REGISTRY.values())
