"""Sweep checkpoint/resume.

The reference has no checkpointing — runs are all-or-nothing (SURVEY.md §5).
Sharded candidate sweeps over 2^30 subsets run for minutes; checkpointing the
sweep frontier lets a preempted run resume instead of restarting (the
TPU-pod-world equivalent of training-step checkpointing).

The checkpoint is deliberately tiny — a JSON ``{position, total,
fingerprint}`` triple — because the sweep is deterministic: position fully
describes progress *for a given problem*.  The fingerprint is a hash of the
exact enumeration (circuit tables, bit-node order, masks), so a stale file
from a *different* FBAS that happens to share the same enumeration size is
never resumed — resuming it would silently skip candidates ``[0, position)``
and could flip the verdict.

**Crash-only discipline** (ISSUE 4): a checkpoint exists to rescue a run,
so it must never kill one.  Every write is atomic AND durable — tmp file,
flush + fsync, rename, best-effort directory fsync (without the fsync a
crash shortly after the rename can leave the OLD file, losing progress the
run believed saved) — and every ``OSError`` on the save path (disk full,
unwritable directory, the injected ``checkpoint.write`` fault) is
downgraded to the ``checkpoint.save_errors`` counter plus a warning: the
run continues unprotected rather than dying.  Unreadable files are renamed
to ``<name>.corrupt`` and quarantined — never retried, never resumed, and
the evidence is preserved for postmortems instead of being overwritten.
The full corruption matrix is pinned by ``tests/test_checkpoint_faults.py``.
"""

from __future__ import annotations

import hashlib
import json
import os
from dataclasses import dataclass
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

from quorum_intersection_tpu.utils.faults import fault_point
from quorum_intersection_tpu.utils.logging import get_logger
from quorum_intersection_tpu.utils.telemetry import get_run_record

log = get_logger("utils.checkpoint")


def _quarantine_corrupt(path: Path, why: str) -> None:
    """Rename an unreadable checkpoint to ``<name>.corrupt`` (overwriting a
    previous quarantine — the newest corpse is the interesting one).  The
    file is never retried: a checkpoint that cannot be parsed is evidence,
    not state, and rereading it on every probe would re-pay the failure."""
    corrupt = path.with_name(path.name + ".corrupt")
    try:
        os.replace(path, corrupt)
    except OSError:
        return  # racing unlink/rename: nothing left to quarantine
    get_run_record().add("checkpoint.corrupt_quarantined")
    get_run_record().event(
        "checkpoint.corrupt_quarantined", path=str(path),
        quarantined_to=str(corrupt), why=why,
    )
    log.warning("corrupt checkpoint quarantined to %s (%s)", corrupt, why)


def _read_json(path: Path) -> Optional[Dict[str, Any]]:
    """Parse a checkpoint file; corrupt content is quarantined, a missing
    file is simply None."""
    try:
        text = path.read_text()
    except OSError:
        return None
    except UnicodeDecodeError as exc:
        # A torn write can leave arbitrary bytes — the most realistic
        # corruption shape, and it must quarantine like any other.
        _quarantine_corrupt(path, f"undecodable bytes: {exc}")
        return None
    try:
        data = json.loads(text)
    except json.JSONDecodeError as exc:
        _quarantine_corrupt(path, f"unparseable JSON: {exc}")
        return None
    if not isinstance(data, dict):
        _quarantine_corrupt(path, f"not a JSON object: {type(data).__name__}")
        return None
    return data


def _write_json(path: Path, payload: Dict[str, Any]) -> bool:
    """Atomic + durable checkpoint write; False (never an exception) on
    failure.

    fsync-before-rename makes the rename publish only fully-persisted
    bytes; the directory fsync afterwards persists the rename itself.  Any
    ``OSError`` — a full disk, a read-only volume, the injected
    ``checkpoint.write`` fault — becomes the ``checkpoint.save_errors``
    counter: the run this file exists to rescue is never the casualty of
    saving it.
    """
    tmp = path.with_suffix(".tmp")
    try:
        fault_point("checkpoint.write")
        with open(tmp, "w", encoding="utf-8") as fh:
            fh.write(json.dumps(payload))
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
        try:
            dir_fd = os.open(str(path.parent), os.O_RDONLY)
            try:
                os.fsync(dir_fd)
            finally:
                os.close(dir_fd)
        except OSError:
            pass  # directory fsync is best-effort (not supported everywhere)
    except OSError as exc:
        get_run_record().add("checkpoint.save_errors")
        get_run_record().event(
            "checkpoint.save_error", path=str(path), error=str(exc),
        )
        log.warning(
            "checkpoint save failed (%s); run continues without this "
            "checkpoint update", exc,
        )
        try:
            tmp.unlink()
        except OSError:
            pass
        return False
    get_run_record().add("checkpoint.saves")
    return True


def sweep_fingerprint(*arrays: Optional[np.ndarray]) -> str:
    """Stable hash of the enumeration identity: feed the circuit tables,
    bit-node order, and availability masks; any difference ⇒ new problem."""
    h = hashlib.sha256()
    for a in arrays:
        if a is None:
            h.update(b"\x00none")
            continue
        arr = np.ascontiguousarray(a)
        h.update(str(arr.dtype).encode())
        h.update(str(arr.shape).encode())
        h.update(arr.tobytes())
    return h.hexdigest()[:32]


@dataclass
class SweepCheckpoint:
    path: Union[str, Path]

    def __post_init__(self) -> None:
        self.path = Path(self.path)

    def has_progress(self, total: int) -> bool:
        """Cheap probe: does the file hold recorded progress for an
        enumeration of this size?  (No fingerprint check — resume_position
        still guards the actual resume; callers like the auto router only
        need 'plausibly this problem' to decide routing.)

        Also recognizes a frontier-format file at the same path (the CLI
        hands the same --checkpoint path to whichever backend routing
        picks), so the on-disk file may legitimately hold either format
        mid-run."""
        data = self._read()
        if data is None:
            return False
        if data.get("total") == total and int(data.get("position", 0) or 0) > 0:
            return True
        return bool(data.get("states"))

    def _read(self) -> Optional[Dict[str, Any]]:
        return _read_json(self.path)

    def resume_position(
        self,
        total: int,
        fingerprint: Optional[str] = None,
        alt_fingerprints: tuple = (),
    ) -> int:
        """Last recorded block-aligned position, or 0 if absent/mismatched.

        ``alt_fingerprints``: additional fingerprints accepted as THIS
        problem — callers pass the hashes older builds would have computed
        for an identical enumeration (e.g. the pre-r4 6-array sweep hash,
        valid only for unrestricted problems) so a format-widening change
        doesn't silently discard the long runs checkpoints exist for
        (ADVICE r4).  The next record() rewrites the current format."""
        data = self._read()
        if data is None:
            return 0
        if data.get("total") != total:
            log.info("checkpoint total %s != current %d; ignoring", data.get("total"), total)
            return 0
        if fingerprint is not None and data.get("fingerprint") != fingerprint:
            if data.get("fingerprint") in alt_fingerprints:
                log.info("resuming from a legacy-format checkpoint fingerprint")
            else:
                log.info("checkpoint belongs to a different problem; ignoring")
                return 0
        pos = int(data.get("position", 0))
        if 0 < pos <= total:
            get_run_record().add("checkpoint.restores")
            get_run_record().event(
                "checkpoint.restore", position=pos, total=total,
                path=str(self.path),
            )
        return pos if 0 <= pos <= total else 0

    def record(self, position: int, total: int, fingerprint: Optional[str] = None) -> None:
        payload: Dict[str, Any] = {"position": position, "total": total}
        if fingerprint is not None:
            payload["fingerprint"] = fingerprint
        _write_json(self.path, payload)

    def clear(self) -> None:
        try:
            self.path.unlink()
        except OSError:
            pass


@dataclass
class FrontierCheckpoint:
    """Checkpoint/resume for the branch-and-bound frontier search.

    (Introduced with the retired round-trip hybrid engine; the
    device-resident frontier shares the exact on-disk format, so files
    written by pre-r5 builds resume unchanged.)

    Unlike the sweep, B&B progress is not a scalar position: it is the
    explicit worklist of unresolved branch-and-bound states.  The invariant
    that makes this sound: every unresolved state always has at least one
    request in the pending/in-flight queues (phase transitions happen
    synchronously on the host), so the set of states referenced there IS the
    resume frontier — re-pushing exactly those states reproduces the rest of
    the search; states fully resolved before the write are never re-expanded.

    Same fingerprint discipline as :class:`SweepCheckpoint`: the file is tied
    to the exact problem (circuit tables, SCC, scoping); anything else is
    ignored rather than resumed.
    """

    path: Union[str, Path]

    def __post_init__(self) -> None:
        self.path = Path(self.path)

    def has_progress(self, total: int = 0) -> bool:
        """Cheap probe: a non-empty saved frontier (``total`` accepted for
        signature parity with :meth:`SweepCheckpoint.has_progress`)."""
        data = self._read()
        return data is not None and bool(data.get("states"))

    def _read(self) -> Optional[Dict[str, Any]]:
        return _read_json(self.path)

    def resume_states(
        self, fingerprint: str
    ) -> Optional[List[List[List[int]]]]:
        """Saved frontier [(to_remove, dont_remove), ...], or None."""
        data = self._read()
        if data is None:
            return None
        if data.get("fingerprint") != fingerprint:
            log.info("frontier checkpoint belongs to a different problem; ignoring")
            return None
        states = data.get("states") or None
        if states is not None and not (
            isinstance(states, list)
            and all(
                isinstance(s, list) and len(s) == 2
                and all(isinstance(part, list) for part in s)
                and all(isinstance(v, int) for part in s for v in part)
                for s in states
            )
        ):
            # Malformed/foreign schema: the contract is "ignored, never
            # crashed into" — a checkpoint must not break the run it was
            # meant to rescue.
            log.info("frontier checkpoint states malformed; ignoring")
            return None
        if states:
            log.info("resuming search from %d frontier states", len(states))
            get_run_record().add("checkpoint.restores")
            get_run_record().event(
                "checkpoint.restore", states=len(states), path=str(self.path)
            )
        return states

    def record(
        self, states: Sequence[Sequence[Sequence[int]]], fingerprint: str
    ) -> None:
        if not states:
            return  # an empty frontier means the search is finishing anyway
        _write_json(
            self.path, {"fingerprint": fingerprint, "states": list(states)}
        )

    def clear(self) -> None:
        try:
            self.path.unlink()
        except OSError:
            pass
