"""Sweep checkpoint/resume.

The reference has no checkpointing — runs are all-or-nothing (SURVEY.md §5).
Sharded candidate sweeps over 2^30 subsets run for minutes; checkpointing the
sweep frontier lets a preempted run resume instead of restarting (the
TPU-pod-world equivalent of training-step checkpointing).

The checkpoint is deliberately tiny — a JSON ``{position, total}`` pair —
because the sweep is deterministic: position fully describes progress.
Written atomically (tmp + rename) so a crash mid-write never corrupts it.
A stale file whose ``total`` disagrees with the current enumeration is
ignored: it belongs to a different problem.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from pathlib import Path
from typing import Union

from quorum_intersection_tpu.utils.logging import get_logger

log = get_logger("utils.checkpoint")


@dataclass
class SweepCheckpoint:
    path: Union[str, Path]

    def __post_init__(self) -> None:
        self.path = Path(self.path)

    def resume_position(self, total: int) -> int:
        """Last recorded block-aligned position, or 0 if absent/mismatched."""
        try:
            data = json.loads(self.path.read_text())
        except (OSError, json.JSONDecodeError):
            return 0
        if data.get("total") != total:
            log.info("checkpoint total %s != current %d; ignoring", data.get("total"), total)
            return 0
        pos = int(data.get("position", 0))
        return pos if 0 <= pos <= total else 0

    def record(self, position: int, total: int) -> None:
        tmp = self.path.with_suffix(".tmp")
        tmp.write_text(json.dumps({"position": position, "total": total}))
        os.replace(tmp, self.path)

    def clear(self) -> None:
        try:
            self.path.unlink()
        except OSError:
            pass
