"""Structured per-phase timers and candidate-throughput counters.

The reference's only observability is trace logs and a static branch-and-bound
call counter (`/root/reference/quorum_intersection.cpp:258`).  The TPU-native
equivalent (SURVEY.md §5) is structured: named phase timers plus a throughput
counter measuring candidate quorums checked per second (the BASELINE.json
headline metric).

Since ISSUE 2 the timers are a thin façade over the process-wide telemetry
record (:mod:`quorum_intersection_tpu.utils.telemetry`): every
:meth:`PhaseTimers.phase` opens a ``phase.<name>`` span in the run record —
one instrumentation point feeds both the legacy ``SolveResult.timers`` dict
(``--timing`` stays byte-compatible) and the machine-readable JSONL stream.
:class:`Throughput` is fed by the sweep's window-drain loop
(`backends/tpu/sweep.py`) and surfaces as ``window_candidates_per_sec``.
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Dict, Iterator

from quorum_intersection_tpu.utils.telemetry import get_run_record


@dataclass
class PhaseTimers:
    """Accumulating named wall-clock timers (each phase also recorded as a
    ``phase.<name>`` telemetry span)."""

    totals: Dict[str, float] = field(default_factory=dict)
    counts: Dict[str, int] = field(default_factory=dict)

    @contextmanager
    def phase(self, name: str) -> Iterator[None]:
        start = time.perf_counter()
        try:
            with get_run_record().span(f"phase.{name}"):
                yield
        finally:
            elapsed = time.perf_counter() - start
            self.totals[name] = self.totals.get(name, 0.0) + elapsed
            self.counts[name] = self.counts.get(name, 0) + 1

    def summary(self) -> Dict[str, float]:
        return dict(sorted(self.totals.items(), key=lambda kv: -kv[1]))


@dataclass
class Throughput:
    """Candidate-checking throughput counter (candidates/sec).

    Fed by the sweep driver's window-drain loop with (candidates, interval)
    pairs; ``per_second`` is the drain-interval rate — setup and blocking
    compiles excluded, unlike the end-to-end ``candidates_per_sec`` stat.
    """

    candidates: int = 0
    seconds: float = 0.0

    def add(self, n: int, seconds: float) -> None:
        self.candidates += n
        self.seconds += seconds

    @property
    def per_second(self) -> float:
        return self.candidates / self.seconds if self.seconds > 0 else 0.0
