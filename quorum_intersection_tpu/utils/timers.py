"""Structured per-phase timers and candidate-throughput counters.

The reference's only observability is trace logs and a static branch-and-bound
call counter (`/root/reference/quorum_intersection.cpp:258`).  The TPU-native
equivalent (SURVEY.md §5) is structured: named phase timers plus a throughput
counter measuring candidate quorums checked per second (the BASELINE.json
headline metric).
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Dict, Iterator


@dataclass
class PhaseTimers:
    """Accumulating named wall-clock timers."""

    totals: Dict[str, float] = field(default_factory=dict)
    counts: Dict[str, int] = field(default_factory=dict)

    @contextmanager
    def phase(self, name: str) -> Iterator[None]:
        start = time.perf_counter()
        try:
            yield
        finally:
            elapsed = time.perf_counter() - start
            self.totals[name] = self.totals.get(name, 0.0) + elapsed
            self.counts[name] = self.counts.get(name, 0) + 1

    def summary(self) -> Dict[str, float]:
        return dict(sorted(self.totals.items(), key=lambda kv: -kv[1]))


@dataclass
class Throughput:
    """Candidate-checking throughput counter (candidates/sec)."""

    candidates: int = 0
    seconds: float = 0.0

    def add(self, n: int, seconds: float) -> None:
        self.candidates += n
        self.seconds += seconds

    @property
    def per_second(self) -> float:
        return self.candidates / self.seconds if self.seconds > 0 else 0.0
