"""qi-serve/1 — crash-only snapshot-stream serving layer (ISSUE 8 tentpole).

The repo was shaped like a one-shot CLI: parse stdin, solve, print a
boolean, exit.  The ROADMAP's production target is a long-lived service
that ingests a stream of stellarbeat snapshots and answers verdict queries
for many concurrent clients — and a service that runs for weeks must be
robust before it is fast: the NP-hard solve (arXiv:1902.06493) means any
individual request can blow any latency budget, so deadlines, backpressure
and shedding are first-class semantics here, not afterthoughts.

:class:`ServeEngine` is that layer, built on the primitives PRs 1-7 left:

- **Admission queue, bounded** (``QI_SERVE_QUEUE_DEPTH``): compatible
  requests accumulate and drain in batches through
  :func:`pipeline.check_many` — which lane-packs sweep-sized problems into
  full MXU tiles (ISSUE 5) — and a request arriving over-depth is shed
  with a typed :class:`Overloaded`, never an unbounded queue.
- **Per-request deadlines** (``QI_SERVE_DEADLINE_S``): wired into the
  existing CancelToken lattice (the racing router's cancellation plumbing,
  PR 1) — a deadline supervisor cancels an in-flight batch mid-window and
  the expired request returns a typed :class:`DeadlineExceeded` carrying
  its partial-coverage certificate (windows enumerated/cancelled before
  the cancel landed), not a wedge.
- **Verdict cache** keyed by the sanitized-SCC fingerprint
  (:func:`snapshot_fingerprint`): the canonical graph structure — resolved
  quorum sets in vertex order plus the SCC partition and the front-end
  policy — so cosmetic snapshot churn (names, JSON formatting) still hits.
  Single-flight: concurrent identical queries share one solve
  (``serve.coalesced``).  Bounded (``QI_SERVE_CACHE_MAX``) with LRU
  eviction counters.
- **Crash-only request journal** (:class:`RequestJournal`,
  ``QI_SERVE_JOURNAL``): accepted requests are journaled — fsync per
  entry, the ``utils/checkpoint.py`` durability discipline — before
  solving and marked ``done`` after, so ``kill -9`` + restart replays
  in-flight work with zero lost and zero duplicated verdicts; corrupt or
  foreign-fingerprint entries quarantine to ``<journal>.corrupt`` instead
  of blocking startup.  ``/readyz`` (utils/metrics_server.py) reports 503
  until replay completes.

Every boundary declares a fault point (``serve.admit`` / ``serve.cache`` /
``serve.journal`` / ``serve.drain`` / ``serve.respond`` —
docs/ROBUSTNESS.md) and degrades instead of dying: a cache fault bypasses
the cache, a journal fault serves un-journaled (loudly), a drain fault
falls back to per-request solves, a respond fault turns into a typed error
response — never a silent drop, never a flipped verdict
(``tools/soak.py --serve --chaos`` is the gate).  Telemetry
(``qi-telemetry/1``): ``serve.*`` spans/events/counters plus queue-depth,
shed-state and p50/p99 latency gauges; served certificates carry a
``provenance.serve`` stamp.

CLI: ``python -m quorum_intersection_tpu serve`` (one JSON request per
stdin line, one JSON response per stdout line — :func:`serve_main`);
``benchmarks/serve.py`` is the open-loop load driver.  Since the ISSUE 11
engine/transport split the engine here is transport-agnostic: the stdio
loop, the socket transport and the fleet supervisor's per-worker sessions
all live in ``serve_transport.py``, and ``fleet.py`` runs N of these
engines behind a consistent-hash front door.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import tempfile
import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable, Deque, Dict, List, Optional, Tuple, Union

from quorum_intersection_tpu.backends.base import (
    CancelToken,
    SearchBackend,
    SearchCancelled,
    get_backend,
)
from quorum_intersection_tpu.cert import CERT_SCHEMA
from quorum_intersection_tpu.delta import (
    DeltaEngine,
    SccVerdictStore,
    SharedSccStore,
)
from quorum_intersection_tpu.fbas.graph import IndexedQSet, TrustGraph, build_graph
from quorum_intersection_tpu.fbas.schema import Fbas, QSet, parse_fbas
from quorum_intersection_tpu.fuse import BatchFormer
from quorum_intersection_tpu.pipeline import SolveResult, check_many
from quorum_intersection_tpu.query import (
    Query,
    QueryEngine,
    QueryError,
    QueryResult,
)
from quorum_intersection_tpu.utils.env import (
    qi_env,
    qi_env_float,
    qi_env_int,
)
from quorum_intersection_tpu.utils.faults import FaultInjected, fault_point
from quorum_intersection_tpu.utils.logging import get_logger
from quorum_intersection_tpu.utils.telemetry import (
    LATENCY_WINDOW,
    TraceContext,
    dump_exemplar,
    get_run_record,
    percentile,
)

log = get_logger("serve")

# Deterministic-interleaving hook (tools/analyze/schedules.py, the same
# mechanism as backends/auto.py's _race_sync): a no-op in production, the
# schedule harness swaps in a SyncController to FORCE the admission/drain
# orderings the wall clock almost never produces — coalesce-during-solve,
# deadline-between-pop-and-solve, submit-racing-stop.
_serve_sync: Callable[[str], None] = lambda point: None

SERVE_SCHEMA = "qi-serve/1"
JOURNAL_SCHEMA = "qi-serve-journal/1"

# The p50/p99 gauge window (LATENCY_WINDOW) and the nearest-rank estimator
# now live in utils/telemetry.py beside the Histogram primitive they feed
# (ISSUE 15 dedupe) — re-exported here so the import surface
# (`serve._percentile`, the bench driver and tests) stays stable.
_percentile = percentile

# One deadline-cancelled batch requeues its surviving (un-expired)
# requests for a fresh solve; past this many attempts a request returns a
# typed error instead of cycling the queue forever.
MAX_SOLVE_ATTEMPTS = 2


# ---- typed request outcomes -------------------------------------------------


class ServeError(RuntimeError):
    """Base of the serving layer's typed request failures.

    Typed (mirroring the ``FaultInjected`` family, docs/ROBUSTNESS.md): the
    chaos contract is "a served verdict equals the fault-free chain or the
    request fails LOUDLY with a typed error" — these classes are the typed
    errors, and ``code`` is the machine-readable discriminator the CLI
    emits in its JSONL error responses."""

    code = "serve_error"


class Overloaded(ServeError):
    """Admission queue at its depth bound: the request was shed.

    Load shedding is a *feature*: a bounded queue with typed rejections
    keeps p99 latency honest under overload, where an unbounded queue
    converts overload into unbounded latency for every client."""

    code = "overloaded"

    def __init__(self, depth: int, bound: int) -> None:
        self.depth = depth
        self.bound = bound
        super().__init__(
            f"admission queue full ({depth} >= bound {bound}); request shed"
        )


class DeadlineExceeded(ServeError):
    """The request's deadline budget expired before a verdict.

    Carries the partial-coverage certificate (``cert``): a ``qi-cert/1``-
    shaped block with ``verdict: null, partial: true`` and the window
    coverage the cancelled search completed before the deadline supervisor
    tripped the CancelToken — evidence of work done, never mistakable for
    a verdict."""

    code = "deadline_exceeded"

    def __init__(self, request_id: str, deadline_s: float,
                 cert: Optional[Dict[str, object]] = None) -> None:
        self.request_id = request_id
        self.deadline_s = deadline_s
        self.cert = cert
        super().__init__(
            f"request {request_id} exceeded its {deadline_s:g}s deadline"
        )


class ServeClosed(ServeError):
    """The engine is stopping and no longer admits requests."""

    code = "closed"


# ---- fingerprinting ---------------------------------------------------------


def _qset_canonical(q: IndexedQSet) -> List[object]:
    """Canonical nested form of one resolved quorum set (threshold, member
    vertex indices, inner sets, dropped-dangling count) — exactly the
    inputs the verdict and its certificate depend on."""
    return [
        q.threshold,
        list(q.members),
        [_qset_canonical(iq) for iq in q.inner],
        q.n_dangling,
    ]


def snapshot_fingerprint(
    graph: TrustGraph,
    *,
    scc_select: str = "quorum-bearing",
    scope_to_scc: bool = False,
) -> str:
    """Sanitized-SCC fingerprint of one snapshot's verdict problem.

    Hashes the canonical *sanitized* graph structure — per-vertex node id
    + resolved quorum set in vertex order, the dangling policy the graph
    was built under, the SCC partition, and the solve options — i.e.
    everything the verdict AND its certificate depend on, and nothing
    else: node *names*, JSON key order and formatting churn all hash
    identically, so the overwhelmingly common unchanged-topology query is
    a cache hit.  Vertex order is deliberately included: certificates
    carry vertex indices (``q1_index``/``q2_index``), and two snapshots
    must fingerprint equal only when their certs are interchangeable.
    """
    from quorum_intersection_tpu.fbas.graph import group_sccs, tarjan_scc

    count, comp = tarjan_scc(graph.n, graph.succ)
    payload = {
        "v": 1,
        "dangling": graph.dangling,
        "scc_select": scc_select,
        "scope_to_scc": bool(scope_to_scc),
        "nodes": [
            [graph.node_ids[v], _qset_canonical(graph.qsets[v])]
            for v in range(graph.n)
        ],
        "sccs": group_sccs(graph.n, comp, count),
    }
    return hashlib.sha256(
        json.dumps(payload, separators=(",", ":")).encode()
    ).hexdigest()[:32]


# ---- responses and tickets --------------------------------------------------


@dataclass
class ServeResponse:
    """One served verdict: the solve result plus serve-side provenance.

    ``result`` carries a typed query's structured payload (qi-query/1,
    ISSUE 12) — None for the legacy boolean intersection path, so the
    pre-query response shape is untouched."""

    request_id: str
    intersects: bool
    cert: Optional[Dict[str, object]]
    stats: Dict[str, object]
    cached: bool
    seconds: float  # admission → delivery latency
    result: Optional[Dict[str, object]] = None
    # Wire trace echo (qi-pulse, ISSUE 15): the request's carried
    # ``trace_id:span_id[:pid]`` context, echoed back so the client (and
    # the fleet front door relaying worker responses) can join the
    # response to its distributed trace.  None on trace-less requests.
    trace: Optional[str] = None
    # Per-request device cost (qi-cost/1, ISSUE 17): what this verdict
    # paid for on the device — lane·windows, MACs, pro-rated dispatch
    # wall, delta reuse credits.  None when attribution degraded, on
    # cache hits (zero new device work) and on cost-less backends.
    cost: Optional[Dict[str, object]] = None


_Outcome = Tuple[str, object]  # ("ok", ServeResponse) | ("err", Exception)


class Ticket:
    """A client's handle on one submitted request (thread-safe)."""

    def __init__(self, request_id: str, submitted_t: float,
                 deadline_t: Optional[float]) -> None:
        self.request_id = request_id
        self.submitted_t = submitted_t
        self.deadline_t = deadline_t  # absolute monotonic, None = no deadline
        # qi-pulse: THIS submission's wire trace — a coalesced waiter's
        # response must echo its OWN context, not the leader entry's.
        self.trace: Optional[str] = None
        # qi-cost: THIS submission's client id — it rides the ticket (not
        # the solve entry) so coalesced waiters and cache hits each book
        # to their OWN tenant.  None books as "anon".
        self.client: Optional[str] = None
        self._event = threading.Event()
        self._outcome: Optional[_Outcome] = None
        self._callbacks: List[Callable[["Ticket"], None]] = []
        self._cb_lock = threading.Lock()

    def _resolve(self, outcome: _Outcome) -> None:
        """Deliver exactly once; later resolutions are ignored (a requeued
        request that also expired must not flip its recorded outcome)."""
        with self._cb_lock:
            if self._outcome is not None:
                return
            self._outcome = outcome
            # Set INSIDE the lock: add_done_callback's immediate-invoke
            # path observes _outcome under this lock and may call
            # result(timeout=0) from the callback — the event must already
            # be set by then or a resolved ticket reads as timed out.
            self._event.set()
            callbacks = list(self._callbacks)
        for cb in callbacks:
            try:
                cb(self)
            except Exception as exc:  # noqa: BLE001 — a client callback must not kill the drain
                log.warning("ticket callback failed: %s", exc)

    def add_done_callback(self, cb: Callable[["Ticket"], None]) -> None:
        """Run ``cb(ticket)`` on delivery (immediately if already done) —
        the CLI's streaming-output hook."""
        with self._cb_lock:
            if self._outcome is None:
                self._callbacks.append(cb)
                return
        cb(self)

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None) -> ServeResponse:
        """Block for the outcome; raises the typed error on failure."""
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"request {self.request_id} not finished after {timeout}s"
            )
        assert self._outcome is not None
        kind, value = self._outcome
        if kind == "ok":
            return value  # type: ignore[return-value]
        raise value  # type: ignore[misc]


@dataclass
class _Entry:
    """One solve unit: a fingerprint-distinct admitted request plus every
    coalesced waiter sharing its verdict (single-flight).  ``query`` is
    the typed qi-query/1 request (the default is the degenerate
    intersection query — the legacy path)."""

    request_id: str
    fingerprint: str
    fbas: Fbas
    nodes: List[Dict[str, object]]
    query: Query = field(default_factory=Query)
    waiters: List[Ticket] = field(default_factory=list)
    journaled: bool = False
    replayed: bool = False
    cache_bypass: bool = False
    attempts: int = 0
    done: bool = False
    admitted_t: float = 0.0
    # qi-pulse (ISSUE 15): the wire-carried trace context this request
    # arrived with (the drain adopts it around the solve) and the
    # per-stage latency breakdown the exemplar dump reports.
    trace: Optional[str] = None
    stages: Dict[str, float] = field(default_factory=dict)

    def trace_ctx(self) -> Optional[TraceContext]:
        return TraceContext.from_env(self.trace) if self.trace else None


# ---- crash-only request journal --------------------------------------------


class RequestJournal:
    """Append-only JSONL request journal with the crash-only discipline.

    Every append is flushed **and fsynced** before :meth:`append` returns
    — the same durability bar as ``utils/checkpoint.py``'s
    fsync-before-rename, adapted to an append-only log (there is no
    rename per entry; the fsync is what makes "accepted" mean "survives a
    power cut").  A ``kill -9`` can tear at most the final line, which
    replay tolerates; any OSError on the write path downgrades to the
    ``serve.journal_errors`` counter (the request proceeds un-journaled,
    loudly) — a journal exists to rescue requests, never to reject them.
    """

    def __init__(self, path: Union[str, Path]) -> None:
        self.path = Path(path)
        self._lock = threading.Lock()
        self._fh: Optional[object] = None

    def _append_line(self, payload: Dict[str, object]) -> bool:
        """One durable append; False (never an exception) on failure."""
        rec = get_run_record()
        try:
            fault_point("serve.journal")
            with self._lock:
                if self._fh is None:
                    fresh = not self.path.exists()
                    self._fh = open(self.path, "a", encoding="utf-8")
                    if fresh:
                        self._fh.write(json.dumps({
                            "kind": "meta", "schema": JOURNAL_SCHEMA,
                            "pid": os.getpid(),
                        }) + "\n")
                self._fh.write(json.dumps(payload, default=str) + "\n")
                self._fh.flush()
                # qi-lint: allow(lock-blocking) — fsync-before-release IS the journal contract: an append is not durable until fsync returns, and a later entry must never land before an earlier one
                os.fsync(self._fh.fileno())
        except (OSError, FaultInjected) as exc:
            rec.add("serve.journal_errors")
            rec.event("serve.journal_error", error=str(exc))
            log.warning(
                "request journal append failed (%s); request proceeds "
                "UN-journaled — replay protection lost for it", exc,
            )
            return False
        return True

    def append_request(self, request_id: str, fingerprint: str,
                       nodes: List[Dict[str, object]],
                       deadline_s: Optional[float],
                       query: Optional[Dict[str, object]] = None,
                       trace: Optional[str] = None) -> bool:
        payload: Dict[str, object] = {
            "kind": "req", "request_id": request_id,
            "fingerprint": fingerprint, "deadline_s": deadline_s,
            "nodes": nodes, "t_wall": round(time.time(), 3),
        }
        if trace is not None:
            # Wire trace context (qi-pulse): journaled so a replay
            # re-adopts the ORIGINAL request's trace — the recovered
            # solve's spans join the trace the front door started.
            payload["trace"] = trace
        if query is not None:
            # Typed queries (qi-query/1) journal their wire form so a
            # replay re-resolves the SAME question — the fingerprint
            # already carries the query kind, so a replayed relaxed query
            # can never serve from an intersection cache line.
            payload["query"] = query
        ok = self._append_line(payload)
        if ok:
            get_run_record().add("serve.journal_entries")
        return ok

    def append_done(self, request_id: str, fingerprint: str,
                    outcome: str, verdict: Optional[bool]) -> bool:
        ok = self._append_line({
            "kind": "done", "request_id": request_id,
            "fingerprint": fingerprint, "outcome": outcome,
            "verdict": verdict, "t_wall": round(time.time(), 3),
        })
        if ok:
            get_run_record().add("serve.journal_done")
        return ok

    def scan(self) -> Tuple[List[Dict[str, object]], List[str], bool]:
        """Read the journal: ``(entries, corrupt_lines, torn_tail)``.

        A non-JSON **final** line is the expected ``kill -9`` artifact
        (torn mid-append) and is reported separately; corrupt lines
        anywhere else are returned for quarantine.  Never raises on
        content — a journal must not block the startup it exists for.
        """
        try:
            raw = self.path.read_text(encoding="utf-8", errors="replace")
        except OSError:
            return [], [], False
        lines = [ln for ln in raw.splitlines() if ln.strip()]
        entries: List[Dict[str, object]] = []
        corrupt: List[str] = []
        torn_tail = False
        for i, line in enumerate(lines):
            try:
                obj = json.loads(line)
                if not isinstance(obj, dict) or "kind" not in obj:
                    raise ValueError("not a journal entry object")
            except (ValueError, json.JSONDecodeError):
                if i == len(lines) - 1:
                    torn_tail = True  # the one corruption a hard kill writes
                else:
                    corrupt.append(line)
                continue
            if obj.get("kind") != "meta":
                entries.append(obj)
        return entries, corrupt, torn_tail

    def quarantine(self, lines: List[str], why: str) -> None:
        """Append unusable journal lines to ``<journal>.corrupt`` —
        evidence preserved for postmortems, startup never blocked."""
        if not lines:
            return
        rec = get_run_record()
        corrupt = self.path.with_name(self.path.name + ".corrupt")
        try:
            with open(corrupt, "a", encoding="utf-8") as fh:
                for line in lines:
                    fh.write(line.rstrip("\n") + "\n")
        except OSError as exc:
            log.warning("journal quarantine write failed (%s)", exc)
        rec.add("serve.journal_quarantined", len(lines))
        rec.event(
            "serve.journal_quarantined", lines=len(lines), why=why,
            quarantined_to=str(corrupt),
        )
        log.warning(
            "%d corrupt journal line(s) quarantined to %s (%s)",
            len(lines), corrupt, why,
        )

    def compact(self, keep: List[Dict[str, object]]) -> None:
        """Rewrite the journal to ``meta + keep`` atomically (tmp + fsync +
        rename + best-effort dir fsync): replayed/done pairs drop out so
        the file stays bounded across restarts; still-pending entries
        survive for the next replay.  Failure downgrades (the un-compacted
        journal is larger, not wrong)."""
        tmp = self.path.with_suffix(".tmp")
        try:
            with self._lock:
                if self._fh is not None:
                    try:
                        self._fh.close()  # type: ignore[attr-defined]
                    except OSError:
                        pass
                    self._fh = None
                with open(tmp, "w", encoding="utf-8") as fh:
                    fh.write(json.dumps({
                        "kind": "meta", "schema": JOURNAL_SCHEMA,
                        "pid": os.getpid(), "compacted": True,
                    }) + "\n")
                    for entry in keep:
                        fh.write(json.dumps(entry, default=str) + "\n")
                    fh.flush()
                    # qi-lint: allow(lock-blocking) — compaction must publish a fully fsynced replacement before any concurrent append reopens the journal; the lock covers exactly that atomic swap
                    os.fsync(fh.fileno())
                os.replace(tmp, self.path)
            try:
                dir_fd = os.open(str(self.path.parent), os.O_RDONLY)
                try:
                    os.fsync(dir_fd)
                finally:
                    os.close(dir_fd)
            except OSError:
                pass  # best-effort, as in utils/checkpoint.py
        except OSError as exc:
            log.warning("journal compaction failed (%s); journal kept as-is", exc)
            try:
                tmp.unlink()
            except OSError:
                pass

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()  # type: ignore[attr-defined]
                except OSError:
                    pass
                self._fh = None


# ---- the engine -------------------------------------------------------------


class ServeEngine:
    """Long-lived snapshot-verdict service (see module docstring).

    All requests of one engine share its front-end options (dangling
    policy, SCC selection, scoping, backend), which is what makes queued
    requests *compatible*: any subset of the queue can fuse into one
    ``check_many`` batch.
    """

    def __init__(
        self,
        backend: Union[str, SearchBackend] = "auto",
        *,
        queue_depth: Optional[int] = None,
        batch_max: Optional[int] = None,
        deadline_s: Optional[float] = None,
        cache_max: Optional[int] = None,
        journal: Optional[Union[str, Path]] = None,
        dangling: str = "strict",
        scc_select: str = "quorum-bearing",
        scope_to_scc: bool = False,
        pack: Optional[bool] = None,
        delta: Optional[bool] = None,
        shared_store: Optional[SharedSccStore] = None,
        fuse_window_ms: Optional[Union[float, str]] = None,
    ) -> None:
        self.backend = backend
        self.queue_depth = (
            queue_depth if queue_depth is not None
            else max(qi_env_int("QI_SERVE_QUEUE_DEPTH", 64), 1)
        )
        self.batch_max = (
            batch_max if batch_max is not None
            else max(qi_env_int("QI_SERVE_BATCH_MAX", 8), 1)
        )
        self.deadline_s = (
            deadline_s if deadline_s is not None
            else qi_env_float("QI_SERVE_DEADLINE_S", 0.0)
        )
        self.cache_max = (
            cache_max if cache_max is not None
            else max(qi_env_int("QI_SERVE_CACHE_MAX", 1024), 1)
        )
        journal_path = journal if journal is not None else (
            qi_env("QI_SERVE_JOURNAL") or None
        )
        self._journal = (
            RequestJournal(journal_path) if journal_path else None
        )
        self.dangling = dangling
        self.scc_select = scc_select
        self.scope_to_scc = scope_to_scc
        self.pack = pack
        # Cross-request pack fusion (qi-fuse, ISSUE 16): while positive,
        # the drain runs each popped entry in its own worker and a shared
        # BatchFormer merges their window work into one lane-packed solve;
        # 0 (the default) keeps the byte-compatible legacy drain.
        # 'auto' (qi-cost, ISSUE 17): the window is chosen per flush cycle
        # by cost.choose_fuse_window from the pulse queue-wait p99 and the
        # SLO burn state — the raw env string is checked FIRST because
        # qi_env_float would silently fall 'auto' back to the registered
        # default.
        fuse_raw: Union[float, str] = (
            fuse_window_ms if fuse_window_ms is not None
            else qi_env("QI_SERVE_FUSE_WINDOW_MS")
        )
        self.fuse_window_auto = (
            isinstance(fuse_raw, str) and fuse_raw.strip().lower() == "auto"
        )
        if self.fuse_window_auto:
            self.fuse_window_ms = 0.0
        elif fuse_window_ms is not None:
            self.fuse_window_ms = float(fuse_window_ms)
        else:
            self.fuse_window_ms = qi_env_float("QI_SERVE_FUSE_WINDOW_MS", 0.0)
        # Incremental re-analysis (qi-delta, ISSUE 9): the drain consults
        # the per-SCC verdict store BEFORE check_many, so a churn step that
        # leaves the quorum-bearing SCC structurally unchanged composes its
        # verdict from cached fragments and never reaches a backend.  On by
        # default; delta=False (CLI --no-delta) or QI_DELTA_CACHE_MAX=0
        # restores the all-or-nothing pre-delta behavior.
        delta_cache = qi_env_int("QI_DELTA_CACHE_MAX", 4096)
        delta_on = delta if delta is not None else delta_cache > 0
        # Two-level store tier (qi-fleet, ISSUE 11): with a shared fragment
        # store attached — explicitly, or via QI_FLEET_STORE_DIR in a fleet
        # worker's environment — the per-process LRU reads through to the
        # fingerprint-keyed shared tier, so an SCC fragment solved by any
        # worker composes into every worker's certs.  A dead shared tier
        # degrades to local-LRU-only (fleet.store fault point), loudly.
        if shared_store is None:
            store_dir = qi_env("QI_FLEET_STORE_DIR")
            shared_store = SharedSccStore(store_dir) if store_dir else None
        self._delta: Optional[DeltaEngine] = (
            DeltaEngine(
                SccVerdictStore(
                    delta_cache if delta_cache > 0 else None,
                    shared=shared_store,
                ),
                dangling=dangling, scc_select=scc_select,
                scope_to_scc=scope_to_scc,
            )
            if delta_on else None
        )
        # Typed query resolver (qi-query, ISSUE 12): shares this engine's
        # front-end options, so every query kind answers the same FBAS
        # under the same flags as the boolean verdict; the drain injects
        # its delta-aware, deadline-cancellable batch solver per batch.
        self._query_engine = QueryEngine(
            dangling=dangling, scc_select=scc_select,
            scope_to_scc=scope_to_scc, pack=pack,
        )
        # Slow-request exemplars (qi-pulse, ISSUE 15): a served request
        # slower end-to-end than this many ms dumps a qi-exemplar/1
        # record through the crash-only dump path.  0: off.
        self._slow_ms = qi_env_float("QI_PULSE_SLOW_MS", 0.0)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queue: Deque[_Entry] = deque()
        self._reserved = 0  # admission slots between depth check and enqueue
        self._inflight: Dict[str, _Entry] = {}  # fingerprint → live entry
        self._cache: "OrderedDict[str, Union[SolveResult, QueryResult]]" = OrderedDict()
        self._closed = False
        self._stopping = False
        self._started = False
        self._drain_thread: Optional[threading.Thread] = None
        self._replay_report: Optional[Dict[str, object]] = None

    # ---- lifecycle -------------------------------------------------------

    @property
    def journal_path(self) -> Optional[Path]:
        """This engine's crash-only journal file (``None`` without one) —
        the mesh ship protocol streams exactly this file to an inheriting
        peer (serve_transport.py ``ship_journal``)."""
        return self._journal.path if self._journal is not None else None

    @property
    def replay_report(self) -> Optional[Dict[str, object]]:
        """The start-time journal replay report (``None`` before start or
        without a journal) — the mesh hello_ok carries it so a joining
        front door learns readiness without re-driving the replay."""
        return self._replay_report

    def attach_remote_store(self, remote: object) -> bool:
        """Attach a remote fragment tier (qi-mesh, ISSUE 19): the shared
        SCC store reads through to the front door's store gateway on
        every local miss (fetch-on-miss) and publishes every banked
        fragment back (publish-on-solve).  Safe by construction — a
        fetched fragment passes the same strict shape validation as a
        local file, and composed certs still re-verify through the
        checker.  Returns ``False`` (degrade, loud at the caller) when
        the delta tier is off — there is no fragment store to extend."""
        if self._delta is None:
            return False
        store = self._delta.store
        if store.shared is None:
            # A worker joined with no shared directory of its own still
            # participates in the mesh tier: fetched fragments bank into
            # a private spill directory so a re-fetch is a local hit.
            store.shared = SharedSccStore(
                Path(tempfile.mkdtemp(prefix="qi-mesh-store-")),
            )
        store.shared.remote = remote
        return True

    def start(self) -> Optional[Dict[str, object]]:
        """Replay the journal (if any), then start the drain loop.

        Returns the replay report (``None`` without a journal).  Until
        replay completes the ``serve.replay_complete`` gauge is 0 and
        ``/readyz`` answers 503 — a restarted instance must not take
        traffic while its crashed predecessor's work is outstanding.
        """
        if self._started:
            return self._replay_report
        self._started = True
        rec = get_run_record()
        rec.gauge("serve.queue_depth", 0)
        rec.gauge("serve.shed_state", 0)
        if self._journal is not None:
            rec.gauge("serve.replay_complete", 0)
            self._replay_report = self._replay_journal()
        rec.gauge("serve.replay_complete", 1)
        # The drain loop arms a per-batch deadline CancelToken itself
        # (_drain_batch) and stop() shuts the thread down; there is no
        # outer token to forward.
        # qi-lint: allow(cancel-token-plumbed) — drain arms its own per-batch token; stop() owns shutdown
        self._drain_thread = threading.Thread(
            target=self._drain_loop, name="qi-serve-drain", daemon=True,
        )
        self._drain_thread.start()
        log.info(
            "serve engine started (queue_depth=%d batch_max=%d "
            "deadline_s=%g cache_max=%d journal=%s)",
            self.queue_depth, self.batch_max, self.deadline_s,
            self.cache_max,
            self._journal.path if self._journal else "off",
        )
        return self._replay_report

    def stop(self, drain: bool = True,
             timeout: Optional[float] = 30.0) -> None:
        """Close admission; optionally wait for the queue to drain.

        ``timeout=None`` waits indefinitely for the drain thread — the
        CLI's EOF path uses it, because "EOF drains and exits 0" must hold
        even when the final solve is an NP-hard blowup that outlives any
        fixed bound.  ``drain=False`` discards the queue, but every
        discarded entry's waiters are resolved with a typed
        :class:`ServeClosed` — a stop is never a silent drop (the soak's
        "a ticket that never resolves" failure class)."""
        dropped: List[_Entry] = []
        with self._cond:
            self._closed = True
            if not drain:
                dropped = list(self._queue)
                self._queue.clear()
            self._stopping = True
            self._cond.notify_all()
        for entry in dropped:
            self._resolve_err(
                entry,
                ServeClosed("serve engine stopped before this request "
                            "drained"),
                outcome="error",
            )
        if self._drain_thread is not None:
            self._drain_thread.join(timeout=timeout)
        if self._journal is not None:
            self._journal.close()

    # ---- admission -------------------------------------------------------

    def submit(
        self,
        source: Union[str, bytes, List[Dict[str, object]], Fbas],
        *,
        request_id: Optional[str] = None,
        deadline_s: Optional[float] = None,
        query: Optional[object] = None,
        trace: Optional[str] = None,
        client: Optional[str] = None,
    ) -> Ticket:
        """Admit one snapshot-verdict request.

        Raises typed :class:`ServeClosed` / :class:`Overloaded` (and
        propagates an injected ``serve.admit`` fault) — admission is
        synchronous backpressure, so a shed request costs its client one
        exception, not a timeout.  Returns a :class:`Ticket` immediately;
        a cache hit resolves it before this call returns.

        ``query`` (qi-query/1, ISSUE 12) is the raw wire query — a kind
        string, a params object, or an already-parsed :class:`Query`;
        ``None`` means the degenerate intersection query and the whole
        path stays byte-identical to the pre-query engine.  A malformed
        query raises typed :class:`QueryError` at admission.  The
        fingerprint is extended with the query kind + params, so the
        verdict cache, single-flight coalescing and journal replay never
        cross query types.

        ``trace`` (qi-pulse, ISSUE 15) is the wire-carried trace context
        ``trace_id:span_id[:pid]`` — the ``QI_TRACE_CONTEXT`` format the
        fleet front door stamps on dispatch: admission and the eventual
        solve adopt it, so this request's spans (admit, solve, ladder
        rung, native call) parent under the remote request span and the
        response/journal echo it.  ``None``: the engine's own trace.
        """
        rec = get_run_record()
        fault_point("serve.admit")
        request_id = request_id or f"req-{os.getpid()}-{id(object()):x}-{time.monotonic_ns():x}"
        budget = deadline_s if deadline_s is not None else self.deadline_s
        now = time.monotonic()
        ticket = Ticket(
            request_id, now,
            deadline_t=(now + budget) if budget and budget > 0 else None,
        )
        ticket.trace = trace
        # qi-cost: the tenant this request books to (None → "anon").
        ticket.client = client
        ctx = TraceContext.from_env(trace) if trace else None
        with rec.adopted(ctx), rec.span(
            "serve.admit", request_id=request_id,
        ) as admit_span:
            outcome = self._admit(
                source, ticket, request_id, budget, now, query, trace,
            )
            admit_span.set(outcome=outcome)
        return ticket

    def _admit(
        self,
        source: Union[str, bytes, List[Dict[str, object]], Fbas],
        ticket: Ticket,
        request_id: str,
        budget: float,
        now: float,
        query: Optional[object],
        trace: Optional[str],
    ) -> str:
        """The admission body (under :meth:`submit`'s adopted trace +
        ``serve.admit`` span).  Returns the admission outcome for the
        span; typed rejections raise through."""
        rec = get_run_record()
        parsed_query = (
            query if isinstance(query, Query) else Query.parse(query)
        )
        fbas = source if isinstance(source, Fbas) else parse_fbas(source)
        nodes = _raw_nodes(source, fbas)
        graph = build_graph(fbas, dangling=self.dangling)
        fp = snapshot_fingerprint(
            graph, scc_select=self.scc_select, scope_to_scc=self.scope_to_scc,
        )
        qfp = parsed_query.fingerprint()
        if qfp:
            fp = f"{fp}:q:{qfp}"
        rec.add("serve.requests")

        # Cache probe (its own fault point: an injected cache failure
        # bypasses the cache for this request and solves from scratch —
        # never costs the verdict).  Timed into the pulse.cache_ms stage
        # histogram (qi-pulse).
        cache_bypass = False
        hit: Optional[SolveResult] = None
        cache_t0 = time.perf_counter()
        try:
            fault_point("serve.cache")
        except (FaultInjected, OSError) as exc:
            cache_bypass = True
            rec.add("serve.cache_errors")
            rec.event("serve.cache_error", error=str(exc), phase="lookup")
        shed: Optional[Tuple[int, int]] = None
        coalesced = False
        closed = False
        with self._lock:
            if self._closed:
                closed = True
            elif not cache_bypass and fp in self._cache:
                self._cache.move_to_end(fp)
                hit = self._cache[fp]
            elif fp in self._inflight and not self._inflight[fp].done:
                self._inflight[fp].waiters.append(ticket)
                coalesced = True
            else:
                depth = len(self._queue) + self._reserved
                if depth >= self.queue_depth:
                    shed = (depth, self.queue_depth)
                else:
                    self._reserved += 1
        rec.histogram("pulse.cache_ms").observe(
            (time.perf_counter() - cache_t0) * 1000.0
        )
        if closed:
            rec.add("serve.errors")
            raise ServeClosed("serve engine is closed to new requests")
        if hit is not None:
            rec.add("serve.cache_hits")
            _serve_sync("admit.cache_hit")
            # Deliberately NOT journaled: the journal protects requests
            # that are accepted-but-unanswered (a ticket returned pending),
            # where a kill strands a client mid-wait.  A cache hit resolves
            # before submit() returns — the client holds the verdict the
            # moment it holds the ticket — and an fsync per hit would put
            # the durability tax on exactly the path the cache exists to
            # make cheap.
            self._resolve_ok(ticket, hit, fp, cached=True, trace=trace)
            return "cache_hit"
        if coalesced:
            rec.add("serve.coalesced")
            # A coalesced request is ACCEPTED: it must survive a hard kill
            # like any queued request (the zero-lost contract), so it
            # journals its own req entry and marks its own done on
            # delivery.  The done-mark callback registers BEFORE the
            # caller can attach response emission (add_done_callback runs
            # callbacks in registration order, immediately if already
            # resolved), preserving done-before-response durability.
            if self._journal is not None and self._journal.append_request(
                request_id, fp, nodes,
                budget if budget and budget > 0 else None,
                query=parsed_query.to_wire(), trace=trace,
            ):
                journal = self._journal

                def _mark_done(t: Ticket, _fp: str = fp) -> None:
                    try:
                        resp = t.result(timeout=0)
                    except Exception:  # noqa: BLE001 — any failure outcome journals as error
                        journal.append_done(t.request_id, _fp, "error", None)
                        return
                    journal.append_done(
                        t.request_id, _fp, "verdict", bool(resp.intersects),
                    )

                ticket.add_done_callback(_mark_done)
            _serve_sync("admit.coalesced")
            return "coalesced"
        rec.add("serve.cache_misses")
        if shed is not None:
            rec.add("serve.shed")
            # A shed is a DELIVERED typed failure: it counts toward the
            # requests == verdicts + errors invariant like every other
            # terminal outcome.
            rec.add("serve.errors")
            rec.gauge("serve.shed_state", 1)
            rec.event("serve.shed", request_id=request_id,
                      depth=shed[0], bound=shed[1])
            raise Overloaded(*shed)

        # Journal BEFORE the queue: an accepted request must survive a hard
        # kill from this point on (the crash-only contract).
        entry = _Entry(
            request_id=request_id, fingerprint=fp, fbas=fbas, nodes=nodes,
            query=parsed_query,
            waiters=[ticket], cache_bypass=cache_bypass, admitted_t=now,
            trace=trace,
        )
        if self._journal is not None:
            entry.journaled = self._journal.append_request(
                request_id, fp, nodes,
                budget if budget and budget > 0 else None,
                query=parsed_query.to_wire(), trace=trace,
            )
        with self._cond:
            self._reserved -= 1
            if self._closed:
                # stop() won the race between the depth check and this
                # enqueue: the drain thread may already be gone, so an
                # enqueue here would wedge the ticket forever.  Deliver the
                # typed rejection instead (the journaled entry is balanced
                # below so a restart does not replay a request its client
                # already saw rejected).
                closed = True
            else:
                self._queue.append(entry)
                self._inflight[fp] = entry
                depth = len(self._queue)
                self._cond.notify()
        if closed:
            if self._journal is not None and entry.journaled:
                self._journal.append_done(request_id, fp, "error", None)
            rec.add("serve.errors")
            raise ServeClosed("serve engine closed while admitting")
        rec.gauge("serve.queue_depth", depth)
        if depth < self.queue_depth:
            rec.gauge("serve.shed_state", 0)
        _serve_sync("admit.queued")
        return "queued"

    # ---- drain loop ------------------------------------------------------

    def _drain_loop(self) -> None:
        while True:
            with self._cond:
                while not self._queue and not self._stopping:
                    self._cond.wait(timeout=0.1)
                if self._stopping and not self._queue:
                    return
                batch = [
                    self._queue.popleft()
                    for _ in range(min(len(self._queue), self.batch_max))
                ]
                depth = len(self._queue)
            rec = get_run_record()
            rec.gauge("serve.queue_depth", depth)
            if depth < self.queue_depth:
                rec.gauge("serve.shed_state", 0)
            # Held by the schedule harness to force coalesce-during-solve
            # and deadline-between-pop-and-solve orderings; outside the
            # engine lock, so a parked drain never blocks admission.
            _serve_sync("drain.popped")
            try:
                self._drain_batch(batch)
            except Exception as exc:  # noqa: BLE001 — the drain loop must survive anything
                # Whatever escaped _drain_batch's own handling becomes each
                # waiter's (typed or not) outcome — never a dead loop with
                # wedged clients.
                log.warning("drain batch failed (%s); delivering error", exc)
                for entry in batch:
                    self._resolve_err(entry, exc, outcome="error")

    def _auto_fuse_window(self) -> float:
        """One adaptive fuse-window decision (qi-cost, ISSUE 17).

        Inputs: the live queue depth beyond this batch, the pulse
        queue-wait p99 (the bounded raw window — the same estimator
        behind the p50/p99 gauges) and the SLO burn state (one lazy
        evaluation — this is one of the plane's three trigger sites).
        Every decision is a ``serve.fuse_window`` event carrying its
        inputs; the active window rides the ``serve.fuse_window_ms``
        gauge.  A broken controller degrades to 0.0 — no fusion wait,
        never a lost verdict."""
        rec = get_run_record()
        try:
            fault_point("cost.attribute")
            from quorum_intersection_tpu.cost import (
                choose_fuse_window, slo_plane,
            )
            with self._lock:
                queue_depth = len(self._queue)
            wait_p99 = rec.histogram(
                "pulse.queue_wait_ms").window_percentile(99.0)
            burning = False
            slo = slo_plane()
            if slo.enabled:
                burning = bool(slo.evaluate().get("burning"))
            window = choose_fuse_window(queue_depth, wait_p99, burning)
            rec.gauge("serve.fuse_window_ms", round(window, 3))
            rec.event(
                "serve.fuse_window", window_ms=round(window, 3),
                queue_depth=queue_depth, wait_p99_ms=round(wait_p99, 3),
                burning=burning,
            )
            return window
        except (FaultInjected, OSError) as exc:
            rec.add("cost.attribute_errors")
            rec.event("cost.degraded", site="serve.fuse_window",
                      error=repr(exc))
            return 0.0

    def _make_backend(self, cancel: Optional[CancelToken]) -> SearchBackend:
        """One backend per batch.  A string spec is constructed fresh with
        the deadline token threaded in where the engine supports it; a
        caller-supplied instance is used as-is (deadlines then enforce
        only at queue boundaries)."""
        if not isinstance(self.backend, str):
            return self.backend
        options: Dict[str, object] = {}
        if cancel is not None and self.backend in (
            "auto", "tpu", "python", "cpp", "tpu-sweep",
        ):
            options["cancel"] = cancel
        if self.pack is not None and self.backend in ("auto", "tpu"):
            options["pack"] = self.pack
        return get_backend(self.backend, **options)

    def _check_many(
        self, sources: List[Fbas], backend: SearchBackend
    ) -> List[SolveResult]:
        """One batched solve, delta-aware when qi-delta is enabled: the
        incremental engine serves structurally unchanged SCCs from its
        per-SCC store and sends only dirty/new ones to ``backend`` (its
        ``delta.diff`` fault point degrades back to the full chain)."""
        if self._delta is not None:
            return self._delta.check_many(
                sources, backend=backend, pack=self.pack,
            )
        return check_many(
            sources, backend=backend, dangling=self.dangling,
            scc_select=self.scc_select, scope_to_scc=self.scope_to_scc,
            pack=self.pack,
        )

    def _split_expired(
        self, entry: _Entry, now: float
    ) -> Tuple[List[Ticket], List[Ticket]]:
        """Partition ``entry``'s waiters into (expired, alive) and retire
        the entry when nothing stays alive — in ONE lock acquisition:
        coalescers append to ``entry.waiters`` under the same lock, so
        each waiter lands on exactly one side of the split and an emptied
        entry can't absorb a waiter between the split and the retire (the
        invariant documented at :meth:`_finish_entry_locked`)."""
        with self._lock:
            expired = [
                t for t in entry.waiters
                if t.deadline_t is not None and now >= t.deadline_t
            ]
            alive = [t for t in entry.waiters if t not in expired]
            entry.waiters = alive
            if not alive:
                self._finish_entry_locked(entry)
        return expired, alive

    def _partition_expired(
        self, entries: List[_Entry], now: float
    ) -> List[_Entry]:
        """Resolve every already-expired waiter with DeadlineExceeded;
        return the entries that still have live waiters."""
        live: List[_Entry] = []
        for entry in entries:
            expired, alive = self._split_expired(entry, now)
            for t in expired:
                self._resolve_deadline(entry, t, partial=None)
            if alive:
                live.append(entry)
            elif self._journal is not None and entry.journaled:
                self._journal.append_done(
                    entry.request_id, entry.fingerprint,
                    "error", None,
                )
        return live

    def _drain_batch(self, batch: List[_Entry]) -> None:
        rec = get_run_record()
        per_request = False
        try:
            fault_point("serve.drain")
        except (FaultInjected, OSError) as exc:
            # Degrade, don't die: the batched path is an optimization; the
            # per-request path answers the same questions one at a time.
            per_request = True
            rec.add("serve.drain_faults")
            rec.event("serve.drain_degraded", error=str(exc))
        live = self._partition_expired(batch, time.monotonic())
        if not live:
            return
        # Stage histogram (qi-pulse): admission→pop queue wait, per solve
        # unit (a requeued entry's wait accumulates from its original
        # admission — the client-visible number).  Observed BEFORE the
        # adaptive fuse-window decision below, so the controller reads a
        # queue-wait p99 that includes THIS batch's waits — the freshest
        # possible picture of the queue it is sizing the window for.
        queue_h = rec.histogram("pulse.queue_wait_ms")
        pop_t = time.monotonic()
        for entry in live:
            wait_ms = max((pop_t - entry.admitted_t) * 1000.0, 0.0)
            queue_h.observe(wait_ms)
            entry.stages["queue_wait_ms"] = round(wait_ms, 3)
        fuse_window = self.fuse_window_ms if not per_request else 0.0
        if self.fuse_window_auto and not per_request:
            # qi-cost closed loop (ISSUE 17): the window is chosen per
            # flush cycle from the observed queue state and the SLO burn
            # plane — 0.0 (sparse traffic / degraded controller) falls
            # through to the byte-compatible unfused batch below.
            fuse_window = self._auto_fuse_window()
        if fuse_window > 0:
            try:
                fault_point("serve.fuse")
            except (FaultInjected, OSError) as exc:
                # Same discipline one layer up: fusion is an optimization,
                # never a precondition for a verdict — a broken batch
                # former degrades THIS batch in place to the unfused path.
                fuse_window = 0.0
                rec.add("serve.fuse_faults")
                rec.event("serve.fuse_degraded", error=str(exc))
        # Typed queries (qi-query, ISSUE 12) split out of the batched
        # intersection path: each kind resolves through its own engine
        # chain (whatif expands into its OWN lane-packed check_many batch;
        # relaxed/analytics never batch), under the same deadline
        # supervisor as the intersection batch they drained with.
        q_live = [e for e in live if e.query.kind != "intersection"]
        live = [e for e in live if e.query.kind == "intersection"]
        if fuse_window > 0:
            self._drain_batch_fused(live, q_live, fuse_window)
            return
        deadlines = [
            t.deadline_t for e in (live + q_live) for t in e.waiters
            if t.deadline_t is not None
        ]
        deadline_cancel = CancelToken() if deadlines else None
        timer: Optional[threading.Timer] = None
        counters0, _ = rec.snapshot()
        with rec.span(
            "serve.batch", requests=len(live) + len(q_live),
            waiters=sum(len(e.waiters) for e in live + q_live),
            per_request=per_request, queries=len(q_live),
        ):
            try:
                if deadline_cancel is not None:
                    # qi-lint: allow(cancel-token-plumbed) — this Timer IS
                    # the deadline supervisor: its whole job is to trip the
                    # batch's CancelToken; the finally below disarms it.
                    timer = threading.Timer(
                        max(min(deadlines) - time.monotonic(), 0.001),
                        deadline_cancel.cancel,
                    )
                    timer.daemon = True
                    timer.start()
                if live:
                    if per_request:
                        self._solve_per_request(
                            live, deadline_cancel, counters0
                        )
                    else:
                        self._solve_batch(live, deadline_cancel, counters0)
                if q_live:
                    self._solve_queries(q_live, deadline_cancel, counters0)
            finally:
                if timer is not None:
                    timer.cancel()

    # ---- fused drain (qi-fuse, ISSUE 16) ---------------------------------

    def _drain_batch_fused(
        self, live: List[_Entry], q_live: List[_Entry], window_ms: float
    ) -> None:
        """Fleet-aware drain: one worker per entry, one shared
        :class:`~.fuse.BatchFormer` merging every worker's window work —
        plain intersection SCCs and what-if variants alike — into shared
        lane packs (dispatching on tile-full, all-waiting, or the
        deadline-aware ``window_ms`` timer).

        Each entry keeps its OWN CancelToken + deadline supervisor: a
        tripped token retires that request's lane groups mid-pack via the
        sweep's dead-lane machinery while co-packed entries keep their
        full-coverage certs; verdicts and certs stay byte-identical per
        request to the unfused path (docs/PARITY.md §Fusion invariants)."""
        rec = get_run_record()
        entries = live + q_live
        counters0, _ = rec.snapshot()
        former = BatchFormer(self._fused_check_many, window_ms=window_ms)
        with rec.span(
            "serve.batch", requests=len(entries),
            waiters=sum(len(e.waiters) for e in entries),
            per_request=False, queries=len(q_live), fused=True,
        ):
            timers: List[threading.Timer] = []
            threads: List[threading.Thread] = []
            try:
                for entry in entries:
                    cancel = CancelToken()
                    deadline_t = min(
                        (
                            t.deadline_t for t in entry.waiters
                            if t.deadline_t is not None
                        ),
                        default=None,
                    )
                    if deadline_t is not None:
                        # qi-lint: allow(cancel-token-plumbed) — this Timer
                        # IS the per-entry deadline supervisor: its whole
                        # job is to trip the entry's CancelToken; the
                        # finally below disarms it.
                        timer = threading.Timer(
                            max(deadline_t - time.monotonic(), 0.001),
                            cancel.cancel,
                        )
                        timer.daemon = True
                        timer.start()
                        timers.append(timer)
                    former.register()
                    # qi-lint: allow(cancel-token-plumbed) — each worker
                    # carries its entry's own cancel token (argument 3).
                    worker = threading.Thread(
                        target=self._fuse_worker,
                        args=(entry, former, cancel, deadline_t, counters0),
                        name=f"qi-fuse-{entry.request_id}",
                        daemon=True,
                    )
                    threads.append(worker)
                for worker in threads:
                    worker.start()
            finally:
                for worker in threads:
                    worker.join()
                for timer in timers:
                    timer.cancel()

    def _fuse_worker(
        self,
        entry: _Entry,
        former: BatchFormer,
        cancel: CancelToken,
        deadline_t: Optional[float],
        counters0: Dict[str, float],
    ) -> None:
        """Solve ONE drained entry through the shared batch former; every
        outcome is delivered exactly as the legacy drain would — typed
        errors, deadline partials with requeue, or the verdict."""
        rec = get_run_record()
        run = self._run_check_many(
            former=former, origin=entry.request_id, cancel=cancel,
            deadline_t=deadline_t,
        )
        t0 = time.perf_counter()
        try:
            try:
                with rec.adopted(entry.trace_ctx()), rec.span(
                    "serve.solve", requests=1, fused=True,
                    delta=self._delta is not None,
                    query=entry.query.kind,
                ):
                    if entry.query.kind == "intersection":
                        # Direct submit (not ``run``): a lane-retired
                        # result must come back AS a result here so its
                        # exact per-request ledger rides the deadline
                        # outcome below, not the raising wrapper the query
                        # resolver needs.
                        res: Union[SolveResult, QueryResult] = former.submit(
                            [entry.fbas], origin=entry.request_id,
                            cancel=cancel, deadline_t=deadline_t,
                        )[0]
                    else:
                        res = self._query_engine.resolve(
                            entry.nodes, entry.query, check_many_fn=run,
                            cancel=cancel,
                        )
            finally:
                former.done()
        except SearchCancelled:
            self._after_deadline_cancel([entry], counters0)
            return
        except QueryError as exc:
            self._resolve_err(entry, exc, outcome="error")
            return
        except Exception as exc:  # noqa: BLE001 — one bad request must not starve the rest
            rec.add("serve.drain_errors")
            self._resolve_err(entry, exc, outcome="error")
            return
        if res.stats.get("cancelled"):
            # The entry's own deadline retired its lanes mid-pack: its
            # PARTIAL coverage cert (the exact per-request ledger, not the
            # legacy batch-level counter diff) rides the deadline outcome;
            # survivors requeue exactly as the legacy path.
            self._after_deadline_cancel(
                [entry], counters0,
                partial_override=getattr(res, "cert", None),
            )
            return
        self._note_solve([entry], (time.perf_counter() - t0) * 1000.0)
        self._deliver_ok(entry, res)

    def _fused_check_many(
        self,
        sources: List[Fbas],
        cancels: List[Optional[CancelToken]],
        origins: List[str],
    ) -> List[SolveResult]:
        """The batch former's flush target: the drain's usual delta-aware
        chain with per-source cancels/origins riding down to the lane
        packer (pipeline → check_sccs → the sweep's per-group ownership)."""
        backend = self._make_backend(None)
        if self._delta is not None:
            return self._delta.check_many(
                sources, backend=backend, pack=self.pack,
                cancels=cancels, origins=origins,
            )
        return check_many(
            sources, backend=backend, dangling=self.dangling,
            scc_select=self.scc_select, scope_to_scc=self.scope_to_scc,
            pack=self.pack, cancels=cancels, origins=origins,
        )

    def _run_check_many(
        self,
        backend: Optional[SearchBackend] = None,
        *,
        former: Optional[BatchFormer] = None,
        origin: str = "",
        cancel: Optional[CancelToken] = None,
        deadline_t: Optional[float] = None,
    ) -> Callable[[List[Fbas]], List[SolveResult]]:
        """The ONE place every serve-side ``check_many`` closure is built
        (drain queries, fused workers, journal replay): unfused callers
        pass a ``backend`` and get the delta-aware chain; fused callers
        pass the shared ``former`` and their work joins cross-request
        packs.  A fused result that came back lane-retired raises
        ``SearchCancelled`` — the uniform deadline outcome — so no caller
        can mistake partial coverage for a verdict."""
        if former is not None:
            def run(sources: List[Fbas]) -> List[SolveResult]:
                results = former.submit(
                    sources, origin=origin, cancel=cancel,
                    deadline_t=deadline_t,
                )
                for res in results:
                    if res.stats.get("cancelled"):
                        raise SearchCancelled(
                            f"fused lanes retired by request {origin}'s "
                            f"deadline"
                        )
                return results
            return run

        def run_backend(
            sources: List[Fbas], _backend: Optional[SearchBackend] = backend,
        ) -> List[SolveResult]:
            return self._check_many(sources, _backend)
        return run_backend

    def _solve_batch(
        self,
        live: List[_Entry],
        cancel: Optional[CancelToken],
        counters0: Dict[str, float],
    ) -> None:
        rec = get_run_record()
        backend = self._make_backend(cancel)
        # Wire-trace adoption (qi-pulse): a single-entry batch solves
        # entirely under the request's carried trace, so the ladder-rung /
        # native-call spans the backends open on this thread graft under
        # the front door's request span.  A fused multi-trace batch keeps
        # the engine's own trace (batch-level attribution, like batched
        # certs) — the per-request e2e histogram still covers every entry.
        ctx = live[0].trace_ctx() if len(live) == 1 else None
        t0 = time.perf_counter()
        try:
            with rec.adopted(ctx), rec.span(
                "serve.solve", requests=len(live),
                delta=self._delta is not None,
            ):
                results = self._check_many([e.fbas for e in live], backend)
        except SearchCancelled:
            self._after_deadline_cancel(live, counters0)
            return
        except Exception as exc:  # noqa: BLE001 — degrade to per-request, never wedge the batch
            rec.add("serve.drain_errors")
            log.info(
                "batched drain failed (%s: %s); degrading to per-request "
                "solves", type(exc).__name__, exc,
            )
            self._solve_per_request(live, cancel, counters0)
            return
        self._note_solve(live, (time.perf_counter() - t0) * 1000.0)
        for entry, res in zip(live, results):
            self._deliver_ok(entry, res)

    def _note_solve(self, live: List[_Entry], solve_ms: float) -> None:
        """Book one solve call into the qi-pulse stage histograms and the
        entries' exemplar breakdowns (a fused batch's wall is shared —
        batch-level attribution, the cancelled-batch cert discipline)."""
        rec = get_run_record()
        rec.histogram("pulse.solve_ms").observe(solve_ms)
        if self._delta is not None:
            # The delta-aware chain answered this solve: the same wall,
            # bucketed separately so a reuse regression (delta_ms growing
            # toward solve-from-scratch) is visible in one scrape.
            rec.histogram("pulse.delta_ms").observe(solve_ms)
        for entry in live:
            entry.stages["solve_ms"] = round(solve_ms, 3)

    def _solve_per_request(
        self,
        live: List[_Entry],
        cancel: Optional[CancelToken],
        counters0: Dict[str, float],
    ) -> None:
        rec = get_run_record()
        for ix, entry in enumerate(live):
            if cancel is not None and cancel.cancelled:
                self._after_deadline_cancel(live[ix:], counters0)
                return
            backend = self._make_backend(cancel)
            t0 = time.perf_counter()
            try:
                with rec.adopted(entry.trace_ctx()), rec.span(
                    "serve.solve", requests=1,
                    delta=self._delta is not None,
                ):
                    results = self._check_many([entry.fbas], backend)
            except SearchCancelled:
                self._after_deadline_cancel(live[ix:], counters0)
                return
            except Exception as exc:  # noqa: BLE001 — one bad request must not starve the rest
                rec.add("serve.drain_errors")
                self._resolve_err(entry, exc, outcome="error")
                continue
            self._note_solve([entry], (time.perf_counter() - t0) * 1000.0)
            self._deliver_ok(entry, results[0])

    def _solve_queries(
        self,
        entries: List[_Entry],
        cancel: Optional[CancelToken],
        counters0: Dict[str, float],
    ) -> None:
        """Resolve the drained typed-query entries one by one (qi-query).

        Every failure is a typed outcome: a ``query.dispatch`` degrade or
        resolver error lands as :class:`QueryError`, a deadline cancel
        follows the same partial-coverage path as the intersection batch
        — never a wedged ticket, never a wrong verdict."""
        rec = get_run_record()
        for ix, entry in enumerate(entries):
            if cancel is not None and cancel.cancelled:
                self._after_deadline_cancel(entries[ix:], counters0)
                return
            backend = self._make_backend(cancel)
            run = self._run_check_many(backend)
            t0 = time.perf_counter()
            try:
                with rec.adopted(entry.trace_ctx()), rec.span(
                    "serve.solve", requests=1, query=entry.query.kind,
                ):
                    qres = self._query_engine.resolve(
                        entry.nodes, entry.query, check_many_fn=run,
                        cancel=cancel,
                    )
            except SearchCancelled:
                self._after_deadline_cancel(entries[ix:], counters0)
                return
            except QueryError as exc:
                self._resolve_err(entry, exc, outcome="error")
                continue
            except Exception as exc:  # noqa: BLE001 — one bad query must not starve the rest
                rec.add("serve.drain_errors")
                self._resolve_err(entry, exc, outcome="error")
                continue
            self._note_solve([entry], (time.perf_counter() - t0) * 1000.0)
            self._deliver_ok(entry, qres)

    def _after_deadline_cancel(
        self,
        entries: List[_Entry],
        counters0: Dict[str, float],
        partial_override: Optional[Dict[str, object]] = None,
    ) -> None:
        """The deadline supervisor tripped the CancelToken mid-solve:
        expired waiters get DeadlineExceeded with the partial-coverage
        certificate; survivors requeue for a fresh solve (bounded by
        MAX_SOLVE_ATTEMPTS).

        ``partial_override`` (qi-fuse): the fused drain already holds the
        cancelled request's OWN exact coverage ledger — it replaces the
        legacy batch-level counter diff below."""
        rec = get_run_record()
        counters1, _ = rec.snapshot()
        partial = partial_override if partial_override is not None else {
            "schema": CERT_SCHEMA,
            "verdict": None,
            "partial": True,
            "coverage": {
                # Batch-level attribution, like batched certs' shared event
                # slice: the cancelled solve's window accounting cannot be
                # split per fused lane.
                "batch_level": True,
                "windows_enumerated": int(
                    counters1.get("cert.windows_enumerated", 0)
                    - counters0.get("cert.windows_enumerated", 0)
                ),
                "windows_cancelled": int(
                    counters1.get("cert.windows_cancelled", 0)
                    - counters0.get("cert.windows_cancelled", 0)
                ),
            },
            "provenance": {"trace_id": rec.trace_id},
        }
        now = time.monotonic()
        requeue: List[_Entry] = []
        for entry in entries:
            expired, alive = self._split_expired(entry, now)
            for t in expired:
                self._resolve_deadline(entry, t, partial=partial)
            if not alive:
                if self._journal is not None and entry.journaled:
                    self._journal.append_done(
                        entry.request_id, entry.fingerprint, "error", None,
                    )
                continue
            entry.attempts += 1
            if entry.attempts >= MAX_SOLVE_ATTEMPTS:
                self._resolve_err(
                    entry,
                    ServeError(
                        f"request {entry.request_id} cancelled "
                        f"{entry.attempts} times by co-batched deadlines"
                    ),
                    outcome="error",
                )
                continue
            requeue.append(entry)
        if requeue:
            rec.add("serve.requeues", len(requeue))
            with self._cond:
                for entry in reversed(requeue):
                    self._queue.appendleft(entry)
                self._cond.notify()

    # ---- delivery --------------------------------------------------------

    def _finish_entry_locked(self, entry: _Entry) -> None:
        """Retire ``entry`` from single-flight.  Caller holds ``_lock`` —
        and MUST snapshot ``entry.waiters`` in the SAME lock acquisition:
        a submit that coalesces between a waiter snapshot and this retire
        would be appended to a list nobody will ever resolve (a silent
        drop — the exact bug the serve chaos soak caught under a
        ``serve.cache`` fault, where the cache can't mask the window)."""
        entry.done = True
        if self._inflight.get(entry.fingerprint) is entry:
            del self._inflight[entry.fingerprint]

    def _deliver_ok(
        self, entry: _Entry, res: Union[SolveResult, QueryResult]
    ) -> None:
        """One solved entry: cache, journal-done, respond to every waiter."""
        rec = get_run_record()
        evicted = 0
        if not entry.cache_bypass:
            try:
                fault_point("serve.cache")
                with self._lock:
                    self._cache[entry.fingerprint] = res
                    self._cache.move_to_end(entry.fingerprint)
                    while len(self._cache) > self.cache_max:
                        self._cache.popitem(last=False)
                        evicted += 1
            except (FaultInjected, OSError) as exc:
                rec.add("serve.cache_errors")
                rec.event("serve.cache_error", error=str(exc), phase="insert")
        if evicted:
            rec.add("serve.cache_evictions", evicted)
        with self._lock:
            cache_size = len(self._cache)
            # Atomic with the retire: a coalescer lands either in this
            # snapshot (resolved below) or after the retire (fresh entry /
            # cache hit) — never in a gap between the two.
            waiters = list(entry.waiters)
            self._finish_entry_locked(entry)
        rec.gauge("serve.cache_size", cache_size)
        if self._journal is not None and entry.journaled:
            self._journal.append_done(
                entry.request_id, entry.fingerprint, "verdict",
                bool(res.intersects),
            )
        # Deadline enforcement at delivery: a waiter that coalesced onto
        # this entry AFTER the batch's deadline supervisor was armed was
        # never supervised — its expiry must still be honored here, or a
        # late coalescer silently outlives its budget.  (The verdict is
        # cached above, so the typed error costs one retry, not a solve.)
        now = time.monotonic()
        respond_t0 = time.perf_counter()
        delivered: List[Ticket] = []
        for ticket in waiters:
            if ticket.deadline_t is not None and now >= ticket.deadline_t:
                self._resolve_deadline(entry, ticket, partial=None)
            else:
                self._resolve_ok(ticket, res, entry.fingerprint,
                                 cached=False, replayed=entry.replayed,
                                 trace=ticket.trace)
                delivered.append(ticket)
        rec.histogram("pulse.respond_ms").observe(
            (time.perf_counter() - respond_t0) * 1000.0
        )
        self._maybe_exemplar(entry, delivered)
        _serve_sync("drain.delivered")

    def _maybe_exemplar(self, entry: _Entry,
                        delivered: List[Ticket]) -> None:
        """Slow-request exemplar (qi-pulse), ONE per solve entry however
        many waiters coalesced onto it (a per-waiter dump would fsync the
        same file K times inside the delivery loop).  Fired after every
        waiter already holds its verdict, so neither the dump nor an
        injected dump failure can touch an outcome."""
        if self._slow_ms <= 0 or not delivered:
            return
        now = time.monotonic()
        slowest = max(delivered, key=lambda t: now - t.submitted_t)
        e2e_ms = (now - slowest.submitted_t) * 1000.0
        if e2e_ms <= self._slow_ms:
            return
        ctx = entry.trace_ctx()
        rec = get_run_record()
        breakdown = dict(entry.stages)
        breakdown["e2e_ms"] = round(e2e_ms, 3)
        dump_exemplar({
            "reason": "slow-request",
            "request_id": slowest.request_id,
            "fingerprint": entry.fingerprint,
            "trace_id": ctx.trace_id if ctx is not None else rec.trace_id,
            "trace": entry.trace,
            "e2e_ms": round(e2e_ms, 3),
            "slow_ms": self._slow_ms,
            "waiters": len(delivered),
            "stages": breakdown,
        })

    def _resolve_ok(
        self,
        ticket: Ticket,
        res: Union[SolveResult, QueryResult],
        fingerprint: str,
        *,
        cached: bool,
        replayed: bool = False,
        trace: Optional[str] = None,
    ) -> None:
        rec = get_run_record()
        seconds = time.monotonic() - ticket.submitted_t
        cert = res.cert
        if cert is not None:
            # Per-delivery copy: two waiters (or a later cache hit) each
            # get their own serve stamp without mutating the shared cert.
            cert = dict(cert)
            prov = dict(cert.get("provenance") or {})
            prov["serve"] = {
                "schema": SERVE_SCHEMA,
                "request_id": ticket.request_id,
                "fingerprint": fingerprint,
                "cached": cached,
                "replayed": replayed,
                "journaled": self._journal is not None,
                "latency_s": round(seconds, 6),
            }
            if ticket.client is not None:
                prov["serve"]["client"] = ticket.client
            cert["provenance"] = prov
        # qi-cost (ISSUE 17): book this delivery to its tenant and attach
        # the cost to the response.  A cache hit books the request but no
        # cost (zero new device work — re-billing the original solve would
        # double-count it); a degraded attribution drops the cost, touches
        # nothing else (verdict, cert and latency stay byte-identical).
        cost: Optional[Dict[str, object]] = None
        try:
            fault_point("cost.attribute")
            raw_cost = res.stats.get("cost")
            if not cached and isinstance(raw_cost, dict):
                cost = dict(raw_cost)
            from quorum_intersection_tpu.cost import tenant_table
            tenant_table().book(ticket.client or "anon", cost)
        except (FaultInjected, OSError) as exc:
            cost = None
            rec.add("cost.attribute_errors")
            rec.event("cost.degraded", site="serve.respond",
                      error=repr(exc))
        response = ServeResponse(
            request_id=ticket.request_id,
            intersects=bool(res.intersects),
            cert=cert,
            stats=dict(res.stats),
            cached=cached,
            seconds=seconds,
            # Typed-query payload (qi-query): None on the legacy boolean
            # path, the structured result table/witness/report otherwise.
            result=getattr(res, "result", None),
            # Wire trace echo (qi-pulse): the request's carried context
            # rides the response line so the caller can join the trace.
            trace=trace,
            cost=cost,
        )
        outcome_err: Optional[BaseException] = None
        try:
            fault_point("serve.respond")
        except (FaultInjected, OSError) as exc:
            # The verdict exists (cached + journaled); this CLIENT's copy
            # failed to deliver — a typed error, never a silent drop, and a
            # retry of the same snapshot is a cache hit.
            rec.add("serve.respond_errors")
            rec.event(
                "serve.respond_error", request_id=ticket.request_id,
                error=str(exc),
            )
            outcome_err = exc
        if outcome_err is not None:
            rec.add("serve.errors")
            ticket._resolve(("err", outcome_err))
            return
        rec.add("serve.verdicts")
        self._note_latency(seconds)
        ticket._resolve(("ok", response))

    def _resolve_deadline(
        self, entry: _Entry, ticket: Ticket,
        partial: Optional[Dict[str, object]],
    ) -> None:
        rec = get_run_record()
        budget = (
            (ticket.deadline_t - ticket.submitted_t)
            if ticket.deadline_t is not None else 0.0
        )
        cert = None
        if partial is not None:
            cert = dict(partial)
            prov = dict(cert.get("provenance") or {})
            prov["serve"] = {
                "schema": SERVE_SCHEMA,
                "request_id": ticket.request_id,
                "fingerprint": entry.fingerprint,
                "deadline_s": round(budget, 6),
            }
            cert["provenance"] = prov
        rec.add("serve.deadline_expired")
        rec.add("serve.errors")
        rec.event(
            "serve.deadline", request_id=ticket.request_id,
            deadline_s=round(budget, 6),
            mid_solve=partial is not None,
        )
        ticket._resolve(("err", DeadlineExceeded(
            ticket.request_id, budget, cert=cert,
        )))

    def _resolve_err(
        self, entry: _Entry, exc: BaseException, *, outcome: str
    ) -> None:
        rec = get_run_record()
        with self._lock:
            waiters = list(entry.waiters)
            self._finish_entry_locked(entry)
        if self._journal is not None and entry.journaled:
            self._journal.append_done(
                entry.request_id, entry.fingerprint, outcome, None,
            )
        rec.add("serve.errors", len(waiters))
        for ticket in waiters:
            ticket._resolve(("err", exc))

    def _note_latency(self, seconds: float) -> None:
        # End-to-end stage histogram (qi-pulse): the buckets are what the
        # fleet aggregation plane merges; the histogram's bounded raw
        # window keeps the serve.p50_ms/p99_ms gauges byte-compatible
        # (same nearest-rank estimator over the same 512-sample window
        # the pre-pulse deque carried, sorted outside any engine lock).
        rec = get_run_record()
        h = rec.histogram("pulse.e2e_ms")
        h.observe(seconds * 1000.0)
        rec.gauge("serve.p50_ms", round(h.window_percentile(50.0), 3))
        rec.gauge("serve.p99_ms", round(h.window_percentile(99.0), 3))

    # ---- journal replay --------------------------------------------------

    def _replay_journal(self) -> Dict[str, object]:
        """Crash-only restart: re-solve every journaled request that never
        reached ``done`` — zero lost (every ``req`` reaches an outcome),
        zero duplicated (a ``done`` entry is final; replay skips it)."""
        assert self._journal is not None
        rec = get_run_record()
        entries, corrupt, torn_tail = self._journal.scan()
        if corrupt:
            self._journal.quarantine(corrupt, "unparseable journal line")
        if torn_tail:
            rec.add("serve.journal_torn_tail")
            log.info(
                "journal tail torn (expected after a hard kill mid-append); "
                "final partial line ignored"
            )
        done_ids = {
            e.get("request_id") for e in entries if e.get("kind") == "done"
        }
        pending: List[Dict[str, object]] = []
        foreign: List[str] = []
        for e in entries:
            if e.get("kind") != "req" or e.get("request_id") in done_ids:
                continue
            nodes = e.get("nodes")
            try:
                if not isinstance(nodes, list):
                    raise ValueError(
                        "journaled nodes payload is not a node array"
                    )
                # Typed queries (qi-query) journal their wire form; an
                # unparseable query quarantines exactly like unparseable
                # nodes — a replayed request must re-ask the SAME question.
                query = Query.parse(e.get("query"))
                fbas = parse_fbas(nodes)
                graph = build_graph(fbas, dangling=self.dangling)
                fp = snapshot_fingerprint(
                    graph, scc_select=self.scc_select,
                    scope_to_scc=self.scope_to_scc,
                )
                qfp = query.fingerprint()
                if qfp:
                    fp = f"{fp}:q:{qfp}"
            except (ValueError, TypeError, KeyError, AttributeError) as exc:
                foreign.append(json.dumps(e, default=str))
                log.warning(
                    "journaled request %s unparseable on replay (%s); "
                    "quarantined", e.get("request_id"), exc,
                )
                continue
            if fp != e.get("fingerprint"):
                # Foreign fingerprint: the entry's recorded identity does
                # not match its own payload (bit rot, a hand-edited file, a
                # journal from a different engine configuration) — replaying
                # it could serve a verdict under the wrong cache key.
                foreign.append(json.dumps(e, default=str))
                log.warning(
                    "journaled request %s has a foreign fingerprint "
                    "(recorded %s != recomputed %s); quarantined",
                    e.get("request_id"), e.get("fingerprint"), fp,
                )
                continue
            raw_trace = e.get("trace")
            pending.append({
                "entry": e, "fbas": fbas, "nodes": nodes,
                "fingerprint": fp, "query": query,
                # qi-pulse: the journaled wire trace — replay re-adopts
                # it so the recovered solve joins the ORIGINAL request's
                # distributed trace instead of minting a disconnected one.
                "trace": raw_trace if isinstance(raw_trace, str) else None,
            })
        if foreign:
            self._journal.quarantine(foreign, "foreign fingerprint / payload")
        report: Dict[str, object] = {
            "schema": SERVE_SCHEMA,
            "journal": str(self._journal.path),
            "entries": len(entries),
            "already_done": len([
                e for e in entries
                if e.get("kind") == "req" and e.get("request_id") in done_ids
            ]),
            "pending": len(pending),
            "quarantined": len(corrupt) + len(foreign),
            "torn_tail": torn_tail,
            "verdicts": {},
            "errors": {},
        }
        rec.event(
            "serve.replay_started", pending=len(pending),
            already_done=report["already_done"],
            quarantined=report["quarantined"],
        )
        still_pending: List[Dict[str, object]] = []
        # Typed-query entries replay one at a time through the query
        # resolver (their batches, if any, are their own — a whatif
        # expands its own lane-packed frontier); intersection entries keep
        # the batched replay below.
        q_pending = [
            p for p in pending if p["query"].kind != "intersection"  # type: ignore[attr-defined]
        ]
        pending = [
            p for p in pending if p["query"].kind == "intersection"  # type: ignore[attr-defined]
        ]
        with rec.span("serve.replay",
                      pending=len(pending) + len(q_pending)):
            for p in q_pending:
                rid = str(p["entry"].get("request_id"))
                fp = str(p["fingerprint"])
                run = self._run_check_many(self._make_backend(None))
                replay_ctx = (
                    TraceContext.from_env(p["trace"])  # type: ignore[arg-type]
                    if p["trace"] else None
                )
                try:
                    with rec.adopted(replay_ctx):
                        res = self._query_engine.resolve(
                            p["nodes"], p["query"],  # type: ignore[arg-type]
                            check_many_fn=run,
                        )
                except Exception as exc:  # noqa: BLE001 — replay must not block startup
                    report["errors"][rid] = (  # type: ignore[index]
                        f"{type(exc).__name__}: {exc}"
                    )
                    still_pending.append(p["entry"])  # type: ignore[arg-type]
                    rec.add("serve.replay_errors")
                    continue
                with self._lock:
                    self._cache[fp] = res
                    self._cache.move_to_end(fp)
                    while len(self._cache) > self.cache_max:
                        self._cache.popitem(last=False)
                self._journal.append_done(
                    rid, fp, "verdict", bool(res.intersects),
                )
                rec.add("serve.journal_replayed")
                report["verdicts"][rid] = bool(  # type: ignore[index]
                    res.intersects
                )
            for i in range(0, len(pending), self.batch_max):
                chunk = pending[i:i + self.batch_max]
                # Trace re-adoption follows the drain's batching rule: a
                # single-entry chunk re-solves entirely under its journaled
                # trace; a fused chunk keeps batch-level attribution.
                replay_ctx = (
                    TraceContext.from_env(chunk[0]["trace"])  # type: ignore[arg-type]
                    if len(chunk) == 1 and chunk[0]["trace"] else None
                )
                try:
                    with rec.adopted(replay_ctx):
                        results = self._check_many(
                            [p["fbas"] for p in chunk],
                            self._make_backend(None),
                        )
                except Exception as exc:  # noqa: BLE001 — replay must not block startup
                    for p in chunk:
                        rid = str(p["entry"].get("request_id"))
                        report["errors"][rid] = (  # type: ignore[index]
                            f"{type(exc).__name__}: {exc}"
                        )
                        still_pending.append(p["entry"])  # type: ignore[arg-type]
                    rec.add("serve.replay_errors")
                    continue
                for p, res in zip(chunk, results):
                    rid = str(p["entry"].get("request_id"))
                    fp = str(p["fingerprint"])
                    with self._lock:
                        self._cache[fp] = res
                        self._cache.move_to_end(fp)
                        while len(self._cache) > self.cache_max:
                            self._cache.popitem(last=False)
                    self._journal.append_done(
                        rid, fp, "verdict", bool(res.intersects),
                    )
                    rec.add("serve.journal_replayed")
                    report["verdicts"][rid] = bool(  # type: ignore[index]
                        res.intersects
                    )
        # Compact: resolved pairs drop out, unresolved req entries survive
        # for the next restart's replay.
        self._journal.compact(still_pending)
        rec.event(
            "serve.replay_done", replayed=len(report["verdicts"]),  # type: ignore[arg-type]
            errors=len(report["errors"]),  # type: ignore[arg-type]
        )
        log.info(
            "journal replay complete: %d replayed, %d already done, %d "
            "quarantined", len(report["verdicts"]),  # type: ignore[arg-type]
            report["already_done"], report["quarantined"],
        )
        return report


def _qset_raw(q: Optional[QSet]) -> Optional[Dict[str, object]]:
    """Stellarbeat-shaped dict of one parsed QSet (``None`` for the
    never-satisfiable null qset) — the inverse of ``schema._parse_qset``."""
    if q is None or q.threshold is None:
        return None
    return {
        "threshold": q.threshold,
        "validators": list(q.validators),
        "innerQuorumSets": [_qset_raw(iq) for iq in q.inner],
    }


def _raw_nodes(
    source: Union[str, bytes, List[Dict[str, object]], Fbas],
    fbas: Fbas,
) -> List[Dict[str, object]]:
    """The raw node list to journal for ``source`` (re-parsed on replay)."""
    if isinstance(source, list):
        return source
    if isinstance(source, (str, bytes)):
        # parse_fbas already accepted this source, so its top level is a
        # JSON array (anything else raised before we got here).
        data = json.loads(source)
        if isinstance(data, list):
            return data
    # A pre-parsed Fbas: rebuild raw dicts from the parsed nodes —
    # ``parse_fbas(_raw_nodes(...))`` round-trips to the same graph, which
    # is all replay needs.
    return [
        {
            "publicKey": node.public_key,
            "name": node.name,
            "quorumSet": _qset_raw(node.qset),
        }
        for node in fbas
    ]


# ---- CLI subcommand ---------------------------------------------------------
#
# The transport half of the serving layer moved to serve_transport.py in
# the ISSUE 11 engine/transport split (the ROADMAP-named seam): the same
# ServeEngine now runs under the stdio loop, a socket transport, and the
# fleet supervisor (fleet.py).  These wrappers keep the public import
# surface (`from quorum_intersection_tpu.serve import serve_main`) and the
# cli.py dispatch stable.


def build_serve_parser() -> argparse.ArgumentParser:
    from quorum_intersection_tpu.serve_transport import (
        build_serve_parser as _build,
    )

    return _build()


def serve_main(argv: Optional[List[str]] = None) -> int:
    """The ``serve`` subcommand body (dispatched from cli.py)."""
    from quorum_intersection_tpu.serve_transport import serve_main as _main

    return _main(argv)
