"""Orchestration: parse → graph → SCC reduction → guard → backend search.

Capability parity with the reference's ``solve`` drivers
(`/root/reference/quorum_intersection.cpp:615-716`), with the Q5 fix
(SURVEY.md §2.3): the exponential search runs in **the** quorum-bearing SCC,
not blindly ``sccs.front()``.  When the guard passes (exactly one SCC contains
a quorum) the two coincide on every Stellar-like topology — and on all bundled
fixtures [verified] — but ``front()`` could silently return a vacuous ``true``
if Tarjan numbering ever put the quorum-bearing SCC elsewhere;
``scc_select="front"`` reproduces the reference choice for differential runs.

Verbose narration mirrors the reference's ``-v`` messages (cpp:640, :662-664,
:673-679, :683-685, :693-697, :702-704).
"""

from __future__ import annotations

import sys
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, TextIO, Tuple, Union

from quorum_intersection_tpu.backends.base import (
    CancelToken,
    SccCheckResult,
    SearchBackend,
    get_backend,
)
from quorum_intersection_tpu.cert import CERT_SCHEMA, build_certificate
from quorum_intersection_tpu.encode.circuit import Circuit, encode_circuit
from quorum_intersection_tpu.fbas.graph import TrustGraph, build_graph, group_sccs, tarjan_scc
from quorum_intersection_tpu.fbas.schema import Fbas, parse_fbas
from quorum_intersection_tpu.fbas.semantics import max_quorum
from quorum_intersection_tpu.utils.logging import get_logger
from quorum_intersection_tpu.utils.telemetry import get_run_record
from quorum_intersection_tpu.utils.timers import PhaseTimers

log = get_logger("pipeline")


# Above this vertex count the per-SCC quorum scan routes to the native
# oracle's `qi_max_quorum` (C speed) instead of N interpreted-Python
# fixpoints; below it the Python loop is already sub-millisecond and small
# CLI runs stay free of any compile dependency.
NATIVE_SCAN_LIMIT = 256


def scan_scc_quorums(
    graph: TrustGraph, sccs: List[List[int]], *, allow_native: bool = True
) -> List[List[int]]:
    """One max-quorum per SCC, restricted to its members (cpp:645-672).

    Big graphs use the native scan (same semantics, ~100× the interpreted
    loop; VERDICT r1 §weak-7); failures degrade to the Python loop.
    ``allow_native=False`` keeps everything interpreted — set when the user
    explicitly chose the pure-Python backend, whose point is zero native
    dependencies."""
    if allow_native and graph.n > NATIVE_SCAN_LIMIT:
        try:
            from quorum_intersection_tpu.backends.cpp import native_scc_scan

            return native_scc_scan(graph, sccs)
        except Exception as exc:  # noqa: BLE001 — no g++ etc.
            log.info("native SCC scan unavailable (%s); using Python scan", exc)
    quorums: List[List[int]] = []
    for members in sccs:
        avail = [False] * graph.n
        for v in members:
            avail[v] = True
        quorums.append(max_quorum(graph, members, avail))
    return quorums


def quorum_bearing_sccs(
    graph: TrustGraph, *, allow_native: bool = True
) -> List[Tuple[int, List[int]]]:
    """``[(scc_id, members), ...]`` for every SCC that contains a quorum
    when restricted to itself — the shared scaffolding of the CLI analysis
    modes (top tier, blocking/splitting sets)."""
    count, comp = tarjan_scc(graph.n, graph.succ)
    sccs = group_sccs(graph.n, comp, count)
    return [
        (sid, sccs[sid])
        for sid, quorum in enumerate(
            scan_scc_quorums(graph, sccs, allow_native=allow_native)
        )
        if quorum
    ]


def _classify_sccs(
    graph: TrustGraph,
    *,
    allow_native: bool,
    scc_select: str,
    timers: PhaseTimers,
    scan: Optional[
        Callable[..., List[Optional[List[int]]]]
    ] = None,
) -> Tuple[int, List[List[int]], List[int], Dict[int, List[int]], List[int]]:
    """The SCC-classification prefix shared by :func:`solve_graph`,
    :func:`check_many` and the incremental engine (``delta.py``): Tarjan +
    per-SCC quorum scan + main-SCC selection (Q5/Q8 semantics), under the
    same ``scc``/``scc_scan`` timer phases — one implementation, so the
    entry points' guard verdicts cannot drift.  ``scan`` substitutes the
    scan provider (same signature as :func:`scan_scc_quorums`) — qi-delta
    passes a verdict-store-aware one that serves fingerprint-matched SCCs
    from cache.  Returns ``(count, sccs, quorum_scc_ids, scc_quorums,
    main_scc)``."""
    with timers.phase("scc"):
        count, comp = tarjan_scc(graph.n, graph.succ)
        sccs = group_sccs(graph.n, comp, count)
    quorum_scc_ids: List[int] = []
    scc_quorums: Dict[int, List[int]] = {}
    with timers.phase("scc_scan"):
        for sid, quorum in enumerate(
            (scan or scan_scc_quorums)(graph, sccs, allow_native=allow_native)
        ):
            if quorum:
                quorum_scc_ids.append(sid)
                scc_quorums[sid] = quorum
    # "Main" SCC: the reference labels sccs.front() the main component
    # (cpp:675-678) — that is the *sink*, not the largest (Q8).  With the
    # Q5 fix the main component is the quorum-bearing one when unique.
    if scc_select == "front" or not quorum_scc_ids:
        main_scc = sccs[0] if sccs else []
    else:
        main_scc = sccs[quorum_scc_ids[0]]
    return count, sccs, quorum_scc_ids, scc_quorums, main_scc


@dataclass
class SolveResult:
    intersects: bool
    n_sccs: int = 0
    quorum_scc_ids: List[int] = field(default_factory=list)
    main_scc: List[int] = field(default_factory=list)
    q1: Optional[List[int]] = None
    q2: Optional[List[int]] = None
    stats: Dict[str, object] = field(default_factory=dict)
    timers: Dict[str, float] = field(default_factory=dict)
    # qi-cert/1 verdict certificate (cert.py): witness evidence for false,
    # coverage ledger for true, provenance always.  Not part of `stats` so
    # the legacy --timing [stats] lines stay byte-compatible with
    # certificates enabled (CLI --cert-out writes it to disk).
    cert: Optional[Dict[str, object]] = None


def print_quorum(quorum: List[int], graph: TrustGraph, out: TextIO) -> None:
    """Verbose quorum dump — same information as the reference's
    ``printQuorum`` (cpp:475-490): per node its name, ID, top-level threshold
    and top-level validator IDs."""
    for v in quorum:
        q = graph.qsets[v]
        names = " ".join(graph.node_ids[v2] for v2 in q.members) if q.members else ""
        threshold = "null" if q.threshold is None else str(q.threshold)
        out.write(
            f"{graph.names[v]} {graph.node_ids[v]}\n"
            f"( quorumslice: threshold = {threshold} {names}{' ' if names else ''}) \n\n"
        )
    out.write("\n")


def solve_graph(
    graph: TrustGraph,
    *,
    backend: Union[str, SearchBackend] = "auto",
    verbose: bool = False,
    out: TextIO = sys.stdout,
    graphviz: bool = False,
    scc_select: str = "quorum-bearing",
    scope_to_scc: bool = False,
    circuit: Optional[Circuit] = None,
    timers: Optional[PhaseTimers] = None,
    with_cert: bool = True,
) -> SolveResult:
    """Decide quorum intersection for a built trust graph.

    ``with_cert=False`` skips qi-cert assembly (``SolveResult.cert`` stays
    None): for internal analytics probes that solve in a combinatorial
    loop (``analytics/splitting.py``), per-candidate certificate assembly
    and its ``cert.*`` telemetry are pure overhead — and the event spam
    would saturate the in-memory event cap that real certificates'
    provenance slices read from."""
    timers = timers or PhaseTimers()
    if isinstance(backend, str):
        backend = get_backend(backend)
    # qi-cert provenance anchor: the routing/degrade/calibration events of
    # THIS solve are the record slice from here to verdict (cert.py).
    rec = get_run_record()
    cert_ev0 = rec.event_count()

    # Per-SCC quorum scan (cpp:645-672): which SCCs, restricted to themselves,
    # contain a quorum?  All minimal quorums live inside some SCC.
    allow_native_scan = getattr(backend, "name", "") != "python"
    count, sccs, quorum_scc_ids, scc_quorums, main_scc = _classify_sccs(
        graph, allow_native=allow_native_scan, scc_select=scc_select,
        timers=timers,
    )

    if graphviz:
        from quorum_intersection_tpu.analytics.graphviz import write_graphviz_sccs

        write_graphviz_sccs(graph, sccs, out)

    if verbose:
        out.write(f"total number of strongly connected components: {count}\n")
    log.debug("%d strongly connected components; scanning for quorums", count)
    for sid in quorum_scc_ids:
        log.debug(
            "scc %d (size %d) contains a quorum (size %d)",
            sid, len(sccs[sid]), len(scc_quorums[sid]),
        )
        if verbose:
            out.write("found quorum inside of a strongly connected component:\n")
            print_quorum(scc_quorums[sid], graph, out)

    if verbose:
        out.write(
            f"number of strongly connected components containing some quorum: {len(quorum_scc_ids)}\n"
        )
        out.write(f"size of the main strongly connected component: {len(main_scc)}\n")
        out.write(
            "main strongly connected component (all minimal quorums are included in it; "
            "small size means small resilience of the network):\n"
        )
        print_quorum(main_scc, graph, out)

    if len(quorum_scc_ids) != 1:
        # Guard (cpp:681-688): zero quorum-bearing SCCs means no quorum at all;
        # two or more means two disjoint quorums exist across components.
        if verbose:
            out.write(
                "network's configuration is broken - more than one strongly connected "
                f"component contains a quorum - {len(quorum_scc_ids)}\n"
            )
        # The reference only narrates here (cpp:683-685); the API can do
        # better: with ≥2 quorum-bearing SCCs the per-SCC quorums are a
        # valid witness pair (SCCs are vertex-disjoint and the scan
        # restricts availability to members).  Zero quorum-bearing SCCs
        # means no quorum exists at all — no witness is possible.
        q1 = q2 = None
        if len(quorum_scc_ids) >= 2:
            q1 = scc_quorums[quorum_scc_ids[0]]
            q2 = scc_quorums[quorum_scc_ids[1]]
        return SolveResult(
            intersects=False,
            n_sccs=count,
            quorum_scc_ids=quorum_scc_ids,
            main_scc=main_scc,
            q1=q1,
            q2=q2,
            stats={"reason": "scc_guard"},
            timers=timers.summary(),
            cert=build_certificate(
                graph, intersects=False, reason="scc_guard",
                n_sccs=count, quorum_bearing=len(quorum_scc_ids),
                scc_select=scc_select, scope_to_scc=scope_to_scc,
                stats={"reason": "scc_guard"}, q1=q1, q2=q2,
                events=rec.events_since(cert_ev0),
            ) if with_cert else None,
        )

    # Backends that search on the host set-semantics directly (python, cpp via
    # CSR) advertise whether they read the dense circuit; skip the O(U·n + U²)
    # array build when nobody will consume it.
    if circuit is None and getattr(backend, "needs_circuit", True):
        with timers.phase("encode"):
            circuit = encode_circuit(graph)

    target_scc = sccs[0] if scc_select == "front" else sccs[quorum_scc_ids[0]]
    with timers.phase("search"):
        res = backend.check_scc(graph, circuit, target_scc, scope_to_scc=scope_to_scc)

    if verbose:
        if not res.intersects:
            out.write("found two non-intersecting quorums\n")
            out.write("first quorum:\n")
            print_quorum(res.q1 or [], graph, out)
            out.write("second quorum:\n")
            print_quorum(res.q2 or [], graph, out)
        else:
            out.write("all quorums are intersecting\n")

    return SolveResult(
        intersects=res.intersects,
        n_sccs=count,
        quorum_scc_ids=quorum_scc_ids,
        main_scc=main_scc,
        q1=res.q1,
        q2=res.q2,
        stats=dict(res.stats),
        timers=timers.summary(),
        cert=build_certificate(
            graph, intersects=res.intersects, reason="search",
            n_sccs=count, quorum_bearing=len(quorum_scc_ids),
            scc_select=scc_select, scope_to_scc=scope_to_scc,
            stats=res.stats, q1=res.q1, q2=res.q2,
            target_scc=target_scc,
            target_scc_index=(
                0 if scc_select == "front" else quorum_scc_ids[0]
            ),
            events=rec.events_since(cert_ev0),
        ) if with_cert else None,
    )


def check_many(
    sources: List[object],
    *,
    backend: Union[str, SearchBackend] = "auto",
    dangling: str = "strict",
    scc_select: str = "quorum-bearing",
    scope_to_scc: bool = False,
    pack: Optional[bool] = None,
    delta: Optional[Dict[str, object]] = None,
    scan: Optional[Callable[..., List[Optional[List[int]]]]] = None,
    cancels: Optional[Sequence[Optional[CancelToken]]] = None,
    origins: Optional[Sequence[str]] = None,
) -> List[SolveResult]:
    """Batch entry point (ISSUE 5): decide quorum intersection for MANY
    FBAS sources in one call — the shape heavy multi-snapshot traffic
    arrives in (ROADMAP north star), and the third pack-filling source of
    the lane-packed sweep.

    Each source runs the same parse → graph → SCC scan → guard pipeline as
    :func:`solve` (minus narration); guard-decided snapshots (zero or >= 2
    quorum-bearing SCCs) resolve immediately from the scan, and the rest
    become ONE batched backend call.  A backend exposing a ``check_sccs``
    batch entry (``auto``, ``tpu-sweep``) fuses sweep-sized problems into
    lane packs so queued snapshot requests fill full MXU tiles together;
    any other backend is called per problem.  Results come back in source
    order with per-source timers and the backend's stats.

    ``pack`` forwards to the auto router: None (default) engages packing
    only behind a measured calibration win, True forces it, False never
    packs.

    ``delta`` (qi-delta, ISSUE 9) is an optional provenance stamp the
    incremental re-analysis engine (``delta.py``) attaches when this batch
    is the *re-solve* leg of an incremental step: it rides every produced
    certificate as ``provenance.delta`` (cert.py) so composed and
    fresh-solved certificates are distinguishable downstream.  ``scan``
    substitutes the per-SCC scan provider (see :func:`_classify_sccs`) —
    the same engine passes its verdict-store-aware one so the re-solve leg
    still reuses every fingerprint-matched SCC's cached scan.

    ``cancels``/``origins`` (qi-fuse) are source-aligned: when the backend
    declares ``supports_job_cancels`` they ride into its batch entry so a
    fused pack can retire one request's lanes on that request's own
    deadline while its co-packed sources keep sweeping.  A cancelled
    source comes back as a PARTIAL result (``stats["cancelled"]``, no
    verdict-bearing certificate — just the exact cancelled-coverage
    ledger); callers route it as a deadline miss, never as a verdict.
    """
    caller_backend = not isinstance(backend, str)
    if isinstance(backend, str):
        options: Dict[str, object] = {}
        if pack is not None and backend == "auto":
            options["pack"] = pack
        backend = get_backend(backend, **options)

    results: List[Optional[SolveResult]] = [None] * len(sources)
    jobs: List[Tuple[int, TrustGraph, Optional[Circuit], List[int]]] = []
    metas: Dict[int, Tuple[int, List[int], List[int], Dict[str, float]]] = {}
    allow_native_scan = getattr(backend, "name", "") != "python"
    rec = get_run_record()
    for ix, source in enumerate(sources):
        timers = PhaseTimers()
        cert_ev0 = rec.event_count()
        with timers.phase("parse"):
            fbas = source if isinstance(source, Fbas) else parse_fbas(source)
        with timers.phase("graph"):
            graph = build_graph(fbas, dangling=dangling)
        count, sccs, quorum_scc_ids, scc_quorums, main_scc = _classify_sccs(
            graph, allow_native=allow_native_scan, scc_select=scc_select,
            timers=timers, scan=scan,
        )
        if len(quorum_scc_ids) != 1:
            # Guard-decided, exactly as solve_graph: >= 2 quorum-bearing
            # SCCs yield the scan's witness pair, zero means no quorum.
            q1 = q2 = None
            if len(quorum_scc_ids) >= 2:
                q1 = scc_quorums[quorum_scc_ids[0]]
                q2 = scc_quorums[quorum_scc_ids[1]]
            results[ix] = SolveResult(
                intersects=False, n_sccs=count,
                quorum_scc_ids=quorum_scc_ids, main_scc=main_scc,
                q1=q1, q2=q2, stats={"reason": "scc_guard"},
                timers=timers.summary(),
                cert=build_certificate(
                    graph, intersects=False, reason="scc_guard",
                    n_sccs=count, quorum_bearing=len(quorum_scc_ids),
                    scc_select=scc_select, scope_to_scc=scope_to_scc,
                    stats={"reason": "scc_guard"}, q1=q1, q2=q2,
                    events=rec.events_since(cert_ev0), batched=True,
                    delta=delta,
                ),
            )
            continue
        circuit: Optional[Circuit] = None
        if getattr(backend, "needs_circuit", True):
            with timers.phase("encode"):
                circuit = encode_circuit(graph)
        target_scc = sccs[0] if scc_select == "front" else sccs[quorum_scc_ids[0]]
        jobs.append((ix, graph, circuit, target_scc))
        metas[ix] = (count, quorum_scc_ids, main_scc, timers.summary())

    restore_pack: Tuple = ()
    if pack is not None and caller_backend and hasattr(backend, "pack"):
        # Caller-supplied backend: apply the override for THIS call only —
        # restored in the finally below, so a forced pack=True batch never
        # leaks into the caller's later (default-gated) calls.
        restore_pack = (backend, backend.pack)
        backend.pack = pack
    try:
        if jobs:
            # pack=False means NEVER packed, whatever the backend: a
            # backend without a pack knob (e.g. a bare TpuSweepBackend,
            # whose batch entry packs unconditionally) is dispatched
            # per-problem instead.
            batch = (
                None if pack is False and not hasattr(backend, "pack")
                else getattr(backend, "check_sccs", None)
            )
            t_search = time.perf_counter()
            # One provenance slice for the whole batch (qi-cert): a fused
            # pack's routing/degrade events cannot be attributed per job,
            # so every batched certificate carries the batch's slice with
            # `batched: true`.
            batch_ev0 = rec.event_count()
            # The batched search is one span (qi-trace): every job's route/
            # pack/native span of this batch nests under it, so the serving-
            # layer timeline shows "one request batch" as one block.
            with rec.span(
                "pipeline.check_many", sources=len(sources), jobs=len(jobs),
                batched=batch is not None,
            ):
                job_cancels = (
                    [cancels[ix] for ix, _, _, _ in jobs]
                    if cancels is not None else None
                )
                if batch is not None and (
                    job_cancels is not None or origins is not None
                ) and getattr(backend, "supports_job_cancels", False):
                    scc_results = batch(
                        [(g, c, s) for _, g, c, s in jobs],
                        scope_to_scc=scope_to_scc,
                        cancels=job_cancels,
                        origins=(
                            [origins[ix] for ix, _, _, _ in jobs]
                            if origins is not None else None
                        ),
                    )
                elif batch is not None:
                    scc_results = batch(
                        [(g, c, s) for _, g, c, s in jobs],
                        scope_to_scc=scope_to_scc,
                    )
                else:
                    scc_results = []
                    for jx, (_, g, c, s) in enumerate(jobs):
                        tok = (
                            job_cancels[jx] if job_cancels is not None
                            else None
                        )
                        if tok is not None and tok.cancelled:
                            # qi-fuse: dead request — book the whole window
                            # space as cancelled coverage instead of solving.
                            total = 1 << max(len(s) - 1, 0)
                            rec.add("cert.windows_cancelled", total)
                            scc_results.append(SccCheckResult(
                                intersects=False, stats={
                                    "backend": getattr(backend, "name", "?"),
                                    "cancelled": True,
                                    "candidates_checked": 0,
                                    "enumeration_total": total,
                                    "cert": {
                                        "window_space": total,
                                        "windows_enumerated": 0,
                                        "windows_pruned_guard": 0,
                                        "windows_skipped_pack_fill": 0,
                                        "windows_cancelled": total,
                                    },
                                },
                            ))
                            continue
                        scc_results.append(backend.check_scc(
                            g, c, s, scope_to_scc=scope_to_scc
                        ))
            search_s = time.perf_counter() - t_search
            batch_events = rec.events_since(batch_ev0)
            for (ix, graph, _, target_scc), res in zip(jobs, scc_results):
                count, quorum_scc_ids, main_scc, timer_summary = metas[ix]
                # The batched call is one shared phase: every job's timers
                # carry the SAME "search" wall (per-job attribution of a
                # fused pack is in res.stats["seconds"]), so solve-vs-
                # check_many phase comparisons see the dominant phase
                # instead of a silently absent one.
                timer_summary = dict(timer_summary)
                timer_summary["search"] = search_s
                if res.stats.get("cancelled"):
                    # qi-fuse: the request behind this source died mid-
                    # batch.  No verdict is claimed — the "cert" is an
                    # explicitly PARTIAL coverage record (the exact
                    # cancelled ledger), never a qi-cert verdict document,
                    # so nothing downstream can mistake it for one.
                    results[ix] = SolveResult(
                        intersects=res.intersects, n_sccs=count,
                        quorum_scc_ids=quorum_scc_ids, main_scc=main_scc,
                        q1=None, q2=None, stats=dict(res.stats),
                        timers=timer_summary,
                        cert={
                            "schema": CERT_SCHEMA,
                            "partial": True,
                            "verdict": None,
                            "reason": "cancelled",
                            "coverage": dict(res.stats.get("cert", {})),
                        },
                    )
                    continue
                results[ix] = SolveResult(
                    intersects=res.intersects, n_sccs=count,
                    quorum_scc_ids=quorum_scc_ids, main_scc=main_scc,
                    q1=res.q1, q2=res.q2, stats=dict(res.stats),
                    timers=timer_summary,
                    cert=build_certificate(
                        graph, intersects=res.intersects, reason="search",
                        n_sccs=count,
                        quorum_bearing=len(quorum_scc_ids),
                        scc_select=scc_select, scope_to_scc=scope_to_scc,
                        stats=res.stats, q1=res.q1, q2=res.q2,
                        target_scc=target_scc,
                        target_scc_index=(
                            0 if scc_select == "front"
                            else quorum_scc_ids[0]
                        ),
                        events=batch_events, batched=True, delta=delta,
                    ),
                )
    finally:
        if restore_pack:
            restore_pack[0].pack = restore_pack[1]
    return [r for r in results if r is not None]


def solve(
    source: Union[str, bytes, List[Dict[str, object]], Fbas],
    *,
    backend: Union[str, SearchBackend] = "auto",
    dangling: str = "strict",
    verbose: bool = False,
    out: TextIO = sys.stdout,
    graphviz: bool = False,
    scc_select: str = "quorum-bearing",
    scope_to_scc: bool = False,
    with_cert: bool = True,
) -> SolveResult:
    """Full pipeline from JSON (stream/str/list) or a parsed :class:`Fbas` —
    parity with the reference's ``solve(istream&)`` overload (cpp:709-716)."""
    timers = PhaseTimers()
    with timers.phase("parse"):
        fbas = source if isinstance(source, Fbas) else parse_fbas(source)
    with timers.phase("graph"):
        graph = build_graph(fbas, dangling=dangling)
    return solve_graph(
        graph,
        backend=backend,
        verbose=verbose,
        out=out,
        graphviz=graphviz,
        scc_select=scc_select,
        scope_to_scc=scope_to_scc,
        timers=timers,
        with_cert=with_cert,
    )
