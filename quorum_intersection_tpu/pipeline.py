"""Orchestration: parse → graph → SCC reduction → guard → backend search.

Capability parity with the reference's ``solve`` drivers
(`/root/reference/quorum_intersection.cpp:615-716`), with the Q5 fix
(SURVEY.md §2.3): the exponential search runs in **the** quorum-bearing SCC,
not blindly ``sccs.front()``.  When the guard passes (exactly one SCC contains
a quorum) the two coincide on every Stellar-like topology — and on all bundled
fixtures [verified] — but ``front()`` could silently return a vacuous ``true``
if Tarjan numbering ever put the quorum-bearing SCC elsewhere;
``scc_select="front"`` reproduces the reference choice for differential runs.

Verbose narration mirrors the reference's ``-v`` messages (cpp:640, :662-664,
:673-679, :683-685, :693-697, :702-704).
"""

from __future__ import annotations

import sys
from dataclasses import dataclass, field
from typing import Dict, List, Optional, TextIO, Tuple, Union

from quorum_intersection_tpu.backends.base import SearchBackend, get_backend
from quorum_intersection_tpu.encode.circuit import Circuit, encode_circuit
from quorum_intersection_tpu.fbas.graph import TrustGraph, build_graph, group_sccs, tarjan_scc
from quorum_intersection_tpu.fbas.schema import Fbas, parse_fbas
from quorum_intersection_tpu.fbas.semantics import max_quorum
from quorum_intersection_tpu.utils.logging import get_logger
from quorum_intersection_tpu.utils.timers import PhaseTimers

log = get_logger("pipeline")


# Above this vertex count the per-SCC quorum scan routes to the native
# oracle's `qi_max_quorum` (C speed) instead of N interpreted-Python
# fixpoints; below it the Python loop is already sub-millisecond and small
# CLI runs stay free of any compile dependency.
NATIVE_SCAN_LIMIT = 256


def scan_scc_quorums(
    graph: TrustGraph, sccs: List[List[int]], *, allow_native: bool = True
) -> List[List[int]]:
    """One max-quorum per SCC, restricted to its members (cpp:645-672).

    Big graphs use the native scan (same semantics, ~100× the interpreted
    loop; VERDICT r1 §weak-7); failures degrade to the Python loop.
    ``allow_native=False`` keeps everything interpreted — set when the user
    explicitly chose the pure-Python backend, whose point is zero native
    dependencies."""
    if allow_native and graph.n > NATIVE_SCAN_LIMIT:
        try:
            from quorum_intersection_tpu.backends.cpp import native_scc_scan

            return native_scc_scan(graph, sccs)
        except Exception as exc:  # noqa: BLE001 — no g++ etc.
            log.info("native SCC scan unavailable (%s); using Python scan", exc)
    quorums: List[List[int]] = []
    for members in sccs:
        avail = [False] * graph.n
        for v in members:
            avail[v] = True
        quorums.append(max_quorum(graph, members, avail))
    return quorums


def quorum_bearing_sccs(
    graph: TrustGraph, *, allow_native: bool = True
) -> List[Tuple[int, List[int]]]:
    """``[(scc_id, members), ...]`` for every SCC that contains a quorum
    when restricted to itself — the shared scaffolding of the CLI analysis
    modes (top tier, blocking/splitting sets)."""
    count, comp = tarjan_scc(graph.n, graph.succ)
    sccs = group_sccs(graph.n, comp, count)
    return [
        (sid, sccs[sid])
        for sid, quorum in enumerate(
            scan_scc_quorums(graph, sccs, allow_native=allow_native)
        )
        if quorum
    ]


@dataclass
class SolveResult:
    intersects: bool
    n_sccs: int = 0
    quorum_scc_ids: List[int] = field(default_factory=list)
    main_scc: List[int] = field(default_factory=list)
    q1: Optional[List[int]] = None
    q2: Optional[List[int]] = None
    stats: Dict[str, object] = field(default_factory=dict)
    timers: Dict[str, float] = field(default_factory=dict)


def print_quorum(quorum: List[int], graph: TrustGraph, out: TextIO) -> None:
    """Verbose quorum dump — same information as the reference's
    ``printQuorum`` (cpp:475-490): per node its name, ID, top-level threshold
    and top-level validator IDs."""
    for v in quorum:
        q = graph.qsets[v]
        names = " ".join(graph.node_ids[v2] for v2 in q.members) if q.members else ""
        threshold = "null" if q.threshold is None else str(q.threshold)
        out.write(
            f"{graph.names[v]} {graph.node_ids[v]}\n"
            f"( quorumslice: threshold = {threshold} {names}{' ' if names else ''}) \n\n"
        )
    out.write("\n")


def solve_graph(
    graph: TrustGraph,
    *,
    backend: Union[str, SearchBackend] = "auto",
    verbose: bool = False,
    out: TextIO = sys.stdout,
    graphviz: bool = False,
    scc_select: str = "quorum-bearing",
    scope_to_scc: bool = False,
    circuit: Optional[Circuit] = None,
    timers: Optional[PhaseTimers] = None,
) -> SolveResult:
    """Decide quorum intersection for a built trust graph."""
    timers = timers or PhaseTimers()
    if isinstance(backend, str):
        backend = get_backend(backend)

    with timers.phase("scc"):
        count, comp = tarjan_scc(graph.n, graph.succ)
        sccs = group_sccs(graph.n, comp, count)

    if graphviz:
        from quorum_intersection_tpu.analytics.graphviz import write_graphviz_sccs

        write_graphviz_sccs(graph, sccs, out)

    if verbose:
        out.write(f"total number of strongly connected components: {count}\n")

    # Per-SCC quorum scan (cpp:645-672): which SCCs, restricted to themselves,
    # contain a quorum?  All minimal quorums live inside some SCC.
    quorum_scc_ids: List[int] = []
    scc_quorums: Dict[int, List[int]] = {}
    log.debug("%d strongly connected components; scanning for quorums", count)
    allow_native_scan = getattr(backend, "name", "") != "python"
    with timers.phase("scc_scan"):
        for sid, quorum in enumerate(
            scan_scc_quorums(graph, sccs, allow_native=allow_native_scan)
        ):
            if quorum:
                quorum_scc_ids.append(sid)
                scc_quorums[sid] = quorum
                log.debug(
                    "scc %d (size %d) contains a quorum (size %d)",
                    sid, len(sccs[sid]), len(quorum),
                )
                if verbose:
                    out.write("found quorum inside of a strongly connected component:\n")
                    print_quorum(quorum, graph, out)

    # "Main" SCC: the reference labels sccs.front() the main component
    # (cpp:675-678) — that is the *sink*, not the largest (Q8).  With the Q5
    # fix the main component is the quorum-bearing one when unique.
    if scc_select == "front" or not quorum_scc_ids:
        main_scc = sccs[0] if sccs else []
    else:
        main_scc = sccs[quorum_scc_ids[0]]

    if verbose:
        out.write(
            f"number of strongly connected components containing some quorum: {len(quorum_scc_ids)}\n"
        )
        out.write(f"size of the main strongly connected component: {len(main_scc)}\n")
        out.write(
            "main strongly connected component (all minimal quorums are included in it; "
            "small size means small resilience of the network):\n"
        )
        print_quorum(main_scc, graph, out)

    if len(quorum_scc_ids) != 1:
        # Guard (cpp:681-688): zero quorum-bearing SCCs means no quorum at all;
        # two or more means two disjoint quorums exist across components.
        if verbose:
            out.write(
                "network's configuration is broken - more than one strongly connected "
                f"component contains a quorum - {len(quorum_scc_ids)}\n"
            )
        # The reference only narrates here (cpp:683-685); the API can do
        # better: with ≥2 quorum-bearing SCCs the per-SCC quorums are a
        # valid witness pair (SCCs are vertex-disjoint and the scan
        # restricts availability to members).  Zero quorum-bearing SCCs
        # means no quorum exists at all — no witness is possible.
        q1 = q2 = None
        if len(quorum_scc_ids) >= 2:
            q1 = scc_quorums[quorum_scc_ids[0]]
            q2 = scc_quorums[quorum_scc_ids[1]]
        return SolveResult(
            intersects=False,
            n_sccs=count,
            quorum_scc_ids=quorum_scc_ids,
            main_scc=main_scc,
            q1=q1,
            q2=q2,
            stats={"reason": "scc_guard"},
            timers=timers.summary(),
        )

    # Backends that search on the host set-semantics directly (python, cpp via
    # CSR) advertise whether they read the dense circuit; skip the O(U·n + U²)
    # array build when nobody will consume it.
    if circuit is None and getattr(backend, "needs_circuit", True):
        with timers.phase("encode"):
            circuit = encode_circuit(graph)

    target_scc = sccs[0] if scc_select == "front" else sccs[quorum_scc_ids[0]]
    with timers.phase("search"):
        res = backend.check_scc(graph, circuit, target_scc, scope_to_scc=scope_to_scc)

    if verbose:
        if not res.intersects:
            out.write("found two non-intersecting quorums\n")
            out.write("first quorum:\n")
            print_quorum(res.q1 or [], graph, out)
            out.write("second quorum:\n")
            print_quorum(res.q2 or [], graph, out)
        else:
            out.write("all quorums are intersecting\n")

    return SolveResult(
        intersects=res.intersects,
        n_sccs=count,
        quorum_scc_ids=quorum_scc_ids,
        main_scc=main_scc,
        q1=res.q1,
        q2=res.q2,
        stats=dict(res.stats),
        timers=timers.summary(),
    )


def solve(
    source,
    *,
    backend: Union[str, SearchBackend] = "auto",
    dangling: str = "strict",
    verbose: bool = False,
    out: TextIO = sys.stdout,
    graphviz: bool = False,
    scc_select: str = "quorum-bearing",
    scope_to_scc: bool = False,
) -> SolveResult:
    """Full pipeline from JSON (stream/str/list) or a parsed :class:`Fbas` —
    parity with the reference's ``solve(istream&)`` overload (cpp:709-716)."""
    timers = PhaseTimers()
    with timers.phase("parse"):
        fbas = source if isinstance(source, Fbas) else parse_fbas(source)
    with timers.phase("graph"):
        graph = build_graph(fbas, dangling=dangling)
    return solve_graph(
        graph,
        backend=backend,
        verbose=verbose,
        out=out,
        graphviz=graphviz,
        scc_select=scc_select,
        scope_to_scc=scope_to_scc,
        timers=timers,
    )
