"""Safety-margin analytics: splitting sets of the quorum structure.

A set ``S`` of validators is **splitting** when tolerating ``S`` as
byzantine leaves two disjoint quorums among the survivors — i.e. if the
members of ``S`` misbehave they can drive the network into divergence.
The size of a minimum splitting set is the standard safety-margin number
of an FBAS, complementing
:mod:`quorum_intersection_tpu.analytics.resilience`'s liveness number;
together with the intersection verdict they form the classic FBAS-analysis
triple.

Deletion follows the FBAS ``delete`` operation (byzantine semantics, not
crash semantics): removing ``S`` from a quorum set *decrements its
threshold* by the number of deleted members — byzantine nodes vote for
both sides, so they satisfy everyone's slices.  A (sub-)set whose
threshold reaches 0 becomes **trivially satisfiable**: a trivially
satisfiable inner set contributes its vote to the parent unconditionally
(encoded by dropping it and decrementing the parent threshold), and a
node whose whole slice becomes trivial is encoded as ``1-of-[self]`` —
satisfiable whenever the node itself is available (quirk Q4 makes that
exactly "always").

A candidate is splitting only when the reduced FBAS exhibits an actual
disjoint-quorum WITNESS (``q1``/``q2``); a reduced FBAS with *no* quorum
at all is a halt — that is a blocking set's signature, not a split.

Each candidate check is a full intersection solve of the reduced FBAS
(deletion changes the SCC structure, so nothing short of the whole
pipeline is sound) — NP-hard per check, so the exact search is doubly
capped: candidate pool ≤ :data:`POOL_LIMIT` and subset size ≤ ``max_k``.
Minimal splitting sets live inside the quorum-bearing SCCs (deleting a
node no quorum uses cannot create a disjoint pair), which keeps the pool
small on snapshot-shaped networks.
"""

from __future__ import annotations

from itertools import combinations
from typing import List, Optional, Sequence, Tuple

# Candidate pool cap: C(pool, k) solves is the cost envelope.
POOL_LIMIT = 22
DEFAULT_MAX_K = 2


def _scrub(qset, removed: frozenset) -> Tuple[Optional[dict], bool]:
    """FBAS ``delete`` on one quorum set: returns ``(qset', trivial)``
    where ``trivial`` means the set BECAME trivially satisfiable through
    deletions.  A threshold that was ≤ 0 to begin with keeps the pinned Q3
    semantics (never satisfiable) — only deletion-driven drops flip it."""
    if not isinstance(qset, dict):
        return qset, False
    t = qset.get("threshold")
    if isinstance(t, str):
        # ptree compat: the schema accepts numeric-string thresholds
        # (schema.py); mirror it or deletion silently degrades.
        try:
            t = int(t)
        except ValueError:
            return qset, False  # malformed: leave for the schema to reject
    if not isinstance(t, int) or isinstance(t, bool):
        return qset, False  # malformed: leave for the schema to reject
    if t <= 0:
        return qset, False  # Q3: degenerate threshold stays unsatisfiable
    validators = [v for v in (qset.get("validators") or []) if v not in removed]
    t -= len(qset.get("validators") or []) - len(validators)
    inner: List[dict] = []
    for child in qset.get("innerQuorumSets") or []:
        scrubbed, trivial = _scrub(child, removed)
        if trivial:
            t -= 1  # the child now votes unconditionally
        else:
            inner.append(scrubbed)
    if t <= 0:
        return None, True
    return {"threshold": t, "validators": validators, "innerQuorumSets": inner}, False


def delete_nodes(nodes: Sequence[dict], removed_keys: Sequence[str]) -> List[dict]:
    """The FBAS ``delete`` operation over a raw stellarbeat node list."""
    removed = frozenset(removed_keys)
    out = []
    for node in nodes:
        key = node.get("publicKey")
        if key in removed:
            continue
        q = node.get("quorumSet")
        if q is None:
            out.append(dict(node))
            continue
        scrubbed, trivial = _scrub(q, removed)
        if trivial:
            # Whole slice satisfied by byzantine votes: the node is happy in
            # any quorum containing itself (Q4 supplies the availability).
            scrubbed = {"threshold": 1, "validators": [key], "innerQuorumSets": []}
        out.append({**node, "quorumSet": scrubbed})
    return out


def is_splitting(
    nodes: Sequence[dict], removed_keys: Sequence[str], dangling: str = "strict"
) -> bool:
    """True iff deleting ``removed_keys`` (byzantine semantics) leaves two
    disjoint quorums — witnessed, not merely a failed verdict.  ``dangling``
    follows the caller's Q1 policy so the analysis answers the same FBAS
    as the verdict under the same flags.

    Since qi-cert (ISSUE 7) the witness requirement is checked by the
    certificate layer's per-member slice-satisfaction audit instead of a
    bare ``q1 is not None``: a candidate counts as splitting only when
    every member of BOTH claimed quorums is actually satisfied — the same
    audit ``tools/check_cert.py`` performs, so the analytics and the
    checker cannot disagree about what a witness is.  The solve itself
    runs with ``with_cert=False``: this function sits in
    :func:`minimum_splitting_set`'s combinatorial loop, so per-candidate
    certificate assembly and ``cert.*`` telemetry would be pure overhead
    — the evidence is computed directly, and only for the rare candidate
    whose verdict is actually false."""
    from quorum_intersection_tpu.cert import witness_evidence
    from quorum_intersection_tpu.fbas.graph import build_graph
    from quorum_intersection_tpu.fbas.schema import parse_fbas
    from quorum_intersection_tpu.pipeline import solve

    remaining = delete_nodes(nodes, removed_keys)
    if not remaining:
        return False
    res = solve(remaining, backend="python", dangling=dangling,
                with_cert=False)
    if res.intersects or res.q1 is None or res.q2 is None:
        return False
    # Same deterministic front end the solve ran (res.q1/q2 are vertex
    # indices of this graph), audited member-by-member.
    graph = build_graph(parse_fbas(list(remaining)), dangling=dangling)
    members = [*witness_evidence(graph, res.q1),
               *witness_evidence(graph, res.q2)]
    return bool(members) and all(m["satisfied"] for m in members)


def quorum_scc_keys(nodes: Sequence[dict], dangling: str = "strict") -> List[str]:
    """publicKeys of every quorum-bearing SCC's members — the candidate
    pool for splitting-set search."""
    from quorum_intersection_tpu.fbas.graph import build_graph, group_sccs, tarjan_scc
    from quorum_intersection_tpu.fbas.schema import parse_fbas
    from quorum_intersection_tpu.pipeline import scan_scc_quorums

    graph = build_graph(parse_fbas(list(nodes)), dangling=dangling)
    count, comp = tarjan_scc(graph.n, graph.succ)
    sccs = group_sccs(graph.n, comp, count)
    keys: List[str] = []
    for sid, quorum in enumerate(scan_scc_quorums(graph, sccs)):
        if quorum:
            keys.extend(graph.node_ids[v] for v in sccs[sid])
    return keys


def minimum_splitting_set(
    nodes: Sequence[dict],
    max_k: int = DEFAULT_MAX_K,
    dangling: str = "strict",
    pool: Optional[Sequence[str]] = None,
) -> Optional[List[str]]:
    """Smallest splitting set with ≤ ``max_k`` members, searching subsets
    of the quorum-bearing SCCs; None when no such set exists within the
    caps (caller distinguishes "safe up to k" from "pool too large" via
    :func:`quorum_scc_keys`).  k = 0 (the FBAS is already split) returns
    ``[]``.  Pass ``pool`` (e.g. from an already-built graph) to skip the
    internal front-end pass."""
    if pool is None:
        pool = quorum_scc_keys(nodes, dangling=dangling)
    if len(pool) > POOL_LIMIT:
        return None
    for k in range(0, max_k + 1):
        for combo in combinations(pool, k):
            if is_splitting(nodes, combo, dangling=dangling):
                return list(combo)
    return None
