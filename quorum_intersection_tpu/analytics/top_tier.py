"""Top-tier analytics: the union of all minimal quorums' members.

The **top tier** of an FBAS is the set of validators that appear in at
least one minimal quorum — the nodes whose configuration actually shapes
consensus (everyone else piggybacks on them).  Third member of the
analysis suite around the verdict, with
:mod:`~quorum_intersection_tpu.analytics.resilience` (liveness) and
:mod:`~quorum_intersection_tpu.analytics.splitting` (safety margin).

Computed by the same branch-and-bound the verdict engines use, with two
deliberate differences (see ``qi_top_tier`` in
``backends/cpp/qi_oracle.cpp``): the half-size prune is DISABLED (it is
sound only for the disjointness search — minimal quorums larger than
⌊|scc|/2⌋ exist and belong in the union), and the visitor collects
members instead of probing for a disjoint partner.  Enumeration is
exponential in the worst case, so a B&B call budget bounds the work;
exceeding it reports "not computed" rather than a partial answer.
"""

from __future__ import annotations

import sys
from typing import List, Optional, Sequence, Tuple

from quorum_intersection_tpu.fbas.graph import TrustGraph
from quorum_intersection_tpu.utils.logging import get_logger

log = get_logger("analytics.top_tier")

# ~2 s of native enumeration at the measured ~1 µs/call; the CLI surfaces
# a "not computed" line beyond it rather than running unbounded.
DEFAULT_BUDGET_CALLS = 2_000_000


def top_tier(
    graph: TrustGraph,
    scc: Sequence[int],
    budget_calls: int = DEFAULT_BUDGET_CALLS,
) -> Tuple[Optional[List[int]], int]:
    """``(members, minimal_quorum_count)`` for the SCC; members is None
    when the enumeration exceeded ``budget_calls`` (count is then the
    partial tally).  Native enumeration with a pure-Python fallback."""
    try:
        from quorum_intersection_tpu.backends.cpp import native_top_tier

        return native_top_tier(graph, list(scc), budget_calls)
    except Exception as exc:  # noqa: BLE001 — no g++ etc.
        log.info("native top-tier unavailable (%s); using Python enumeration", exc)
    # The budget is calibrated for native speed (~1 µs/call); the
    # interpreted recursion is ~40× slower per call (the auto router's
    # measured ORACLE_SECONDS_PER_CALL ratio), so scale it down to keep
    # the same wall-clock bound.
    from quorum_intersection_tpu.backends.auto import ORACLE_SECONDS_PER_CALL

    ratio = ORACLE_SECONDS_PER_CALL["python"] / ORACLE_SECONDS_PER_CALL["cpp"]
    return _python_top_tier(graph, scc, max(int(budget_calls / ratio), 1))


def _python_top_tier(
    graph: TrustGraph, scc: Sequence[int], budget_calls: int
) -> Tuple[Optional[List[int]], int]:
    from quorum_intersection_tpu.backends.python_oracle import (
        _SearchState,
        iterate_minimal_quorums,
    )

    union: set = set()
    count = [0]

    def visitor(quorum: List[int]) -> bool:
        union.update(quorum)
        count[0] += 1
        return False  # keep enumerating

    state = _SearchState(budget_calls=budget_calls)
    needed = 4 * len(scc) + 1000
    old_limit = sys.getrecursionlimit()
    if needed > old_limit:
        sys.setrecursionlimit(needed)
    try:
        iterate_minimal_quorums(
            list(scc), [], graph, visitor,
            lambda _candidate: False,  # half-size prune disabled
            state, None,
        )
    finally:
        if needed > old_limit:
            sys.setrecursionlimit(old_limit)
    # The python oracle counts minimal quorums in state; the visitor tally
    # must agree — trust the visitor (it owns the union).
    if state.budget_exceeded:
        return None, count[0]
    return sorted(union), count[0]
