"""Graphviz export with SCC coloring — capability of the reference's
``printGraphvizWithSccs`` (`/root/reference/quorum_intersection.cpp:492-530`):

- node fill color ``#%06x`` computed as ``(0xFFFFFF // scc_count) * scc_index``
  (cpp:498, :505) — a crude but deterministic palette;
- label is the node name, falling back to the publicKey (cpp:507);
- white font (cpp:509);
- one edge line per edge occurrence (parallel edges preserved).
"""

from __future__ import annotations

from typing import List, TextIO

from quorum_intersection_tpu.fbas.graph import TrustGraph


def _escape(s: str) -> str:
    return s.replace("\\", "\\\\").replace('"', '\\"')


def write_graphviz_sccs(graph: TrustGraph, sccs: List[List[int]], out: TextIO) -> None:
    colors = [0] * graph.n
    for scc_index, members in enumerate(sccs):
        for v in members:
            colors[v] = scc_index
    offset = 0xFFFFFF // max(len(sccs), 1)
    out.write("digraph G {\n")
    for v in range(graph.n):
        color = f"{offset * colors[v]:06x}"
        label = _escape(graph.label(v))
        out.write(
            f'{v}[style=filled color="#{color}" label="{label}" fontcolor="white"];\n'
        )
    for v, targets in enumerate(graph.succ):
        for w in targets:
            out.write(f"{v}->{w} ;\n")
    out.write("}\n")
