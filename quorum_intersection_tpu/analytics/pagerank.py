"""Trust-graph PageRank — power iteration as one dense jnp matvec loop,
semantics-equivalent to the reference's custom variant
(`/root/reference/quorum_intersection.cpp:532-583`), which differs from
textbook PageRank in several pinned ways (SURVEY.md C15):

- initial mass 1 on **vertex 0** only (cpp:543), not uniform;
- per iteration every vertex gets base mass ``m / N`` (cpp:555-557) where
  ``m`` is the ``--dangling_factor`` (default 0.0001, *not* the classic 0.15);
- each vertex with out-degree > 0 sends ``(1-m)/outdeg · rank`` along **every**
  out-edge occurrence — parallel edges and self-loops count with multiplicity
  (Q7, cpp:561-570); dangling vertices simply leak their mass;
- the L1 convergence diff is computed on the **un-normalized** new vector
  (cpp:573-575), which is then normalized by the accumulated sum (cpp:576);
- stop at ``diff ≤ convergence`` or ``maxIterations`` (cpp:551).

The whole loop is a ``lax.while_loop`` over a dense (N, N) float32 count
matrix — a single fused matvec per iteration, trivially TPU-native.  Exact
float accumulation order differs from the C++ per-edge loop; agreement is to
float32 tolerance, pinned by differential tests against a pure-Python
re-model.
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np

from quorum_intersection_tpu.fbas.graph import TrustGraph


def adjacency_counts(graph: TrustGraph) -> np.ndarray:
    """Dense (N, N) float32 matrix: A[v, w] = multiplicity of edge v→w."""
    a = np.zeros((graph.n, graph.n), dtype=np.float32)
    for v, targets in enumerate(graph.succ):
        for w in targets:
            a[v, w] += 1.0
    return a


def pagerank_np(
    graph: TrustGraph,
    m: float = 0.0001,
    convergence: float = 0.0001,
    max_iterations: int = 100000,
) -> np.ndarray:
    """NumPy re-model of cpp:532-583 — the differential baseline for the JAX
    path and a dependency-light fallback."""
    n = graph.n
    if n == 0:
        return np.zeros(0, dtype=np.float32)
    a = adjacency_counts(graph)
    outdeg = a.sum(axis=1)
    rank = np.zeros(n, dtype=np.float32)
    rank[0] = 1.0
    m = np.float32(m)
    base = m / np.float32(n)
    diff = np.float32(convergence) + 1
    it = 0
    while diff > convergence and it < max_iterations:
        send = np.where(outdeg > 0, (1 - m) / np.maximum(outdeg, 1) * rank, 0.0).astype(
            np.float32
        )
        tmp = base + a.T @ send
        total = m + (outdeg * send).sum(dtype=np.float32)
        diff = np.abs(tmp - rank).sum(dtype=np.float32)
        rank = (tmp / total).astype(np.float32)
        it += 1
    return rank


def pagerank(
    graph: TrustGraph,
    m: float = 0.0001,
    convergence: float = 0.0001,
    max_iterations: int = 100000,
) -> np.ndarray:
    """JAX power iteration (jit + lax.while_loop); runs on TPU or CPU."""
    n = graph.n
    if n == 0:
        return np.zeros(0, dtype=np.float32)
    import jax
    import jax.numpy as jnp
    from jax import lax

    a = jnp.asarray(adjacency_counts(graph))
    outdeg = a.sum(axis=1)
    has_out = outdeg > 0
    inv_out = jnp.where(has_out, 1.0 / jnp.maximum(outdeg, 1.0), 0.0)
    mf = jnp.float32(m)
    base = mf / n
    conv = jnp.float32(convergence)

    def cond(carry):
        rank, diff, it = carry
        return jnp.logical_and(diff > conv, it < max_iterations)

    def body(carry):
        rank, _, it = carry
        send = (1 - mf) * inv_out * rank
        tmp = base + a.T @ send
        total = mf + jnp.sum(outdeg * send)
        diff = jnp.sum(jnp.abs(tmp - rank))
        return tmp / total, diff, it + 1

    rank0 = jnp.zeros(n, dtype=jnp.float32).at[0].set(1.0)
    init = (rank0, conv + 1, jnp.int32(0))
    rank, _, _ = jax.jit(lambda c: lax.while_loop(cond, body, c))(init)
    return np.asarray(rank)


def sorted_ranks(graph: TrustGraph, ranks: np.ndarray) -> List[Tuple[str, float]]:
    """Sort descending by rank, ties ascending by label (cpp:601-608)."""
    pairs = [(graph.label(v), float(ranks[v])) for v in range(graph.n)]
    return sorted(pairs, key=lambda p: (-p[1], p[0]))


def format_pagerank(graph: TrustGraph, ranks: np.ndarray) -> str:
    """``label: value`` lines under a ``PageRank:`` header (cpp:585-613, :731)."""
    lines = ["PageRank:"]
    for label, value in sorted_ranks(graph, ranks):
        lines.append(f"{label}: {value:g}")
    return "\n".join(lines) + "\n"
