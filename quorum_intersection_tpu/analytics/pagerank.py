"""Trust-graph PageRank — power iteration, semantics-equivalent to the
reference's custom variant (`/root/reference/quorum_intersection.cpp:532-583`),
which differs from textbook PageRank in several pinned ways (SURVEY.md C15):

- initial mass 1 on **vertex 0** only (cpp:543), not uniform;
- per iteration every vertex gets base mass ``m / N`` (cpp:555-557) where
  ``m`` is the ``--dangling_factor`` (default 0.0001, *not* the classic 0.15);
- each vertex with out-degree > 0 sends ``(1-m)/outdeg · rank`` along **every**
  out-edge occurrence — parallel edges and self-loops count with multiplicity
  (Q7, cpp:561-570); dangling vertices simply leak their mass;
- the L1 convergence diff is computed on the **un-normalized** new vector
  (cpp:573-575), which is then normalized by the accumulated sum (cpp:576);
- stop at ``diff ≤ convergence`` or ``maxIterations`` (cpp:551).

Two matvec representations behind one API, selected by graph size:

- **dense** (n ≤ ``DENSE_LIMIT``): an (N, N) float32 count matrix, one fused
  matvec per iteration — the fastest shape for the MXU at snapshot scale;
- **sparse** (n > ``DENSE_LIMIT``): per-edge COO arrays with a segment-sum
  scatter-add matvec — O(E) memory, so a full stellarbeat nodes dump
  (thousands of mostly-sparse vertices) never materializes an O(N²) matrix.

Exact float accumulation order differs between representations and from the
C++ per-edge loop; agreement is to float32 tolerance, pinned by differential
tests (``tests/test_pagerank.py``).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from quorum_intersection_tpu.fbas.graph import TrustGraph

# Above this vertex count the O(N²) dense count matrix is replaced by the
# O(E) edge-list representation (VERDICT r1 §missing-4).
DENSE_LIMIT = 512


def adjacency_counts(graph: TrustGraph) -> np.ndarray:
    """Dense (N, N) float32 matrix: A[v, w] = multiplicity of edge v→w."""
    a = np.zeros((graph.n, graph.n), dtype=np.float32)
    for v, targets in enumerate(graph.succ):
        for w in targets:
            a[v, w] += 1.0
    return a


def edge_arrays(graph: TrustGraph) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """COO edge arrays ``(src, dst, outdeg)`` with multiplicity preserved —
    one entry per edge occurrence (Q7), so the scatter-add matvec counts
    parallel edges exactly like the dense matrix and the reference's per-edge
    loop (cpp:561-570)."""
    n_edges = graph.n_edges
    src = np.empty(n_edges, dtype=np.int32)
    dst = np.empty(n_edges, dtype=np.int32)
    outdeg = np.zeros(graph.n, dtype=np.float32)
    k = 0
    for v, targets in enumerate(graph.succ):
        outdeg[v] = len(targets)
        for w in targets:
            src[k] = v
            dst[k] = w
            k += 1
    return src, dst, outdeg


def _use_dense(graph: TrustGraph, dense: Optional[bool]) -> bool:
    return graph.n <= DENSE_LIMIT if dense is None else dense


def pagerank_np(
    graph: TrustGraph,
    m: float = 0.0001,
    convergence: float = 0.0001,
    max_iterations: int = 100000,
    dense: Optional[bool] = None,
) -> np.ndarray:
    """NumPy re-model of cpp:532-583 — the differential baseline for the JAX
    path and a dependency-light fallback.  ``dense=None`` selects the
    representation by graph size."""
    n = graph.n
    if n == 0:
        return np.zeros(0, dtype=np.float32)
    if _use_dense(graph, dense):
        a = adjacency_counts(graph)
        outdeg = a.sum(axis=1)

        def matvec(send: np.ndarray) -> np.ndarray:
            return a.T @ send
    else:
        src, dst, outdeg = edge_arrays(graph)

        def matvec(send: np.ndarray) -> np.ndarray:
            return np.bincount(dst, weights=send[src], minlength=n).astype(np.float32)

    rank = np.zeros(n, dtype=np.float32)
    rank[0] = 1.0
    m = np.float32(m)
    base = m / np.float32(n)
    diff = np.float32(convergence) + 1
    it = 0
    while diff > convergence and it < max_iterations:
        send = np.where(outdeg > 0, (1 - m) / np.maximum(outdeg, 1) * rank, 0.0).astype(
            np.float32
        )
        tmp = base + matvec(send)
        total = m + (outdeg * send).sum(dtype=np.float32)
        diff = np.abs(tmp - rank).sum(dtype=np.float32)
        rank = (tmp / total).astype(np.float32)
        it += 1
    return rank


def _jitted_power_loops():
    """Module-cached jitted loops (dense and sparse), **ladder-shaped**:
    operands are padded to the canonical :data:`PAD_LADDER` rung by
    :func:`pagerank` and the true sizes ride along as *traced* scalars,
    so one compile serves every graph in a rung bucket.  (The previous
    shape-specialized signature recompiled the while_loop program for
    every distinct graph size — a recompile hazard on the serve-drain
    hot path, flagged by ``tools/analyze`` pass 7.)

    Padding is inert by construction: padded rows/columns/edges carry
    zero out-degree and zero scatter weight, so they contribute exact
    ``0.0`` terms to every accumulation; the per-vertex base mass is
    masked to the true ``n`` vertices."""
    global _POWER_LOOPS
    if _POWER_LOOPS is not None:
        return _POWER_LOOPS
    import jax
    import jax.numpy as jnp
    from jax import lax

    def loop(matvec, outdeg_j, mf, conv, max_iterations, n):
        inv_out = jnp.where(outdeg_j > 0, 1.0 / jnp.maximum(outdeg_j, 1.0), 0.0)
        base = mf / n.astype(jnp.float32)
        # 1.0 on the true n vertices, 0.0 on ladder padding: the base
        # mass lands only on real vertices (padded matvec/outdeg terms
        # are already exactly zero).
        mask = (jnp.arange(outdeg_j.shape[0]) < n).astype(jnp.float32)

        def cond(carry):
            rank, diff, it = carry
            return jnp.logical_and(diff > conv, it < max_iterations)

        def body(carry):
            rank, _, it = carry
            send = (1 - mf) * inv_out * rank
            tmp = (base + matvec(send)) * mask
            total = mf + jnp.sum(outdeg_j * send)
            diff = jnp.sum(jnp.abs(tmp - rank))
            return tmp / total, diff, it + 1

        rank0 = jnp.zeros(outdeg_j.shape[0], dtype=jnp.float32).at[0].set(1.0)
        rank, _, _ = lax.while_loop(cond, body, (rank0, conv + 1, jnp.int32(0)))
        return rank

    @jax.jit
    def dense(a, mf, conv, max_iterations, n):
        return loop(lambda s: a.T @ s, a.sum(axis=1), mf, conv,
                    max_iterations, n)

    @jax.jit
    def sparse(src, dst, outdeg_j, edge_mask, mf, conv, max_iterations, n):
        def matvec(send):
            return jnp.zeros(outdeg_j.shape[0], dtype=jnp.float32) \
                .at[dst].add(send[src] * edge_mask)

        return loop(matvec, outdeg_j, mf, conv, max_iterations, n)

    _POWER_LOOPS = (dense, sparse)
    return _POWER_LOOPS


_POWER_LOOPS = None


def pagerank(
    graph: TrustGraph,
    m: float = 0.0001,
    convergence: float = 0.0001,
    max_iterations: int = 100000,
    dense: Optional[bool] = None,
) -> np.ndarray:
    """JAX power iteration (jit + lax.while_loop); runs on TPU or CPU.

    Dense path: one matvec per iteration on the MXU.  Sparse path: gather +
    ``.at[dst].add`` segment-sum — O(E) work and memory per iteration.
    Vertex and edge counts round up to the canonical pad ladder
    (``encode/circuit.py``), so compiled program shapes collapse to one
    per rung bucket instead of one per exact graph size.
    """
    n = graph.n
    if n == 0:
        return np.zeros(0, dtype=np.float32)
    import jax.numpy as jnp

    from quorum_intersection_tpu.encode.circuit import ladder_up

    dense_fn, sparse_fn = _jitted_power_loops()
    mf = jnp.float32(m)
    conv = jnp.float32(convergence)
    max_it = jnp.int32(max_iterations)
    n_pad = ladder_up(n)
    n_true = jnp.int32(n)
    if _use_dense(graph, dense):
        a_np = adjacency_counts(graph)
        if n_pad != n:
            a_np = np.pad(a_np, ((0, n_pad - n), (0, n_pad - n)))
        rank = dense_fn(jnp.asarray(a_np), mf, conv, max_it, n_true)
    else:
        src_np, dst_np, outdeg_np = edge_arrays(graph)
        n_edges = len(src_np)
        e_pad = ladder_up(max(n_edges, 1))
        edge_mask = np.zeros(e_pad, dtype=np.float32)
        edge_mask[:n_edges] = 1.0
        src_p = np.zeros(e_pad, dtype=np.int32)
        src_p[:n_edges] = src_np
        dst_p = np.zeros(e_pad, dtype=np.int32)
        dst_p[:n_edges] = dst_np
        outdeg_p = np.pad(outdeg_np, (0, n_pad - n))
        rank = sparse_fn(
            jnp.asarray(src_p), jnp.asarray(dst_p), jnp.asarray(outdeg_p),
            jnp.asarray(edge_mask), mf, conv, max_it, n_true,
        )
    # qi-lint: allow(hygiene-host-sync) — the single sanctioned readback after convergence; one transfer per query
    return np.asarray(rank)[:n]



# Product-path engine selection: on the CPU platform the NumPy loop wins
# below this vertex count (no compile latency, sub-ms iterations); above it
# the compiled sparse matvec amortizes its ~1 s compile.  Accelerator
# platforms route by the edge floor below instead.
JAX_CPU_LIMIT = 1024
# Accelerator crossover, measured on the r3 chip
# (benchmarks/results/bench_full_r3_onchip.json): the fully-on-device power
# loop still pays ~one dispatch round-trip (77 ms warm) while the NumPy
# re-model finishes the 2,971-node / 14.4k-edge dump fixture in 3 ms — the
# device wins only once the host iteration cost clears the dispatch floor.
# Extrapolating the measured NumPy rate (~5 µs per k-edges per iteration
# set), that is ~50k+ edges.
ACCEL_MIN_EDGES = 50_000


def pagerank_auto(
    graph: TrustGraph,
    m: float = 0.0001,
    convergence: float = 0.0001,
    max_iterations: int = 100000,
) -> Tuple[np.ndarray, str]:
    """Latency-aware engine selection for the product path (CLI, bench).

    Routes by measured time-to-result, not platform pride: on accelerators
    the device power iteration wins only above ``ACCEL_MIN_EDGES`` (below
    it the dispatch round-trip alone exceeds the whole NumPy solve); on the
    CPU platform the vectorized XLA loop wins above ``JAX_CPU_LIMIT``
    nodes.  Device failures degrade to NumPy so ``--pagerank`` always
    yields output.  Returns ``(ranks, engine)``, engine in {"jax", "numpy"}."""
    from quorum_intersection_tpu.utils.platform import is_cpu_platform

    use_jax = (
        graph.n > JAX_CPU_LIMIT
        if is_cpu_platform()
        else graph.n_edges >= ACCEL_MIN_EDGES
    )
    if use_jax:
        try:
            return pagerank(graph, m, convergence, max_iterations), "jax"
        except Exception as exc:  # noqa: BLE001 — no jax / device init failure
            from quorum_intersection_tpu.utils.logging import get_logger

            get_logger("analytics.pagerank").warning(
                "device PageRank unavailable (%s); degrading to NumPy", exc
            )
    return pagerank_np(graph, m, convergence, max_iterations), "numpy"


def sorted_ranks(graph: TrustGraph, ranks: np.ndarray) -> List[Tuple[str, float]]:
    """Sort descending by rank, ties ascending by label (cpp:601-608)."""
    pairs = [(graph.label(v), float(ranks[v])) for v in range(graph.n)]
    return sorted(pairs, key=lambda p: (-p[1], p[0]))


def format_pagerank(graph: TrustGraph, ranks: np.ndarray) -> str:
    """``label: value`` lines under a ``PageRank:`` header (cpp:585-613, :731)."""
    lines = ["PageRank:"]
    for label, value in sorted_ranks(graph, ranks):
        lines.append(f"{label}: {value:g}")
    return "\n".join(lines) + "\n"
