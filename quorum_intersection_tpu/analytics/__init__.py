"""Analytics side modes: trust-graph PageRank and Graphviz export."""

from quorum_intersection_tpu.analytics.pagerank import pagerank, format_pagerank
from quorum_intersection_tpu.analytics.graphviz import write_graphviz_sccs

__all__ = ["pagerank", "format_pagerank", "write_graphviz_sccs"]
