"""Liveness-resilience analytics: blocking sets of the quorum structure.

Beyond the reference's feature set (it decides only quorum *intersection* —
safety): a set ``B`` of validators is **blocking** for the quorum-bearing
SCC when no quorum survives inside ``scc ∖ B`` — i.e. the network halts if
every member of ``B`` fails.  The size of a minimal blocking set is the
standard liveness-resilience number of an FBAS (how many node failures can
stop consensus), the dual of the safety question the verdict answers.

Built entirely on the pinned host semantics
(:func:`quorum_intersection_tpu.fbas.semantics.max_quorum` — the same
greatest-fixpoint the verdict engines use, cpp:140-177), so the analysis
inherits every quirk policy (Q2/Q3/Q4) without re-deciding them.

Exactness: :func:`minimal_blocking_set` returns an (inclusion-)**minimal**
blocking set via greedy shrinking — no proper subset of the result is
blocking — which upper-bounds the minimum-cardinality blocking set.  The
minimum itself is NP-hard (hitting set over minimal quorums);
:func:`minimum_blocking_size` does an exact subset search for small SCCs.
"""

from __future__ import annotations

from itertools import combinations
from typing import List, Optional, Sequence

from quorum_intersection_tpu.fbas.graph import TrustGraph
from quorum_intersection_tpu.fbas.semantics import max_quorum

# Exact minimum search is C(|scc|, k)-shaped; cap the SCC size so the CLI
# can never wander into hours of work (the greedy bound has no such limit).
EXACT_LIMIT = 22


def _has_quorum(graph: TrustGraph, members: Sequence[int], blocked: frozenset) -> bool:
    avail = [False] * graph.n
    alive = [v for v in members if v not in blocked]
    for v in alive:
        avail[v] = True
    return bool(max_quorum(graph, alive, avail))


def is_blocking(graph: TrustGraph, scc: Sequence[int], blocked: Sequence[int]) -> bool:
    """True iff no quorum survives in ``scc ∖ blocked`` (SCC-scoped
    availability — the principled scoping, cf. quirk Q6)."""
    return not _has_quorum(graph, scc, frozenset(blocked))


def minimal_blocking_set(graph: TrustGraph, scc: Sequence[int]) -> List[int]:
    """An inclusion-minimal blocking set for the SCC.

    Greedy shrink from the full SCC: drop any member whose removal keeps
    the set blocking, until no single member can be dropped.  Each step is
    one fixpoint, so the whole computation is O(|scc|²) fixpoints.  If the
    SCC holds no quorum at all, the empty set is (vacuously) blocking.
    """
    if is_blocking(graph, scc, ()):
        return []
    blocked = list(scc)
    # Drop higher-degree nodes last: keeping well-connected nodes in the
    # blocking set tends to free more droppable members (pure heuristic —
    # minimality of the RESULT does not depend on the order).
    indeg = graph.in_degrees()
    blocked.sort(key=lambda v: indeg[v])
    # One pass suffices: blocking is upward-monotone, so once dropping v
    # fails (a quorum survives in scc ∖ (blocked ∖ {v})), it fails against
    # every later, smaller blocked set too — a second pass can never drop
    # anything more.
    for v in list(blocked):
        trial = [w for w in blocked if w != v]
        if is_blocking(graph, scc, trial):
            blocked = trial
    return sorted(blocked)


def minimum_blocking_size(
    graph: TrustGraph,
    scc: Sequence[int],
    limit: Optional[int] = None,
    upper: Optional[int] = None,
) -> Optional[int]:
    """Exact minimum-cardinality blocking-set size, or None when |scc|
    exceeds the exact-search cap.  Searches k = 0, 1, 2, … over all
    C(|scc|, k) subsets; the greedy bound caps k so the loop always
    terminates at or below it.  Pass ``upper`` (e.g. the length of an
    already-computed :func:`minimal_blocking_set`) to skip the internal
    greedy pass."""
    cap = EXACT_LIMIT if limit is None else limit
    if len(scc) > cap:
        return None
    if upper is None:
        upper = len(minimal_blocking_set(graph, scc))
    for k in range(upper + 1):
        for combo in combinations(scc, k):
            if is_blocking(graph, scc, combo):
                return k
    return upper
