"""``python -m quorum_intersection_tpu`` — the CLI entry point."""

import sys

from quorum_intersection_tpu.cli import run

sys.exit(run())
