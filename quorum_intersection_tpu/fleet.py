"""qi-fleet/1 — a replicated serve tier (ISSUE 11 tentpole).

qi-serve (PR 8) made the verdict pipeline a long-lived service, but ONE
process on ONE stream; the ROADMAP's millions-of-users north star needs N
of them behaving like one.  This module is that tier, following
"Read-Write Quorum Systems Made Practical" (PAPERS.md, arXiv:2104.04102 —
quorum analysis operated as a continuously-queried, load-balanced service)
and quorum-keyed work distribution à la "Scaling Distributed All-Pairs
Algorithms" (arXiv:1608.05174):

- **Workers**: N :class:`~quorum_intersection_tpu.serve.ServeEngine`\\ s —
  subprocesses speaking the existing JSONL protocol over pipes
  (:class:`ProcWorker`, the production shape: ``python -m
  quorum_intersection_tpu serve --journal ... --emit-certs``) or
  in-process engines behind the identical duck-type (:class:`LocalWorker`
  — the schedule harness / test / bench-smoke shape).  Both answer in the
  exact wire shape ``serve_transport.ticket_response`` emits, so the
  front door cannot tell them apart.
- **Consistent-hash routing** (:class:`HashRing`): the front door keys on
  the *sanitized snapshot fingerprint* (``serve.snapshot_fingerprint``),
  so identical snapshots from any client coalesce fleet-wide through one
  worker's existing single-flight path, and join/leave moves only ~1/N of
  the key space (virtual nodes smooth the split).
- **Shared per-SCC verdict store**: every worker's ``SccVerdictStore``
  reads through to one :class:`~quorum_intersection_tpu.delta.SharedSccStore`
  directory (``QI_FLEET_STORE_DIR``, exported to each worker), so an SCC
  fragment solved on worker A composes into worker B's certificate — the
  fragments are SCC-local and coordinate-free (PR 10 proved transplant
  across key spaces), and the composed cert still passes the unmodified
  ``tools/check_cert.py``.
- **Journal-backed failover**: each worker keeps its own crash-only
  ``RequestJournal``; when health probes (or a broken pipe) declare a
  worker dead, the front door evicts it from the ring and replays its
  unfinished journal — every request re-routes to the peer inheriting its
  hash range, deduplicated against the front door's own in-flight tickets
  and the journal's ``done`` marks: **zero lost, zero duplicated**, the
  PR 8 ``kill -9`` guarantee extended to kill-one-of-N.
- **Degradation, not death** — four declared fault points
  (``fleet.route`` / ``fleet.probe`` / ``fleet.replay`` / ``fleet.store``,
  docs/ROBUSTNESS.md): a broken ring lookup falls back to the first live
  worker, an injected probe failure is inconclusive (never a spurious
  eviction), an unreadable dead journal degrades to re-routing the front
  door's own tickets, and a dead shared store tier degrades each worker
  to local-LRU-only — all loud, none a wrong verdict.

Telemetry: ``fleet.*`` spans/counters/gauges (docs/OBSERVABILITY.md §Fleet
registry); per-worker health rides the JSONL ``ping``/``pong`` probe and
aggregates into the front door's ``fleet.workers_live`` /
``fleet.ring_size`` / ``fleet.store_hit_pct`` gauges, which ``/healthz``
(utils/metrics_server.py) exposes; ``/readyz`` answers 503 until every
live worker finished journal replay (``fleet.replay_complete``).

CLI: ``python -m quorum_intersection_tpu fleet -n 4`` — same JSONL
stdin/stdout contract as ``serve``, requests fanned across the ring.
``benchmarks/serve.py --fleet`` is the closed-loop driver (aggregate
verdicts/sec, p99, fleet-wide cache hit %, ``delta_scc_reuse_pct`` under
zipfian churn at N ∈ {1, 2, 4}, with a kill-one-worker bench phase).
"""

from __future__ import annotations

import argparse
import base64
import bisect
import hashlib
import hmac
import json
import os
import random
import socket
import socketserver
import subprocess
import sys
import tempfile
import threading
import time
from dataclasses import dataclass
from pathlib import Path
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple, Union

from quorum_intersection_tpu.delta import STORE_SCHEMA, SharedSccStore
from quorum_intersection_tpu.fbas.graph import build_graph
from quorum_intersection_tpu.fbas.schema import Fbas, parse_fbas
from quorum_intersection_tpu.query import Query
from quorum_intersection_tpu.serve import (
    RequestJournal,
    ServeEngine,
    ServeError,
    ServeResponse,
    Ticket,
    _raw_nodes,
    snapshot_fingerprint,
)
from quorum_intersection_tpu.serve_transport import (
    MESH_PROTOCOL,
    PROTOCOL_SCHEMA,
    JsonlSession,
    fleet_token_digest,
    package_fingerprint,
    pong_payload,
    run_jsonl_loop,
    ticket_response,
)
from quorum_intersection_tpu.utils.env import (
    qi_env,
    qi_env_float,
    qi_env_int,
)
from quorum_intersection_tpu.utils.faults import FaultInjected, fault_point
from quorum_intersection_tpu.utils.logging import get_logger
from quorum_intersection_tpu.utils.telemetry import (
    Histogram,
    RunRecord,
    TraceContext,
    get_run_record,
)

log = get_logger("fleet")

FLEET_SCHEMA = "qi-fleet/1"

# Deterministic-interleaving hook (tools/analyze/schedules.py): a no-op in
# production; the schedule harness swaps in a SyncController to FORCE the
# routing/eviction/replay orderings the wall clock almost never produces —
# route-during-eviction, replay-races-new-request.
_fleet_sync: Callable[[str], None] = lambda point: None

# The fleet p50/p99 gauge window and nearest-rank estimator live with the
# Histogram primitive in utils/telemetry.py (ISSUE 15 dedupe) — the front
# door's pulse.fleet_e2e_ms histogram carries both the mergeable buckets
# and the bounded raw window those gauges derive from.


# ---- typed mesh errors (qi-mesh, ISSUE 19) ----------------------------------


class MeshHandshakeError(ServeError):
    """A join handshake the peer REFUSED with a typed ``hello_err``
    (protocol_mismatch / fingerprint_mismatch / bad_token): the mesh
    contract is a typed reject, never a silently skewed fleet — this is
    never retried, it propagates to the operator."""

    code = "mesh_handshake"

    def __init__(self, reject_code: str, message: str) -> None:
        self.reject_code = reject_code
        super().__init__(
            f"mesh join rejected ({reject_code}): {message}"
        )


class JournalUnreadableError(ServeError):
    """``adopt_journal`` was handed a path this host cannot read —
    missing, permission-denied, or (the common multi-host mistake) a path
    that only exists on a REMOTE peer's filesystem.  Typed so callers are
    routed to the mesh ship protocol (``serve --socket`` +
    ``fleet --join``: the journal streams over the wire, chunked +
    digest-checked + fsync-before-ack) instead of debugging a bare
    OSError."""

    code = "journal_unreadable"


# ---- consistent-hash ring ---------------------------------------------------


class HashRing:
    """Deterministic consistent-hash ring with virtual nodes.

    Each worker owns ``vnodes`` points (``sha256(worker_id + '#' + i)``,
    first 8 bytes) on a 64-bit circle; a key routes to the first point at
    or after its own hash.  Determinism is the routing contract: the same
    worker set and vnode count produce the identical key→worker map in
    every process and on every run, and adding/removing one worker moves
    only the keys whose arcs that worker's points own — **bounded
    rebalance**, ~1/N of the key space (``tests/test_qi_fleet.py`` pins
    both properties).
    """

    def __init__(self, vnodes: Optional[int] = None) -> None:
        self.vnodes = max(
            vnodes if vnodes is not None
            else qi_env_int("QI_FLEET_VNODES", 32),
            1,
        )
        self._points: List[Tuple[int, str]] = []  # sorted (hash, worker_id)
        self._workers: Set[str] = set()

    @staticmethod
    def _hash(key: str) -> int:
        return int.from_bytes(
            hashlib.sha256(key.encode("utf-8")).digest()[:8], "big",
        )

    def add(self, worker_id: str) -> None:
        if worker_id in self._workers:
            return
        self._workers.add(worker_id)
        for v in range(self.vnodes):
            bisect.insort(
                self._points, (self._hash(f"{worker_id}#{v}"), worker_id),
            )

    def remove(self, worker_id: str) -> None:
        if worker_id not in self._workers:
            return
        self._workers.discard(worker_id)
        self._points = [p for p in self._points if p[1] != worker_id]

    def route(self, key: str) -> str:
        """The worker owning ``key``'s arc; ``LookupError`` on an empty
        ring (the caller turns it into a typed no-live-workers error)."""
        if not self._points:
            raise LookupError("consistent-hash ring is empty")
        h = self._hash(key)
        ix = bisect.bisect_left(self._points, (h, ""))
        if ix == len(self._points):
            ix = 0
        return self._points[ix][1]

    def route_excluding(self, key: str,
                        exclude: Set[str]) -> Optional[str]:
        """The first arc owner at or after ``key``'s hash whose worker is
        NOT in ``exclude`` — the hedge secondary's "next arc owner"
        contract (qi-mesh): walking the ring point-by-point keeps the
        secondary deterministic for a given worker set, like
        :meth:`route` itself.  ``None`` when every point is excluded."""
        if not self._points:
            return None
        h = self._hash(key)
        start = bisect.bisect_left(self._points, (h, ""))
        for k in range(len(self._points)):
            wid = self._points[(start + k) % len(self._points)][1]
            if wid not in exclude:
                return wid
        return None

    def workers(self) -> List[str]:
        return sorted(self._workers)

    def __len__(self) -> int:
        return len(self._workers)

    def __contains__(self, worker_id: str) -> bool:
        return worker_id in self._workers


# ---- worker handles ---------------------------------------------------------

# A worker handle's response callback: (worker_id, response object).
_OnResponse = Callable[[str, Dict[str, object]], None]


class ProcWorker:
    """One serve worker subprocess speaking JSONL over pipes.

    The production worker shape: ``python -m quorum_intersection_tpu serve
    --journal <own journal> --emit-certs`` with ``QI_FLEET_STORE_DIR``
    exported, so its verdict responses carry certificates (the front door
    relays them verbatim) and its per-SCC store shares the fleet tier.  A
    reader thread demultiplexes the pipe: replay reports resolve
    readiness, pongs resolve pending pings, everything else is a response
    handed to the front door.
    """

    kind = "proc"

    def __init__(
        self,
        worker_id: str,
        journal_path: Union[str, Path],
        on_response: _OnResponse,
        *,
        backend: str = "auto",
        store_dir: Optional[Union[str, Path]] = None,
        deadline_s: Optional[float] = None,
        batch_max: Optional[int] = None,
        cache_max: Optional[int] = None,
        queue_depth: Optional[int] = None,
        dangling: str = "strict",
        scc_select: str = "quorum-bearing",
        scope_to_scc: bool = False,
        on_exit: Optional[Callable[[str], None]] = None,
    ) -> None:
        self.worker_id = worker_id
        self.journal_path = Path(journal_path)
        self._on_response = on_response
        self._on_exit = on_exit
        self._closing = False
        self._wlock = threading.Lock()
        self._plock = threading.Lock()
        self._pings: Dict[str, Tuple[threading.Event, List[Dict[str, object]]]] = {}
        self._ready = threading.Event()
        self.replay_report: Optional[Dict[str, object]] = None
        cmd = [
            sys.executable, "-m", "quorum_intersection_tpu", "serve",
            "--journal", str(self.journal_path),
            "--backend", backend,
            "--emit-certs",
            "--dangling-policy", dangling,
            "--scc-select", scc_select,
        ]
        if scope_to_scc:
            cmd.append("--scope-scc")
        if deadline_s is not None:
            cmd += ["--deadline-s", str(deadline_s)]
        if batch_max is not None:
            cmd += ["--batch-max", str(batch_max)]
        if cache_max is not None:
            cmd += ["--cache-max", str(cache_max)]
        if queue_depth is not None:
            cmd += ["--queue-depth", str(queue_depth)]
        env = dict(os.environ)
        if store_dir is not None:
            env["QI_FLEET_STORE_DIR"] = str(store_dir)
        # One scrape port cannot be shared by N workers; their health rides
        # the ping/pong protocol instead.
        env["QI_METRICS_PORT"] = "0"
        self._proc = subprocess.Popen(
            cmd, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL, text=True, env=env,
        )
        # qi-lint: allow(cancel-token-plumbed) — pipe demultiplexer; close()/kill() end it via EOF
        self._reader = threading.Thread(
            target=self._read_loop, name=f"qi-fleet-read-{worker_id}",
            daemon=True,
        )
        self._reader.start()

    def _read_loop(self) -> None:
        assert self._proc.stdout is not None
        for line in self._proc.stdout:
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                continue
            if not isinstance(obj, dict):
                continue
            if obj.get("kind") == "replay":
                self.replay_report = obj
                self._ready.set()
                continue
            if obj.get("kind") == "listening":
                continue
            if "pong" in obj:
                token = str(obj.get("pong"))
                with self._plock:
                    waiter = self._pings.pop(token, None)
                if waiter is not None:
                    waiter[1].append(obj)
                    waiter[0].set()
                continue
            self._on_response(self.worker_id, obj)
        if not self._closing and self._on_exit is not None:
            self._on_exit(self.worker_id)

    def _write(self, obj: Dict[str, object]) -> bool:
        try:
            assert self._proc.stdin is not None
            with self._wlock:
                self._proc.stdin.write(json.dumps(obj, default=str) + "\n")
                self._proc.stdin.flush()
            return True
        except (OSError, ValueError):
            # Broken pipe / closed stdin: the worker is gone — the caller
            # turns this into eviction + failover.
            return False

    def wait_ready(self, timeout: float) -> bool:
        return self._ready.wait(timeout)

    def submit(self, request_id: str, nodes: List[Dict[str, object]],
               deadline_s: Optional[float],
               query: Optional[Dict[str, object]] = None,
               trace: Optional[str] = None,
               client: Optional[str] = None) -> bool:
        line: Dict[str, object] = {"request_id": request_id, "nodes": nodes}
        if deadline_s is not None:
            line["deadline_s"] = deadline_s
        if query is not None:
            line["query"] = query
        if trace is not None:
            # qi-pulse: the front door's request-span context — the worker
            # adopts it so its spans join this request's trace.
            line["trace"] = trace
        if client is not None:
            # qi-cost: the tenant this request books to on the worker.
            line["client"] = client
        return self._write(line)

    def ping(self, timeout: float = 2.0) -> Optional[Dict[str, object]]:
        token = f"{self.worker_id}-{time.monotonic_ns():x}"
        ev: threading.Event = threading.Event()
        box: List[Dict[str, object]] = []
        with self._plock:
            self._pings[token] = (ev, box)
        if not self._write({"ping": token}) or not ev.wait(timeout):
            with self._plock:
                self._pings.pop(token, None)
            return None
        return box[0]

    def alive(self) -> bool:
        return self._proc.poll() is None

    def kill(self) -> None:
        """SIGKILL — the bench's kill-one-of-N hook (a real hard kill: the
        journal's torn tail and unfinished entries are genuine)."""
        self._proc.kill()

    def close(self, timeout: float = 30.0) -> None:
        """Graceful shutdown: stdin EOF drains the worker (every accepted
        request answers before exit, the serve CLI contract)."""
        self._closing = True
        try:
            assert self._proc.stdin is not None
            with self._wlock:
                self._proc.stdin.close()
        except (OSError, ValueError):
            pass
        try:
            self._proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            log.warning("fleet worker %s did not drain in %gs; killed",
                        self.worker_id, timeout)
            self._proc.kill()
        self._reader.join(timeout=5.0)


class LocalWorker:
    """In-process worker: the same ``ServeEngine`` behind the same handle
    duck-type, answering in the exact shape the JSONL transport emits
    (``serve_transport.ticket_response``) — the front door cannot tell a
    LocalWorker from a ProcWorker.  Used by the deterministic schedule
    harness, the test matrix, and bench smokes where N subprocess
    spin-ups would dominate the measurement.

    ``kill()`` simulates a hard kill at the fidelity an in-process worker
    allows: responses stop immediately (suppressed, as a dead process's
    would be) and the engine is torn down without draining — the real
    SIGKILL matrix (torn journal tails) is covered by :class:`ProcWorker`
    rounds and the journal-construction tests.
    """

    kind = "local"

    def __init__(
        self,
        worker_id: str,
        journal_path: Union[str, Path],
        on_response: _OnResponse,
        *,
        backend: str = "auto",
        store_dir: Optional[Union[str, Path]] = None,
        deadline_s: Optional[float] = None,
        batch_max: Optional[int] = None,
        cache_max: Optional[int] = None,
        queue_depth: Optional[int] = None,
        dangling: str = "strict",
        scc_select: str = "quorum-bearing",
        scope_to_scc: bool = False,
        on_exit: Optional[Callable[[str], None]] = None,
    ) -> None:
        self.worker_id = worker_id
        self.journal_path = Path(journal_path)
        self._on_response = on_response
        self._dead = False
        self.replay_report: Optional[Dict[str, object]] = None
        self.engine = ServeEngine(
            backend=backend,
            journal=self.journal_path,
            deadline_s=deadline_s,
            batch_max=batch_max,
            cache_max=cache_max,
            queue_depth=queue_depth,
            dangling=dangling,
            scc_select=scc_select,
            scope_to_scc=scope_to_scc,
            shared_store=(
                SharedSccStore(store_dir) if store_dir is not None else None
            ),
        )
        self.replay_report = self.engine.start()

    def wait_ready(self, timeout: float) -> bool:
        return True  # start() above already replayed synchronously

    def _respond(self, obj: Dict[str, object]) -> None:
        if self._dead:
            return  # a killed worker answers nobody
        self._on_response(self.worker_id, obj)

    def _on_ticket_done(self, ticket: Ticket) -> None:
        self._respond(ticket_response(ticket, emit_certs=True))

    def submit(self, request_id: str, nodes: List[Dict[str, object]],
               deadline_s: Optional[float],
               query: Optional[Dict[str, object]] = None,
               trace: Optional[str] = None,
               client: Optional[str] = None) -> bool:
        if self._dead:
            return False
        try:
            ticket = self.engine.submit(
                nodes, request_id=request_id, deadline_s=deadline_s,
                query=query, trace=trace, client=client,
            )
        except ServeError as exc:
            self._respond({"request_id": request_id,
                           "error": {"code": exc.code, "message": str(exc)}})
            return True
        except (ValueError, TypeError, FaultInjected) as exc:
            self._respond({"request_id": request_id,
                           "error": {"code": str(getattr(exc, "code",
                                                         "invalid")),
                                     "message": str(exc)}})
            return True
        ticket.add_done_callback(self._on_ticket_done)
        return True

    def ping(self, timeout: float = 2.0) -> Optional[Dict[str, object]]:
        if self._dead:
            return None
        return pong_payload(f"local-{self.worker_id}")

    def alive(self) -> bool:
        return not self._dead

    def kill(self) -> None:
        self._dead = True
        self.engine.stop(drain=False, timeout=2.0)

    def close(self, timeout: float = 30.0) -> None:
        if not self._dead:
            self.engine.stop(drain=True, timeout=timeout)


class SocketWorker:
    """One REMOTE serve worker joined over TCP (qi-mesh, ISSUE 19): a
    peer running ``serve --socket PORT [--bind ADDR]`` on another host,
    behind the same handle duck-type as :class:`ProcWorker` /
    :class:`LocalWorker` — the front door cannot tell them apart.

    The constructor performs the versioned join handshake (protocol +
    package fingerprint + ``QI_FLEET_TOKEN`` digest); the peer's
    ``hello_ok`` carries its replay report (readiness), a ``hello_err``
    is a TYPED reject surfaced via :attr:`handshake_error` — never a
    silently skewed mesh.  The hello also advertises the front door's
    store gateway, so the peer's SCC fragments flow both ways
    (fetch-on-miss, publish-on-solve).

    Liveness is two-tier: a broken CONNECTION (reader EOF) is death —
    same as a ProcWorker's pipe EOF; missed *pings on a live connection*
    are a PARTITION signal the front door turns into suspicion + lease
    accounting, because a stalled wire heals where a dead process never
    does.  ``journal_path`` is ``None`` — the peer's journal lives on its
    host and ships over the wire (:meth:`ship_journal`) instead.
    """

    kind = "socket"

    def __init__(
        self,
        worker_id: str,
        addr: Tuple[str, int],
        on_response: _OnResponse,
        *,
        store_port: Optional[int] = None,
        on_exit: Optional[Callable[[str], None]] = None,
        timeout_s: float = 10.0,
    ) -> None:
        self.worker_id = worker_id
        self.addr = (str(addr[0]), int(addr[1]))
        self.journal_path: Optional[Path] = None  # remote: ships over the wire
        self._on_response = on_response
        self._on_exit = on_exit
        self._closing = False
        self._dead = False
        self._wlock = threading.Lock()
        self._plock = threading.Lock()
        self._pings: Dict[str, Tuple[threading.Event, List[Dict[str, object]]]] = {}
        self._ready = threading.Event()
        self.replay_report: Optional[Dict[str, object]] = None
        self.handshake_error: Optional[Dict[str, object]] = None
        # Journal-ship collector.  _ship_lock guards only the collector
        # fields (quick mutations — waiting and fsync happen outside any
        # lock); ship serialization itself is the callers' contract: the
        # evict path is deduplicated by _dead_handled and the retire path
        # removed the worker from _live first, so at most one ship is in
        # flight per worker.
        self._ship_lock = threading.Lock()
        self._ship_done = threading.Event()
        self._ship_chunks: Dict[int, bytes] = {}
        self._ship_end: Optional[Dict[str, object]] = None
        self._ship_err: Optional[Dict[str, object]] = None
        self._sock = socket.create_connection(self.addr, timeout=timeout_s)
        # Reads block on the reader thread; every write is deadline-free
        # JSONL guarded by _wlock (a stuck peer surfaces as ping misses,
        # not a wedged front door — the socket's send buffer absorbs the
        # line or the OS errors the write).
        self._sock.settimeout(None)
        self._rfile = self._sock.makefile("r", encoding="utf-8")
        self._wfile = self._sock.makefile("w", encoding="utf-8")
        hello: Dict[str, object] = {
            "schema": PROTOCOL_SCHEMA,
            "protocol": MESH_PROTOCOL,
            "fingerprint": package_fingerprint(),
            "token": fleet_token_digest(),
            "peer": worker_id,
        }
        if store_port is not None:
            # The address THIS host is reachable at from the peer's side
            # of this very connection — the one host answer that is
            # correct on loopback and multi-homed hosts alike.
            hello["store"] = {
                "host": self._sock.getsockname()[0],
                "port": int(store_port),
            }
        # qi-lint: allow(cancel-token-plumbed) — socket demultiplexer; close()/kill() end it via EOF
        self._reader = threading.Thread(
            target=self._read_loop, name=f"qi-fleet-sock-{worker_id}",
            daemon=True,
        )
        if not self._write({"hello": hello}):
            raise OSError(f"mesh hello write to {self.addr} failed")
        self._reader.start()

    # ---- wire ------------------------------------------------------------

    def _read_loop(self) -> None:
        try:
            for line in self._rfile:
                try:
                    obj = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if not isinstance(obj, dict):
                    continue
                if "hello_ok" in obj:
                    ok = obj["hello_ok"]
                    if isinstance(ok, dict):
                        rep = ok.get("replay")
                        self.replay_report = (
                            rep if isinstance(rep, dict) else None
                        )
                    self._ready.set()
                    continue
                if "hello_err" in obj:
                    err = obj["hello_err"]
                    self.handshake_error = (
                        err if isinstance(err, dict)
                        else {"code": "hello_err"}
                    )
                    self._ready.set()
                    continue
                if "ship_chunk" in obj:
                    self._collect_chunk(obj["ship_chunk"])
                    continue
                if "ship_end" in obj:
                    end = obj["ship_end"]
                    with self._ship_lock:
                        self._ship_end = end if isinstance(end, dict) else {}
                    self._ship_done.set()
                    continue
                if "ship_err" in obj:
                    err = obj["ship_err"]
                    with self._ship_lock:
                        self._ship_err = err if isinstance(err, dict) else {}
                    self._ship_done.set()
                    continue
                if "pong" in obj:
                    token = str(obj.get("pong"))
                    with self._plock:
                        waiter = self._pings.pop(token, None)
                    if waiter is not None:
                        waiter[1].append(obj)
                        waiter[0].set()
                    continue
                self._on_response(self.worker_id, obj)
        except (OSError, ValueError):
            pass
        self._dead = True
        self._ready.set()  # a join blocked in wait_ready wakes to False
        self._ship_done.set()  # a ship blocked mid-stream wakes to None
        if not self._closing and self._on_exit is not None:
            self._on_exit(self.worker_id)

    def _collect_chunk(self, chunk: object) -> None:
        if not isinstance(chunk, dict):
            return
        try:
            data = base64.b64decode(str(chunk.get("data") or ""))
            seq = int(chunk.get("seq") or 0)
            want = int(chunk.get("len"))  # type: ignore[arg-type]
        except (ValueError, TypeError):
            return  # a malformed chunk fails the digest check downstream
        if len(data) == want:
            with self._ship_lock:
                self._ship_chunks[seq] = data

    def _write(self, obj: Dict[str, object]) -> bool:
        try:
            with self._wlock:
                self._wfile.write(json.dumps(obj, default=str) + "\n")
                self._wfile.flush()
            return True
        except (OSError, ValueError):
            # Broken connection: the peer (or the wire) is gone — the
            # caller turns this into suspicion/eviction.
            return False

    # ---- worker duck-type ------------------------------------------------

    def wait_ready(self, timeout: float) -> bool:
        if not self._ready.wait(timeout):
            return False
        return self.handshake_error is None and not self._dead

    def submit(self, request_id: str, nodes: List[Dict[str, object]],
               deadline_s: Optional[float],
               query: Optional[Dict[str, object]] = None,
               trace: Optional[str] = None,
               client: Optional[str] = None) -> bool:
        if self._dead:
            return False
        line: Dict[str, object] = {"request_id": request_id, "nodes": nodes}
        if deadline_s is not None:
            line["deadline_s"] = deadline_s
        if query is not None:
            line["query"] = query
        if trace is not None:
            line["trace"] = trace
        if client is not None:
            line["client"] = client
        return self._write(line)

    def ping(self, timeout: float = 2.0) -> Optional[Dict[str, object]]:
        if self._dead:
            return None
        token = f"{self.worker_id}-{time.monotonic_ns():x}"
        ev: threading.Event = threading.Event()
        box: List[Dict[str, object]] = []
        with self._plock:
            self._pings[token] = (ev, box)
        if not self._write({"ping": token}) or not ev.wait(timeout):
            with self._plock:
                self._pings.pop(token, None)
            return None
        return box[0]

    def alive(self) -> bool:
        # Connection-level liveness only: a SIGSTOPped/partitioned peer
        # keeps its TCP session and stays "alive" here — its missed
        # pings drive the suspect→lease-lapse path instead, because a
        # partition heals where a dead process never does.
        return not self._dead

    def ship_journal(self, spool_dir: Path,
                     timeout: float = 30.0) -> Optional[Path]:
        """Pull the peer's crash-only journal into a local spool file:
        chunked + length-checked + digest-verified, and **fsynced before
        the ack goes back** — an acked ship is durable on this side, and
        a torn stream is detected (digest mismatch), never replayed.
        ``None`` on a broken wire or failed verification."""
        with self._ship_lock:
            self._ship_chunks = {}
            self._ship_end = None
            self._ship_err = None
        self._ship_done.clear()
        if not self._write(
            {"ship_journal": {"token": fleet_token_digest()}}
        ):
            return None
        if not self._ship_done.wait(timeout):
            return None
        with self._ship_lock:
            end = self._ship_end
            err = self._ship_err
            chunks = dict(self._ship_chunks)
        if err is not None or end is None:
            return None
        raw = b"".join(chunks[i] for i in sorted(chunks))
        try:
            intact = (
                len(chunks) == int(end.get("chunks") or 0)
                and len(raw) == int(end.get("bytes") or -1)
                and hashlib.sha256(raw).hexdigest() == end.get("sha256")
            )
        except (ValueError, TypeError):
            intact = False
        if not intact:
            return None
        spool_dir.mkdir(parents=True, exist_ok=True)
        spool = spool_dir / f"{self.worker_id}.shipped.journal"
        with spool.open("wb") as fh:
            fh.write(raw)
            fh.flush()
            os.fsync(fh.fileno())
        self._write({"ship_ack": {"bytes": len(raw)}})
        return spool

    def kill(self) -> None:
        """Hard-drop the CONNECTION (the peer process keeps running on
        its host; from this fleet's view the worker is gone)."""
        try:
            self._sock.close()
        except OSError:
            pass

    def close(self, timeout: float = 30.0) -> None:
        """Graceful: half-close the write side so the peer sees EOF and
        drains this session (every accepted request answers through the
        still-open read half), then tear down."""
        self._closing = True
        try:
            self._sock.shutdown(socket.SHUT_WR)
        except OSError:
            pass
        self._reader.join(timeout=timeout)
        self._dead = True
        for closer in (self._rfile, self._wfile, self._sock):
            try:
                closer.close()
            except OSError:
                pass


class StoreGateway:
    """qi-store/1 over TCP (qi-mesh, ISSUE 19): the front door's
    :class:`~quorum_intersection_tpu.delta.SharedSccStore` served to
    socket-joined peers, so SCC fragments flow across hosts with no
    shared filesystem — fetch-on-miss, publish-on-solve, through
    ``delta.RemoteStoreClient`` on the peer side.

    Sessions open with a token-authenticated ``store_hello`` (digest
    compare, like the join handshake); each subsequent line is one
    ``get``/``put`` op answered with one ``{"ok": ...}`` line.  Serving
    reads/writes the same atomic file tier the local workers share, and
    safety is unchanged: a forged, torn or stale payload fails the
    client's strict shape validation and re-verification — it is only
    ever a miss, never a trusted verdict.
    """

    def __init__(self, store: SharedSccStore, *,
                 host: Optional[str] = None, port: int = 0) -> None:
        outer = self
        host = host or qi_env("QI_SERVE_BIND") or "127.0.0.1"

        class _Handler(socketserver.StreamRequestHandler):
            def handle(self) -> None:
                outer._serve_conn(self.rfile, self.wfile)

        self.store = store
        self._srv = socketserver.ThreadingTCPServer(
            (host, port), _Handler, bind_and_activate=True,
        )
        self._srv.daemon_threads = True
        self.host = host
        self.port = int(self._srv.server_address[1])
        # qi-lint: allow(cancel-token-plumbed) — daemon accept loop, no solve work; stop() shuts it down
        self._thread = threading.Thread(
            target=self._srv.serve_forever, name="qi-store-gateway",
            daemon=True,
        )
        self._thread.start()
        log.info("fleet store gateway on %s:%d", host, self.port)

    def _serve_conn(self, rfile: object, wfile: object) -> None:
        """One authenticated gateway session; a client that dies mid-line
        ends THIS session (logged), never the acceptor."""
        rec = get_run_record()

        def reply(obj: Dict[str, object]) -> None:
            wfile.write(  # type: ignore[attr-defined]
                (json.dumps(obj, default=str) + "\n").encode("utf-8")
            )
            wfile.flush()  # type: ignore[attr-defined]

        try:
            first = (rfile.readline() or b"null")  # type: ignore[attr-defined]
            hello = json.loads(first.decode("utf-8", errors="replace"))
            inner = (hello.get("store_hello")
                     if isinstance(hello, dict) else None)
            if not (isinstance(inner, dict) and hmac.compare_digest(
                str(inner.get("token") or ""), fleet_token_digest(),
            )):
                rec.add("fleet.store_gateway_rejects")
                rec.event("fleet.store_gateway_rejected")
                reply({"ok": False, "error": "store_hello token mismatch"})
                return
            reply({"ok": True, "schema": STORE_SCHEMA})
            for line in rfile:  # type: ignore[attr-defined]
                op = json.loads(line.decode("utf-8", errors="replace"))
                if not isinstance(op, dict):
                    reply({"ok": False, "error": "op is not an object"})
                    continue
                kind = str(op.get("kind") or "")
                fp = str(op.get("fp") or "")
                scope = str(op.get("scope") or "")
                if op.get("op") == "get":
                    reply({"ok": True,
                           "payload": self.store.get(kind, fp, scope)})
                elif op.get("op") == "put":
                    payload = op.get("payload")
                    stored = (
                        self.store.put(kind, fp, payload, scope)
                        if isinstance(payload, dict) else False
                    )
                    reply({"ok": True, "stored": stored})
                else:
                    reply({"ok": False, "error": "unknown op"})
        except (OSError, ValueError) as exc:
            log.warning("store gateway session ended (%s); acceptor "
                        "unaffected", exc)

    def stop(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()


# ---- the front door ---------------------------------------------------------


@dataclass
class _Pending:
    """One in-flight fleet request: the client ticket plus everything a
    failover needs to re-route it (journal-grade payload, routing key).

    ``wire_id`` is the id on the worker protocol — normally the client's
    ``request_id``, made unique when a client reuses an id while the
    first request is still in flight (the serve contract answers every
    submission, so a duplicate must not orphan the earlier ticket)."""

    ticket: Ticket
    wire_id: str
    fingerprint: str
    nodes: List[Dict[str, object]]
    deadline_s: Optional[float]
    worker_id: str = ""
    internal: bool = False  # journal-inherited work with no client ticket
    replaying: bool = False  # dispatched by a failover; gates /readyz
    query: Optional[Dict[str, object]] = None  # qi-query/1 wire form
    # qi-pulse (ISSUE 15): the wire trace context stamped at admission
    # ("trace_id:span_id[:pid]", parented on the fleet.request span) —
    # re-sent on every failover re-dispatch so the inheriting worker's
    # spans still join the original request's trace.
    trace: Optional[str] = None
    # qi-cost (ISSUE 17): the client id forwarded to whichever worker ends
    # up solving this request — failover re-dispatches keep the tenant.
    client: Optional[str] = None


class FleetEngine:
    """The replicated serve tier's front door (see module docstring).

    ``submit`` has the same signature and Ticket semantics as
    ``ServeEngine.submit``, so the JSONL transports drive either — the
    ``fleet`` CLI subcommand IS ``serve_transport.JsonlSession`` over this
    class.
    """

    def __init__(
        self,
        workers: Optional[int] = None,
        *,
        backend: str = "auto",
        worker_mode: str = "subprocess",
        journal_dir: Optional[Union[str, Path]] = None,
        store_dir: Optional[Union[str, Path]] = None,
        deadline_s: Optional[float] = None,
        batch_max: Optional[int] = None,
        cache_max: Optional[int] = None,
        queue_depth: Optional[int] = None,
        dangling: str = "strict",
        scc_select: str = "quorum-bearing",
        scope_to_scc: bool = False,
        vnodes: Optional[int] = None,
        probe_interval_s: Optional[float] = None,
        probe_fails: Optional[int] = None,
        respawn_max: Optional[int] = None,
        joins: Optional[Sequence[str]] = None,
        lease_s: Optional[float] = None,
        scale_interval_s: Optional[float] = None,
        scale_min: Optional[int] = None,
        scale_max: Optional[int] = None,
    ) -> None:
        if worker_mode not in ("subprocess", "local"):
            raise ValueError(f"unknown worker_mode {worker_mode!r}")
        # Socket joins (qi-mesh, ISSUE 19): "HOST:PORT" peers running
        # ``serve --socket``; slot ids j0.. so the respawn machinery can
        # REDIAL a slot's address after an eviction (the rejoin path).
        self._join_addrs: Dict[str, Tuple[str, int]] = {}
        for i, spec in enumerate(joins or ()):
            host, _, port = str(spec).rpartition(":")
            if not host or not port.isdigit():
                raise ValueError(
                    f"--join expects HOST:PORT, got {spec!r}"
                )
            self._join_addrs[f"j{i}"] = (host, int(port))
        self.n_workers = max(
            workers if workers is not None
            else qi_env_int("QI_FLEET_WORKERS", 2),
            # A pure socket mesh may run with ZERO local workers; without
            # joins at least one local worker keeps the ring non-empty.
            0 if self._join_addrs else 1,
        )
        self.backend = backend
        self.worker_mode = worker_mode
        self.deadline_s = deadline_s
        self.batch_max = batch_max
        self.cache_max = cache_max
        self.queue_depth = queue_depth
        self.dangling = dangling
        self.scc_select = scc_select
        self.scope_to_scc = scope_to_scc
        self.probe_interval_s = (
            probe_interval_s if probe_interval_s is not None
            else max(qi_env_float("QI_FLEET_PROBE_INTERVAL_S", 0.5), 0.05)
        )
        self.probe_fails = max(
            probe_fails if probe_fails is not None
            else qi_env_int("QI_FLEET_PROBE_FAILS", 2),
            1,
        )
        # Worker auto-respawn (ROADMAP follow-up: without it the ring
        # shrinks on every eviction until restart).  Bounded per SLOT so a
        # crash-looping worker cannot respawn forever; 0 disables.
        self.respawn_max = max(
            respawn_max if respawn_max is not None
            else qi_env_int("QI_FLEET_RESPAWN_MAX", 2),
            0,
        )
        self._respawn_counts: Dict[str, int] = {}
        # Heartbeat leases (qi-mesh): a socket peer that misses its probe
        # hysteresis is SUSPECTED — routed around with hedged dispatch —
        # and only evicted when its lease (renewed by every pong) lapses.
        self.lease_s = max(
            lease_s if lease_s is not None
            else qi_env_float("QI_FLEET_LEASE_S", 3.0),
            0.1,
        )
        self._suspected: Set[str] = set()
        self._leases: Dict[str, float] = {}
        self._store_gateway: Optional[StoreGateway] = None
        # Elasticity (qi-mesh): the pulse→fleet-size supervisor. 0 = off.
        self.scale_interval_s = (
            scale_interval_s if scale_interval_s is not None
            else qi_env_float("QI_FLEET_SCALE_INTERVAL_S", 0.0)
        )
        self.scale_up_ms = qi_env_float("QI_FLEET_SCALE_UP_MS", 250.0)
        self.scale_down_ms = qi_env_float("QI_FLEET_SCALE_DOWN_MS", 20.0)
        self.scale_min = max(
            scale_min if scale_min is not None
            else qi_env_int("QI_FLEET_SCALE_MIN", 1),
            1,
        )
        self.scale_max = max(
            scale_max if scale_max is not None
            else qi_env_int("QI_FLEET_SCALE_MAX", 8),
            self.scale_min,
        )
        self._next_scale_t = 0.0
        self._elastic_seq = 0
        self._tmpdir: Optional[tempfile.TemporaryDirectory] = None
        if journal_dir is None:
            self._tmpdir = tempfile.TemporaryDirectory(prefix="qi-fleet-")
            journal_dir = self._tmpdir.name
        self.journal_dir = Path(journal_dir)
        env_store = qi_env("QI_FLEET_STORE_DIR")
        self.store_dir = Path(
            store_dir if store_dir is not None
            else (env_store or self.journal_dir / "store")
        )
        self._lock = threading.Lock()
        self._ring = HashRing(vnodes=vnodes)
        self._workers: Dict[str, Union[ProcWorker, LocalWorker]] = {}
        self._live: Set[str] = set()
        self._pending: Dict[str, _Pending] = {}  # wire_id → pending
        self._dead_handled: Set[str] = set()
        self._failovers_active = 0
        self._replays_outstanding = 0
        # Aggregation plane (qi-pulse, ISSUE 15): merge the workers'
        # pong-carried pulse histograms fleet-wide each probe cycle.
        # "0" restores per-worker-only metrics.
        self._pulse_agg = qi_env("QI_PULSE_AGG") not in ("", "0")
        self._pongs: Dict[str, Dict[str, object]] = {}
        self._closed = False
        self._started = False
        self._stop = threading.Event()
        self._probe_thread: Optional[threading.Thread] = None

    # ---- lifecycle -------------------------------------------------------

    def worker_ids(self) -> List[str]:
        with self._lock:
            return sorted(self._live)

    def start(self) -> Dict[str, object]:
        """Spawn the workers, replay their journals, build the ring.

        ``fleet.replay_complete`` stays 0 (``/readyz`` answers 503) until
        EVERY live worker finished its own journal replay — a restarted
        fleet must not take traffic while any predecessor's work is
        outstanding.  Returns a start report (per-worker replay reports).
        """
        if self._started:
            return {"schema": FLEET_SCHEMA, "workers": self.worker_ids()}
        self._started = True
        rec = get_run_record()
        rec.gauge("fleet.replay_complete", 0)
        self.journal_dir.mkdir(parents=True, exist_ok=True)
        self.store_dir.mkdir(parents=True, exist_ok=True)
        with rec.span("fleet.start", workers=self.n_workers,
                      mode=self.worker_mode):
            if self._join_addrs:
                # Socket peers need a wire path to the shared fragment
                # tier: serve this front door's store directory over the
                # mesh (its address rides each join hello).
                self._store_gateway = StoreGateway(
                    SharedSccStore(self.store_dir),
                )
            for i in range(self.n_workers):
                wid = f"w{i}"
                self._workers[wid] = self._make_worker(wid)
            for wid in sorted(self._join_addrs):
                joined = self._join_worker(wid, self._join_addrs[wid])
                if joined is not None:
                    self._workers[wid] = joined
            reports: Dict[str, object] = {}
            for wid, worker in self._workers.items():
                if not worker.wait_ready(timeout=120.0):
                    log.warning(
                        "fleet worker %s never reported replay-complete; "
                        "left out of the ring", wid,
                    )
                    continue
                reports[wid] = worker.replay_report
                with self._lock:
                    self._live.add(wid)
                    self._ring.add(wid)
                    self._leases[wid] = time.monotonic() + self.lease_s
        with self._lock:
            live, ring_size = len(self._live), len(self._ring)
        rec.gauge("fleet.workers_live", live)
        rec.gauge("fleet.ring_size", ring_size)
        rec.gauge("fleet.replay_complete", 1)
        # qi-lint: allow(cancel-token-plumbed) — health-probe loop, no solve work; stop() ends it via the event
        self._probe_thread = threading.Thread(
            target=self._probe_loop, name="qi-fleet-probe", daemon=True,
        )
        self._probe_thread.start()
        log.info(
            "fleet started: %d/%d workers live (mode=%s, store=%s)",
            live, self.n_workers, self.worker_mode, self.store_dir,
        )
        return {
            "schema": FLEET_SCHEMA,
            "workers": self.worker_ids(),
            "mode": self.worker_mode,
            "replay": reports,
        }

    def _make_worker(
        self, wid: str,
    ) -> Union[ProcWorker, LocalWorker, "SocketWorker"]:
        """Construct one worker for slot/replacement id ``wid`` — shared
        by :meth:`start`, the auto-respawn path and the elastic scale-up
        path, so a replacement is configured byte-identically to the
        worker it replaces (only its journal file is fresh: the dead
        journal already failed over).  A join slot (``j<i>`` or its
        ``.r<n>`` replacement) REDIALS its peer address instead — the
        respawn machinery doubles as the mesh rejoin path."""
        addr = self._join_addrs.get(wid.split(".", 1)[0])
        if addr is not None:
            joined = self._join_worker(wid, addr)
            if joined is None:
                raise OSError(
                    f"re-join of {addr[0]}:{addr[1]} failed"
                )
            return joined
        make = ProcWorker if self.worker_mode == "subprocess" else LocalWorker
        return make(
            wid, self.journal_dir / f"{wid}.journal",
            self._on_response,
            backend=self.backend, store_dir=self.store_dir,
            deadline_s=self.deadline_s, batch_max=self.batch_max,
            cache_max=self.cache_max, queue_depth=self.queue_depth,
            dangling=self.dangling,
            scc_select=self.scc_select,
            scope_to_scc=self.scope_to_scc,
            on_exit=self._on_worker_exit,
        )

    def _join_worker(self, wid: str,
                     addr: Tuple[str, int]) -> Optional[SocketWorker]:
        """Dial one remote peer behind the ``fleet.join`` fault point:
        versioned handshake, deadline on the connect, bounded
        backoff+jitter retries.  A typed handshake reject
        (:class:`MeshHandshakeError`) PROPAGATES — a skewed mesh is
        refused, never retried into; wire/injected errors degrade to a
        fleet WITHOUT this peer (standalone workers keep serving),
        loudly (``fleet.join_errors`` + ``fleet.join_degraded``)."""
        rec = get_run_record()
        store = self._store_gateway
        last: Optional[Exception] = None
        for attempt in range(3):
            if attempt:
                # Bounded backoff+jitter: a rebooting peer gets breathing
                # room, a blip retries almost immediately.
                time.sleep(
                    min(0.1 * (2 ** (attempt - 1)), 1.0)
                    * (1.0 + random.random())
                )
            worker: Optional[SocketWorker] = None
            try:
                fault_point("fleet.join")
                worker = SocketWorker(
                    wid, addr, self._on_response,
                    store_port=store.port if store is not None else None,
                    on_exit=self._on_worker_exit,
                )
                if not worker.wait_ready(timeout=120.0):
                    err = worker.handshake_error
                    worker.kill()
                    if err is not None:
                        raise MeshHandshakeError(
                            str(err.get("code") or "hello_err"),
                            str(err.get("message") or ""),
                        )
                    raise OSError("join handshake timed out")
                rec.add("fleet.joins")
                rec.event("fleet.joined", worker=wid,
                          addr=f"{addr[0]}:{addr[1]}")
                log.info("fleet worker %s joined from %s:%d", wid,
                         addr[0], addr[1])
                return worker
            except MeshHandshakeError:
                raise
            except (FaultInjected, OSError, ValueError) as exc:
                last = exc
                if worker is not None:
                    worker.kill()
        rec.add("fleet.join_errors")
        rec.event("fleet.join_degraded", worker=wid, error=str(last))
        log.warning(
            "fleet join %s (%s:%d) failed after retries (%s); continuing "
            "without this peer", wid, addr[0], addr[1], last,
        )
        return None

    def stop(self, drain: bool = True, timeout: float = 60.0) -> None:
        """Close admission, drain (or kill) every worker, resolve whatever
        is left with a typed error — a fleet stop is never a silent drop."""
        with self._lock:
            self._closed = True
        self._stop.set()
        if self._probe_thread is not None:
            self._probe_thread.join(timeout=self.probe_interval_s + 5.0)
        for worker in list(self._workers.values()):
            if drain:
                worker.close(timeout=timeout)
            else:
                worker.kill()
        if self._store_gateway is not None:
            self._store_gateway.stop()
            self._store_gateway = None
        with self._lock:
            leftovers = list(self._pending.values())
            self._pending.clear()
        rec = get_run_record()
        for pending in leftovers:
            if not pending.internal:
                rec.add("fleet.errors")
            pending.ticket._resolve(("err", ServeError(
                "fleet stopped before this request resolved"
            )))
            self._note_replay_resolved(pending)
        if self._tmpdir is not None:
            self._tmpdir.cleanup()
            self._tmpdir = None

    # ---- admission / routing ---------------------------------------------

    def submit(
        self,
        source: Union[str, bytes, List[Dict[str, object]], Fbas],
        *,
        request_id: Optional[str] = None,
        deadline_s: Optional[float] = None,
        query: Optional[object] = None,
        trace: Optional[str] = None,
        client: Optional[str] = None,
    ) -> Ticket:
        """Admit one request: fingerprint, route, dispatch.  Same contract
        as ``ServeEngine.submit`` (typed errors, Ticket immediately).
        ``query`` (qi-query/1) extends the ROUTING key with the query
        kind + params, so identical snapshots asked different questions
        route (and coalesce) independently — fingerprints never cross
        query types fleet-wide either.

        ``trace`` (qi-pulse): an upstream client's wire trace context.
        The front door's ``fleet.request`` span adopts it (grafting under
        the client's span), then re-stamps the wire with its OWN span as
        the workers' remote parent — the chain stays one trace end to
        end: client → front door request span → worker spans."""
        rec = get_run_record()
        client_ctx = TraceContext.from_env(trace) if trace else None
        with self._lock:
            closed = self._closed
        if closed:
            rec.add("fleet.errors")
            raise ServeError("fleet is closed to new requests")
        request_id = (
            request_id
            or f"flt-{os.getpid()}-{time.monotonic_ns():x}"
        )
        # The front-door REQUEST SPAN (qi-pulse, ISSUE 15): it covers
        # fingerprint + route + dispatch, and its span id — stamped into
        # the wire "trace" field in the QI_TRACE_CONTEXT format — is the
        # remote parent every worker span for this request grafts under,
        # so one fleet request renders as one cross-process trace.
        with rec.adopted(client_ctx), rec.span(
            "fleet.request", request_id=request_id,
        ) as req_span:
            parsed_query = (
                query if isinstance(query, Query) else Query.parse(query)
            )
            fbas = source if isinstance(source, Fbas) else parse_fbas(source)
            nodes = _raw_nodes(source, fbas)
            graph = build_graph(fbas, dangling=self.dangling)
            fp = snapshot_fingerprint(
                graph, scc_select=self.scc_select,
                scope_to_scc=self.scope_to_scc,
            )
            qfp = parsed_query.fingerprint()
            if qfp:
                fp = f"{fp}:q:{qfp}"
            ticket = Ticket(request_id, time.monotonic(), deadline_t=None)
            pending = _Pending(
                ticket=ticket, wire_id=request_id, fingerprint=fp,
                nodes=nodes,
                deadline_s=deadline_s if deadline_s is not None
                else self.deadline_s,
                query=parsed_query.to_wire(),
                trace=TraceContext(
                    client_ctx.trace_id if client_ctx is not None
                    else rec.trace_id,
                    req_span.span_id, rec.pid,
                ).to_env(),
                client=client,
            )
            with self._lock:
                # A client may reuse a request_id while the first request
                # is still in flight (the serve contract answers every
                # submission): give the duplicate a unique wire id so the
                # earlier pending entry is never orphaned — both tickets
                # resolve, the client-facing request_id stays its own.
                n = 0
                while pending.wire_id in self._pending:
                    n += 1
                    pending.wire_id = f"{request_id}~dup{n}"
                self._pending[pending.wire_id] = pending
            rec.add("fleet.requests")
            self._dispatch(pending)
        return ticket

    def _route(self, fingerprint: str) -> str:
        """One ring lookup behind the ``fleet.route`` fault point: an
        injected/real failure degrades to the first live worker — only
        fleet-wide coalescing locality is lost, loudly."""
        rec = get_run_record()
        try:
            fault_point("fleet.route")
            with self._lock:
                return self._ring.route(fingerprint)
        except (FaultInjected, OSError) as exc:
            rec.add("fleet.route_errors")
            rec.event("fleet.route_degraded", error=str(exc))
            log.warning(
                "ring routing failed (%s); degrading to first live worker",
                exc,
            )
            with self._lock:
                live = sorted(self._live)
            if not live:
                raise LookupError("no live fleet workers") from exc
            return live[0]

    def _dispatch(self, pending: _Pending) -> None:
        """Route-and-send with bounded retry: a dead worker discovered at
        dispatch time is evicted (its journal replays) and the request
        re-routes through the shrunken ring."""
        rec = get_run_record()
        rid = pending.wire_id
        route_t0 = time.perf_counter()
        try:
            self._dispatch_inner(pending, rec, rid)
        finally:
            # Stage histogram (qi-pulse): ring lookup + wire write per
            # dispatch attempt chain (failover re-dispatches book again).
            rec.histogram("pulse.route_ms").observe(
                (time.perf_counter() - route_t0) * 1000.0
            )

    def _dispatch_inner(self, pending: _Pending, rec: RunRecord,
                        rid: str) -> None:
        for _ in range(len(self._workers) + 1):
            try:
                wid = self._route(pending.fingerprint)
            except LookupError:
                break
            with self._lock:
                if self._pending.get(rid) is not pending:
                    return  # already resolved or superseded
                pending.worker_id = wid
            _fleet_sync("route.resolved")
            with self._lock:
                if pending.worker_id != wid:
                    return  # a concurrent failover re-routed it already
                worker = self._workers.get(wid) if wid in self._live else None
                suspected = wid in self._suspected
            if worker is not None and suspected:
                # The arc owner is under suspicion (missed heartbeats on
                # a live connection): hedge instead of betting on it.
                if self._hedge_dispatch(pending, wid, worker, rec, rid):
                    return
            elif worker is not None and worker.submit(
                rid, pending.nodes, pending.deadline_s, pending.query,
                pending.trace, pending.client,
            ):
                rec.add("fleet.routed")
                return
            self._handle_worker_death(wid, "dispatch failed")
            with self._lock:
                if (self._pending.get(rid) is not pending
                        or pending.worker_id != wid):
                    return  # the failover replay re-dispatched it
        with self._lock:
            still_mine = self._pending.pop(rid, None) is pending
        if still_mine:
            if not pending.internal:
                rec.add("fleet.errors")
            pending.ticket._resolve(("err", ServeError(
                "no live fleet workers to route this request to"
            )))
            self._note_replay_resolved(pending)

    # ---- hedged dispatch (qi-mesh) ---------------------------------------

    def _next_arc_owner(
        self, fingerprint: str, exclude: Set[str],
    ) -> Optional[Tuple[str, Union[ProcWorker, LocalWorker, SocketWorker]]]:
        """The next LIVE, unsuspected worker on the ring after the
        excluded arc owner(s) — the hedge secondary."""
        with self._lock:
            skip = set(exclude) | self._suspected | {
                w for w in self._ring.workers() if w not in self._live
            }
            wid = self._ring.route_excluding(fingerprint, skip)
            worker = self._workers.get(wid) if wid is not None else None
        if wid is None or worker is None:
            return None
        return (wid, worker)

    def _hedge_dispatch(self, pending: _Pending, wid: str,
                        worker: Union[ProcWorker, LocalWorker, SocketWorker],
                        rec: RunRecord, rid: str) -> bool:
        """Dispatch to a SUSPECTED primary and simultaneously to the next
        live arc owner under the SAME wire id: whichever answers first
        resolves the client ticket, the straggler's answer books
        ``fleet.duplicate_responses`` — the PR 11 dedup IS the hedge
        dedup, so a primary that rejoins mid-hedge cannot double-answer.
        The ``fleet.hedge`` fault point degrades to a SINGLE dispatch to
        the secondary (one bet on the healthy peer, none on the
        suspect).  Every exit books exactly one of ``fleet.hedges`` /
        ``fleet.hedge_errors`` (pass-8 conservation: a hedge decision is
        never silent); ``False`` sends the caller down the
        worker-death/re-route path."""
        _fleet_sync("hedge.decided")
        secondary = self._next_arc_owner(pending.fingerprint, {wid})
        try:
            fault_point("fleet.hedge")
        except (FaultInjected, OSError) as exc:
            rec.add("fleet.hedge_errors")
            rec.event("fleet.hedge_degraded", worker=wid, error=str(exc))
            log.warning(
                "hedge degraded (%s): single dispatch to the next arc "
                "owner", exc,
            )
            twid, tworker = secondary if secondary is not None else (
                wid, worker,
            )
            with self._lock:
                pending.worker_id = twid
            if tworker.submit(rid, pending.nodes, pending.deadline_s,
                              pending.query, pending.trace, pending.client):
                rec.add("fleet.routed")
                return True
            return False
        sent = 0
        if worker.submit(rid, pending.nodes, pending.deadline_s,
                         pending.query, pending.trace, pending.client):
            sent += 1
        if secondary is not None:
            swid, sworker = secondary
            if sworker.submit(rid, pending.nodes, pending.deadline_s,
                              pending.query, pending.trace, pending.client):
                sent += 1
                with self._lock:
                    # Failover bookkeeping follows the HEALTHY secondary:
                    # if the suspect lapses, this request is already owned
                    # by a live peer and must not re-dispatch.
                    pending.worker_id = swid
        if not sent:
            rec.add("fleet.hedge_errors")
            rec.event("fleet.hedge_degraded", worker=wid,
                      error="neither hedge leg accepted the request")
            return False
        rec.add("fleet.hedges")
        rec.add("fleet.routed")
        rec.event(
            "fleet.hedged", worker=wid,
            secondary=secondary[0] if secondary is not None else "",
            legs=sent,
        )
        _fleet_sync("hedge.sent")
        return True

    # ---- responses -------------------------------------------------------

    def _on_response(self, worker_id: str, obj: Dict[str, object]) -> None:
        rec = get_run_record()
        rid = obj.get("request_id")
        with self._lock:
            pending = (
                self._pending.pop(rid, None) if isinstance(rid, str) else None
            )
        if pending is None:
            # A late answer for a request that already failed over or was
            # hedged (two workers solved it): the first resolution won,
            # the client never sees two outcomes.
            rec.add("fleet.duplicate_responses")
            return
        _fleet_sync("response.delivered")
        err = obj.get("error")
        if isinstance(err, dict):
            exc = ServeError(str(err.get("message") or "upstream serve error"))
            exc.code = str(err.get("code") or ServeError.code)  # type: ignore[assignment]
            if not pending.internal:
                rec.add("fleet.errors")
            pending.ticket._resolve(("err", exc))
            self._note_replay_resolved(pending)
            return
        seconds = time.monotonic() - pending.ticket.submitted_t
        cert = obj.get("cert")
        stats = obj.get("stats")
        result = obj.get("result")
        wire_trace = obj.get("trace")
        response = ServeResponse(
            # The CLIENT's id, not the wire id (a deduplicated duplicate
            # answers under the id its client actually sent).
            request_id=pending.ticket.request_id,
            intersects=bool(obj.get("verdict")),
            cert=cert if isinstance(cert, dict) else None,
            stats=dict(stats) if isinstance(stats, dict) else {},
            cached=bool(obj.get("cached")),
            seconds=seconds,
            result=result if isinstance(result, dict) else None,
            # Trace echo (qi-pulse): the worker echoes the context this
            # front door stamped; fall back to the pending record so the
            # client sees the trace even from a pre-pulse worker.
            trace=(wire_trace if isinstance(wire_trace, str)
                   else pending.trace),
            # qi-cost echo: the worker's attributed cost rides the wire
            # line; absent from pre-cost workers, cache hits and degraded
            # attribution — the response shape stays byte-compatible.
            cost=(obj.get("cost")
                  if isinstance(obj.get("cost"), dict) else None),
        )
        if not pending.internal:
            rec.add("fleet.verdicts")
            self._note_latency(seconds)
        else:
            rec.add("fleet.replayed_verdicts")
        pending.ticket._resolve(("ok", response))
        self._note_replay_resolved(pending)

    def _note_replay_resolved(self, pending: _Pending) -> None:
        """One failover-dispatched request reached its outcome; flip
        ``fleet.replay_complete`` back to 1 only when NO failover is mid-
        replay and every inherited request has resolved — the /readyz 503
        window covers the re-SOLVE of inherited work, not just its
        re-dispatch (docs/ROBUSTNESS.md §Fleet tier)."""
        with self._lock:
            if not pending.replaying:
                return
            pending.replaying = False
            self._replays_outstanding -= 1
            done = (
                self._replays_outstanding == 0
                and self._failovers_active == 0
            )
        if done:
            get_run_record().gauge("fleet.replay_complete", 1)

    def _note_latency(self, seconds: float) -> None:
        # Front-door end-to-end histogram (qi-pulse): submit→delivery as
        # the CLIENT experienced it.  The fleet.p50_ms/p99_ms gauges stay
        # byte-compatible — same nearest-rank estimator over the same
        # 512-sample window the pre-pulse deque carried.
        rec = get_run_record()
        h = rec.histogram("pulse.fleet_e2e_ms")
        h.observe(seconds * 1000.0)
        rec.gauge("fleet.p50_ms", round(h.window_percentile(50.0), 3))
        rec.gauge("fleet.p99_ms", round(h.window_percentile(99.0), 3))

    # ---- health probing / eviction ---------------------------------------

    def _on_worker_exit(self, worker_id: str) -> None:
        self._handle_worker_death(worker_id, "stdout EOF")

    def _probe_loop(self) -> None:
        rec = get_run_record()
        fails: Dict[str, int] = {}
        while not self._stop.wait(self.probe_interval_s):
            _fleet_sync("probe.tick")
            with self._lock:
                targets = [
                    (wid, self._workers[wid]) for wid in sorted(self._live)
                ]
            pongs: Dict[str, Dict[str, object]] = {}
            for wid, worker in targets:
                # The liveness check runs BEFORE the fault point: a dead
                # process must evict even while the probe path is broken
                # (the FLEET_PROBE contract — only the ping half degrades).
                if not worker.alive():
                    self._handle_worker_death(wid, "process exited")
                    continue
                try:
                    fault_point("fleet.probe")
                except (FaultInjected, OSError) as exc:
                    # Inconclusive, not dead: an injected probe failure
                    # must never cost a healthy worker its ring arc.
                    rec.add("fleet.probe_errors")
                    rec.event("fleet.probe_degraded", worker=wid,
                              error=str(exc))
                    continue
                pong = worker.ping(timeout=2.0)
                if pong is None:
                    fails[wid] = fails.get(wid, 0) + 1
                    rec.add("fleet.probe_timeouts")
                    if fails[wid] >= self.probe_fails:
                        reason = f"{fails[wid]} consecutive failed probes"
                        if worker.kind == "socket":
                            # A live-connection socket peer that stops
                            # ponging is PARTITIONED, not dead: suspect
                            # (hedged routing) and let the lease decide.
                            self._suspect_worker(wid, reason)
                        else:
                            self._handle_worker_death(wid, reason)
                else:
                    fails[wid] = 0
                    pongs[wid] = pong
                    self._renew_lease(wid)
            self._aggregate_health(pongs)
            self._expire_leases()
            self.scale_tick()

    # ---- partition tolerance: suspect → hedge → lease (qi-mesh) ----------

    def _suspect_worker(self, wid: str, reason: str) -> None:
        """Missed heartbeats on a SOCKET peer mean *suspected*, never
        immediately dead — a partition heals where a dead process does
        not.  A suspect keeps its ring arc, but every request routed to
        it is HEDGED to the next arc owner until it pongs again (rejoin)
        or its lease lapses (eviction + journal ship)."""
        rec = get_run_record()
        with self._lock:
            if wid in self._suspected or wid not in self._live:
                return
            self._suspected.add(wid)
            n_susp = len(self._suspected)
        rec.add("fleet.suspects")
        rec.gauge("fleet.suspected", n_susp)
        rec.event("fleet.suspected", worker=wid, reason=reason)
        log.warning(
            "fleet worker %s suspected (%s); its requests hedge to the "
            "next arc owner until it pongs or its %.3gs lease lapses",
            wid, reason, self.lease_s,
        )

    def _renew_lease(self, wid: str) -> None:
        """A pong renews the worker's heartbeat lease; a SUSPECTED worker
        answering again is a REJOIN — it takes its ring arc back, and its
        in-flight hedges deduplicate by wire request id (first answer
        resolves the ticket, the straggler books
        ``fleet.duplicate_responses``)."""
        rec = get_run_record()
        with self._lock:
            self._leases[wid] = time.monotonic() + self.lease_s
            rejoined = wid in self._suspected
            if rejoined:
                self._suspected.discard(wid)
                n_susp = len(self._suspected)
        if rejoined:
            rec.add("fleet.rejoins")
            rec.gauge("fleet.suspected", n_susp)
            rec.event("fleet.rejoined", worker=wid)
            log.info("fleet worker %s rejoined; suspicion lifted", wid)

    def _expire_leases(self) -> None:
        """Evict suspected peers whose heartbeat lease lapsed — behind
        the ``fleet.lease`` fault point, which degrades to SUSPECT-ONLY:
        a broken lease clock must never evict a healthy-but-slow peer
        (hedging keeps its requests answered), while a DEAD connection
        still evicts immediately through the reader-EOF path."""
        rec = get_run_record()
        now = time.monotonic()
        with self._lock:
            lapsed = [
                wid for wid in sorted(self._suspected)
                if wid in self._live and now > self._leases.get(wid, 0.0)
            ]
        if not lapsed:
            return
        try:
            fault_point("fleet.lease")
        except (FaultInjected, OSError) as exc:
            rec.add("fleet.lease_errors")
            rec.event("fleet.lease_degraded", error=str(exc))
            log.warning(
                "lease-lapse check degraded (%s); lapsed peers stay "
                "suspect-only (hedged) this cycle", exc,
            )
            return
        for wid in lapsed:
            self._handle_worker_death(wid, "heartbeat lease lapsed")

    # ---- elasticity (qi-mesh) --------------------------------------------

    def scale_tick(self, *, force: bool = False) -> Optional[str]:
        """One elasticity decision — the probe loop calls this every
        cycle when ``QI_FLEET_SCALE_INTERVAL_S`` > 0 (rate-limited to
        that cadence); tests and the bench drive it deterministically
        with ``force=True``.  Returns "up" / "down" / ``None``."""
        if not force:
            if self.scale_interval_s <= 0:
                return None
            now = time.monotonic()
            with self._lock:
                if now < self._next_scale_t or self._closed:
                    return None
                self._next_scale_t = now + self.scale_interval_s
        return self._apply_scale()

    def _apply_scale(self) -> Optional[str]:
        """The pulse→fleet-size control loop: the fleet-MERGED queue-wait
        p99 (the aggregation plane's ``fleet.pulse.queue_wait_ms``) plus
        the SLO plane's burn count turn into a spawn / retire / hold
        decision, bounded by ``QI_FLEET_SCALE_MIN``/``_MAX``.  Behind the
        ``fleet.scale`` fault point: any failure FREEZES the fleet at its
        current size, loudly.  Every exit books exactly one of
        ``fleet.scale_ups`` / ``fleet.scale_downs`` /
        ``fleet.scale_holds`` / ``fleet.scale_errors`` (the pass-8
        conservation law: a scale decision is never silent)."""
        rec = get_run_record()
        try:
            fault_point("fleet.scale")
            p99 = rec.histogram("fleet.pulse.queue_wait_ms").quantile_ms(99.0)
            from quorum_intersection_tpu.cost import slo_plane

            burning = slo_plane().burning_count()
            with self._lock:
                live = len(self._live)
            if (p99 > self.scale_up_ms or burning) and live < self.scale_max:
                wid = self._spawn_elastic()
                if wid is None:
                    rec.add("fleet.scale_errors")
                    rec.event("fleet.scale_degraded",
                              error="elastic spawn failed")
                    log.warning(
                        "elasticity degraded (elastic spawn failed); "
                        "fleet size frozen at its current size",
                    )
                    return None
                rec.add("fleet.scale_ups")
                rec.event("fleet.scaled", direction="up", worker=wid,
                          queue_p99_ms=round(p99, 3), burning=burning)
                log.info(
                    "fleet scaled UP to %s (queue p99 %.1fms, %d SLO "
                    "target(s) burning)", wid, p99, burning,
                )
                return "up"
            if (p99 < self.scale_down_ms and not burning
                    and live > self.scale_min):
                wid = self._retire_one()
                if wid is not None:
                    rec.add("fleet.scale_downs")
                    rec.event("fleet.scaled", direction="down", worker=wid,
                              queue_p99_ms=round(p99, 3))
                    log.info(
                        "fleet scaled DOWN (%s drained + retired, queue "
                        "p99 %.1fms)", wid, p99,
                    )
                    return "down"
            rec.add("fleet.scale_holds")
            return None
        except (FaultInjected, OSError, ValueError) as exc:
            rec.add("fleet.scale_errors")
            rec.event("fleet.scale_degraded", error=str(exc))
            log.warning(
                "elasticity degraded (%s); fleet size frozen at its "
                "current size", exc,
            )
            return None

    def _spawn_elastic(self) -> Optional[str]:
        """Scale-up: one fresh ``e<n>`` worker through the same
        construction + ready gate the respawn machinery uses, spawned
        synchronously (the scale loop already runs off the probe
        thread, never on a request path)."""
        with self._lock:
            self._elastic_seq += 1
            wid = f"e{self._elastic_seq}"
        worker = self._make_worker(wid)
        if not worker.wait_ready(timeout=120.0):
            worker.kill()
            return None
        with self._lock:
            arrived_dead = self._closed
            if not arrived_dead:
                self._workers[wid] = worker
                self._live.add(wid)
                self._ring.add(wid)
                self._leases[wid] = time.monotonic() + self.lease_s
                live, ring_size = len(self._live), len(self._ring)
        if arrived_dead:
            worker.kill()
            return None
        rec = get_run_record()
        rec.gauge("fleet.workers_live", live)
        rec.gauge("fleet.ring_size", ring_size)
        return wid

    def _retire_one(self) -> Optional[str]:
        """Scale-down by DRAIN-THROUGH-JOURNAL-INHERITANCE: admission to
        the retiree closes first (ring + live removal, so a racing
        dispatch re-routes through the shrunken ring), it drains
        gracefully (every accepted request answers), and then its journal
        — local file, or SHIPPED over the wire for a socket peer — runs
        the standard failover dedup: zero lost, zero duplicated, the
        PR 11 guarantee extended to voluntary shrink.  Prefers the
        newest elastic (``e<n>``) worker; never touches the last
        ``scale_min``."""
        rec = get_run_record()
        with self._lock:
            if len(self._live) <= self.scale_min:
                return None
            order = sorted(self._live, reverse=True)
            elastic = [w for w in order if w.startswith("e")]
            target = (elastic or order)[0]
            self._live.discard(target)
            self._ring.remove(target)
            # The voluntary close below must not re-enter death handling
            # when the reader thread sees its EOF.
            self._dead_handled.add(target)
            self._suspected.discard(target)
            self._leases.pop(target, None)
            live, ring_size = len(self._live), len(self._ring)
        rec.gauge("fleet.workers_live", live)
        rec.gauge("fleet.ring_size", ring_size)
        rec.gauge("fleet.suspected", len(self._suspected))
        _fleet_sync("scale.retire")
        worker = self._workers.get(target)
        if worker is None:
            return target
        journal: Optional[Path] = worker.journal_path
        if isinstance(worker, SocketWorker):
            # Quiesce, then pull the journal BEFORE closing the wire —
            # after the half-close there is nothing left to ship over.
            self._await_quiesce(target, timeout=30.0)
            journal = self._ship_journal(worker)
            worker.close(timeout=30.0)
        else:
            worker.close(timeout=60.0)
        self._failover(target, journal)
        return target

    def _await_quiesce(self, wid: str, timeout: float) -> None:
        """Bounded wait for every in-flight request assigned to ``wid``
        to resolve (their responses are still flowing on the open
        connection); leftovers after the bound re-route through the
        failover path anyway — bounded staleness, never a lost ticket."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                busy = any(
                    p.worker_id == wid for p in self._pending.values()
                )
            if not busy:
                return
            time.sleep(0.02)

    def _ship_journal(self, worker: SocketWorker) -> Optional[Path]:
        """Pull a remote peer's crash-only journal over the wire before
        its failover replay (chunked + digest-checked + fsync-before-ack
        in :meth:`SocketWorker.ship_journal`).  The ``fleet.ship`` fault
        point — or a wire already dead — degrades to LOCAL-JOURNAL-ONLY
        failover, loudly: the front door's own in-flight tickets still
        re-route (zero lost for everything it admitted), and the peer's
        journaled-but-unshipped work waits for its host to rejoin.
        Every exit books exactly one of ``fleet.ships`` /
        ``fleet.ship_errors`` (pass-8 conservation)."""
        rec = get_run_record()
        with rec.span("fleet.ship", worker=worker.worker_id):
            spool: Optional[Path] = None
            try:
                fault_point("fleet.ship")
                spool = worker.ship_journal(self.journal_dir / "shipped")
            except (FaultInjected, OSError) as exc:
                rec.add("fleet.ship_errors")
                rec.event("fleet.ship_degraded", worker=worker.worker_id,
                          error=str(exc))
                log.warning(
                    "journal ship from %s degraded (%s); failover "
                    "re-routes the front door's own in-flight tickets "
                    "only", worker.worker_id, exc,
                )
                return None
            if spool is None:
                rec.add("fleet.ship_errors")
                rec.event("fleet.ship_degraded", worker=worker.worker_id,
                          error="wire broken or stream torn")
                log.warning(
                    "journal ship from %s degraded (wire broken or stream "
                    "torn); failover re-routes the front door's own "
                    "in-flight tickets only", worker.worker_id,
                )
                return None
            rec.add("fleet.ships")
            rec.event("fleet.shipped", worker=worker.worker_id,
                      path=str(spool))
            return spool

    def _aggregate_health(self, pongs: Dict[str, Dict[str, object]]) -> None:
        """Fold the workers' pong snapshots into the fleet gauges the
        front door's ``/healthz`` exposes (fleet_workers_live /
        fleet_ring_size / fleet_store_hit_pct)."""
        rec = get_run_record()
        with self._lock:
            # Retain the last-known pong per STILL-LIVE worker: a single
            # missed ping (or one slow cycle) must not drop that worker
            # from the merged histograms — counts on /metrics would go
            # backwards and Prometheus rate() would read the dip+bounce
            # as a counter reset.  Evicted workers are pruned here.
            retained = {
                wid: pong for wid, pong in self._pongs.items()
                if wid in self._live
            }
            retained.update(pongs)
            self._pongs = retained
            live, ring_size = len(self._live), len(self._ring)
        rec.gauge("fleet.workers_live", live)
        rec.gauge("fleet.ring_size", ring_size)
        hits = misses = 0
        d_hits = d_misses = 0
        for pong in pongs.values():
            counters = pong.get("counters")
            if not isinstance(counters, dict):
                continue
            hits += int(counters.get("fleet.store_hits", 0) or 0)
            misses += int(counters.get("fleet.store_misses", 0) or 0)
            d_hits += int(counters.get("delta.scc_hits", 0) or 0)
            d_misses += int(counters.get("delta.scc_misses", 0) or 0)
        if hits + misses:
            rec.gauge(
                "fleet.store_hit_pct",
                round(100.0 * hits / (hits + misses), 2),
            )
        if d_hits + d_misses:
            rec.gauge(
                "fleet.delta_scc_reuse_pct",
                round(100.0 * d_hits / (d_hits + d_misses), 2),
            )
        # The pulse merge covers every live worker's LAST-KNOWN pong, not
        # just this cycle's successes, so the merged view is monotonic
        # between evictions.
        self._aggregate_pulse(retained, rec)
        self._aggregate_cost(retained, rec)

    def _aggregate_pulse(self, pongs: Dict[str, Dict[str, object]],
                         rec: RunRecord) -> None:
        """The qi-pulse aggregation plane (ISSUE 15): merge the workers'
        pong-carried pulse histogram snapshots bucket-wise into the front
        door's ``fleet.pulse.*`` views — mergeable by construction, so
        the fleet-wide p99 is computed over the UNION of worker samples,
        not the max of per-worker gauges.  Behind the ``pulse.aggregate``
        fault point: any failure degrades this CYCLE to per-worker-only
        metrics (loud counters, stale fleet view) and can never touch a
        verdict — aggregation sits entirely off the request path."""
        if not self._pulse_agg or not pongs:
            return
        try:
            fault_point("pulse.aggregate")
            # One snapshot per distinct worker PROCESS: in local-worker
            # mode every in-process engine shares one RunRecord, so N
            # pongs alias the same histograms — summing them would
            # multiply the fleet view N-fold.  Keyed by the pong's pid,
            # subprocess fleets (distinct pids) merge every worker.
            by_pid: Dict[str, Dict[object, Dict[str, object]]] = {}
            for pong in pongs.values():
                pulse = pong.get("pulse")
                if not isinstance(pulse, dict):
                    continue
                for name, snap in pulse.items():
                    if isinstance(snap, dict):
                        by_pid.setdefault(str(name), {})[
                            pong.get("pid")] = snap
            for name, snaps in sorted(by_pid.items()):
                merged = Histogram.merge_wire(list(snaps.values()))
                rec.histogram(f"fleet.{name}").set_from_wire(merged)
            if "pulse.e2e_ms" in by_pid:
                rec.gauge(
                    "fleet.e2e_p99_ms",
                    rec.histogram("fleet.pulse.e2e_ms").quantile_ms(99.0),
                )
        except (FaultInjected, OSError, ValueError, TypeError, KeyError) as exc:
            rec.add("pulse.agg_errors")
            rec.event("pulse.agg_degraded", error=str(exc))
            log.warning(
                "pulse aggregation degraded this cycle (%s); per-worker "
                "metrics remain available", exc,
            )

    def _aggregate_cost(self, pongs: Dict[str, Dict[str, object]],
                        rec: RunRecord) -> None:
        """The qi-cost aggregation plane (ISSUE 17): merge the workers'
        pong-carried per-tenant cost snapshots into the front door's
        fleet-wide tenant table.  Same pid-dedupe rule as the pulse merge
        (local workers share one process table — summing N aliased pongs
        would multiply the view N-fold) and the merge REPLACES the fleet
        table each cycle: pong snapshots are cumulative, so accumulating
        them would double-count every prior cycle.  Behind the
        ``cost.attribute`` fault point: a failure degrades this CYCLE to
        per-worker tables only, never touches a verdict."""
        if not pongs:
            return
        try:
            fault_point("cost.attribute")
            from quorum_intersection_tpu.cost import (
                fleet_tenant_table, merge_tenant_snapshots,
            )
            by_pid: Dict[object, Dict[str, Dict[str, object]]] = {}
            for pong in pongs.values():
                cost = pong.get("cost")
                if isinstance(cost, dict) and cost:
                    by_pid[pong.get("pid")] = cost  # type: ignore[assignment]
            if not by_pid:
                return
            merged = merge_tenant_snapshots(list(by_pid.values()))
            fleet_tenant_table().replace(merged)
            rec.gauge("fleet.cost_tenants", len(merged))
        except (FaultInjected, OSError, ValueError, TypeError, KeyError) as exc:
            rec.add("cost.attribute_errors")
            rec.event("cost.degraded", site="fleet.aggregate",
                      error=str(exc))
            log.warning(
                "cost aggregation degraded this cycle (%s); per-worker "
                "tables remain available", exc,
            )

    def healthz(self) -> Dict[str, object]:
        """The aggregated fleet health picture (per-worker last pongs +
        ring state) — the bench and tests read it; the qi-health/1
        endpoint exposes the gauge subset."""
        with self._lock:
            return {
                "schema": FLEET_SCHEMA,
                "workers_live": len(self._live),
                "ring_size": len(self._ring),
                "pending": len(self._pending),
                "suspected": sorted(self._suspected),
                "workers": dict(self._pongs),
            }

    def kill_worker(self, worker_id: str, *, evict: bool = False) -> None:
        """Hard-kill one worker (the bench's kill-one-of-N hook).  With
        ``evict=False`` (default) the health probes discover the death —
        the production path; ``evict=True`` runs eviction + journal
        failover immediately (the deterministic schedule/test path)."""
        worker = self._workers.get(worker_id)
        if worker is None:
            raise KeyError(f"unknown fleet worker {worker_id!r}")
        worker.kill()
        if evict:
            self._handle_worker_death(worker_id, "killed (explicit)")

    # ---- failover --------------------------------------------------------

    def _handle_worker_death(self, worker_id: str, reason: str) -> None:
        rec = get_run_record()
        with self._lock:
            if worker_id in self._dead_handled or worker_id not in self._live:
                return
            self._dead_handled.add(worker_id)
            self._live.discard(worker_id)
            self._ring.remove(worker_id)
            self._suspected.discard(worker_id)
            self._leases.pop(worker_id, None)
            live, ring_size = len(self._live), len(self._ring)
            n_susp = len(self._suspected)
        rec.add("fleet.evictions")
        rec.gauge("fleet.workers_live", live)
        rec.gauge("fleet.ring_size", ring_size)
        rec.gauge("fleet.suspected", n_susp)
        rec.event("fleet.evicted", worker=worker_id, reason=reason)
        log.warning(
            "fleet worker %s evicted (%s); its hash range and unfinished "
            "journal move to the surviving peers", worker_id, reason,
        )
        _fleet_sync("evict.removed")
        worker = self._workers.get(worker_id)
        journal = worker.journal_path if worker is not None else None
        if journal is None and isinstance(worker, SocketWorker):
            # A remote peer's journal lives on its host: ship it over the
            # wire while (if) the connection still answers — a lease
            # lapse often leaves a usable wire, a hard kill does not.
            journal = self._ship_journal(worker)
        self._failover(worker_id, journal)
        self._maybe_respawn(worker_id)

    # ---- auto-respawn ----------------------------------------------------

    def _maybe_respawn(self, dead_id: str) -> None:
        """Schedule a replacement for a dead worker's slot (ROADMAP
        follow-up: pre-respawn the ring shrank on every eviction until
        restart).  Bounded per slot by ``QI_FLEET_RESPAWN_MAX`` so a
        crash-looping configuration cannot respawn forever; the spawn
        itself runs off-thread with exponential backoff — eviction and
        failover never wait on a subprocess start."""
        slot = dead_id.split(".", 1)[0]
        with self._lock:
            if self._closed or self.respawn_max <= 0:
                return
            n = self._respawn_counts.get(slot, 0) + 1
            if n > self.respawn_max:
                exhausted = True
            else:
                exhausted = False
                self._respawn_counts[slot] = n
        if exhausted:
            get_run_record().event(
                "fleet.respawn_exhausted", worker=dead_id,
                max=self.respawn_max,
            )
            log.warning(
                "fleet worker slot %s exhausted its %d respawns; the ring "
                "stays shrunk for this slot", slot, self.respawn_max,
            )
            return
        new_id = f"{slot}.r{n}"
        # qi-lint: allow(cancel-token-plumbed) — bounded one-shot respawn; stop() flips _closed and an arriving replacement is torn down
        threading.Thread(
            target=self._respawn_worker, args=(new_id, n),
            name=f"qi-fleet-respawn-{new_id}", daemon=True,
        ).start()

    def _respawn_worker(self, new_id: str, attempt: int) -> None:
        rec = get_run_record()
        # Bounded exponential backoff: a dying host gets breathing room,
        # a one-off crash gets its replacement almost immediately.
        time.sleep(min(0.1 * (2 ** (attempt - 1)), 2.0))
        with self._lock:
            if self._closed:
                return  # stop() won the backoff window; nothing to restore
        _fleet_sync("respawn.begin")
        try:
            worker = self._make_worker(new_id)
        except Exception as exc:  # noqa: BLE001 — a failed spawn must not kill the probe loop
            rec.add("fleet.respawn_errors")
            rec.event("fleet.respawn_failed", worker=new_id, error=str(exc))
            log.warning("fleet respawn %s failed (%s)", new_id, exc)
            return
        if not worker.wait_ready(timeout=120.0):
            rec.add("fleet.respawn_errors")
            rec.event("fleet.respawn_failed", worker=new_id,
                      error="never reported replay-complete")
            worker.kill()
            return
        with self._lock:
            arrived_dead = self._closed
            if not arrived_dead:
                self._workers[new_id] = worker
                self._live.add(new_id)
                self._ring.add(new_id)
                self._leases[new_id] = time.monotonic() + self.lease_s
                live, ring_size = len(self._live), len(self._ring)
        if arrived_dead:
            worker.kill()
            return
        rec.add("fleet.respawns")
        rec.gauge("fleet.workers_live", live)
        rec.gauge("fleet.ring_size", ring_size)
        rec.event("fleet.respawned", worker=new_id, attempt=attempt)
        log.info(
            "fleet worker %s respawned (attempt %d); ring restored to %d "
            "worker(s)", new_id, attempt, ring_size,
        )
        _fleet_sync("respawn.done")

    def adopt_journal(self, journal_path: Union[str, Path],
                      worker_id: str = "adopted") -> int:
        """Inherit a crashed predecessor's request journal: every
        journaled-but-unfinished request re-solves on the worker its hash
        range now belongs to.  Returns the number of requests replayed
        (the front-door-restart recovery path; also the schedule
        harness's deterministic failover entry).

        The path must be readable on THIS host — an unreadable or
        remote-host path raises the typed
        :class:`JournalUnreadableError` (code ``journal_unreadable``)
        pointing at the mesh ship protocol, instead of letting the
        ``fleet.replay`` degrade path silently swallow what is really a
        caller mistake."""
        path = Path(journal_path)
        try:
            with path.open("rb"):
                pass
        except OSError as exc:
            rec = get_run_record()
            rec.add("fleet.errors")
            rec.event("fleet.adopt_rejected", path=str(path),
                      error=str(exc))
            raise JournalUnreadableError(
                f"journal {path} is not readable on this host ({exc}); a "
                f"REMOTE peer's journal cannot be adopted by path — join "
                f"the peer over the mesh (serve --socket + fleet --join) "
                f"and let the ship_journal protocol stream it (chunked, "
                f"digest-checked, fsync-before-ack)"
            ) from exc
        return self._failover(worker_id, path)

    def _failover(self, worker_id: str,
                  journal_path: Optional[Path]) -> int:
        """Replay a dead worker's unfinished work on the peers inheriting
        its hash range: the front door's own in-flight tickets first
        (they re-route with their clients still attached), then the
        journal's pending entries (zero lost), deduplicated against both
        the in-flight set and the journal's done marks (zero duplicated).
        """
        rec = get_run_record()
        with self._lock:
            self._failovers_active += 1
        rec.gauge("fleet.replay_complete", 0)
        _fleet_sync("replay.begin")
        entries: List[Dict[str, object]] = []
        if journal_path is not None:
            try:
                fault_point("fleet.replay")
                journal = RequestJournal(journal_path)
                scanned, corrupt, torn = journal.scan()
                done_ids = {
                    e.get("request_id") for e in scanned
                    if e.get("kind") == "done"
                }
                entries = [
                    e for e in scanned
                    if e.get("kind") == "req"
                    and e.get("request_id") not in done_ids
                ]
                if torn:
                    rec.add("fleet.replay_torn_tails")
                if corrupt:
                    journal.quarantine(
                        corrupt, "corrupt line in a dead worker's journal",
                    )
            except (FaultInjected, OSError) as exc:
                rec.add("fleet.replay_errors")
                rec.event("fleet.replay_degraded", worker=worker_id,
                          error=str(exc))
                log.warning(
                    "dead worker %s journal unreadable (%s); failover "
                    "degrades to re-routing the front door's own in-flight "
                    "tickets only", worker_id, exc,
                )
                entries = []
        with self._lock:
            local = [
                p for p in self._pending.values()
                if p.worker_id == worker_id
            ]
        replayed = 0
        seen: Set[str] = set()
        with rec.span("fleet.replay", worker=worker_id,
                      inflight=len(local), journaled=len(entries)):
            for pending in local:
                seen.add(pending.wire_id)
                with self._lock:
                    # Flag + counter move together under the lock, and
                    # only while the entry is still unresolved — a ticket
                    # resolving concurrently must not leave the
                    # outstanding count stuck above zero.
                    if (self._pending.get(pending.wire_id) is pending
                            and not pending.replaying):
                        pending.replaying = True
                        self._replays_outstanding += 1
                self._dispatch(pending)
                replayed += 1
            for entry in entries:
                rid = entry.get("request_id")
                nodes = entry.get("nodes")
                if (not isinstance(rid, str) or rid in seen
                        or not isinstance(nodes, list)):
                    continue
                seen.add(rid)
                with self._lock:
                    known = rid in self._pending
                if known:
                    continue  # already re-routed under a different owner
                entry_query = entry.get("query")
                entry_trace = entry.get("trace")
                pending = _Pending(
                    ticket=Ticket(rid, time.monotonic(), None),
                    wire_id=rid,
                    fingerprint=str(entry.get("fingerprint") or rid),
                    nodes=nodes,
                    deadline_s=None,  # its original budget is long since moot
                    internal=True,
                    replaying=True,
                    # Inherited typed queries re-ask the SAME question on
                    # the inheriting peer (the journal carries the wire
                    # form; the fingerprint already keys the kind).
                    query=(entry_query
                           if isinstance(entry_query, dict) else None),
                    # qi-pulse: the dead worker journaled the original
                    # wire trace — the inheriting peer's re-solve joins
                    # the request's trace, not a fresh one.
                    trace=(entry_trace
                           if isinstance(entry_trace, str) else None),
                )
                with self._lock:
                    self._pending[rid] = pending
                    self._replays_outstanding += 1
                _fleet_sync("replay.dispatch")
                self._dispatch(pending)
                replayed += 1
        if replayed:
            rec.add("fleet.replays", replayed)
        rec.event("fleet.replayed", worker=worker_id, requests=replayed)
        _fleet_sync("replay.done")
        with self._lock:
            self._failovers_active -= 1
            done = (
                self._replays_outstanding == 0
                and self._failovers_active == 0
            )
        if done:
            rec.gauge("fleet.replay_complete", 1)
        return replayed


# ---- CLI subcommand ---------------------------------------------------------


def build_fleet_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m quorum_intersection_tpu fleet",
        description=(
            "Replicated snapshot-verdict service: N serve workers behind "
            "a consistent-hash front door.  Same JSONL contract as the "
            "serve subcommand — one JSON request per stdin line, one JSON "
            "response per stdout line in completion order; EOF drains "
            "every worker and exits 0."
        ),
    )
    p.add_argument("-n", "--workers", type=int, default=None, metavar="N",
                   help="worker count (env twin: QI_FLEET_WORKERS)")
    p.add_argument("--journal-dir", metavar="DIR", default=None,
                   help="directory of the per-worker crash-only request "
                        "journals (default: a temporary directory); a "
                        "dead worker's unfinished journal replays on the "
                        "peer inheriting its hash range")
    p.add_argument("--store-dir", metavar="DIR", default=None,
                   help="shared SCC-fragment store directory exported to "
                        "every worker (env twin: QI_FLEET_STORE_DIR; "
                        "default: <journal-dir>/store)")
    p.add_argument("--backend", default="auto",
                   choices=["auto", "python", "cpp", "tpu", "tpu-sweep",
                            "tpu-frontier"],
                   help="search backend inside each worker (default auto)")
    p.add_argument("--local-workers", action="store_true",
                   help="run the workers in-process instead of as "
                        "subprocesses (debug/smoke mode)")
    p.add_argument("--join", action="append", default=None,
                   metavar="HOST:PORT",
                   help="join a REMOTE serve worker into the ring (a peer "
                        "running 'serve --socket PORT --bind ADDR'); "
                        "repeatable.  The join runs the versioned qi-mesh "
                        "handshake — protocol + package fingerprint + "
                        "QI_FLEET_TOKEN digest — and a mismatch is a "
                        "typed reject, never a silently skewed mesh")
    p.add_argument("--lease-s", type=float, default=None, metavar="F",
                   help="heartbeat lease for socket-joined peers (env "
                        "twin: QI_FLEET_LEASE_S).  Missed probes SUSPECT "
                        "a peer (its requests hedge to the next arc "
                        "owner); only a lapsed lease evicts and ships its "
                        "journal")
    p.add_argument("--scale-interval-s", type=float, default=None,
                   metavar="F",
                   help="elasticity cadence (env twin: "
                        "QI_FLEET_SCALE_INTERVAL_S; 0 disables): the "
                        "fleet-merged pulse queue-wait p99 + SLO burn "
                        "state drive spawn/retire between "
                        "QI_FLEET_SCALE_MIN and QI_FLEET_SCALE_MAX")
    p.add_argument("--deadline-s", type=float, default=None, metavar="F",
                   help="per-request deadline budget forwarded to the "
                        "workers (env twin: QI_SERVE_DEADLINE_S)")
    p.add_argument("--batch-max", type=int, default=None, metavar="N",
                   help="per-worker drain batch bound (QI_SERVE_BATCH_MAX)")
    p.add_argument("--cache-max", type=int, default=None, metavar="N",
                   help="per-worker verdict-cache capacity "
                        "(QI_SERVE_CACHE_MAX)")
    p.add_argument("--dangling-policy", default="strict",
                   choices=["strict", "alias0"],
                   help="unknown validator refs (default strict)")
    p.add_argument("--scc-select", default="quorum-bearing",
                   choices=["quorum-bearing", "front"],
                   help="which SCC to search (default quorum-bearing)")
    p.add_argument("--scope-scc", action="store_true",
                   help="scope availability to the searched SCC")
    p.add_argument("--emit-certs", action="store_true",
                   help="verdict responses carry their qi-cert/1 "
                        "certificate and solve stats")
    p.add_argument("--metrics-json", metavar="PATH", default=None,
                   help="stream qi-telemetry/1 JSONL to PATH")
    p.add_argument("--metrics-prom", metavar="PATH", default=None,
                   help="write final counters/gauges to PATH "
                        "(Prometheus textfile)")
    return p


def fleet_main(argv: Optional[List[str]] = None) -> int:
    """The ``fleet`` subcommand body (dispatched from cli.py)."""
    from quorum_intersection_tpu.utils import telemetry

    args = build_fleet_parser().parse_args(argv)
    record = telemetry.get_run_record()
    if args.metrics_json:
        record.add_sink(telemetry.JsonlSink(args.metrics_json))
    if args.metrics_prom:
        record.add_sink(telemetry.PromFileSink(args.metrics_prom))
    engine = FleetEngine(
        args.workers,
        backend=args.backend,
        worker_mode="local" if args.local_workers else "subprocess",
        journal_dir=args.journal_dir,
        store_dir=args.store_dir,
        deadline_s=args.deadline_s,
        batch_max=args.batch_max,
        cache_max=args.cache_max,
        dangling=args.dangling_policy,
        scc_select=args.scc_select,
        scope_to_scc=args.scope_scc,
        joins=args.join,
        lease_s=args.lease_s,
        scale_interval_s=args.scale_interval_s,
    )
    session = JsonlSession(
        engine,  # type: ignore[arg-type] — same submit/Ticket contract
        sys.stdout, emit_certs=args.emit_certs,
    )
    try:
        report = engine.start()
        session.emit({"kind": "fleet", **report})
        run_jsonl_loop(session, sys.stdin)
        engine.stop(drain=True)
        session.wait_drained(timeout=None)
        return 0
    finally:
        engine.stop(drain=False)
        record.finish()
