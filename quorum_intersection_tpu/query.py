"""qi-query/1 — the typed query subsystem (ISSUE 12 tentpole).

The engine answered exactly one question — "do all quorums intersect?" —
while the ROADMAP's north star is a serving tier answering millions of
users' *questions*, plural.  This module is the layer that turns the
verdict pipeline into that multi-scenario service: a typed query schema
with four kinds, all resolving through the existing engine stack, all
emitting checker-validated certificates, and all served through the same
JSONL protocol (`serve.py` / `fleet.py` accept a ``"query"`` field on the
request line; absent means ``intersection`` and the wire stays
byte-compatible).

- **``intersection``** — today's boolean verdict, unchanged: the
  degenerate query.  Deliberately NOT routed through the query dispatch
  fault point, so injected query faults can never touch the legacy path.
- **``relaxed``** — two-family mode (Fast Flexible Paxos,
  arXiv:2008.02671): accept a SECOND quorum-set family over the same node
  set and decide whether every family-A quorum intersects every family-B
  quorum — fast-vs-classic quorum safety.  The search enumerates family
  A's windows inside its quorum-bearing SCC(s) (the cross-family fixpoint
  in ``fbas/semantics.py``; the vectorized path rides the two-circuit
  restriction ``encode/circuit.restrict_two_family``) and guards each
  distinct A-quorum with one family-B fixpoint.  A ``false`` verdict
  carries a cross-family witness pair — one quorum from each family —
  with per-member slice evidence against each family's own graph.
- **``whatif``** — "does the network survive if validators X, Y, Z
  leave?" (Read-Write Quorum Systems Made Practical, arXiv:2104.04102):
  the removal frontier (subsets of the candidate validators up to
  ``max_k``) expands into masked variants of ONE base topology — a
  departed validator's quorum set is nulled, never deleted, so every
  variant keeps the identical shape and the batch lane-packs perfectly
  through ``pipeline.check_many``; with qi-delta enabled the k-subset
  frontier is incremental (structurally untouched SCCs re-serve their
  fragments across steps).  The result is a per-subset verdict table
  plus the minimal failing subset.
- **``analytics``** — the ``analytics/`` suite (top tier, minimal
  blocking set, minimum splitting set, PageRank) promoted to first-class
  served query types with provenance-stamped result certificates;
  splitting/blocking results embed a re-provable ``qi-cert/1`` (the
  reduced/masked network's verdict certificate plus the exact node list
  it is against), which ``tools/check_cert.py`` re-validates through the
  existing witness-evidence / no-quorum paths.

Dispatch of every non-intersection kind sits behind the declared
``query.dispatch`` fault point (docs/ROBUSTNESS.md): an injected or real
failure — including an unknown kind — degrades to a typed
:class:`QueryError`, NEVER a wrong or silently-absent verdict.  Telemetry:
``query.*`` counters/events (docs/OBSERVABILITY.md registry).  Serving
integration extends the snapshot fingerprint with the query kind so the
verdict cache, single-flight coalescing, journal replay and the shared
SCC store never cross query types.

CLI: ``python -m quorum_intersection_tpu query`` (one-shot typed query
over stdin); ``benchmarks/serve.py --queries`` is the mixed-workload
load phase.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import sys
from dataclasses import dataclass, field
from itertools import combinations
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from quorum_intersection_tpu.cert import (
    CERT_SCHEMA,
    witness_evidence,
)
from quorum_intersection_tpu.encode.circuit import (
    encode_circuit,
    max_quorum_np,
    restrict_two_family,
)
from quorum_intersection_tpu.fbas.graph import TrustGraph, build_graph
from quorum_intersection_tpu.fbas.schema import Fbas, parse_fbas
from quorum_intersection_tpu.fbas.semantics import (
    cross_family_disjoint_quorum,
    max_quorum,
)
from quorum_intersection_tpu.pipeline import (
    SolveResult,
    check_many,
    quorum_bearing_sccs,
)
from quorum_intersection_tpu.utils.env import qi_env_int
from quorum_intersection_tpu.utils.faults import FaultInjected, fault_point
from quorum_intersection_tpu.utils.logging import get_logger
from quorum_intersection_tpu.utils.telemetry import get_run_record

log = get_logger("query")

QUERY_SCHEMA = "qi-query/1"
QUERY_CERT_SCHEMA = "qi-query-cert/1"

KINDS = ("intersection", "relaxed", "whatif", "analytics")
ANALYTICS_METRICS = ("top_tier", "blocking_set", "splitting_set", "pagerank")

# Window batch one vectorized relaxed chunk evaluates at once: big enough
# to amortize the numpy fixpoint, small enough that the (B, m) masks and
# (B, U) satisfaction arrays stay cache-resident.
RELAXED_CHUNK = 2048

# Hard size cap on one relaxed enumeration: 2^22 windows is the same
# order as the single-family sweep's narrow-window practical bound; past
# it the query degrades to a TYPED error instead of an unbounded burn.
RELAXED_SCC_MAX = 22

# What-if candidate default pool cap: the frontier is C(candidates, k)
# variants, so the default candidate pool (the main SCC's members) is
# clipped deterministically before expansion.
WHATIF_CANDIDATES_MAX = 16

_CheckMany = Callable[[List[Fbas]], List[SolveResult]]


class QueryError(ValueError):
    """Typed query-layer failure (the ``query.dispatch`` degrade target).

    Subclasses ``ValueError`` so transports that predate the query layer
    still turn it into a typed ``invalid`` error line rather than a
    crash; query-aware transports emit ``code`` verbatim.  The contract
    (docs/ROBUSTNESS.md): an unknown kind, a malformed parameter, an
    over-budget frontier, or an injected dispatch fault all land HERE —
    never a wrong verdict, never a silent drop."""

    code = "query_error"

    def __init__(self, message: str, code: Optional[str] = None) -> None:
        super().__init__(message)
        if code is not None:
            self.code = code


@dataclass(frozen=True)
class Query:
    """One parsed, validated typed query (``qi-query/1``)."""

    kind: str = "intersection"
    family_b: Optional[Tuple[str, ...]] = None  # canonical JSON per node
    candidates: Optional[Tuple[str, ...]] = None
    max_k: int = 1
    metric: Optional[str] = None
    splitting_max_k: int = 2

    @staticmethod
    def parse(raw: object) -> "Query":
        """Parse the wire form: ``None``/absent ⇒ intersection (the
        byte-compatible degenerate), a string ⇒ ``{"kind": str}``, a dict
        ⇒ full params.  Raises typed :class:`QueryError` on anything
        unknown or malformed — at ADMISSION, so a bad query costs its
        client one typed rejection, not a queue slot."""
        if raw is None:
            return Query()
        if isinstance(raw, str):
            raw = {"kind": raw}
        if not isinstance(raw, dict):
            raise QueryError(
                f"query must be a kind string or an object, got "
                f"{type(raw).__name__}", code="invalid_query",
            )
        kind = raw.get("kind", "intersection")
        if kind not in KINDS:
            raise QueryError(
                f"unknown query kind {kind!r} (expected one of {KINDS})",
                code="unknown_query",
            )
        family_b: Optional[Tuple[str, ...]] = None
        if kind == "relaxed":
            fb = raw.get("family_b")
            if not isinstance(fb, list) or not fb:
                raise QueryError(
                    "relaxed query requires family_b: a non-empty "
                    "stellarbeat node array (the second quorum-set family "
                    "over the same node set)", code="invalid_query",
                )
            family_b = tuple(
                json.dumps(n, sort_keys=True, separators=(",", ":"))
                for n in fb
            )
        candidates: Optional[Tuple[str, ...]] = None
        if raw.get("candidates") is not None:
            cand = raw.get("candidates")
            if not isinstance(cand, list) or not all(
                isinstance(c, str) for c in cand
            ):
                raise QueryError(
                    "whatif candidates must be a list of publicKeys",
                    code="invalid_query",
                )
            candidates = tuple(cand)
        max_k = raw.get("max_k", 1)
        if not isinstance(max_k, int) or isinstance(max_k, bool) or max_k < 1:
            raise QueryError(
                f"whatif max_k must be a positive integer, got {max_k!r}",
                code="invalid_query",
            )
        metric: Optional[str] = None
        if kind == "analytics":
            metric = raw.get("metric")
            if metric not in ANALYTICS_METRICS:
                raise QueryError(
                    f"unknown analytics metric {metric!r} (expected one of "
                    f"{ANALYTICS_METRICS})", code="unknown_query",
                )
        smk = raw.get("splitting_max_k", 2)
        if not isinstance(smk, int) or isinstance(smk, bool) or smk < 0:
            raise QueryError(
                f"splitting_max_k must be a non-negative integer, got "
                f"{smk!r}", code="invalid_query",
            )
        return Query(
            kind=str(kind), family_b=family_b, candidates=candidates,
            max_k=int(max_k), metric=metric, splitting_max_k=int(smk),
        )

    def family_b_nodes(self) -> List[Dict[str, object]]:
        """The second family's raw node list (relaxed queries only)."""
        assert self.family_b is not None
        return [json.loads(n) for n in self.family_b]

    def to_wire(self) -> Optional[Dict[str, object]]:
        """The JSON wire form (``None`` for the degenerate intersection
        query, keeping legacy request lines byte-identical)."""
        if self.kind == "intersection":
            return None
        out: Dict[str, object] = {"kind": self.kind}
        if self.family_b is not None:
            out["family_b"] = self.family_b_nodes()
        if self.candidates is not None:
            out["candidates"] = list(self.candidates)
        if self.kind == "whatif":
            out["max_k"] = self.max_k
        if self.metric is not None:
            out["metric"] = self.metric
            if self.metric == "splitting_set":
                out["splitting_max_k"] = self.splitting_max_k
        return out

    def fingerprint(self) -> str:
        """Cache/routing fingerprint component: empty for intersection (so
        legacy fingerprints stay byte-identical), else a stable digest of
        the CANONICAL WIRE FORM — fingerprints never cross query types,
        two relaxed queries with different B families never share a cache
        line, and ``fingerprint(parse(to_wire(q))) == fingerprint(q)``
        always holds (the fleet front door keys its routing on this while
        the worker re-parses the wire form and keys its cache/journal on
        the SAME digest; a param the wire form drops — e.g. a stray
        ``splitting_max_k`` on a top-tier query — must therefore not
        participate)."""
        if self.kind == "intersection":
            return ""
        return hashlib.sha256(
            json.dumps({"v": 2, "wire": self.to_wire()}, sort_keys=True,
                       separators=(",", ":")).encode()
        ).hexdigest()[:16]


@dataclass
class QueryResult:
    """One resolved query: verdict + structured payload + certificate.

    Duck-types the slice of :class:`pipeline.SolveResult` the serving
    layer's cache/delivery path reads (``intersects`` / ``cert`` /
    ``stats``), so a QueryResult rides the existing verdict cache,
    single-flight coalescing and journal done-marks unchanged."""

    kind: str
    verdict: bool
    result: Dict[str, object] = field(default_factory=dict)
    cert: Optional[Dict[str, object]] = None
    stats: Dict[str, object] = field(default_factory=dict)

    @property
    def intersects(self) -> bool:
        return bool(self.verdict)


def mask_nodes(
    nodes: Sequence[Dict[str, object]], removed: Sequence[str]
) -> List[Dict[str, object]]:
    """One what-if variant: the departed validators' quorum sets are
    NULLED, never deleted — a null-qset node is never satisfiable (quirk
    Q2) and never available to anyone else's slice, which is exactly
    "validator left", while the node COUNT and order stay identical so
    every variant of one base shares one circuit shape (the lane-packing
    precondition, docs/PARITY.md §Lane packing)."""
    gone = frozenset(removed)
    out: List[Dict[str, object]] = []
    for node in nodes:
        if node.get("publicKey") in gone:
            out.append({**node, "quorumSet": None})
        else:
            out.append(dict(node))
    return out


def _default_check_many(
    backend: object, dangling: str, scc_select: str, scope_to_scc: bool,
    pack: Optional[bool],
) -> _CheckMany:
    def run(sources: List[Fbas]) -> List[SolveResult]:
        return check_many(
            sources, backend=backend, dangling=dangling,  # type: ignore[arg-type]
            scc_select=scc_select, scope_to_scc=scope_to_scc, pack=pack,
        )

    return run


class QueryEngine:
    """Resolver for all four query kinds (see module docstring).

    One engine per serving configuration (dangling policy, SCC selection,
    scoping, backend) — the same compatibility contract as
    :class:`serve.ServeEngine`, whose drain loop owns one of these.
    ``check_many_fn`` substitutes the batch solver (the serving layer
    injects its delta-aware, deadline-cancellable one); the default is
    plain :func:`pipeline.check_many`.
    """

    def __init__(
        self,
        *,
        backend: object = "auto",
        dangling: str = "strict",
        scc_select: str = "quorum-bearing",
        scope_to_scc: bool = False,
        pack: Optional[bool] = None,
        whatif_limit: Optional[int] = None,
    ) -> None:
        self.backend = backend
        self.dangling = dangling
        self.scc_select = scc_select
        self.scope_to_scc = scope_to_scc
        self.pack = pack
        self.whatif_limit = (
            whatif_limit if whatif_limit is not None
            else max(qi_env_int("QI_QUERY_WHATIF_LIMIT", 512), 1)
        )

    # ---- dispatch --------------------------------------------------------

    def resolve(
        self,
        nodes: List[Dict[str, object]],
        query: Query,
        *,
        check_many_fn: Optional[_CheckMany] = None,
        cancel: Optional[object] = None,
    ) -> QueryResult:
        """Resolve one typed query against one snapshot.

        Every non-intersection kind routes through the ``query.dispatch``
        fault point first: an injected fault, an unknown kind (belt and
        braces — :meth:`Query.parse` already rejects them), or ANY
        resolver failure degrades to a typed :class:`QueryError` — the
        verdict of a query is either computed or loudly absent, never
        wrong (docs/ROBUSTNESS.md).  ``cancel`` (a
        ``backends.base.CancelToken``) is the serve deadline supervisor's
        handle: the relaxed enumeration checks it per window chunk and
        the analytics resolvers between SCCs, raising
        ``SearchCancelled`` — which propagates untouched (the whatif
        path is cancelled inside ``check_many_fn`` as ever).
        """
        from quorum_intersection_tpu.backends.base import SearchCancelled

        rec = get_run_record()
        run = check_many_fn or _default_check_many(
            self.backend, self.dangling, self.scc_select, self.scope_to_scc,
            self.pack,
        )
        rec.add("query.requests")
        if query.kind == "intersection":
            res = run([parse_fbas(nodes)])[0]
            return QueryResult(
                kind="intersection", verdict=bool(res.intersects),
                result={"kind": "intersection",
                        "verdict": bool(res.intersects)},
                cert=res.cert, stats=dict(res.stats),
            )
        rec.add(f"query.{query.kind}")
        try:
            fault_point("query.dispatch")
            if query.kind == "relaxed":
                out = self._resolve_relaxed(nodes, query, cancel)
            elif query.kind == "whatif":
                out = self._resolve_whatif(nodes, query, run)
            elif query.kind == "analytics":
                out = self._resolve_analytics(nodes, query, cancel)
            else:  # unreachable past Query.parse; typed anyway
                raise QueryError(
                    f"unknown query kind {query.kind!r}", code="unknown_query"
                )
        except (QueryError, SearchCancelled):
            rec.add("query.errors")
            raise
        except (FaultInjected, OSError) as exc:
            rec.add("query.errors")
            rec.event("query.degraded", kind=query.kind, error=str(exc))
            log.warning(
                "query dispatch degraded (%s); typed error, never a wrong "
                "verdict", exc,
            )
            raise QueryError(
                f"query dispatch degraded: {exc}", code="query_degraded"
            ) from exc
        except Exception as exc:  # noqa: BLE001 — any resolver failure is a typed error
            rec.add("query.errors")
            rec.event("query.degraded", kind=query.kind, error=str(exc))
            raise QueryError(
                f"{query.kind} query failed: {exc}", code="query_failed"
            ) from exc
        rec.event(
            "query.dispatched", kind=query.kind, verdict=out.verdict,
        )
        return out

    # ---- relaxed (two-family) -------------------------------------------

    def _resolve_relaxed(
        self, nodes: List[Dict[str, object]], query: Query,
        cancel: Optional[object] = None,
    ) -> QueryResult:
        from quorum_intersection_tpu.fbas.graph import tarjan_scc

        rec = get_run_record()
        graph_a = build_graph(parse_fbas(nodes), dangling=self.dangling)
        nodes_b = query.family_b_nodes()
        graph_b = build_graph(parse_fbas(nodes_b), dangling=self.dangling)
        if list(graph_a.node_ids) != list(graph_b.node_ids):
            raise QueryError(
                "two-family query requires both families over the SAME "
                "node set in the same order (publicKey sequences differ)",
                code="invalid_query",
            )
        n_sccs_a, _ = tarjan_scc(graph_a.n, graph_a.succ)
        bearing_a = quorum_bearing_sccs(graph_a)
        b_any = max_quorum(
            graph_b, range(graph_b.n), [True] * graph_b.n
        )
        reason = "search"
        qa: Optional[List[int]] = None
        qb: Optional[List[int]] = None
        ledger: List[Dict[str, object]] = []
        engine = "relaxed-host"
        if not bearing_a:
            reason = "no_quorum_family_a"
        elif not b_any:
            reason = "no_quorum_family_b"
        else:
            for sid, members in bearing_a:
                if len(members) > RELAXED_SCC_MAX:
                    raise QueryError(
                        f"relaxed enumeration over a {len(members)}-node "
                        f"SCC exceeds the 2^{RELAXED_SCC_MAX} window "
                        f"budget", code="query_overbudget",
                    )
                qa, qb, enumerated, engine = _relaxed_search(
                    graph_a, graph_b, members, cancel=cancel,
                )
                ledger.append({
                    "scc_index": sid,
                    "size": len(members),
                    "nodes": [graph_a.node_ids[v] for v in members],
                    "window_space": (1 << len(members)) - 1,
                    "windows_enumerated": enumerated,
                    "backend": engine,
                })
                if qa is not None:
                    break
        verdict = qa is None
        cert = self._relaxed_certificate(
            graph_a, graph_b, nodes_b, verdict=verdict, reason=reason,
            n_sccs=n_sccs_a, bearing=len(bearing_a), qa=qa, qb=qb,
            ledger=ledger, engine=engine,
        )
        result: Dict[str, object] = {
            "kind": "relaxed",
            "verdict": verdict,
            "reason": reason,
            "windows_enumerated": sum(
                int(e["windows_enumerated"]) for e in ledger  # type: ignore[arg-type]
            ),
        }
        if qa is not None and qb is not None:
            result["witness"] = {
                "family_a": [graph_a.node_ids[v] for v in qa],
                "family_b": [graph_b.node_ids[v] for v in qb],
            }
        rec.event("query.relaxed_resolved", verdict=verdict, reason=reason)
        return QueryResult(
            kind="relaxed", verdict=verdict, result=result, cert=cert,
            stats={"backend": engine, "reason": reason},
        )

    def _relaxed_certificate(
        self,
        graph_a: TrustGraph,
        graph_b: TrustGraph,
        nodes_b: List[Dict[str, object]],
        *,
        verdict: bool,
        reason: str,
        n_sccs: int,
        bearing: int,
        qa: Optional[List[int]],
        qb: Optional[List[int]],
        ledger: List[Dict[str, object]],
        engine: str,
    ) -> Dict[str, object]:
        """A ``qi-cert/1`` certificate with a ``query`` block: the
        checker validates the witness pair against each family's OWN
        nodes (family B rides inside the cert, self-contained) and the
        two-family ledger arithmetic (docs/PARITY.md §Two-family
        invariants)."""
        rec = get_run_record()
        cert: Dict[str, object] = {
            "schema": CERT_SCHEMA,
            "verdict": verdict,
            "dangling": graph_a.dangling,
            "scc_select": self.scc_select,
            "scope_to_scc": False,
            "graph": {"n": graph_a.n, "edges": graph_a.n_edges},
            "query": {
                "kind": "relaxed",
                "family_b": nodes_b,
            },
            "guard": {
                "n_sccs": n_sccs,
                "quorum_bearing_sccs": bearing,
                "reason": reason,
            },
            "provenance": {
                "backend": engine,
                "trace_id": rec.trace_id,
                "query_kind": "relaxed",
            },
        }
        if verdict:
            cert["coverage"] = {"sccs": list(ledger)}
            if reason != "search":
                cert["vacuous"] = reason
        else:
            assert qa is not None and qb is not None
            cert["witness"] = {
                "convention": (
                    "q1=family-A quorum, q2=family-B quorum (relaxed "
                    "two-family mode)"
                ),
                "q1": [graph_a.node_ids[v] for v in qa],
                "q2": [graph_b.node_ids[v] for v in qb],
                "q1_index": list(qa),
                "q2_index": list(qb),
                "evidence": {
                    "q1": witness_evidence(graph_a, qa),
                    "q2": witness_evidence(graph_b, qb),
                },
            }
        rec.add("cert.certificates")
        rec.event(
            "cert.emitted", verdict=verdict, backend=engine,
            reason=f"relaxed:{reason}",
        )
        return cert

    # ---- whatif ----------------------------------------------------------

    def _resolve_whatif(
        self,
        nodes: List[Dict[str, object]],
        query: Query,
        run: _CheckMany,
    ) -> QueryResult:
        rec = get_run_record()
        graph = build_graph(parse_fbas(nodes), dangling=self.dangling)
        known = set(graph.node_ids)
        if query.candidates is not None:
            candidates = list(query.candidates)
            missing = [c for c in candidates if c not in known]
            if missing:
                raise QueryError(
                    f"whatif candidates not in the snapshot: {missing}",
                    code="invalid_query",
                )
        else:
            # Default pool: the quorum-bearing SCC's members — the nodes
            # whose departure can actually change the verdict — clipped
            # deterministically (vertex order) to keep C(pool, k) sane.
            pool: List[str] = []
            for _sid, members in quorum_bearing_sccs(graph):
                pool.extend(graph.node_ids[v] for v in sorted(members))
            candidates = pool[:WHATIF_CANDIDATES_MAX]
        subsets: List[Tuple[str, ...]] = [()]
        truncated = False
        for k in range(1, min(query.max_k, len(candidates)) + 1):
            for combo in combinations(candidates, k):
                if len(subsets) >= self.whatif_limit:
                    truncated = True
                    break
                subsets.append(combo)
            if truncated:
                break
        if truncated:
            # No silent caps: the result says what was dropped.
            log.warning(
                "whatif frontier truncated at %d variants "
                "(QI_QUERY_WHATIF_LIMIT)", self.whatif_limit,
            )
        variants = [
            parse_fbas(mask_nodes(nodes, subset)) for subset in subsets
        ]
        rec.add("query.whatif_variants", len(variants))
        results = run(variants)
        if any(res.stats.get("cancelled") for res in results):
            # qi-fuse belt-and-braces: a fused ``run`` raises before we
            # ever see a lane-retired variant, but NO caller contract may
            # let partial coverage masquerade as a what-if verdict row.
            from quorum_intersection_tpu.backends.base import SearchCancelled

            raise SearchCancelled(
                "what-if variants cancelled mid-solve (request deadline)"
            )
        rows: List[Dict[str, object]] = []
        minimal_failing: Optional[List[str]] = None
        failing_cert: Optional[Dict[str, object]] = None
        for subset, res in zip(subsets, results):
            rows.append({
                "removed": list(subset),
                "verdict": bool(res.intersects),
                "reason": str(res.stats.get("reason", "search")),
            })
            if (not res.intersects and subset
                    and minimal_failing is None):
                # Subsets expand in (size, lexicographic) order, so the
                # first failing non-empty subset IS minimal-cardinality.
                minimal_failing = list(subset)
                failing_cert = res.cert
        verdict = all(bool(r["verdict"]) for r in rows)
        base_cert = dict(results[0].cert or {})
        base_cert["query"] = {
            "kind": "whatif",
            "candidates": list(candidates),
            "max_k": query.max_k,
        }
        result: Dict[str, object] = {
            "kind": "whatif",
            "verdict": verdict,
            "base_verdict": bool(results[0].intersects),
            "candidates": list(candidates),
            "max_k": query.max_k,
            "variants": len(subsets),
            "truncated": truncated,
            "table": rows,
            "minimal_failing": minimal_failing,
        }
        if failing_cert is not None:
            # Re-provable: tools/check_cert.py validates this cert
            # against mask_nodes(base, minimal_failing) — the variant is
            # reconstructable from the base snapshot + the subset alone.
            result["failing_cert"] = failing_cert
        rec.event(
            "query.whatif_resolved", verdict=verdict,
            variants=len(subsets),
            minimal_failing=len(minimal_failing or []),
        )
        return QueryResult(
            kind="whatif", verdict=verdict, result=result, cert=base_cert,
            stats={
                "backend": str(results[0].stats.get("backend", "?")),
                "variants": len(subsets),
            },
        )

    # ---- analytics -------------------------------------------------------

    def _resolve_analytics(
        self, nodes: List[Dict[str, object]], query: Query,
        cancel: Optional[object] = None,
    ) -> QueryResult:
        from quorum_intersection_tpu.pipeline import solve

        rec = get_run_record()
        graph = build_graph(parse_fbas(nodes), dangling=self.dangling)
        metric = query.metric or "top_tier"
        _check_cancel(cancel)
        payload: Dict[str, object] = {"kind": "analytics", "metric": metric}
        proof: Optional[Dict[str, object]] = None
        if metric == "top_tier":
            from quorum_intersection_tpu.analytics.top_tier import top_tier

            members: List[int] = []
            quorum_count = 0
            exceeded = False
            for _sid, scc in quorum_bearing_sccs(graph):
                _check_cancel(cancel)
                part, n_min = top_tier(graph, scc)
                if part is None:
                    exceeded = True
                    break
                members.extend(part)
                quorum_count += n_min
            payload.update({
                "members": sorted(graph.node_ids[v] for v in members),
                "minimal_quorums": quorum_count,
                "exceeded": exceeded,
            })
        elif metric == "blocking_set":
            from quorum_intersection_tpu.analytics.resilience import (
                minimal_blocking_set,
                minimum_blocking_size,
            )

            blocking: List[int] = []
            minimum_total: Optional[int] = 0
            for _sid, scc in quorum_bearing_sccs(graph):
                _check_cancel(cancel)
                part = minimal_blocking_set(graph, scc)
                blocking.extend(part)
                minimum = minimum_blocking_size(graph, scc, upper=len(part))
                minimum_total = (
                    None if (minimum is None or minimum_total is None)
                    else minimum_total + minimum
                )
            keys = sorted(graph.node_ids[v] for v in blocking)
            payload.update({
                "blocking": keys,
                "minimum_size": minimum_total,
            })
            if keys:
                # Re-proof (docs/PARITY.md): with every quorum-bearing
                # SCC's blocking set masked out, NO quorum survives
                # anywhere — the masked solve must claim no_quorum, which
                # the stdlib checker re-proves via its own graph-wide
                # fixpoint.
                masked = mask_nodes(nodes, keys)
                res = solve(masked, backend="python",
                            dangling=self.dangling)
                proof = {"cert": res.cert, "nodes": masked,
                         "claim": "blocking-halts"}
        elif metric == "splitting_set":
            from quorum_intersection_tpu.analytics.splitting import (
                POOL_LIMIT,
                delete_nodes,
                minimum_splitting_set,
            )

            pool: List[str] = []
            for _sid, scc in quorum_bearing_sccs(graph):
                pool.extend(graph.node_ids[v] for v in scc)
            if len(pool) > POOL_LIMIT:
                raise QueryError(
                    f"splitting-set candidate pool {len(pool)} > "
                    f"{POOL_LIMIT}", code="query_overbudget",
                )
            split = minimum_splitting_set(
                nodes, max_k=query.splitting_max_k,
                dangling=self.dangling, pool=pool,
            )
            payload.update({
                "splitting": split,
                "max_k": query.splitting_max_k,
            })
            if split:
                # Re-proof: the reduced FBAS (byzantine delete) exhibits
                # the disjoint pair — its false certificate re-validates
                # through the checker's existing witness-evidence path.
                reduced = delete_nodes(nodes, split)
                res = solve(reduced, backend="python",
                            dangling=self.dangling)
                proof = {"cert": res.cert, "nodes": reduced,
                         "claim": "splitting-witness"}
        else:  # pagerank
            from quorum_intersection_tpu.analytics.pagerank import (
                pagerank_auto,
            )

            ranks, engine = pagerank_auto(graph)
            order = sorted(
                range(graph.n), key=lambda v: (-ranks[v], graph.node_ids[v])
            )
            payload.update({
                "engine": engine,
                "ranks": [
                    [graph.node_ids[v], round(float(ranks[v]), 8)]
                    for v in order
                ],
            })
        digest = hashlib.sha256(
            json.dumps(payload, sort_keys=True, separators=(",", ":"),
                       default=str).encode()
        ).hexdigest()[:32]
        cert: Dict[str, object] = {
            "schema": QUERY_CERT_SCHEMA,
            "query": {"kind": "analytics", "metric": metric},
            "result_digest": digest,
            "provenance": {
                "trace_id": rec.trace_id,
                "dangling": graph.dangling,
                "scc_select": self.scc_select,
            },
        }
        if proof is not None:
            cert["proof"] = proof
            # The claimed set rides in the cert so the checker can
            # RE-DERIVE the proof's reduced/masked network from the
            # primary snapshot instead of trusting the embedded list.
            cert["result"] = {
                k: payload[k]
                for k in ("blocking", "splitting") if k in payload
            }
        rec.event("query.analytics_resolved", metric=metric)
        # Analytics queries are reports, not verdicts: like the reference
        # CLI's PageRank mode (always exit 0, cpp:787) they succeed as a
        # query whatever the numbers say — verdict True by definition.
        return QueryResult(
            kind="analytics", verdict=True, result=payload, cert=cert,
            stats={"backend": "analytics", "metric": metric},
        )


# ---- relaxed search engines --------------------------------------------------


def _check_cancel(cancel: Optional[object]) -> None:
    """Cooperative cancellation probe (the serve deadline supervisor's
    CancelToken): raises ``SearchCancelled`` once tripped, so a long
    relaxed enumeration or analytics loop can never hold the drain
    thread past every deadline."""
    if cancel is not None and getattr(cancel, "cancelled", False):
        from quorum_intersection_tpu.backends.base import SearchCancelled

        raise SearchCancelled("query cancelled by deadline supervisor")


def _relaxed_search(
    graph_a: TrustGraph, graph_b: TrustGraph, members: List[int],
    cancel: Optional[object] = None,
) -> Tuple[Optional[List[int]], Optional[List[int]], int, str]:
    """The relaxed enumeration over one family-A SCC, vectorized:
    ``(qa, qb, windows_enumerated, engine)``.

    Rides the two-circuit restriction (``encode/circuit.
    restrict_two_family``): family A's candidate-scoped circuit evaluates
    whole window BATCHES through :func:`max_quorum_np` (one (B, m)
    fixpoint instead of B interpreted loops), family B's scoped twin is
    the fast per-candidate overlap guard, and the host
    :func:`cross_family_disjoint_quorum` is the sound slow guard for
    B-quorums leaning on nodes outside the SCC.  Window order, distinct-
    quorum memoization, and the first-witness window are IDENTICAL to the
    stdlib oracle ``fbas/semantics.relaxed_disjoint_witness`` (the
    differential contract ``tests/test_qi_query.py`` pins).
    """
    m = len(members)
    a_scoped, b_scoped, _b_q6 = restrict_two_family(
        encode_circuit(graph_a), encode_circuit(graph_b), list(members)
    )
    member_arr = np.asarray(members, dtype=np.int64)
    bits = np.arange(m, dtype=np.int64)
    enumerated = 0
    seen: Dict[bytes, bool] = {}
    for start in range(1, 1 << m, RELAXED_CHUNK):
        _check_cancel(cancel)
        stop = min(start + RELAXED_CHUNK, 1 << m)
        idx = np.arange(start, stop, dtype=np.int64)
        masks = ((idx[:, None] >> bits) & 1).astype(bool)
        fixes = max_quorum_np(a_scoped, masks)
        nonempty = fixes.any(axis=1)
        for i in range(len(idx)):
            enumerated += 1
            if not nonempty[i]:
                continue
            key = fixes[i].tobytes()
            if key in seen:
                continue
            qa_local = fixes[i]
            qa_global = [int(v) for v in member_arr[qa_local]]
            # Fast guard: a B-quorum wholly inside scc ∖ qa under scoped
            # availability is a real B-quorum (scoped availability only
            # under-approximates).
            qb_fix = max_quorum_np(b_scoped, ~qa_local[None, :])[0]
            if qb_fix.any():
                qb_global = [int(v) for v in member_arr[qb_fix]]
                seen[key] = True
                return (sorted(qa_global), sorted(qb_global), enumerated,
                        "relaxed-vector")
            # Sound slow guard: whole-graph availability for family B.
            qb = cross_family_disjoint_quorum(graph_b, qa_global)
            seen[key] = bool(qb)
            if qb:
                return (sorted(qa_global), sorted(qb), enumerated,
                        "relaxed-vector")
    return None, None, enumerated, "relaxed-vector"


# ---- CLI subcommand ---------------------------------------------------------


def build_query_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m quorum_intersection_tpu query",
        description=(
            "One-shot typed query (qi-query/1) over a stellarbeat node "
            "array on stdin; the JSON result prints to stdout.  The same "
            "query kinds are served live via the serve/fleet subcommands' "
            '"query" request field.'
        ),
    )
    p.add_argument("--kind", default="intersection", choices=list(KINDS),
                   help="query kind (default intersection)")
    p.add_argument("--family-b", metavar="PATH", default=None,
                   help="relaxed mode: the second quorum-set family (a "
                        "stellarbeat node array over the SAME node set)")
    p.add_argument("--remove", action="append", default=None, metavar="KEY",
                   help="whatif mode: candidate validator publicKey "
                        "(repeatable; default: the quorum-bearing SCC's "
                        "members)")
    p.add_argument("--max-k", type=int, default=1, metavar="K",
                   help="whatif mode: removal subsets up to size K "
                        "(default 1)")
    p.add_argument("--metric", default=None, choices=list(ANALYTICS_METRICS),
                   help="analytics mode: which analysis to serve")
    p.add_argument("--splitting-max-k", type=int, default=2, metavar="K",
                   help="analytics splitting_set search depth (default 2)")
    p.add_argument("--backend", default="auto",
                   choices=["auto", "python", "cpp", "tpu", "tpu-sweep",
                            "tpu-frontier"],
                   help="search backend for solve-backed kinds")
    p.add_argument("--dangling-policy", default="strict",
                   choices=["strict", "alias0"])
    p.add_argument("--cert-out", metavar="PATH", default=None,
                   help="write the query certificate to PATH (atomic, "
                        "cert.write fault point — same contract as the "
                        "verdict CLI's --cert-out)")
    return p


def query_main(argv: Optional[List[str]] = None) -> int:
    """The ``query`` subcommand body (dispatched from cli.py).

    Exit semantics mirror the one-shot verdict CLI: 0 when the query
    verdict is true (all intersect / network survives / analytics ran),
    1 when false, 1 with a typed JSON error line on a QueryError."""
    args = build_query_parser().parse_args(argv)
    raw: Dict[str, object] = {"kind": args.kind}
    if args.family_b is not None:
        with open(args.family_b, encoding="utf-8") as fh:
            raw["family_b"] = json.load(fh)
    if args.remove:
        raw["candidates"] = list(args.remove)
    raw["max_k"] = args.max_k
    if args.metric is not None:
        raw["metric"] = args.metric
    raw["splitting_max_k"] = args.splitting_max_k
    try:
        nodes = json.loads(sys.stdin.read())
        if not isinstance(nodes, list):
            raise QueryError("stdin must be a stellarbeat node array",
                             code="invalid_query")
        query = Query.parse(raw)
        engine = QueryEngine(
            backend=args.backend, dangling=args.dangling_policy,
        )
        out = engine.resolve(nodes, query)
    except (QueryError, ValueError) as exc:
        sys.stdout.write(json.dumps({
            "schema": QUERY_SCHEMA,
            "error": {"code": getattr(exc, "code", "invalid"),
                      "message": str(exc)},
        }) + "\n")
        return 1
    if args.cert_out and out.cert is not None:
        from quorum_intersection_tpu.cert import write_certificate

        write_certificate(out.cert, args.cert_out)
    sys.stdout.write(json.dumps({
        "schema": QUERY_SCHEMA,
        "kind": out.kind,
        "verdict": out.verdict,
        "result": out.result,
    }, default=str) + "\n")
    return 0 if out.verdict else 1
