"""Backend protocol and registry."""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Protocol, runtime_checkable

from quorum_intersection_tpu.encode.circuit import Circuit
from quorum_intersection_tpu.fbas.graph import TrustGraph

# Shared miss sentinel for first-hit index reductions: device kernels return
# this value for a clean-miss block; drivers compare against it.  Lives here
# (jax-free) so both the device and host sides import the same constant.
INT32_MAX = 2**31 - 1


class OracleBudgetExceeded(RuntimeError):
    """A budgeted host oracle search exceeded its B&B call budget before
    reaching a verdict.  Raised (never returned as a verdict) so the caller
    — the auto router's latency-aware oracle-first strategy — falls back to
    an exhaustive engine whose cost the budget was derived from."""


class SearchCancelled(RuntimeError):
    """A cooperatively-cancelled search stopped before reaching a verdict.

    Raised (never returned as a verdict) by engines that accept a
    :class:`CancelToken` — the racing auto router cancels the losing engine
    the moment the other one produces a verdict.  Like
    :class:`OracleBudgetExceeded`, cancellation is an abort signal about
    *scheduling*, never information about the verdict."""


class CancelToken:
    """Cooperative cancellation flag shared between racing engines.

    Two views of one bit, set exactly once and never cleared:

    - :attr:`cancelled` / :meth:`cancel` — the Python side, checked by the
      pure-Python oracle's B&B call-budget hook and the sweep driver's
      window loop;
    - :attr:`flag` — a one-element int32 numpy buffer whose POINTER is
      handed to the native oracle (``qi_check_scc_cancel``), which polls it
      alongside its call-budget check.  ctypes releases the GIL during the
      native call, so a concurrent :meth:`cancel` from the race driver is
      observed within one B&B call.

    jax-free and allocation-trivial: safe to create per-race.
    """

    __slots__ = ("flag", "_event")

    def __init__(self) -> None:
        import numpy as np

        self.flag = np.zeros(1, dtype=np.int32)
        self._event = threading.Event()

    def cancel(self) -> None:
        self.flag[0] = 1
        self._event.set()

    @property
    def cancelled(self) -> bool:
        return self._event.is_set()


@dataclass
class SccCheckResult:
    """Outcome of the disjoint-quorum search inside one SCC.

    ``intersects`` is the verdict for this SCC: True iff every pair of quorums
    intersects.  On False, ``q1``/``q2`` are a witness pair of disjoint
    quorums (the reference surfaces the same via out-params, cpp:351-352).
    ``stats`` carries backend counters (branch-and-bound calls, candidates
    checked, device batches, seconds) for observability parity and the
    benchmark metric.
    """

    intersects: bool
    q1: Optional[List[int]] = None
    q2: Optional[List[int]] = None
    stats: Dict[str, float] = field(default_factory=dict)


@runtime_checkable
class SearchBackend(Protocol):
    name: str

    def check_scc(
        self,
        graph: TrustGraph,
        circuit: Optional[Circuit],
        scc: List[int],
        *,
        scope_to_scc: bool = False,
    ) -> SccCheckResult:
        """Decide disjoint-quorum existence within ``scc``.

        ``scope_to_scc=False`` reproduces the reference's availability
        semantics — the whole graph starts available (cpp:354, quirk Q6) —
        which is only sound for a sink SCC.  ``True`` scopes availability to
        the SCC, the principled default for non-sink components.
        """
        ...


def get_backend(name: str, **options) -> SearchBackend:
    """Instantiate a backend by name (lazy imports keep JAX out of the
    pure-CPU paths)."""
    if name == "python":
        from quorum_intersection_tpu.backends.python_oracle import PythonOracleBackend

        return PythonOracleBackend(**options)
    if name == "cpp":
        from quorum_intersection_tpu.backends.cpp import CppOracleBackend

        return CppOracleBackend(**options)
    if name == "tpu-sweep":
        from quorum_intersection_tpu.backends.tpu.sweep import TpuSweepBackend

        return TpuSweepBackend(**options)
    if name == "tpu-hybrid":
        # Retired in r5: the round-trip hybrid lost 100-1000x to the native
        # oracle at every measured size on chip and CPU alike (crossover
        # artifacts r3-r5) while the device-resident frontier carries its
        # checkpoint + mesh capabilities AND beats the native oracle at
        # scc 32 on chip (crossover_tpu_r5.txt).  Fail with the successor
        # rather than silently re-routing.
        raise ValueError(
            "backend 'tpu-hybrid' was retired (measured 100-1000x slower "
            "than the native oracle everywhere, crossover_tpu_r3-r5); use "
            "'tpu-frontier' (same checkpoint format and mesh support)"
        )
    if name == "tpu-frontier":
        from quorum_intersection_tpu.backends.tpu.frontier import TpuFrontierBackend

        return TpuFrontierBackend(**options)
    if name in ("tpu", "auto"):
        from quorum_intersection_tpu.backends.auto import AutoBackend

        return AutoBackend(prefer_tpu=(name == "tpu"), **options)
    raise ValueError(f"unknown backend {name!r}")
