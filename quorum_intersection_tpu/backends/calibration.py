"""Auto-routing cost-model calibration from recorded bench artifacts.

The `auto` backend's oracle-first budget needs two numbers: how fast the
exhaustive sweep runs (per platform) and how fast a host oracle burns B&B
calls.  Through r3 these were hand-pinned constants with the measurement
cited in a comment (VERDICT r3 §weak-3: "will silently skew as kernels
improve").  This module re-derives them at import time from the bench
records actually committed in the repo — the driver's ``BENCH_r*.json``
at the root and anything under ``benchmarks/results/`` — so the cost
model tracks the hardware the suite last measured, with the r3 constants
as fallback and every derived value carrying its source file name in
``CALIBRATION.provenance``.

Artifacts are read from the WORKING TREE, untracked files included —
deliberately (ADVICE r4): a freshly produced on-chip record (the driver
drops ``BENCH_r*.json`` untracked; ``tools/onchip_r4.sh`` tees crossover
rows) must steer routing immediately, without waiting for a commit.  The
flip side is that two checkouts of identical committed code can route
differently if their working trees differ; ``calibrate(paths=[...])``
pins the inputs for tests and reproducibility.

Safety posture (unchanged from the hand-tuned constants):

- the accelerator sweep rate is the best recorded END-TO-END wide-sweep
  rate **halved** for tunnel variance — a conservative budget errs toward
  giving the pruned oracle MORE room, never less;
- the CPU sweep rate is the best recorded steady CPU rate **quartered**
  (steady excludes compile, which a real solve pays);
- derived values are clamped to sanity windows so one corrupt artifact
  cannot wreck routing.
"""

from __future__ import annotations

import json
import pathlib
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Tuple

from quorum_intersection_tpu.utils.logging import get_logger

log = get_logger("backends.calibration")

# r3 fallbacks (benchmarks/results/bench_full_r3_onchip.json wide sweep;
# crossover_cpu_r2.txt majority-18; BASELINE.md n=16) — used whenever no
# artifact yields a usable number.
DEFAULT_SWEEP_RATE = {"cpu": 5e5, "accel": 3e8}
DEFAULT_ORACLE_SPC = {"cpp": 0.7e-6, "python": 3e-5}

# Sanity windows: a derived value outside these is ignored (artifact rot,
# truncated tails, unit bugs) rather than trusted.
_ACCEL_RATE_WINDOW = (1e7, 1e11)
_CPU_RATE_WINDOW = (1e4, 1e8)
_ORACLE_RATE_WINDOW = (1e4, 1e8)  # B&B calls/s

# The static accelerator sweep limit (auto.SWEEP_LIMIT_TPU imports THIS so
# the two can't drift).  The sweep window only decides routing ABOVE it —
# sizes at or below route to the sweep by the static limit regardless — so
# measured losses down there (compile-overhead-bound small rows) must not
# veto a window whose raise they cannot affect.
SWEEP_WINDOW_FLOOR = 35

_REPO = pathlib.Path(__file__).resolve().parent.parent.parent


def _artifact_paths() -> List[pathlib.Path]:
    out = sorted(_REPO.glob("BENCH_r*.json"))
    results = _REPO / "benchmarks" / "results"
    if results.is_dir():
        out += sorted(results.glob("*.json"))
    return out


def _round_rank(name: str) -> int:
    """Recency key: the round number in the ``r<N>`` convention both
    artifact families use (``BENCH_r04.json``, ``bench_full_r3_onchip``);
    -1 when the name carries none.  Deliberately NOT "any integer in the
    name" — a results file like ``verdict_1024.json`` must never outrank
    genuinely newer rounds."""
    rounds = [int(m) for m in re.findall(r"(?:\b|_)[rR](\d+)", name)]
    return max(rounds) if rounds else -1


def _iter_records(paths: Iterable[pathlib.Path]):
    """Yield (name, headline-record) pairs, tolerating the two artifact
    shapes on disk: a bare headline dict, or the driver's wrapper with a
    ``parsed`` record / raw ``tail`` text ending in the headline line."""
    for path in paths:
        try:
            doc = json.loads(path.read_text())
        # qi-lint: allow(degrade-via-ladder) — artifact parsing, not routing
        except Exception:  # noqa: BLE001 — unreadable artifact: skip
            continue
        if not isinstance(doc, dict):
            continue
        rec = None
        if isinstance(doc.get("parsed"), dict):
            rec = doc["parsed"]
        elif "metric" in doc or "sweep_steady_rate" in doc or "device" in doc:
            rec = doc
        elif isinstance(doc.get("tail"), str):
            for ln in reversed(doc["tail"].strip().splitlines()):
                try:
                    cand = json.loads(ln)
                except json.JSONDecodeError:
                    continue
                if isinstance(cand, dict):
                    rec = cand
                    break
        if rec is not None:
            yield path.name, rec


def _is_tpu(rec: dict) -> bool:
    return "tpu" in str(rec.get("device", "")).lower()


def _in(window: Tuple[float, float], value) -> Optional[float]:
    try:
        v = float(value)
    except (TypeError, ValueError):
        return None
    return v if window[0] <= v <= window[1] else None


@dataclass
class Calibration:
    sweep_rate: Dict[str, float] = field(
        default_factory=lambda: dict(DEFAULT_SWEEP_RATE)
    )
    oracle_seconds_per_call: Dict[str, float] = field(
        default_factory=lambda: dict(DEFAULT_ORACLE_SPC)
    )
    # Measured frontier win region: route |scc| >= this to the
    # device-resident frontier on accelerators (None = no measured win on
    # record; the host oracle keeps large SCCs).  Derived from the newest
    # on-chip crossover artifact — see _frontier_win_min_scc.
    frontier_win_min_scc: Optional[int] = None
    # The LARGEST |scc| the winning group actually measured: routing above
    # it is extrapolation, which auto caps at a small documented headroom
    # (a win at scc 28-32 says little about scc 200 under a config tuned
    # for 32) — ADVICE r4 medium.
    frontier_win_max_scc: Optional[int] = None
    # Device kind the win was measured on (jax backend name, e.g. "tpu"):
    # a TPU-measured win must not route a GPU/other accelerator.
    frontier_win_device: Optional[str] = None
    # The frontier constructor kwargs the winning rows were measured UNDER
    # (a win at pop=4096 must not route to a default-pop frontier).
    frontier_config: Dict = field(default_factory=dict)
    # Measured sweep win window (benchmarks/sweep_vs_native.py artifacts):
    # the largest |scc| at which the exhaustive sweep measured >= 1x a
    # COMPLETED native-oracle run on an accelerator.  Raises auto's
    # accelerator sweep limit above the static conservative default
    # (auto._platform_sweep_limit), with the same headroom/device-kind
    # bounds as the frontier region.  None = no measured window.
    sweep_win_max_scc: Optional[int] = None
    # Hard bound on extrapolating past the window top: set (to loss-1) when
    # a LOSS was measured at some |scc| above the largest win — headroom
    # must never route a measured-slower size to the sweep.
    sweep_win_cap_scc: Optional[int] = None
    sweep_win_device: Optional[str] = None
    # Measured warm-start compile ratio (benchmarks/auto_race.py artifacts):
    # warm-run XLA-compile seconds / cold-run, on an accelerator with the
    # persistent compile cache hot.  None = never measured.  auto's budget
    # estimate scales its accelerator overhead term by it, so routing
    # prefers the chip once the cache is known-hot (ISSUE 1 warm-start).
    # NB the budget estimate is deliberately platform-blind (probe-free
    # happy path), so unlike the win-region gates this value is consumed
    # without a device-kind match — see _estimated_sweep_seconds for why
    # the cross-platform leak is bounded; sweep_warm_device is recorded
    # for any future probe-ful consumer.
    sweep_warm_ratio: Optional[float] = None
    sweep_warm_device: Optional[str] = None
    # Measured lane-packing win (benchmarks/sweep_vs_native.py --packed
    # rows): the largest |scc| at which the PACKED multi-problem sweep
    # measured >= 1x the unpacked per-problem sweeps wall-clock, with
    # verdict parity on every packed row.  Gates when the auto router's
    # batch entry (check_sccs) engages packing on its own — forced packing
    # (pack=True) and the structural MACs accounting need no artifact.
    # None = packing never auto-engages (the honest-measurement posture
    # every routing claim in this module follows).
    pack_win_max_scc: Optional[int] = None
    pack_win_device: Optional[str] = None
    # Measured bitset-encoding win region (benchmarks/sweep_vs_native.py
    # --bitset rows): route a solve to the streaming bitset kernel twin
    # when |scc| >= bitset_win_min_scc AND the SCC's qset density is <=
    # bitset_win_max_density, on hardware of the measured kind.  Density
    # is the routing FEATURE (fbas.synth.scc_qset_density): the bitset
    # encoding wins exactly where the dense block-diagonal operand is
    # mostly padding — sparse org-nested cores — and loses nothing where
    # qsets are dense (k-of-n, density ~1.0), which is why both bounds
    # gate together.  None = no measured win on record; the dense engine
    # keeps every solve (the module's honest-measurement posture).
    bitset_win_min_scc: Optional[int] = None
    bitset_win_max_density: Optional[float] = None
    bitset_win_device: Optional[str] = None
    # key -> "file.json: <field>=<value>" (or "default" when no artifact won)
    provenance: Dict[str, str] = field(default_factory=dict)


def _frontier_win_min_scc(
    paths: Iterable[pathlib.Path],
) -> Optional[Tuple[int, int, str, Dict, str]]:
    """Smallest |scc| from which the frontier consistently beats the native
    oracle ON A TPU, per the newest crossover artifact's JSON rows, plus
    the frontier constructor kwargs it was measured under.

    Conservative, per measured configuration: rows group by their recorded
    ``frontier_kw`` (a win at pop=4096 says nothing about the default
    pop), within a group the per-scc speed is the MINIMUM across that
    scc's rows, and the threshold is the smallest scc such that every
    measured scc at or above it wins (>= 1x, verdict+count parity) — one
    losing or unparitied row above kills that group's region.  The group
    with the smallest threshold wins.  Rows measured on CPU emulation
    never qualify (the decision this gates is accelerator routing).

    Returns ``(min_scc, max_measured_scc, device_kind, config, provenance)``
    — the max and the device kind bound how far auto may extrapolate the
    region (ADVICE r4 medium)."""
    newest: Optional[Tuple[int, str, List[Tuple[int, float, str, Dict, str]]]] = None
    for path in paths:
        rows: List[Tuple[int, float, str, Dict, str]] = []
        try:
            text = path.read_text()
        except OSError:
            continue
        for ln in text.splitlines():
            ln = ln.strip()
            if not ln.startswith("{"):
                continue
            try:
                rec = json.loads(ln)
            except json.JSONDecodeError:
                continue
            if not _is_tpu(rec):
                continue
            scc = rec.get("scc")
            speed = rec.get("frontier_speedup_vs_cpp")
            if not isinstance(scc, int) or not isinstance(speed, (int, float)):
                continue
            # Only rows that RECORDED their config and actually measured
            # count parity can gate routing: a verdict-only or config-less
            # row (the bench's standard loop, hand-assembled artifacts)
            # never qualifies — enumeration completeness and the measured
            # kwargs are the whole point of the gate.
            config = rec.get("frontier_kw")
            if not isinstance(config, dict):
                continue
            ok = (
                rec.get("verdict_ok", False)
                and rec.get("counts_ok") is True
            )
            # jax backend kind of the measured device ("TPU v5 lite" ->
            # "tpu") — the routing gate compares it to the live backend.
            # Qualifying rows are TPU-only today (the _is_tpu filter
            # above); widen that filter before recording other kinds here.
            kind = "tpu"
            rows.append((
                scc, float(speed) if ok else 0.0,
                json.dumps(config, sort_keys=True), config, kind,
            ))
        if rows:
            rank = _round_rank(path.name)
            if newest is None or rank > newest[0]:
                newest = (rank, path.name, rows)
    if newest is None:
        return None
    _, name, rows = newest

    groups: Dict[str, Dict] = {}
    for scc, speed, key, config, kind in rows:
        g = groups.setdefault(key, {"config": config, "by_scc": {}, "device": kind})
        prev = g["by_scc"].get(scc)
        g["by_scc"][scc] = speed if prev is None else min(prev, speed)

    best: Optional[Tuple[int, int, str, Dict, float]] = None
    for g in groups.values():
        win = None
        for scc in sorted(g["by_scc"], reverse=True):
            if g["by_scc"][scc] >= 1.0:
                win = scc
            else:
                break
        if win is None:
            continue
        # Group quality on a threshold tie: the worst ratio inside the win
        # region — r5 measured two configs both winning from scc 32, at
        # 1.16x (defaults) and 1.31x (pop=2048); routing must carry the
        # faster measured config, not the first one parsed.
        region_speed = min(v for k, v in g["by_scc"].items() if k >= win)
        if (
            best is None
            or win < best[0]
            or (win == best[0] and region_speed > best[4])
        ):
            best = (win, max(g["by_scc"]), g["device"], g["config"], region_speed)
    if best is None:
        return None
    win, hi, kind, config, _ = best
    cfg = f" under {config}" if config else ""
    return win, hi, kind, config, (
        f"{name}: frontier >= 1x native for scc {win}..{hi} on {kind}{cfg}"
    )


def _sweep_win_max_scc(
    paths: Iterable[pathlib.Path],
) -> Optional[Tuple[int, Optional[int], str, str]]:
    """Largest |scc| at which the exhaustive sweep beat the native oracle
    on an accelerator, per the newest sweep_vs_native artifact's JSON rows.

    Eligibility is strict: the native run must have COMPLETED (an
    estimated-total row proves a floor, not a ratio), verdict parity must
    hold, and emulation (CPU-platform) rows never qualify.

    A ``verdict_ok: false`` row anywhere in the chosen artifact — at ANY
    |scc|, including at or below the static floor — disqualifies the whole
    raise (ADVICE r5 #2): it is evidence of an engine CORRECTNESS bug on
    this hardware, not a slow size, so it must not slip under the
    floor-loss exemption below (which exists only for *performance* losses
    at sizes the window cannot affect).  Logged as a correctness veto.

    Returns ``(max_winning_scc, cap_scc, device_kind, provenance)`` where
    ``cap_scc`` bounds extrapolation when a LOSS was measured above the
    window top (auto's headroom must never route past a measured loss);
    None when no loss was measured above."""
    newest: Optional[Tuple[int, str, Dict[int, float], List[int]]] = None
    for path in paths:
        try:
            text = path.read_text()
        except OSError:
            continue
        by_scc: Dict[int, float] = {}
        vetoes: List[int] = []
        for ln in text.splitlines():
            ln = ln.strip()
            if not ln.startswith("{"):
                continue
            try:
                rec = json.loads(ln)
            except json.JSONDecodeError:
                continue
            if not _is_tpu(rec):
                continue
            scc = rec.get("scc")
            speed = rec.get("sweep_speedup_vs_native")
            if not isinstance(scc, int) or not isinstance(speed, (int, float)):
                continue
            if not rec.get("verdict_ok", False):
                vetoes.append(scc)
                continue
            elif rec.get("native_completed") is not True:
                # An estimate-only row (native didn't finish under the cap)
                # is ABSENCE of a measured ratio, not a loss: skipping it
                # lets a later completed-native run of the same size —
                # appended to the same round artifact — extend the window.
                continue
            else:
                v = float(speed)
            by_scc[scc] = min(by_scc.get(scc, v), v)
        if by_scc or vetoes:
            rank = _round_rank(path.name)
            if newest is None or rank > newest[0]:
                newest = (rank, path.name, by_scc, vetoes)
    if newest is None:
        return None
    _, name, by_scc, vetoes = newest
    if vetoes:
        log.warning(
            "sweep-window raise vetoed: %s records verdict_ok=false at "
            "scc %s — correctness evidence disqualifies the window at "
            "every size until re-measured clean",
            name, sorted(set(vetoes)),
        )
        return None
    if not by_scc:
        return None
    # A measured loss bounds the window from above AND disqualifies any
    # "win" beyond it: the limit this feeds routes EVERY |scc| up to it to
    # the sweep, so the window may contain no measured-slower size — a win
    # above a loss (physically implausible; measurement noise) must not
    # leapfrog the loss.  Losses at or below the static floor are exempt:
    # those sizes route to the sweep by the static limit no matter what
    # this window says, so they cannot veto the raise they don't affect.
    losses = [
        scc for scc, v in by_scc.items()
        if v < 1.0 and scc > SWEEP_WINDOW_FLOOR
    ]
    cap = min(losses) - 1 if losses else None
    wins = [
        scc for scc, v in by_scc.items()
        if v >= 1.0 and (cap is None or scc <= cap)
    ]
    if not wins:
        return None
    win = max(wins)
    capped = f", loss measured at scc {cap + 1}" if cap is not None else ""
    return win, cap, "tpu", (
        f"{name}: sweep >= 1x completed native up to scc {win} on tpu{capped}"
    )


def _pack_win_max_scc(
    paths: Iterable[pathlib.Path],
) -> Optional[Tuple[int, str, str]]:
    """Largest |scc| at which the lane-packed sweep measured >= 1x the
    unpacked sweeps, per the newest sweep_vs_native artifact's ``--packed``
    rows (``packed_speedup_vs_unpacked`` + ``verdict_ok``).

    Same conservative discipline as the sweep window: rows group by the
    device kind they were measured on (a TPU win never engages packing on
    other hardware, and CPU-emulated rows never pollute a chip window —
    when both kinds recorded wins, the accelerator's gate, the prize this
    exists for, is the one kept); per-scc speed is the MINIMUM across that
    scc's rows; a ``verdict_ok: false`` packed row anywhere in the chosen
    artifact vetoes the whole gate (correctness evidence, not a slow
    size); and a measured LOSS above the static floor caps the window from
    below it — a win beyond a loss must not route the losing size.
    """
    newest: Optional[Tuple[int, str, Dict[str, Dict[int, float]], List[int]]] = None
    for path in paths:
        try:
            text = path.read_text()
        except OSError:
            continue
        by_kind: Dict[str, Dict[int, float]] = {}
        vetoes: List[int] = []
        for ln in text.splitlines():
            ln = ln.strip()
            if not ln.startswith("{"):
                continue
            try:
                rec = json.loads(ln)
            except json.JSONDecodeError:
                continue
            scc = rec.get("scc")
            speed = rec.get("packed_speedup_vs_unpacked")
            if not isinstance(scc, int) or not isinstance(speed, (int, float)):
                continue
            if not rec.get("verdict_ok", False):
                vetoes.append(scc)
                continue
            v = float(speed)
            kind_rows = by_kind.setdefault("tpu" if _is_tpu(rec) else "cpu", {})
            kind_rows[scc] = min(kind_rows.get(scc, v), v)
        if by_kind or vetoes:
            rank = _round_rank(path.name)
            if newest is None or rank > newest[0]:
                newest = (rank, path.name, by_kind, vetoes)
    if newest is None:
        return None
    _, name, by_kind, vetoes = newest
    if vetoes:
        log.warning(
            "lane-packing gate vetoed: %s records verdict_ok=false at "
            "packed scc %s", name, sorted(set(vetoes)),
        )
        return None
    for kind in ("tpu", "cpu"):
        by_scc = by_kind.get(kind)
        if not by_scc:
            continue
        losses = [scc for scc, v in by_scc.items() if v < 1.0]
        cap = min(losses) - 1 if losses else None
        wins = [
            scc for scc, v in by_scc.items()
            if v >= 1.0 and (cap is None or scc <= cap)
        ]
        if not wins:
            continue
        win = max(wins)
        capped = f", loss measured at scc {cap + 1}" if cap is not None else ""
        return win, kind, (
            f"{name}: packed sweep >= 1x unpacked up to scc {win} on "
            f"{kind}{capped}"
        )
    return None


def _bitset_win(
    paths: Iterable[pathlib.Path],
) -> Optional[Tuple[int, float, str, str]]:
    """Bitset-encoding win region from the newest sweep_vs_native artifact's
    ``--bitset`` rows (``bitset_speedup_vs_dense`` + ``scc_density`` +
    ``verdict_ok``).

    Same conservative discipline as the pack gate, with the density axis
    added: rows group by measured device kind (an accelerator win gates
    accelerator routing only; when both kinds recorded wins the
    accelerator's gate is kept); a ``verdict_ok: false`` bitset row
    anywhere in the chosen artifact vetoes the whole gate (correctness
    evidence against the ENCODING, not a slow workload); wins require a
    >= 1.1x margin (a tie — kofn at density ~1.0 measures ~1.0x — is no
    reason to leave the default engine); and any measured LOSS (< 1x)
    falling inside the candidate region shrinks it — first by dropping
    win rows at or above the losing row's density, so the density bound
    moves below the loss — until no loss contradicts the region.  The
    region returned is (min winning |scc|, max winning density): routing
    extrapolates UP the scc axis (more windows amortize the fixed costs
    even further) but never up the density axis (denser qsets erode
    exactly the sparsity the encoding streams)."""
    newest: Optional[
        Tuple[int, str, Dict[str, List[Tuple[int, float, float]]], List[int]]
    ] = None
    for path in paths:
        try:
            text = path.read_text()
        except OSError:
            continue
        by_kind: Dict[str, List[Tuple[int, float, float]]] = {}
        vetoes: List[int] = []
        for ln in text.splitlines():
            ln = ln.strip()
            if not ln.startswith("{"):
                continue
            try:
                rec = json.loads(ln)
            except json.JSONDecodeError:
                continue
            if rec.get("bitset") is not True:
                continue
            scc = rec.get("scc")
            speed = rec.get("bitset_speedup_vs_dense")
            density = rec.get("scc_density")
            if (
                not isinstance(scc, int)
                or not isinstance(speed, (int, float))
                or not isinstance(density, (int, float))
            ):
                continue
            if not rec.get("verdict_ok", False):
                vetoes.append(scc)
                continue
            by_kind.setdefault("tpu" if _is_tpu(rec) else "cpu", []).append(
                (scc, float(density), float(speed))
            )
        if by_kind or vetoes:
            rank = _round_rank(path.name)
            if newest is None or rank > newest[0]:
                newest = (rank, path.name, by_kind, vetoes)
    if newest is None:
        return None
    _, name, by_kind, vetoes = newest
    if vetoes:
        log.warning(
            "bitset-encoding gate vetoed: %s records verdict_ok=false at "
            "bitset scc %s", name, sorted(set(vetoes)),
        )
        return None
    for kind in ("tpu", "cpu"):
        rows = by_kind.get(kind)
        if not rows:
            continue
        wins = [(scc, d) for scc, d, v in rows if v >= 1.1]
        losses = [(scc, d) for scc, d, v in rows if v < 1.0]
        while wins:
            min_scc = min(scc for scc, _ in wins)
            max_density = max(d for _, d in wins)
            inside = [
                (scc, d) for scc, d in losses
                if scc >= min_scc and d <= max_density
            ]
            if not inside:
                break
            # Shrink along the density axis past the densest inside loss.
            bound = max(d for _, d in inside)
            wins = [(scc, d) for scc, d in wins if d < bound]
        if not wins:
            continue
        return min_scc, max_density, kind, (
            f"{name}: bitset >= 1.1x dense for scc >= {min_scc} at qset "
            f"density <= {max_density:.4g} on {kind}"
        )
    return None


def _sweep_warm_ratio(
    paths: Iterable[pathlib.Path],
) -> Optional[Tuple[float, str]]:
    """Warm/cold XLA-compile ratio from the newest auto_race artifact's
    accelerator rows (benchmarks/auto_race.py ``--warm-start`` emits
    ``sweep_cold_xla_compile_s`` / ``sweep_warm_xla_compile_s`` pairs).

    Conservative by the same posture as the rate constants: the WORST
    (largest) ratio across the artifact's rows gates, a cold time too small
    to measure (< 0.1 s) never qualifies, and the ratio clamps to [0, 1] —
    a "warm slower than cold" reading is artifact rot, not physics."""
    newest: Optional[Tuple[int, str, float]] = None
    for path in paths:
        try:
            text = path.read_text()
        except OSError:
            continue
        worst: Optional[float] = None
        for ln in text.splitlines():
            ln = ln.strip()
            if not ln.startswith("{"):
                continue
            try:
                rec = json.loads(ln)
            except json.JSONDecodeError:
                continue
            if not _is_tpu(rec):
                continue
            cold = rec.get("sweep_cold_xla_compile_s")
            warm = rec.get("sweep_warm_xla_compile_s")
            if not isinstance(cold, (int, float)) or not isinstance(warm, (int, float)):
                continue
            if cold < 0.1:
                continue
            ratio = min(max(float(warm) / float(cold), 0.0), 1.0)
            worst = ratio if worst is None else max(worst, ratio)
        if worst is not None:
            rank = _round_rank(path.name)
            if newest is None or rank > newest[0]:
                newest = (rank, path.name, worst)
    if newest is None:
        return None
    _, name, ratio = newest
    # Qualifying rows are TPU-only today (the _is_tpu filter above) —
    # widen that filter before recording other kinds here.
    return ratio, "tpu", f"{name}: warm/cold xla compile = {ratio:.3f} (worst row)"


def _crossover_paths() -> List[pathlib.Path]:
    results = _REPO / "benchmarks" / "results"
    if results.is_dir():
        return sorted(results.glob("crossover_tpu_r*.txt"))
    return []


def _sweep_window_paths() -> List[pathlib.Path]:
    results = _REPO / "benchmarks" / "results"
    if results.is_dir():
        return sorted(results.glob("sweep_vs_native*r*.txt"))
    return []


def _auto_race_paths() -> List[pathlib.Path]:
    results = _REPO / "benchmarks" / "results"
    if results.is_dir():
        return sorted(results.glob("auto_race*r*.txt"))
    return []


def calibrate(
    paths: Optional[Iterable[pathlib.Path]] = None,
    crossover_paths: Optional[Iterable[pathlib.Path]] = None,
    sweep_window_paths: Optional[Iterable[pathlib.Path]] = None,
    auto_race_paths: Optional[Iterable[pathlib.Path]] = None,
) -> Calibration:
    cal = Calibration()
    cal.provenance = {k: "default" for k in ("accel", "cpu", "cpp")}
    chosen: Dict[str, Tuple[float, str]] = {}

    if crossover_paths is None:
        # Hermeticity mirrors `paths`: a caller pinning paths=[] gets a
        # fully artifact-free calibration, not one that still absorbs the
        # repo's crossover files.
        crossover_paths = _crossover_paths() if paths is None else []
    if sweep_window_paths is None:
        sweep_window_paths = _sweep_window_paths() if paths is None else []
    # Consumed three times below (sweep window + pack gate + bitset gate):
    # materialize so a generator argument cannot silently starve a later
    # pass.
    sweep_window_paths = list(sweep_window_paths)
    if auto_race_paths is None:
        auto_race_paths = _auto_race_paths() if paths is None else []
    try:
        warm = _sweep_warm_ratio(auto_race_paths)
        if warm is not None:
            (cal.sweep_warm_ratio, cal.sweep_warm_device,
             cal.provenance["warm_start"]) = warm
    # qi-lint: allow(degrade-via-ladder) — import-time artifact parsing
    except Exception:  # noqa: BLE001 — calibration must never break imports
        pass
    try:
        win = _frontier_win_min_scc(crossover_paths)
        if win is not None:
            (cal.frontier_win_min_scc, cal.frontier_win_max_scc,
             cal.frontier_win_device, cal.frontier_config,
             cal.provenance["frontier"]) = win
    # qi-lint: allow(degrade-via-ladder) — import-time artifact parsing
    except Exception:  # noqa: BLE001 — calibration must never break imports
        pass
    try:
        sw = _sweep_win_max_scc(sweep_window_paths)
        if sw is not None:
            (cal.sweep_win_max_scc, cal.sweep_win_cap_scc,
             cal.sweep_win_device, cal.provenance["sweep_window"]) = sw
    # qi-lint: allow(degrade-via-ladder) — import-time artifact parsing
    except Exception:  # noqa: BLE001 — calibration must never break imports
        pass
    try:
        pw = _pack_win_max_scc(sweep_window_paths)
        if pw is not None:
            (cal.pack_win_max_scc, cal.pack_win_device,
             cal.provenance["pack"]) = pw
    # qi-lint: allow(degrade-via-ladder) — import-time artifact parsing
    except Exception:  # noqa: BLE001 — calibration must never break imports
        pass
    try:
        bw = _bitset_win(sweep_window_paths)
        if bw is not None:
            (cal.bitset_win_min_scc, cal.bitset_win_max_density,
             cal.bitset_win_device, cal.provenance["bitset"]) = bw
    # qi-lint: allow(degrade-via-ladder) — import-time artifact parsing
    except Exception:  # noqa: BLE001 — calibration must never break imports
        pass

    try:
        records = list(_iter_records(_artifact_paths() if paths is None else paths))
    # qi-lint: allow(degrade-via-ladder) — import-time artifact parsing
    except Exception:  # noqa: BLE001 — calibration must never break imports
        return cal

    # The NEWEST round's measurement wins, not the fastest ever recorded:
    # the contract is to track the hardware the suite LAST measured — a
    # genuinely slower current chip/tunnel must lower the estimate, or the
    # budget skews exactly the way hand-pinned constants did (stale-fast).
    # Iterating in ascending round order with last-wins overwrites does that.
    for name, rec in sorted(records, key=lambda nr: (_round_rank(nr[0]), nr[0])):
        if _is_tpu(rec):
            # End-to-end wide-sweep rate preferred (session costs amortized);
            # the small-sweep end-to-end rate as a weaker substitute.
            for fld in ("wide_sweep_device_cand_per_sec", "sweep_device_cand_per_sec"):
                v = _in(_ACCEL_RATE_WINDOW, rec.get(fld))
                if v is not None:
                    chosen["accel"] = (v, f"{name}: {fld}={v:.4g}")
                    break
        else:
            v = _in(_CPU_RATE_WINDOW, rec.get("sweep_steady_rate"))
            if v is not None:
                chosen["cpu"] = (v, f"{name}: sweep_steady_rate={v:.4g}")
        # Native oracle call rate: the r4+ verdict phases measure it on the
        # benchmark instance itself (bench.py _native_verdict_baseline).
        # The engine must be EXPLICITLY cpp — a python-measured (or
        # unlabeled) rate would shrink the cpp budget ~50x, violating the
        # "more room for the oracle" posture.
        for key in ("verdict_256", "verdict_1024"):
            vd = rec.get(key)
            if isinstance(vd, dict) and vd.get("native_engine") == "cpp":
                v = _in(_ORACLE_RATE_WINDOW, vd.get("native_rate"))
                if v is not None:
                    chosen["cpp"] = (v, f"{name}: {key}.native_rate={v:.4g}")

    if "accel" in chosen:
        cal.sweep_rate["accel"] = chosen["accel"][0] / 2  # tunnel variance
        cal.provenance["accel"] = chosen["accel"][1] + " (halved)"
    if "cpu" in chosen:
        cal.sweep_rate["cpu"] = chosen["cpu"][0] / 4  # steady excludes compile
        cal.provenance["cpu"] = chosen["cpu"][1] + " (quartered)"
    if "cpp" in chosen:
        cal.oracle_seconds_per_call["cpp"] = 1.0 / chosen["cpp"][0]
        cal.provenance["cpp"] = chosen["cpp"][1] + " (inverted)"
    return cal


CALIBRATION = calibrate()
