"""Auto-routing cost-model calibration from recorded bench artifacts.

The `auto` backend's oracle-first budget needs two numbers: how fast the
exhaustive sweep runs (per platform) and how fast a host oracle burns B&B
calls.  Through r3 these were hand-pinned constants with the measurement
cited in a comment (VERDICT r3 §weak-3: "will silently skew as kernels
improve").  This module re-derives them at import time from the bench
records actually committed in the repo — the driver's ``BENCH_r*.json``
at the root and anything under ``benchmarks/results/`` — so the cost
model tracks the hardware the suite last measured, with the r3 constants
as fallback and every derived value carrying its source file name in
``CALIBRATION.provenance``.

Safety posture (unchanged from the hand-tuned constants):

- the accelerator sweep rate is the best recorded END-TO-END wide-sweep
  rate **halved** for tunnel variance — a conservative budget errs toward
  giving the pruned oracle MORE room, never less;
- the CPU sweep rate is the best recorded steady CPU rate **quartered**
  (steady excludes compile, which a real solve pays);
- derived values are clamped to sanity windows so one corrupt artifact
  cannot wreck routing.
"""

from __future__ import annotations

import json
import pathlib
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Tuple

# r3 fallbacks (benchmarks/results/bench_full_r3_onchip.json wide sweep;
# crossover_cpu_r2.txt majority-18; BASELINE.md n=16) — used whenever no
# artifact yields a usable number.
DEFAULT_SWEEP_RATE = {"cpu": 5e5, "accel": 3e8}
DEFAULT_ORACLE_SPC = {"cpp": 0.7e-6, "python": 3e-5}

# Sanity windows: a derived value outside these is ignored (artifact rot,
# truncated tails, unit bugs) rather than trusted.
_ACCEL_RATE_WINDOW = (1e7, 1e11)
_CPU_RATE_WINDOW = (1e4, 1e8)
_ORACLE_RATE_WINDOW = (1e4, 1e8)  # B&B calls/s

_REPO = pathlib.Path(__file__).resolve().parent.parent.parent


def _artifact_paths() -> List[pathlib.Path]:
    out = sorted(_REPO.glob("BENCH_r*.json"))
    results = _REPO / "benchmarks" / "results"
    if results.is_dir():
        out += sorted(results.glob("*.json"))
    return out


def _round_rank(name: str) -> int:
    """Recency key: the round number in the ``r<N>`` convention both
    artifact families use (``BENCH_r04.json``, ``bench_full_r3_onchip``);
    -1 when the name carries none.  Deliberately NOT "any integer in the
    name" — a results file like ``verdict_1024.json`` must never outrank
    genuinely newer rounds."""
    rounds = [int(m) for m in re.findall(r"(?:\b|_)[rR](\d+)", name)]
    return max(rounds) if rounds else -1


def _iter_records(paths: Iterable[pathlib.Path]):
    """Yield (name, headline-record) pairs, tolerating the two artifact
    shapes on disk: a bare headline dict, or the driver's wrapper with a
    ``parsed`` record / raw ``tail`` text ending in the headline line."""
    for path in paths:
        try:
            doc = json.loads(path.read_text())
        except Exception:  # noqa: BLE001 — unreadable artifact: skip
            continue
        if not isinstance(doc, dict):
            continue
        rec = None
        if isinstance(doc.get("parsed"), dict):
            rec = doc["parsed"]
        elif "metric" in doc or "sweep_steady_rate" in doc or "device" in doc:
            rec = doc
        elif isinstance(doc.get("tail"), str):
            for ln in reversed(doc["tail"].strip().splitlines()):
                try:
                    cand = json.loads(ln)
                except json.JSONDecodeError:
                    continue
                if isinstance(cand, dict):
                    rec = cand
                    break
        if rec is not None:
            yield path.name, rec


def _is_tpu(rec: dict) -> bool:
    return "tpu" in str(rec.get("device", "")).lower()


def _in(window: Tuple[float, float], value) -> Optional[float]:
    try:
        v = float(value)
    except (TypeError, ValueError):
        return None
    return v if window[0] <= v <= window[1] else None


@dataclass
class Calibration:
    sweep_rate: Dict[str, float] = field(
        default_factory=lambda: dict(DEFAULT_SWEEP_RATE)
    )
    oracle_seconds_per_call: Dict[str, float] = field(
        default_factory=lambda: dict(DEFAULT_ORACLE_SPC)
    )
    # key -> "file.json: <field>=<value>" (or "default" when no artifact won)
    provenance: Dict[str, str] = field(default_factory=dict)


def calibrate(paths: Optional[Iterable[pathlib.Path]] = None) -> Calibration:
    cal = Calibration()
    cal.provenance = {k: "default" for k in ("accel", "cpu", "cpp")}
    chosen: Dict[str, Tuple[float, str]] = {}

    try:
        records = list(_iter_records(_artifact_paths() if paths is None else paths))
    except Exception:  # noqa: BLE001 — calibration must never break imports
        return cal

    # The NEWEST round's measurement wins, not the fastest ever recorded:
    # the contract is to track the hardware the suite LAST measured — a
    # genuinely slower current chip/tunnel must lower the estimate, or the
    # budget skews exactly the way hand-pinned constants did (stale-fast).
    # Iterating in ascending round order with last-wins overwrites does that.
    for name, rec in sorted(records, key=lambda nr: (_round_rank(nr[0]), nr[0])):
        if _is_tpu(rec):
            # End-to-end wide-sweep rate preferred (session costs amortized);
            # the small-sweep end-to-end rate as a weaker substitute.
            for fld in ("wide_sweep_device_cand_per_sec", "sweep_device_cand_per_sec"):
                v = _in(_ACCEL_RATE_WINDOW, rec.get(fld))
                if v is not None:
                    chosen["accel"] = (v, f"{name}: {fld}={v:.4g}")
                    break
        else:
            v = _in(_CPU_RATE_WINDOW, rec.get("sweep_steady_rate"))
            if v is not None:
                chosen["cpu"] = (v, f"{name}: sweep_steady_rate={v:.4g}")
        # Native oracle call rate: the r4+ verdict phases measure it on the
        # benchmark instance itself (bench.py _native_verdict_baseline).
        # The engine must be EXPLICITLY cpp — a python-measured (or
        # unlabeled) rate would shrink the cpp budget ~50x, violating the
        # "more room for the oracle" posture.
        for key in ("verdict_256", "verdict_1024"):
            vd = rec.get(key)
            if isinstance(vd, dict) and vd.get("native_engine") == "cpp":
                v = _in(_ORACLE_RATE_WINDOW, vd.get("native_rate"))
                if v is not None:
                    chosen["cpp"] = (v, f"{name}: {key}.native_rate={v:.4g}")

    if "accel" in chosen:
        cal.sweep_rate["accel"] = chosen["accel"][0] / 2  # tunnel variance
        cal.provenance["accel"] = chosen["accel"][1] + " (halved)"
    if "cpu" in chosen:
        cal.sweep_rate["cpu"] = chosen["cpu"][0] / 4  # steady excludes compile
        cal.provenance["cpu"] = chosen["cpu"][1] + " (quartered)"
    if "cpp" in chosen:
        cal.oracle_seconds_per_call["cpp"] = 1.0 / chosen["cpp"][0]
        cal.provenance["cpp"] = chosen["cpp"][1] + " (inverted)"
    return cal


CALIBRATION = calibrate()
